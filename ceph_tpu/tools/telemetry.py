"""telemetry — the cluster-wide observability aggregator.

The mgr-prometheus-module + ``ceph daemonperf`` role: poll every
daemon's admin socket (one ``*.asok`` per daemon under the cluster's
asok dir — MiniCluster binds them there automatically), merge each
``perf dump`` / ``dump_tracing`` / ``dump_ops_in_flight`` into one
cluster snapshot, and render it three ways:

- Prometheus text exposition (``prom``): every counter/gauge/time as a
  sample labeled {daemon, logger}; avg pairs as _sum/_count; log2
  latency histograms as cumulative _bucket{le=...} series.
- a ``ceph daemonperf``-style columnar view (``daemonperf``): per-
  daemon per-second rates between two polls.
- cross-daemon trace reassembly (``traces``): spans from every
  daemon's ring buffer grouped by trace_id and re-parented into one
  tree — the client → messenger → primary OSD → EC encode → shard
  fan-out picture of a single op.

- the continuous plane: ``history`` scrapes every daemon's
  ``dump_metrics_history`` ring into one time-aligned cluster series
  (daemonperf-over-time), and ``top`` renders live rate frames with
  cluster totals (the `ceph_cli top` view).

- the profiling plane (PR 13): ``latency`` folds every completed
  client trace in the snapshot through ``common/attribution.py`` into
  the per-stage critical-path table ("what fraction of write p99 is
  messenger vs fsync vs encode"); ``profile`` broadcasts the
  wallclock sampler's start/stop/dump to every daemon; ``flame``
  merges the per-daemon folded stacks into one cluster flamegraph
  text report.

CLI:
    python -m ceph_tpu.tools.telemetry --asok-dir DIR \
        snapshot | prom | daemonperf [--interval S] [--count N] | \
        traces [--trace-id ID] [--root NAME] | \
        history [--last N] [--json] | top [--interval S] [--count N] \
        | latency [--root NAME] [--json] | flame [--json] | \
        profile --pcmd start|stop|dump
"""

from __future__ import annotations

import argparse
import fnmatch
import glob
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

from ..common.admin_socket import AdminSocket


# -- polling ----------------------------------------------------------

def discover(asok_dir: str) -> Dict[str, str]:
    """{daemon name: socket path} for every *.asok under the dir."""
    out = {}
    for path in sorted(glob.glob(os.path.join(asok_dir, "*.asok"))):
        out[os.path.basename(path)[:-len(".asok")]] = path
    return out


def poll_daemon(path: str, timeout: float = 5.0) -> Optional[Dict]:
    """One daemon's observability payload; None when unreachable (a
    dead daemon must not break the cluster snapshot)."""
    out: Dict = {}
    for key, prefix in (("perf", "perf dump"),
                        ("tracing", "dump_tracing"),
                        ("ops_in_flight", "dump_ops_in_flight"),
                        ("historic_ops", "dump_historic_ops"),
                        ("messenger", "dump_messenger"),
                        ("network", "dump_osd_network")):
        try:
            got = AdminSocket.request(path, prefix, timeout=timeout)
        except (OSError, ValueError):
            if not out:
                return None
            continue
        if isinstance(got, dict) and "error" in got and len(got) <= 2:
            continue  # command not wired on this daemon
        out[key] = got
    return out or None


def cluster_snapshot(asok_dir: Optional[str] = None,
                     paths: Optional[Dict[str, str]] = None,
                     timeout: float = 5.0) -> Dict:
    """Poll every daemon once; unreachable daemons are listed, not
    fatal."""
    assert asok_dir is not None or paths is not None
    targets = dict(paths or {})
    if asok_dir is not None:
        targets = {**discover(asok_dir), **targets}
    daemons, dead = {}, []
    for name, path in sorted(targets.items()):
        got = poll_daemon(path, timeout=timeout)
        if got is None:
            dead.append(name)
        else:
            daemons[name] = got
    return {"ts": time.time(), "daemons": daemons,
            "unreachable": dead}


# -- prometheus text exposition ---------------------------------------

def _sanitize(name: str) -> str:
    """Metric-name charset is [a-zA-Z_:][a-zA-Z0-9_:]* — dotted
    counter names (``ec.engine``-style keys) sanitize to
    underscores, and a leading digit gets a guard underscore."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return "_" + name if re.match(r"^[0-9]", name) else name


def _escape_label(value: str) -> str:
    """Label values are quoted strings with \\, \" and newline
    escaped (the exposition-format grammar) — daemon names are
    user-chosen and must not be able to break a scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def to_prometheus(snapshot: Dict, prefix: str = "ceph_tpu") -> str:
    """Prometheus text exposition.  Counter types survive the wire
    only structurally: plain numbers emit as untyped samples,
    {avgcount, sum} pairs as summary _sum/_count, {buckets, min} log2
    histograms as cumulative _bucket{le=...} + _count (le bounds are
    min * 2^i — bucket 0 is everything <= min).  Each metric FAMILY
    gets exactly one ``# HELP``/``# TYPE`` pair with every sample of
    the family grouped under it (the text-format grammar requirement
    a multi-daemon snapshot used to violate)."""
    fams: Dict[str, Dict] = {}

    def fam(metric: str, ptype: str, key: str) -> List[str]:
        f = fams.get(metric)
        if f is None:
            f = fams[metric] = {
                "type": ptype,
                "help": f"ceph_tpu counter {key}"
                .replace("\\", "").replace("\n", " "),
                "lines": []}
        return f["lines"]

    for daemon, data in sorted(snapshot.get("daemons", {}).items()):
        for logger, counters in sorted((data.get("perf")
                                        or {}).items()):
            if not isinstance(counters, dict):
                continue
            labels = (f'daemon="{_escape_label(daemon)}",'
                      f'logger="{_escape_label(logger)}"')
            for key, val in sorted(counters.items()):
                metric = f"{prefix}_{_sanitize(key)}"
                if isinstance(val, dict) and "buckets" in val:
                    lines = fam(metric, "histogram", key)
                    lo = float(val.get("min", 1.0))
                    cum = 0
                    for i, n in enumerate(val["buckets"]):
                        cum += n
                        lines.append(
                            f'{metric}_bucket{{{labels},'
                            f'le="{lo * (2.0 ** i):.9g}"}} {cum}')
                    lines.append(f'{metric}_bucket{{{labels},'
                                 f'le="+Inf"}} {cum}')
                    lines.append(f"{metric}_count{{{labels}}} {cum}")
                elif isinstance(val, dict) and "avgcount" in val:
                    lines = fam(metric, "summary", key)
                    lines.append(f"{metric}_sum{{{labels}}} "
                                 f"{val.get('sum', 0)}")
                    lines.append(f"{metric}_count{{{labels}}} "
                                 f"{val.get('avgcount', 0)}")
                elif isinstance(val, (int, float)):
                    fam(metric, "untyped", key).append(
                        f"{metric}{{{labels}}} {val}")
    out: List[str] = []
    for metric in sorted(fams):
        f = fams[metric]
        out.append(f"# HELP {metric} {f['help']}")
        out.append(f"# TYPE {metric} {f['type']}")
        out.extend(f["lines"])
    return "\n".join(out) + ("\n" if out else "")


# -- daemonperf (columnar rates between two polls) --------------------

# (logger glob, counter key, column header) — summed over matching
# loggers per daemon, rendered as per-second rates
DEFAULT_COLUMNS: List[Tuple[str, str, str]] = [
    ("msgr.*", "bytes_in", "rx_B/s"),
    ("msgr.*", "bytes_out", "tx_B/s"),
    ("msgr.*", "frames_in", "rxf/s"),
    ("osd.*", "ops_w", "wr/s"),
    ("osd.*", "ops_r", "rd/s"),
    ("client.*", "ops_put", "put/s"),
    ("client.*", "ops_get", "get/s"),
    # the data-plane batching layers (PR 5): journal txns vs shared
    # fsyncs (their ratio IS the group-commit win), EC dispatches,
    # and the pipelined client window
    ("os.wal", "txns", "waltx/s"),
    ("os.wal", "group_commits", "fsync/s"),
    ("ec.engine", "encode_ops", "ecenc/s"),
    ("client.*", "ops_aio_put", "aput/s"),
    # active recovery: objects rebuilt per second (osd family) next
    # to the client rates they compete with under the QoS plane
    ("osd.*", "recovered_objects", "rec/s"),
    ("mon*", "epochs", "epo/s"),
    ("mgr*", "balancer_rounds", "bal/s"),
    # data-race checker violations/s — nonzero here means a daemon
    # recorded an Eraser lockset/confinement report since the last
    # poll (normally dead-zero; see dump_racecheck for the stacks)
    ("analysis.race", "violations", "race"),
    # async-safety budget overruns/s — nonzero means a @nonblocking
    # dispatch callback blew its wallclock budget since the last poll
    # (normally dead-zero; see dump_asyncheck for both-end stacks)
    ("analysis.block", "overruns", "blk"),
]


def _column_value(perf: Dict, logger_glob: str, key: str) -> float:
    total = 0.0
    for logger, counters in (perf or {}).items():
        if not fnmatch.fnmatch(logger, logger_glob):
            continue
        val = (counters or {}).get(key)
        if isinstance(val, (int, float)):
            total += val
    return total


def _time_value(perf: Dict, logger_glob: str, key: str,
                sub: str = "sum") -> float:
    """Sum a TIME counter across matching loggers.  PerfCounters
    dumps TIME counters as PLAIN floats (the cumulative seconds), so
    a number counts directly as the ``sum``; AVG-style {avgcount,
    sum} dicts contribute the requested field.  (The old dict-only
    version silently read 0.0 for every real TIME counter — the
    daemonperf `hb lat` column was computed from nothing.)"""
    total = 0.0
    for logger, counters in (perf or {}).items():
        if not fnmatch.fnmatch(logger, logger_glob):
            continue
        val = (counters or {}).get(key)
        if isinstance(val, dict):
            total += float(val.get(sub, 0) or 0)
        elif isinstance(val, (int, float)) and sub == "sum":
            total += float(val)
    return total


def _hist_buckets(perf: Dict, logger_glob: str,
                  key: str) -> Tuple[List[float], float]:
    """Summed bucket counts (+ the log2 floor) of a HISTOGRAM counter
    across matching loggers."""
    total: List[float] = []
    lo: Optional[float] = None
    for logger, counters in (perf or {}).items():
        if not fnmatch.fnmatch(logger, logger_glob):
            continue
        val = (counters or {}).get(key)
        if isinstance(val, dict) and "buckets" in val:
            b = val["buckets"]
            if len(b) > len(total):
                total.extend([0.0] * (len(b) - len(total)))
            for i, n in enumerate(b):
                total[i] += n
            if lo is None:
                lo = float(val.get("min", 1e-6))
    return total, (lo if lo is not None else 1e-6)


def hist_quantile(buckets: List[float], min_value: float,
                  q: float) -> float:
    """Upper-edge quantile from a log2 bucket list (bucket 0 holds
    values <= min, bucket i holds (min*2^(i-1), min*2^i]): the bound
    is conservative by at most one octave, which is what a log2
    histogram can honestly promise."""
    n = sum(buckets)
    if n <= 0:
        return 0.0
    target = q * n
    cum = 0.0
    for i, c in enumerate(buckets):
        cum += c
        if cum >= target:
            return min_value * (2.0 ** i)
    return min_value * (2.0 ** max(0, len(buckets) - 1))


def _hist_delta(cperf: Dict, pperf: Dict, glob: str,
                key: str) -> Tuple[List[float], float]:
    """Bucket-wise delta of a histogram between two snapshots."""
    cb, lo = _hist_buckets(cperf, glob, key)
    pb, _lo = _hist_buckets(pperf, glob, key)
    return [c - (pb[i] if i < len(pb) else 0.0)
            for i, c in enumerate(cb)], lo


# op-throughput counters the derived cp/op column divides by —
# every client/OSD op the byte-copy ledger can book against
_OP_COUNTERS: List[Tuple[str, str]] = [
    ("osd.*", "ops_w"), ("osd.*", "ops_r"),
    ("client.*", "ops_put"), ("client.*", "ops_get"),
    ("client.*", "ops_write"), ("client.*", "ops_delete"),
]


def unattr_shares(snapshot: Dict,
                  root_prefix: str = "client.") -> Dict[str, float]:
    """Per-daemon unattributed critical-path share: every completed
    client trace in the snapshot is folded (common/attribution.py)
    and charged to the daemon that reported its ROOT span — only
    clients originate ops, so only client rows get a value."""
    from ..common import attribution

    spans = gather_spans(snapshot)
    root_daemon: Dict[str, str] = {}
    for s in spans:
        if not s.get("parent_id") and \
                (s.get("name") or "").startswith(root_prefix):
            root_daemon.setdefault(s.get("trace_id", ""),
                                   s.get("daemon", "?"))
    totals: Dict[str, List[float]] = {}
    for fold in attribution.fold_spans(spans, root_prefix):
        daemon = root_daemon.get(fold.get("trace_id") or "")
        if daemon is None:
            continue
        acc = totals.setdefault(daemon, [0.0, 0.0])
        acc[0] += fold["stages"].get(attribution.UNATTRIBUTED, 0.0)
        acc[1] += fold["total"]
    return {d: (un / tot if tot > 0 else 0.0)
            for d, (un, tot) in totals.items()}


def daemonperf_view(prev: Dict, cur: Dict,
                    columns: Optional[List[Tuple[str, str, str]]]
                    = None, derived: bool = True) -> str:
    """`ceph daemonperf` analogue: one row per daemon, one column per
    (logger glob, key), values are deltas/second between the two
    snapshots.

    ``derived`` appends computed columns: ``cp/op`` (delta obs.copy
    bytes_copied / delta ops — host bytes copied per op) and
    ``unattr%`` (the unattributed critical-path share of the daemon's
    completed traces) from the PR-13 observability families; ``hb
    lat`` — the mean peer ping RTT in ms over the window (delta
    osd.hb ping_time sum / delta acks), the live view of the failure
    detector's latency EWMA input; and the PR-17 saturation pair:
    ``stall%`` (share of the window spent in send stall against
    socket backpressure) and ``dq p99`` (dispatch-queue wait p99 in
    ms over the window, both lanes)."""
    columns = columns or DEFAULT_COLUMNS
    dt = max(1e-9, cur.get("ts", 0) - prev.get("ts", 0))
    headers = [h for _g, _k, h in columns]
    if derived:
        headers = headers + ["cp/op", "unattr%", "hb lat",
                             "stall%", "dq p99"]
    width = max(8, *(len(h) + 1 for h in headers))
    name_w = max([len("daemon")] +
                 [len(d) for d in cur.get("daemons", {})]) + 1
    lines = ["daemon".ljust(name_w)
             + "".join(h.rjust(width) for h in headers)]
    unattr = unattr_shares(cur) if derived else {}
    for daemon in sorted(cur.get("daemons", {})):
        cperf = cur["daemons"][daemon].get("perf") or {}
        pperf = (prev.get("daemons", {}).get(daemon, {})
                 .get("perf")) or {}
        cells = []
        for lg, key, _h in columns:
            rate = (_column_value(cperf, lg, key)
                    - _column_value(pperf, lg, key)) / dt
            cells.append(f"{rate:.1f}".rjust(width))
        if derived:
            d_copied = (_column_value(cperf, "obs.copy",
                                      "bytes_copied")
                        - _column_value(pperf, "obs.copy",
                                        "bytes_copied"))
            d_ops = sum(_column_value(cperf, lg, key)
                        - _column_value(pperf, lg, key)
                        for lg, key in _OP_COUNTERS)
            cells.append((f"{d_copied / d_ops:.0f}" if d_ops > 0
                          else "-").rjust(width))
            cells.append((f"{unattr[daemon]:.1%}"
                          if daemon in unattr else "-").rjust(width))
            d_rtt = (_time_value(cperf, "osd.hb.*", "ping_time",
                                 "sum")
                     - _time_value(pperf, "osd.hb.*", "ping_time",
                                   "sum"))
            d_acks = (_column_value(cperf, "osd.hb.*", "acks")
                      - _column_value(pperf, "osd.hb.*", "acks"))
            cells.append((f"{d_rtt / d_acks * 1000:.1f}"
                          if d_acks > 0 else "-").rjust(width))
            d_stall = (_time_value(cperf, "msgr.*",
                                   "send_stall_time")
                       - _time_value(pperf, "msgr.*",
                                     "send_stall_time"))
            cells.append(f"{max(0.0, d_stall) / dt:.1%}"
                         .rjust(width))
            wb_c, w_lo = _hist_delta(cperf, pperf, "msgr.*",
                                     "dispatch_wait_ctl")
            wb_d, _ = _hist_delta(cperf, pperf, "msgr.*",
                                  "dispatch_wait_data")
            if len(wb_c) < len(wb_d):
                wb_c.extend([0.0] * (len(wb_d) - len(wb_c)))
            merged = [a + (wb_d[i] if i < len(wb_d) else 0.0)
                      for i, a in enumerate(wb_c)]
            cells.append((f"{1e3 * hist_quantile(merged, w_lo, 0.99):.1f}"
                          if sum(merged) > 0 else "-").rjust(width))
        lines.append(daemon.ljust(name_w) + "".join(cells))
    return "\n".join(lines)


# -- the saturation plane (telemetry net, PR 17) ----------------------

def net_summary(cur: Dict, prev: Optional[Dict] = None,
                dt: Optional[float] = None) -> Dict:
    """Cluster messenger-saturation roll-up between two snapshots
    (``prev=None`` with an explicit ``dt`` treats ``cur``'s cumulative
    counters as the whole-run delta — how the bench commits its
    ``net.*`` trajectory columns).

    Per daemon: send-stall share (seconds stalled against socket
    backpressure per wall second), dispatch wait/latency p99 (data
    lane), and per-lane dispatch rates.  Cluster: the same folded
    across daemons, plus the worst heartbeat-RTT peers from any
    ``dump_osd_network`` payloads in the snapshot."""
    if dt is None:
        dt = max(1e-9, cur.get("ts", 0)
                 - (prev or {}).get("ts", 0))
    prev_daemons = (prev or {}).get("daemons", {})
    per: Dict[str, Dict] = {}
    tot_stall = 0.0
    all_lat: List[float] = []
    all_lo = 1e-6
    slow_peers: List[Dict] = []
    for daemon, data in sorted(cur.get("daemons", {}).items()):
        cperf = data.get("perf") or {}
        pperf = (prev_daemons.get(daemon, {}).get("perf")) or {}
        stall = (_time_value(cperf, "msgr.*", "send_stall_time")
                 - _time_value(pperf, "msgr.*", "send_stall_time"))
        wait_b, wait_lo = _hist_delta(cperf, pperf, "msgr.*",
                                      "dispatch_wait_data")
        lat_b, lat_lo = _hist_delta(cperf, pperf, "msgr.*",
                                    "dispatch_lat_data")
        ctl_b, _ = _hist_delta(cperf, pperf, "msgr.*",
                               "dispatch_lat_ctl")
        per[daemon] = {
            "send_stall_s": round(max(0.0, stall), 6),
            "send_stall_share": round(max(0.0, stall) / dt, 6),
            "dispatch_wait_p99_ms": round(
                1e3 * hist_quantile(wait_b, wait_lo, 0.99), 3),
            "dispatch_p99_ms": round(
                1e3 * hist_quantile(lat_b, lat_lo, 0.99), 3),
            "ctl_per_s": round(sum(ctl_b) / dt, 1),
            "data_per_s": round(sum(lat_b) / dt, 1),
        }
        tot_stall += max(0.0, stall)
        if len(lat_b) > len(all_lat):
            all_lat.extend([0.0] * (len(lat_b) - len(all_lat)))
        for i, n in enumerate(lat_b):
            all_lat[i] += n
        all_lo = lat_lo
        net = data.get("network")
        if isinstance(net, dict):
            for e in net.get("entries", []):
                slow_peers.append({
                    "daemon": daemon, "peer": e.get("peer"),
                    "worst_ms": e.get("worst_ms", 0.0)})
    slow_peers.sort(key=lambda e: e["worst_ms"], reverse=True)
    n_daemons = max(1, len(per))
    return {
        "dt_s": round(dt, 3),
        "send_stall_s": round(tot_stall, 6),
        # stall share normalized per daemon: 1.0 would mean every
        # daemon spent every wall second pushing against a full
        # socket buffer
        "send_stall_share": round(tot_stall / (dt * n_daemons), 6),
        "dispatch_p99_ms": round(
            1e3 * hist_quantile(all_lat, all_lo, 0.99), 3),
        "per_daemon": per,
        "slow_peers": slow_peers[:16],
    }


def net_view(cur: Dict, prev: Optional[Dict] = None,
             dt: Optional[float] = None) -> str:
    """Render net_summary as the `telemetry net` table."""
    s = net_summary(cur, prev=prev, dt=dt)
    headers = ("stall%", "dq p99", "lat p99", "ctl/s", "data/s")
    width = max(9, *(len(h) + 1 for h in headers))
    name_w = max([len("daemon")] + [len(d) for d in s["per_daemon"]]
                 ) + 1
    lines = [f"net saturation over {s['dt_s']}s — cluster stall "
             f"share {s['send_stall_share']:.2%}, dispatch p99 "
             f"{s['dispatch_p99_ms']:.2f}ms",
             "daemon".ljust(name_w)
             + "".join(h.rjust(width) for h in headers)]
    for daemon, row in sorted(
            s["per_daemon"].items(),
            key=lambda kv: kv[1]["send_stall_share"], reverse=True):
        lines.append(
            daemon.ljust(name_w)
            + f"{row['send_stall_share']:.2%}".rjust(width)
            + f"{row['dispatch_wait_p99_ms']:.2f}".rjust(width)
            + f"{row['dispatch_p99_ms']:.2f}".rjust(width)
            + f"{row['ctl_per_s']:.1f}".rjust(width)
            + f"{row['data_per_s']:.1f}".rjust(width))
    if s["slow_peers"]:
        worst = ", ".join(
            f"{e['daemon']}->osd.{e['peer']} {e['worst_ms']:.0f}ms"
            for e in s["slow_peers"][:8])
        lines.append(f"slow heartbeat peers (worst first): {worst}")
    return "\n".join(lines)


# -- metrics history (daemonperf-over-time) ---------------------------

def gather_history(asok_dir: Optional[str] = None,
                   paths: Optional[Dict[str, str]] = None,
                   timeout: float = 5.0,
                   last: Optional[int] = None) -> Dict[str, Dict]:
    """Scrape every daemon's ``dump_metrics_history`` ring; daemons
    without the command (or unreachable) are skipped, not fatal."""
    assert asok_dir is not None or paths is not None
    targets = dict(paths or {})
    if asok_dir is not None:
        targets = {**discover(asok_dir), **targets}
    out: Dict[str, Dict] = {}
    for name, path in sorted(targets.items()):
        args = {"last": last} if last else {}
        try:
            got = AdminSocket.request(path, "dump_metrics_history",
                                      timeout=timeout, **args)
        except (OSError, ValueError):
            continue
        if isinstance(got, dict) and "samples" in got:
            out[name] = got
    return out


def history_view(histories: Dict[str, Dict],
                 columns: Optional[List[Tuple[str, str, str]]] = None,
                 bucket_s: float = 1.0) -> str:
    """The time-aligned cluster series: every daemon's ring merged
    into one table — rows are time buckets, columns are the
    daemonperf rate columns summed across daemons.  The
    `daemonperf-over-time` view ROADMAP items 1/3/4 hang their
    scaling/saturation measurements on."""
    columns = columns or DEFAULT_COLUMNS
    headers = [h for _g, _k, h in columns]
    buckets: Dict[float, Dict[str, float]] = {}
    for _daemon, hist in sorted(histories.items()):
        samples = hist.get("samples", [])
        for a, b in zip(samples, samples[1:]):
            dt = max(1e-9, b.get("mono", 0) - a.get("mono", 0))
            bucket = round(b.get("ts", 0) / bucket_s) * bucket_s
            row = buckets.setdefault(bucket,
                                     {h: 0.0 for h in headers})
            for lg, key, hdr in columns:
                delta = (_column_value(b.get("perf", {}), lg, key)
                         - _column_value(a.get("perf", {}), lg, key))
                row[hdr] += max(0.0, delta) / dt
    width = max(8, *(len(h) + 1 for h in headers))
    lines = ["time".ljust(9)
             + "".join(h.rjust(width) for h in headers)]
    for ts in sorted(buckets):
        stamp = time.strftime("%H:%M:%S", time.localtime(ts))
        lines.append(stamp.ljust(9) + "".join(
            f"{buckets[ts][h]:.1f}".rjust(width) for h in headers))
    return "\n".join(lines)


def top_view(prev: Dict, cur: Dict) -> str:
    """One `ceph_cli top` frame: cluster totals header + the
    daemonperf rate table between the two snapshots."""
    daemons = cur.get("daemons", {})
    inflight = 0
    for data in daemons.values():
        ops = data.get("ops_in_flight") or {}
        inflight += int(ops.get("num_ops", 0) or 0)
    stamp = time.strftime("%H:%M:%S",
                          time.localtime(cur.get("ts", 0)))
    head = (f"ceph-tpu top — {stamp}  daemons: {len(daemons)}"
            f"  unreachable: {len(cur.get('unreachable', []))}"
            f"  ops in flight: {inflight}")
    return head + "\n\n" + daemonperf_view(prev, cur)


# -- cross-daemon trace reassembly ------------------------------------

def gather_spans(snapshot: Dict,
                 extra: Optional[List[Dict]] = None) -> List[Dict]:
    """Every span in the snapshot (finished + active), stamped with
    the daemon that reported it."""
    spans: List[Dict] = []
    for daemon, data in snapshot.get("daemons", {}).items():
        tr = data.get("tracing") or {}
        for s in list(tr.get("spans", [])) + list(tr.get("active",
                                                         [])):
            spans.append(dict(s, daemon=daemon))
    for s in extra or []:
        spans.append(dict(s))
    return spans


def find_trace_ids(spans: List[Dict],
                   root_name: Optional[str] = None) -> List[str]:
    """trace_ids that have a ROOT span (optionally named), newest
    first."""
    roots = [s for s in spans if not s.get("parent_id")
             and (root_name is None or s.get("name") == root_name)]
    roots.sort(key=lambda s: s.get("start", 0), reverse=True)
    out: List[str] = []
    for s in roots:
        if s["trace_id"] not in out:
            out.append(s["trace_id"])
    return out


def trace_tree(spans: List[Dict], trace_id: str) -> List[Dict]:
    """Re-parent one trace's spans (from any number of daemons) into
    a forest: nodes are span dicts with a ``children`` list; spans
    whose parent was not reported (sampled out, ring-evicted, daemon
    unreachable) surface as extra roots rather than vanishing."""
    mine = [s for s in spans if s.get("trace_id") == trace_id]
    index: Dict[str, Dict] = {}
    for s in mine:
        index.setdefault(s["span_id"], dict(s, children=[]))
    roots: List[Dict] = []
    for node in index.values():
        parent = node.get("parent_id")
        if parent and parent in index:
            index[parent]["children"].append(node)
        else:
            roots.append(node)

    def order(nodes: List[Dict]) -> None:
        nodes.sort(key=lambda n: n.get("start", 0))
        for n in nodes:
            order(n["children"])

    order(roots)
    return roots


def render_trace(roots: List[Dict]) -> str:
    lines: List[str] = []

    def walk(node: Dict, depth: int) -> None:
        dur = node.get("duration")
        dur_s = f"{dur * 1000:.2f}ms" if isinstance(
            dur, (int, float)) else "?"
        svc = node.get("daemon") or node.get("service", "?")
        tags = node.get("tags") or {}
        tag_s = (" " + json.dumps(tags, sort_keys=True)
                 ) if tags else ""
        lines.append(f"{'  ' * depth}{svc}: {node.get('name')} "
                     f"{dur_s}{tag_s}")
        for child in node["children"]:
            walk(child, depth + 1)

    for root in roots:
        walk(root, 0)
    return "\n".join(lines)


# -- critical-path latency attribution (PR 13) ------------------------

def latency_report(snapshot: Dict,
                   root_prefix: str = "client.") -> Dict:
    """Fold every completed client trace in the snapshot into the
    cluster-wide per-stage attribution report
    (common/attribution.py): {"n_ops", "total", "stages"}."""
    from ..common import attribution

    folds = attribution.fold_spans(gather_spans(snapshot),
                                   root_prefix)
    agg = attribution.StageAggregator()
    for f in folds:
        agg.add(f)
    return agg.report()


# -- wallclock profiler plane (PR 13) ---------------------------------

def gather_profiles(asok_dir: Optional[str] = None,
                    paths: Optional[Dict[str, str]] = None,
                    timeout: float = 5.0,
                    cmd: str = "dump") -> Dict[str, Dict]:
    """Broadcast one ``profile`` admin command (start|stop|dump) to
    every daemon; unreachable daemons and daemons without the command
    are skipped, not fatal."""
    assert asok_dir is not None or paths is not None
    targets = dict(paths or {})
    if asok_dir is not None:
        targets = {**discover(asok_dir), **targets}
    out: Dict[str, Dict] = {}
    for name, path in sorted(targets.items()):
        try:
            got = AdminSocket.request(path, "profile",
                                      timeout=timeout, cmd=cmd)
        except (OSError, ValueError):
            continue
        if isinstance(got, dict) and "error" not in got:
            out[name] = got
    return out


def flame_view(asok_dir: Optional[str] = None,
               paths: Optional[Dict[str, str]] = None) -> str:
    """The merged cluster flamegraph text report: every daemon's
    folded stacks, keyed ``daemon/role;frames``."""
    from ..common.profiler import merge_folded, render_flame

    dumps = gather_profiles(asok_dir, paths)
    return render_flame(merge_folded(dumps))


def span_names(roots: List[Dict]) -> List[str]:
    """Flat preorder list of span names (test/assertion helper)."""
    out: List[str] = []

    def walk(node: Dict) -> None:
        out.append(node.get("name"))
        for child in node["children"]:
            walk(child)

    for root in roots:
        walk(root)
    return out


# -- CLI --------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="telemetry")
    ap.add_argument("--asok-dir", required=True,
                    help="directory of daemon *.asok sockets")
    ap.add_argument("cmd", choices=("snapshot", "prom", "traces",
                                    "daemonperf", "history", "top",
                                    "latency", "flame", "profile",
                                    "net"))
    ap.add_argument("--trace-id", help="traces: reassemble this id")
    ap.add_argument("--root",
                    help="traces: only traces whose root span has "
                         "this name")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="daemonperf/top: seconds between polls")
    ap.add_argument("--count", type=int, default=1,
                    help="daemonperf/top: frames to print")
    ap.add_argument("--last", type=int, default=None,
                    help="history: samples per daemon (default all)")
    ap.add_argument("--json", action="store_true",
                    help="history/latency/flame: raw JSON output")
    ap.add_argument("--pcmd", choices=("start", "stop", "dump"),
                    default="dump",
                    help="profile: subcommand broadcast to daemons")
    args = ap.parse_args(argv)

    if args.cmd == "profile":
        acks = gather_profiles(args.asok_dir, cmd=args.pcmd)
        if not acks:
            print(f"no profiler-capable daemons under "
                  f"{args.asok_dir}", file=sys.stderr)
            return 1
        print(json.dumps(acks, indent=1, default=str))
        return 0
    if args.cmd == "flame":
        if args.json:
            print(json.dumps(gather_profiles(args.asok_dir),
                             indent=1, default=str))
        else:
            print(flame_view(args.asok_dir))
        return 0

    if args.cmd == "history":
        hist = gather_history(args.asok_dir, last=args.last)
        if not hist:
            print(f"no metrics history under {args.asok_dir} "
                  f"(metrics_history_interval disabled?)",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(hist, indent=1, default=str))
        else:
            print(history_view(hist))
        return 0
    if args.cmd == "top":
        prev = cluster_snapshot(args.asok_dir)
        if not prev["daemons"]:
            print(f"no reachable daemons under {args.asok_dir}",
                  file=sys.stderr)
            return 1
        for i in range(max(1, args.count)):
            time.sleep(args.interval)
            cur = cluster_snapshot(args.asok_dir)
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(top_view(prev, cur))
            prev = cur
        return 0

    snap = cluster_snapshot(args.asok_dir)
    if not snap["daemons"]:
        print(f"no reachable daemons under {args.asok_dir}",
              file=sys.stderr)
        return 1
    if args.cmd == "snapshot":
        print(json.dumps(snap, indent=1, default=str))
    elif args.cmd == "latency":
        from ..common import attribution

        report = latency_report(
            snap, root_prefix=(args.root or "client."))
        if args.json:
            print(json.dumps(report, indent=1, default=str))
        elif report["n_ops"] == 0:
            print("no completed client traces in the snapshot "
                  "(trace_sample_rate 0, or ring evicted?)",
                  file=sys.stderr)
            return 1
        else:
            print(attribution.render_report(report))
    elif args.cmd == "prom":
        sys.stdout.write(to_prometheus(snap))
    elif args.cmd == "traces":
        spans = gather_spans(snap)
        ids = [args.trace_id] if args.trace_id else \
            find_trace_ids(spans, args.root)
        if not ids:
            print("no traces found", file=sys.stderr)
            return 1
        for tid in ids:
            print(f"trace {tid}:")
            print(render_trace(trace_tree(spans, tid)))
    elif args.cmd == "daemonperf":
        prev = snap
        for _ in range(max(1, args.count)):
            time.sleep(args.interval)
            cur = cluster_snapshot(args.asok_dir)
            print(daemonperf_view(prev, cur))
            prev = cur
    elif args.cmd == "net":
        prev = snap
        for _ in range(max(1, args.count)):
            time.sleep(args.interval)
            cur = cluster_snapshot(args.asok_dir)
            if args.json:
                print(json.dumps(net_summary(cur, prev=prev),
                                 indent=1, default=str))
            else:
                print(net_view(cur, prev=prev))
            prev = cur
    return 0


if __name__ == "__main__":
    sys.exit(main())
