"""objectstore-tool — offline store surgery.

The ceph-objectstore-tool role (src/tools/ceph_objectstore_tool.cc):
operate on an OSD's data directory while the daemon is DOWN — list
collections/objects, dump an object (data + attrs + omap), export a
PG's objects to a portable file, import them into another store, and
remove objects.  Works on the WALStore layout OSDService mounts
(``<data-dir>/osd.<id>.wal``).

CLI:
    python -m ceph_tpu.tools.objectstore_tool --data-path DIR \
        [--op list|meta-list|export|import|dump|remove]
        [--pgid POOL.PS] [--oid NAME] [--file F]
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
from typing import Dict


def _mount(path: str):
    from ..os.wal_store import WALStore

    st = WALStore(path)
    st.mount()
    return st


def op_list(store, pgid=None) -> Dict:
    out: Dict[str, list] = {}
    for cid in store.list_collections():
        if pgid and cid != pgid:
            continue
        out[cid] = sorted(o for o in store.list_objects(cid))
    return out


def op_dump(store, pgid: str, oid: str) -> Dict:
    data = store.read(pgid, oid)
    st = store.stat(pgid, oid)
    attrs = {}
    for key in ("size", "crc", "v"):
        got = store.getattr(pgid, oid, key)
        if got is not None:
            attrs[key] = got.decode()
    return {"pgid": pgid, "oid": oid, "len": len(data),
            "stat": st, "attrs": attrs,
            "omap_keys": sorted(store.omap_get(pgid, oid)),
            "data_b64": base64.b64encode(data).decode()}


def op_export(store, pgid: str) -> Dict:
    """Portable PG export: every object with data/attrs/omap."""
    objs = []
    for oid in sorted(store.list_objects(pgid)):
        rec = {"oid": oid,
               "data": base64.b64encode(
                   store.read(pgid, oid)).decode(),
               "attrs": {}, "omap": {}}
        for key in ("size", "crc", "v"):
            got = store.getattr(pgid, oid, key)
            if got is not None:
                rec["attrs"][key] = got.decode()
        for k, v in store.omap_get(pgid, oid).items():
            rec["omap"][k] = base64.b64encode(v).decode()
        objs.append(rec)
    return {"format": "ceph_tpu-pg-export-1", "pgid": pgid,
            "objects": objs}


def op_import(store, blob: Dict) -> int:
    from ..os.objectstore import Transaction

    if blob.get("format") != "ceph_tpu-pg-export-1":
        raise SystemExit("unrecognized export format")
    pgid = blob["pgid"]
    txn = Transaction()
    if not store.collection_exists(pgid):
        txn.create_collection(pgid)
    n = 0
    for rec in blob["objects"]:
        oid = rec["oid"]
        txn.write(pgid, oid, 0, base64.b64decode(rec["data"]))
        for k, v in rec.get("attrs", {}).items():
            txn.setattr(pgid, oid, k, v.encode())
        omap = {k: base64.b64decode(v)
                for k, v in rec.get("omap", {}).items()}
        if omap:
            txn.omap_setkeys(pgid, oid, omap)
        n += 1
    store.queue_transaction(txn)
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="objectstore_tool")
    ap.add_argument("--data-path", required=True,
                    help="the WALStore dir (…/osd.N.wal)")
    ap.add_argument("--op", default="list",
                    choices=["list", "dump", "export", "import",
                             "remove"])
    ap.add_argument("--pgid")
    ap.add_argument("--oid")
    ap.add_argument("--file", help="export/import file (default -)")
    args = ap.parse_args(argv)

    store = _mount(args.data_path)
    try:
        if args.op == "list":
            print(json.dumps(op_list(store, args.pgid), indent=1))
        elif args.op == "dump":
            if not (args.pgid and args.oid):
                raise SystemExit("dump needs --pgid and --oid")
            print(json.dumps(op_dump(store, args.pgid, args.oid),
                             indent=1))
        elif args.op == "export":
            if not args.pgid:
                raise SystemExit("export needs --pgid")
            blob = json.dumps(op_export(store, args.pgid))
            if args.file and args.file != "-":
                open(args.file, "w").write(blob)
            else:
                print(blob)
        elif args.op == "import":
            raw = open(args.file).read() if args.file and \
                args.file != "-" else sys.stdin.read()
            n = op_import(store, json.loads(raw))
            print(f"imported {n} objects", file=sys.stderr)
        elif args.op == "remove":
            if not (args.pgid and args.oid):
                raise SystemExit("remove needs --pgid and --oid")
            from ..os.objectstore import Transaction

            store.queue_transaction(
                Transaction().remove(args.pgid, args.oid))
    finally:
        store.umount()
    return 0


if __name__ == "__main__":
    sys.exit(main())
