"""osdmaptool — create/inspect/balance cluster maps.

The role of src/tools/osdmaptool.cc:103-846 with the same verbs:

  --createsimple N [--pg-bits B]   build an N-osd map + pool 1
  --test-map-pgs [--pool P]        map every PG (batched), per-osd stats
  --upmap FILE [--upmap-deviation D] [--upmap-max N] [--upmap-pool P]
                                   run the balancer, write the commands
  --upmap-cleanup                  drop invalid pg_upmap_items
  --export-crush F / --import-crush F
  --mark-up-in                     all osds up+in

OSDMap files are the framework's native JSON (OSDMap.to_dict).

Usage: python -m ceph_tpu.tools.osdmaptool <mapfile> ...
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from ..crush.wrapper import CrushWrapper
from ..osdmap.balancer import build_pgs_by_osd, calc_pg_upmaps
from ..osdmap.osdmap import OSDMap, PgPool


def create_simple(num_osd: int, pg_bits: int = 6) -> OSDMap:
    """--createsimple (osdmaptool.cc / OSDMap::build_simple): one host
    per osd under one root, one replicated pool."""
    w = CrushWrapper()
    for d in range(num_osd):
        w.insert_item(d, 0x10000, f"osd.{d}",
                      {"host": f"host{d}", "root": "default"})
    rid = w.add_simple_rule("replicated_rule", "default", "host", "",
                            "firstn")
    m = OSDMap(w.crush)
    for d in range(num_osd):
        m.add_osd(d)
    m.pools[1] = PgPool(size=3, pg_num=num_osd << pg_bits,
                        crush_rule=rid)
    return m


def test_map_pgs(m: OSDMap, pool: int | None = None,
                 use_batched: bool = True, out=sys.stdout) -> None:
    """--test-map-pgs (osdmaptool.cc:41-43): per-osd pg counts."""
    only = {pool} if pool is not None else None
    pgs_by_osd = build_pgs_by_osd(m, only, use_batched=use_batched)
    counts = np.zeros(m.max_osd, np.int64)
    for osd, pgs in pgs_by_osd.items():
        if 0 <= osd < m.max_osd:
            counts[osd] = len(pgs)
    for osd in range(m.max_osd):
        out.write(f"osd.{osd}\t{counts[osd]}\n")
    total = int(counts.sum())
    in_osds = max(1, sum(1 for w in m.osd_weight if w > 0))
    avg = total / in_osds
    if avg > 0:
        dev = counts[np.asarray(m.osd_weight) > 0] - avg
        stddev = float(np.sqrt((dev ** 2).mean()))
        out.write(f" avg {avg:.4g} stddev {stddev:.4g} "
                  f"({stddev / avg:.4g}x)\n")
    out.write(f" in {in_osds}\n")
    out.write(f" min osd.{int(counts.argmin())} {int(counts.min())}\n")
    out.write(f" max osd.{int(counts.argmax())} {int(counts.max())}\n")
    out.write(f"size {total}\n")


def upmap_cleanup(m: OSDMap) -> int:
    """--upmap-cleanup: drop pg_upmap_items that reference missing
    pools/osds or no longer apply (OSDMap::clean_pg_upmaps role)."""
    removed = 0
    for pgid in list(m.pg_upmap_items):
        pool_id, ps = pgid
        pool = m.pools.get(pool_id)
        bad = pool is None or ps >= pool.pg_num
        if not bad:
            items = [(f, t) for f, t in m.pg_upmap_items[pgid]
                     if m.exists(f) and m.exists(t)]
            if items != m.pg_upmap_items[pgid]:
                bad = not items
                if items:
                    m.pg_upmap_items[pgid] = items
        if bad:
            del m.pg_upmap_items[pgid]
            removed += 1
    return removed


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="osdmaptool")
    p.add_argument("mapfn", help="osdmap JSON file")
    p.add_argument("--createsimple", type=int, default=0)
    p.add_argument("--pg-bits", type=int, default=6)
    p.add_argument("--clobber", action="store_true")
    p.add_argument("--test-map-pgs", action="store_true")
    p.add_argument("--test-map-pgs-dump", action="store_true",
                   help="print every pg's up set + primary "
                        "(osdmaptool.cc:42)")
    p.add_argument("--pool", type=int, default=None)
    p.add_argument("--scalar", action="store_true",
                   help="scalar pipeline instead of batched")
    p.add_argument("--upmap", help="output file for balancer commands")
    p.add_argument("--upmap-deviation", type=int, default=5)
    p.add_argument("--upmap-max", type=int, default=10)
    p.add_argument("--upmap-pool", type=int, action="append",
                   default=[])
    p.add_argument("--upmap-cleanup", action="store_true")
    p.add_argument("--export-crush")
    p.add_argument("--import-crush")
    p.add_argument("--mark-up-in", action="store_true")
    args = p.parse_args(argv)

    if args.createsimple:
        m = create_simple(args.createsimple, args.pg_bits)
        with open(args.mapfn, "w") as f:
            json.dump(m.to_dict(), f)
        print(f"osdmaptool: writing epoch {m.epoch} to {args.mapfn}")
        return 0

    with open(args.mapfn) as f:
        m = OSDMap.from_dict(json.load(f))
    dirty = False

    if args.mark_up_in:
        for d in range(m.max_osd):
            m.add_osd(d)
        dirty = True

    if args.import_crush:
        from .crushtool import load_map

        m.crush = load_map(args.import_crush).crush
        dirty = True

    if args.export_crush:
        from ..crush.wrapper import CrushWrapper as CW

        with open(args.export_crush, "w") as f:
            json.dump(CW(m.crush).to_dict(), f)

    if args.upmap_cleanup:
        removed = upmap_cleanup(m)
        print(f"upmap-cleanup: removed {removed} entries")
        dirty = dirty or removed > 0

    if args.upmap:
        only = set(args.upmap_pool) or None
        before = dict(m.pg_upmap_items)
        changed = calc_pg_upmaps(
            m, max_deviation=args.upmap_deviation,
            max_iterations=args.upmap_max, only_pools=only,
            use_batched=not args.scalar)
        with open(args.upmap, "w") as f:
            for pgid in sorted(set(before) | set(m.pg_upmap_items)):
                now = m.pg_upmap_items.get(pgid)
                if now == before.get(pgid):
                    continue
                tag = f"{pgid[0]}.{pgid[1]:x}"
                if now is None:
                    f.write(f"ceph osd rm-pg-upmap-items {tag}\n")
                else:
                    pairs = " ".join(f"{a} {b}" for a, b in now)
                    f.write(f"ceph osd pg-upmap-items {tag} {pairs}\n")
        print(f"upmap: {changed} changes")
        dirty = dirty or changed > 0

    if args.test_map_pgs_dump:
        for pool_id, pool in sorted(m.pools.items()):
            if args.pool is not None and pool_id != args.pool:
                continue
            for ps in range(pool.pg_num):
                up, up_p, acting, act_p = m.pg_to_up_acting_osds(
                    pool_id, ps)
                print(f"{pool_id}.{ps:x}\t{list(up)}\t{up_p}\t"
                      f"{list(acting)}\t{act_p}")

    if args.test_map_pgs:
        test_map_pgs(m, args.pool, use_batched=not args.scalar)

    if dirty:
        if not args.clobber and (args.upmap or args.upmap_cleanup
                                 or args.mark_up_in
                                 or args.import_crush):
            # the reference only writes with --clobber or -o; keep the
            # upmap flow read-only on the map file unless asked
            pass
        else:
            with open(args.mapfn, "w") as f:
                json.dump(m.to_dict(), f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
