"""Striper — scale one logical object across many RADOS objects.

The role of src/libradosstriper (+ RBD stripe_unit/stripe_count, CephFS
file layouts): SURVEY §5 names striping as the reference's "one logical
object beyond one node" axis.  A striped object is cut into
``stripe_unit`` slices laid out round-robin over ``stripe_count``
backing objects per object set (the standard RADOS striping layout:
stripeno = off / unit; objectno = (stripeno / count) * count +
stripeno % count).  Size travels in a header sub-object, as
libradosstriper keeps it in an xattr of the first piece.

Each backing object then takes the normal pool data path (replicated
copies or EC shards) — striping composes with, not replaces, the EC
layer.
"""

from __future__ import annotations

from typing import List, Tuple

from .client import Client

HEADER_SUFFIX = ".striper-header"


def _piece_name(oid: str, objectno: int) -> str:
    return f"{oid}.{objectno:016x}"


class Striper:
    def __init__(self, client: Client, stripe_unit: int = 4096,
                 stripe_count: int = 4, object_size: int = 1 << 22):
        if stripe_unit <= 0 or stripe_count <= 0:
            raise ValueError("stripe_unit/stripe_count must be > 0")
        if object_size % stripe_unit:
            raise ValueError("object_size must be a multiple of "
                             "stripe_unit")
        self.client = client
        self.unit = stripe_unit
        self.count = stripe_count
        self.object_size = object_size

    # -- layout math ---------------------------------------------------
    def extent_map(self, offset: int, length: int
                   ) -> List[Tuple[int, int, int, int]]:
        """logical [offset, offset+length) ->
        [(objectno, obj_offset, logical_offset, run_length)].

        The standard RADOS layout (file_layout_t semantics): stripes
        rotate over the ``stripe_count`` objects of the current object
        SET; the set advances only once its objects are full
        (``object_size`` bytes each)."""
        spo = self.object_size // self.unit  # stripes per object
        per_set = spo * self.count           # stripes per object set
        out = []
        end = offset + length
        while offset < end:
            stripeno = offset // self.unit
            within = offset % self.unit
            setno = stripeno // per_set
            in_set = stripeno % per_set
            stripepos = in_set % self.count
            block = in_set // self.count     # unit-block inside object
            objectno = setno * self.count + stripepos
            obj_off = block * self.unit + within
            run = min(self.unit - within, end - offset)
            out.append((objectno, obj_off, offset, run))
            offset += run
        return out

    # -- data path -----------------------------------------------------
    def write(self, pool_id: int, oid: str, data: bytes) -> None:
        pieces: dict = {}
        for objectno, obj_off, log_off, run in self.extent_map(
                0, len(data)):
            buf = pieces.setdefault(objectno, bytearray())
            if len(buf) < obj_off + run:
                buf.extend(b"\0" * (obj_off + run - len(buf)))
            buf[obj_off:obj_off + run] = data[log_off:log_off + run]
        for objectno, buf in sorted(pieces.items()):
            self.client.put(pool_id, _piece_name(oid, objectno),
                            bytes(buf))
        header = (f"{len(data)}:{self.unit}:{self.count}:"
                  f"{self.object_size}").encode()
        self.client.put(pool_id, oid + HEADER_SUFFIX, header)

    def read(self, pool_id: int, oid: str, offset: int = 0,
             length: int = -1) -> bytes:
        size, unit, count, osize = self.stat(pool_id, oid)
        if (unit, count, osize) != (self.unit, self.count,
                                    self.object_size):
            raise ValueError(
                f"layout mismatch: object striped "
                f"{unit}/{count}/{osize}, reader configured "
                f"{self.unit}/{self.count}/{self.object_size}")
        if length < 0:
            length = size - offset
        length = max(0, min(length, size - offset))
        if not length:
            return b""
        out = bytearray(length)
        cache: dict = {}
        for objectno, obj_off, log_off, run in self.extent_map(
                offset, length):
            piece = cache.get(objectno)
            if piece is None:
                piece = self.client.get(
                    pool_id, _piece_name(oid, objectno))
                cache[objectno] = piece
            chunk = piece[obj_off:obj_off + run]
            out[log_off - offset:log_off - offset + len(chunk)] = chunk
        return bytes(out)

    def stat(self, pool_id: int, oid: str
             ) -> Tuple[int, int, int, int]:
        """(size, stripe_unit, stripe_count, object_size)."""
        header = self.client.get(pool_id, oid + HEADER_SUFFIX)
        size, unit, count, osize = header.decode().split(":")
        return int(size), int(unit), int(count), int(osize)
