"""MapFollower — the MonClient role: follow OSDMap epochs.

Shared by every map subscriber (OSD services, clients): install full
maps, apply incremental deltas COPY-AND-SWAP (readers holding the old
map object keep a consistent snapshot — placements are never computed
from a half-applied epoch), and catch up across gaps by walking the
monitor's retained incrementals (``get_inc``), falling back to one
full ``get_map`` only when an epoch has aged out — the O(change)
distribution contract.

Users provide ``_lock``, ``map``, ``epoch``, ``osd_addrs``,
``ec_profiles``, ``msgr``, ``mon_addr`` and may override
``_post_map_install()`` (called after every successful install, not
under the lock).
"""

from __future__ import annotations

from typing import Dict

from ..osdmap.incremental import Incremental, apply_incremental
from ..osdmap.osdmap import OSDMap


class MapFollower:
    def _set_extras(self, msg: Dict) -> None:
        """osd address table + EC profiles travel beside the map
        (call under self._lock)."""
        if "osd_addrs" in msg:
            self.osd_addrs = {int(k): tuple(v)
                              for k, v in msg["osd_addrs"].items()}
        if "ec_profiles" in msg:
            self.ec_profiles = msg["ec_profiles"]

    def _install_map(self, payload: Dict) -> None:
        with self._lock:
            if payload["epoch"] <= self.epoch:
                return
            self.map = OSDMap.from_dict(payload["map"])
            self.epoch = payload["epoch"]
            self._set_extras(payload)
        self._post_map_install()

    def _apply_one_inc(self, inc: Incremental) -> bool:
        """Copy-apply-swap under the lock; False when not contiguous."""
        with self._lock:
            if self.map is None or inc.epoch != self.epoch + 1:
                return False
            new = OSDMap.from_dict(self.map.to_dict())
            apply_incremental(new, inc)
            self.map = new
            self.epoch = inc.epoch
            return True

    def _h_map_inc(self, msg: Dict) -> None:
        inc = Incremental.from_dict(msg["inc"])
        with self._lock:
            if inc.epoch <= self.epoch:
                return None
        if self._apply_one_inc(inc):
            with self._lock:
                self._set_extras(msg)
            self._post_map_install()
            return None
        self._catch_up(inc.epoch, msg)
        return None

    def _catch_up(self, target: int, msg: Dict) -> None:
        """Walk missing epochs via get_inc; full fetch on aged-out
        history.  Best-effort: the monitor re-pushes on every commit."""
        try:
            while self.epoch < target and self.map is not None:
                got = self.msgr.call(
                    self.mon_addr,
                    {"type": "get_inc", "epoch": self.epoch + 1},
                    timeout=5)
                inc_d = got.get("inc")
                if inc_d is None or not self._apply_one_inc(
                        Incremental.from_dict(inc_d)):
                    self._install_map(self.msgr.call(
                        self.mon_addr, {"type": "get_map"},
                        timeout=5))
                    return
            with self._lock:
                self._set_extras(msg)
            self._post_map_install()
        except (TimeoutError, OSError):
            pass  # the next push catches us up

    def _post_map_install(self) -> None:  # pragma: no cover - hook
        pass
