"""MapFollower — the MonClient role: follow OSDMap epochs.

Shared by every map subscriber (OSD services, clients): install full
maps, apply incremental deltas COPY-AND-SWAP (readers holding the old
map object keep a consistent snapshot — placements are never computed
from a half-applied epoch), and catch up across gaps by walking the
monitor's retained incrementals (``get_inc``), falling back to one
full ``get_map`` only when an epoch has aged out — the O(change)
distribution contract.

Users provide ``_lock``, ``map``, ``epoch``, ``osd_addrs``,
``ec_profiles``, ``msgr``, ``mon_addr`` and may override
``_post_map_install()`` (called after every successful install, not
under the lock).
"""

from __future__ import annotations

import time
from typing import Dict

from ..analysis.asyncheck import nonblocking
from ..common.backoff import Backoff
from ..common.perf_counters import collection
from ..osdmap.incremental import Incremental, apply_incremental
from ..osdmap.osdmap import OSDMap

# process-global scalar-mapping metrics: every daemon's data path asks
# pg_up_acting per op, so lookup volume, cache efficacy, and walk
# latency live here (served via each daemon's merged `perf dump`)
_pc = collection().create("crush.scalar")
_pc.add_u64_counter("pg_lookups")
_pc.add_u64_counter("cache_hits")
_pc.add_time("map_time")
_pc.add_histogram("map_lat")


class MonError(RuntimeError):
    """Transient quorum condition (no leader yet / pre-genesis) — the
    caller should retry; never used for map-application defects."""


def failover_call(msgr, addrs, msg: Dict, timeout: float = 5.0,
                  tries: int = 3):
    """Call a monitor, rotating across the quorum: connection errors
    move to the next member; 'no quorum' / pre-genesis replies back
    off briefly for the election in flight.  Returns (reply, addr) so
    callers can remember the member that answered.  Shared by daemon
    followers (mon_call) and the MiniCluster harness (mon_command)."""
    last: Exception = MonError("no monitors configured")
    n = max(1, len(addrs))
    # jittered pacing for in-flight elections: N waiting daemons must
    # not re-probe the quorum in lockstep (common/backoff.py)
    bo = Backoff(base=0.1, cap=0.5)
    for i in range(max(1, tries) * n):
        addr = addrs[i % n]
        try:
            rep = msgr.call(addr, msg, timeout=timeout)
        except (OSError, TimeoutError) as e:
            last = e
            continue
        err = rep.get("error") if isinstance(rep, dict) else None
        if err in ("no quorum", "no committed map yet"):
            last = MonError(err)
            bo.sleep()
            continue
        return rep, tuple(addr)
    raise last


class MapFollower:
    # -- monitor targets (quorum-aware MonClient) ----------------------
    def _init_mons(self, mon_addr) -> None:
        """Accept one monitor address or a rank-ordered list of them;
        ``self.mon_addr`` is the currently preferred target and
        rotates on failure."""
        if mon_addr and isinstance(mon_addr[0], (list, tuple)):
            self.mon_addrs = [tuple(a) for a in mon_addr]
        else:
            self.mon_addrs = [tuple(mon_addr)]
        self.mon_addr = self.mon_addrs[0]

    def mon_call(self, msg: Dict, timeout: float = 5.0,
                 tries: int = 3) -> Dict:
        i = self.mon_addrs.index(self.mon_addr)
        order = self.mon_addrs[i:] + self.mon_addrs[:i]
        rep, used = failover_call(self.msgr, order, msg, timeout,
                                  tries)
        self.mon_addr = used
        return rep

    def mon_send(self, msg: Dict) -> None:
        """Fire-and-forget to every quorum member: peons forward or
        drop; send() swallows dead-peer errors, so pinning one target
        could silently blackhole (e.g. a down OSD's re-boot)."""
        for addr in self.mon_addrs:
            self.msgr.send(addr, msg)

    def subscribe_all(self, name: str, timeout: float = 15.0) -> Dict:
        """Subscribe to EVERY quorum member (each pushes committed
        epochs, so losing one monitor loses no updates) and return the
        newest committed payload; retries through elections."""
        bo = Backoff(base=0.1, cap=0.5, deadline=timeout)
        while True:
            payload = None
            for addr in self.mon_addrs:
                try:
                    rep = self.msgr.call(
                        addr, {"type": "subscribe", "name": name,
                               "addr": list(self.msgr.addr)},
                        timeout=3.0)
                except (OSError, TimeoutError):
                    continue
                if isinstance(rep, dict) and "epoch" in rep:
                    if payload is None or rep["epoch"] > \
                            payload["epoch"]:
                        payload = rep
            if payload is not None:
                return payload
            if not bo.sleep():
                raise TimeoutError(f"{name}: no committed map from "
                                   f"any monitor")

    def _set_extras(self, msg: Dict) -> None:
        """osd address table + EC profiles travel beside the map
        (call under self._lock)."""
        if "osd_addrs" in msg:
            self.osd_addrs = {int(k): tuple(v)
                              for k, v in msg["osd_addrs"].items()}
        if "ec_profiles" in msg:
            self.ec_profiles = msg["ec_profiles"]

    def pg_up_acting(self, pool_id: int, ps: int):
        """Cached pg_to_up_acting_osds: the scalar CRUSH walk costs
        ~0.4 ms and the data path asks per op; maps here are
        copy-apply-swap (never mutated in place), so caching per
        installed map object is sound.  Cleared on every swap."""
        key = (pool_id, ps)
        _pc.inc("pg_lookups")
        with self._lock:
            cache = getattr(self, "_pg_cache", None)
            if cache is None:
                cache = self._pg_cache = {}
            hit = cache.get(key)
            if hit is not None:
                _pc.inc("cache_hits")
                return hit
            m = self.map
        t0 = time.monotonic()
        val = m.pg_to_up_acting_osds(pool_id, ps)
        dt = time.monotonic() - t0
        _pc.tinc("map_time", dt)
        _pc.hist_add("map_lat", dt)
        with self._lock:
            if self.map is m:
                if len(cache) > 65536:
                    cache.clear()
                cache[key] = val
        return val

    def _install_map(self, payload: Dict) -> None:
        with self._lock:
            if payload["epoch"] <= self.epoch:
                return
            if "map_bin" in payload:
                # the wire form: versioned binary encode
                # (OSDMap::encode role, ~15x smaller than the JSON)
                from ..osdmap.bincode_maps import osdmap_from_bytes

                self.map = osdmap_from_bytes(payload["map_bin"])  # block-ok: pure in-memory bincode decode — the per-type struct-reader table defeats static resolution, but no reader touches a socket, file, or lock
            else:
                self.map = OSDMap.from_dict(payload["map"])
            self.epoch = payload["epoch"]
            self._pg_cache = {}
            self._set_extras(payload)
        self._post_map_install()

    def _apply_one_inc(self, inc: Incremental) -> bool:
        """Copy-apply-swap under the lock; False when not contiguous."""
        with self._lock:
            if self.map is None or inc.epoch != self.epoch + 1:
                return False
            new = OSDMap.from_dict(self.map.to_dict())
            apply_incremental(new, inc)
            self.map = new
            self.epoch = inc.epoch
            self._pg_cache = {}
            return True

    @nonblocking
    def _h_map_inc(self, msg: Dict) -> None:
        inc = Incremental.from_dict(msg["inc"])
        with self._lock:
            if inc.epoch <= self.epoch:
                return None
        if self._apply_one_inc(inc):
            with self._lock:
                self._set_extras(msg)
            self._post_map_install()
            return None
        self._catch_up(inc.epoch, msg)  # block-ok: gap catch-up is deadline-bounded (5s per mon_call, bounded tries) and best-effort — on timeout the monitor's next commit push retries; deferring it would leave the follower on a stale epoch indefinitely
        return None

    def _catch_up(self, target: int, msg: Dict) -> None:
        """Walk missing epochs via get_inc; full fetch on aged-out
        history.  Best-effort: the monitor re-pushes on every commit."""
        try:
            while self.epoch < target and self.map is not None:
                got = self.mon_call(
                    {"type": "get_inc", "epoch": self.epoch + 1},
                    timeout=5)
                inc_d = got.get("inc")
                if inc_d is None or not self._apply_one_inc(
                        Incremental.from_dict(inc_d)):
                    self._install_map(self.mon_call(
                        {"type": "get_map"}, timeout=5))
                    return
            with self._lock:
                self._set_extras(msg)
            self._post_map_install()
        except (TimeoutError, OSError, MonError):
            pass  # the next push catches us up

    def _post_map_install(self) -> None:  # pragma: no cover - hook
        pass
