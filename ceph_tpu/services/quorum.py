"""Monitor quorum — rank election + replicated epoch log.

The role of src/mon/ElectionLogic.cc + src/mon/Paxos.cc, bounded to the
shape this framework needs: N monitors (typically 3) elect the
lowest-ranked reachable monitor as leader, and every epoch commit is
replicated to a majority before it becomes visible anywhere.

Election (ElectionLogic.cc's lowest-rank-wins, epoch-numbered):
- a candidate bumps the election epoch and proposes itself to every
  peer; peers ack only proposers with a LOWER rank than their own, so
  the lowest reachable rank collects a majority.  A monitor that sees a
  proposal from a higher rank starts its own candidacy; rank-staggered
  retry deadlines break ties.
- the propose round IS the Paxos collect/last phase (Paxos.cc:330-560
  in single-decree form): every ack carries the peer's last_committed
  AND its staged-but-uncommitted entry, and victory requires a majority
  of acks — so the promise majority intersects every accept majority
  and any entry that ever reached a majority is seen and re-proposed.
  Epochs never fork.  (Round-4 advisor finding: the old design gathered
  uncommitted entries in a best-effort second round that could miss the
  one holder; piggybacking on the propose acks closes that.)
- leadership is kept alive with leases (Paxos.cc:1038 lease_*): the
  leader sends lease CALLS; peons ack.  The leader's own authority is
  extended only while a majority of peons ack within the window — an
  isolated leader demotes itself to ELECTING instead of serving stale
  reads forever (round-4 advisor finding; matches the reference where
  the leader's lease rides peon lease_ack).

Durability (MonitorDBStore role, Paxos.cc persistent accepted_pn /
uncommitted value): the election epoch (promise) and any staged entry
are persisted through ``mon.store_quorum_state`` BEFORE the ack leaves
the monitor, so leader-crash + staged-peon-restart cannot lose a
majority-staged entry and a restarted peon cannot un-promise and ack a
deposed leader's accept.

Log replication (Paxos.cc begin/accept/commit, single-decree):
- the leader sends ``mon_accept`` {epoch, version, entry} to peers; a
  peer STAGES the entry (never applies it) and acks if the epoch is
  current and the version is next-in-log.
- on majority ack the leader applies locally and broadcasts
  ``mon_commit``; peers then apply their staged entry.  A peer that
  misses the commit catches up from the lease's last_committed via
  ``mon_fetch``.
- a leader that cannot reach a majority rolls its in-memory state back
  to the last committed entry and abdicates — a partitioned minority
  can commit nothing.

The entry payload is the monitor's full epoch record (map json + inc +
addr/profile extras), so a peon's store is always a prefix of the
leader's and any monitor can serve reads and subscriptions.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..analysis import faults
from ..analysis.lockdep import make_rlock

Addr = Tuple[str, int]

PROBING = "probing"
ELECTING = "electing"
LEADER = "leader"
PEON = "peon"


class Quorum:
    def __init__(self, mon, rank: int, addrs: List[Addr],
                 lease: float = 1.0, election_timeout: float = 1.0,
                 call_timeout: float = 1.5):
        self.mon = mon
        self.rank = rank
        self.addrs = [tuple(a) for a in addrs]
        self.n = len(addrs)
        self.majority = self.n // 2 + 1
        self.lease = lease
        self.election_timeout = election_timeout
        self.call_timeout = call_timeout

        self.state = PROBING
        self.election_epoch = 0
        self.leader_rank: Optional[int] = None
        self.lease_expiry = 0.0
        self._next_election = 0.0
        # accepted-but-uncommitted entry: {"v": int, "e": int,
        # "entry": {...}} — never applied until mon_commit
        self.uncommitted: Optional[Dict] = None
        # one promise per election epoch (Paxos: a node may ack only
        # ONE proposer per ballot, or two same-epoch candidates can
        # both assemble majorities and commit different entries at the
        # same version): rank we acked at election_epoch, or None
        self.promised_rank: Optional[int] = None
        self._lease_fetching = False
        self._lock = make_rlock("quorum::state")
        self._running = False
        self._thread: Optional[threading.Thread] = None

        # ordered=True: quorum messages from one peer must execute in
        # arrival order — a mon_accept(v+1) racing ahead of its
        # predecessor's mon_commit(v) on another dispatch worker is
        # nacked as non-contiguous, and a majority of such races makes
        # the leader spuriously abdicate (round-5 advisor medium #1)
        # control=True as well: election and lease traffic IS failure
        # detection — it must never wait for an op-pool slot behind a
        # burst of client commands (the serial lane drains on the
        # messenger's dedicated control pool)
        m = mon.msgr
        m.register("mon_probe", self._gate(self._h_probe),
                   ordered=True, control=True)
        m.register("mon_propose", self._gate(self._h_propose),
                   ordered=True, control=True)
        m.register("mon_victory", self._gate(self._h_victory),
                   ordered=True, control=True)
        m.register("mon_lease", self._gate(self._h_lease),
                   ordered=True, control=True)
        m.register("mon_fetch", self._gate(self._h_fetch),
                   ordered=True, control=True)
        m.register("mon_accept", self._gate(self._h_accept),
                   ordered=True, control=True)
        m.register("mon_commit", self._gate(self._h_commit),
                   ordered=True, control=True)

        # restore the promise + staged entry a crash may have left
        # (Paxos.cc reads accepted_pn / uncommitted from the store).
        # In __init__, NOT start(): handlers are registered above, and
        # an early mon_propose arriving before a later restore would
        # persist fresh state over the crash-saved entry.
        loader = getattr(self.mon, "load_quorum_state", None)
        if loader is not None:
            st = loader() or {}
            self.election_epoch = max(self.election_epoch,
                                      int(st.get("election_epoch", 0)))
            if st.get("promised_rank") is not None:
                self.promised_rank = int(st["promised_rank"])
            if st.get("uncommitted"):
                self.uncommitted = st["uncommitted"]

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._tick_loop,
                                        daemon=True,
                                        name=f"mon{self.rank}-quorum")
        self._thread.start()

    def shutdown(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=2)

    # -- state queries ---------------------------------------------------
    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def leader_addr(self) -> Optional[Addr]:
        with self._lock:
            if self.leader_rank is None:
                return None
            return self.addrs[self.leader_rank]

    def _others(self):
        return [(r, a) for r, a in enumerate(self.addrs)
                if r != self.rank]

    def _persist_locked(self) -> None:
        """Durably record (election_epoch, uncommitted) — called with
        the lock held, BEFORE the ack that makes the state externally
        visible.  No-op for storeless monitors (tests)."""
        saver = getattr(self.mon, "store_quorum_state", None)
        if saver is not None:
            saver({"election_epoch": self.election_epoch,
                   "promised_rank": self.promised_rank,
                   "uncommitted": self.uncommitted})

    # -- the ticker -------------------------------------------------------
    def _tick_loop(self) -> None:
        # rank-staggered first election so rank 0 usually wins round 1
        time.sleep(0.02 * self.rank)
        while self._running:
            try:
                self._tick()
            except Exception as e:  # a tick must never kill the thread
                self.mon.log.derr(f"quorum tick: {e!r}")
            time.sleep(self.lease / 3)  # fault-ok: election tick
            # cadence, not retry pacing against a failing peer

    def _tick(self) -> None:
        now = time.monotonic()
        with self._lock:
            state = self.state
            lease_out = now > self.lease_expiry
            due = now >= self._next_election
            # a live monitor that OUTRANKS its leader stands for
            # election (the reference re-elects when a lower rank
            # joins, ElectionLogic's lowest-rank-wins is a standing
            # invariant, not a startup accident)
            outranked = (state == PEON
                         and self.leader_rank is not None
                         and self.leader_rank > self.rank)
        if state == LEADER and lease_out:
            # a majority of peons stopped acking leases: this leader is
            # partitioned/isolated and must stop serving leader-only
            # duties instead of running on a stale map forever
            self.mon.log.dout(1, f"mon.{self.rank}: leader lease "
                                 f"lapsed (no peon-ack majority), "
                                 f"demoting")
            self.abdicate()
        elif state == LEADER:
            self._send_leases()
        elif state == PEON and lease_out:
            self.mon.log.dout(1, f"mon.{self.rank}: lease expired, "
                                 f"calling election")
            self._start_election()
        elif outranked and due:
            self._start_election()
        elif state == PROBING and due:
            # discover an existing quorum before forcing a round: a
            # RESTARTED member's immediate candidacy used to depose a
            # healthy leader (its higher-epoch propose invalidates
            # leadership on every peer) and seesaw elections for
            # seconds — the thrash-test quorum outages.  The
            # reference's probing phase (Monitor.cc handle_probe)
            # joins an established quorum without an election.
            if not self._probe():
                self._start_election()
        elif state == ELECTING and due:
            self._start_election()

    def _gate(self, handler):
        """Fault-injection door on every inbound mon-to-mon frame:
        when ``mon.isolate_rank`` fires for this rank the frame is
        swallowed — no reply, no ack (InjectedKill semantics in the
        messenger) — so peers see a partitioned monitor, not an
        error-returning one."""

        def h(msg: Dict):
            if faults._ACTIVE and faults.fires(
                    "mon.isolate_rank", f"mon.{self.rank}"):
                raise faults.InjectedKill(
                    f"mon.{self.rank} isolated")
            return handler(msg)

        return h

    # -- probe (rejoin without deposing) ----------------------------------
    def _h_probe(self, _msg: Dict) -> Dict:
        """Report current leadership (None unless the lease is live)
        so a (re)starting monitor can rejoin as a peon."""
        with self._lock:
            leader = self.leader_rank
            if self.state not in (LEADER, PEON) or \
                    time.monotonic() > self.lease_expiry:
                leader = None
            return {"leader": leader, "epoch": self.election_epoch,
                    "last_committed": self.mon.last_committed()}

    def _probe(self) -> bool:
        """Ask peers for the standing quorum; adopt it when found.
        Returns False when no live leader is known anywhere — the
        caller elects.  A provisional lease window is granted; if the
        reported leader is actually gone, its non-renewal leads to a
        normal election one window later."""
        for r, addr in self._others():
            try:
                rep = self.mon.msgr.call(
                    addr, {"type": "mon_probe"},
                    timeout=min(self.call_timeout, 0.5))
            except (OSError, TimeoutError):
                continue
            leader = rep.get("leader")
            e = int(rep.get("epoch", 0))
            with self._lock:
                if leader is None or e < self.election_epoch:
                    continue
                if int(leader) == self.rank:
                    # a peer still believes the PRE-restart us leads;
                    # leadership without a fresh collect majority is
                    # unsafe — run the election instead
                    continue
                if self.state != PROBING:
                    return True  # something else settled us meanwhile
                if e > self.election_epoch:
                    self.promised_rank = None  # new epoch, new promise
                self.election_epoch = e
                self.leader_rank = int(leader)
                self.state = PEON
                self.lease_expiry = time.monotonic() + self.lease * 3
                self._persist_locked()
            self.mon.log.dout(1, f"mon.{self.rank}: probe found "
                                 f"leader mon.{leader} at epoch {e}; "
                                 f"joining as peon")
            return True
        return False

    # -- election ---------------------------------------------------------
    def _start_election(self) -> None:
        with self._lock:
            self.election_epoch += 1
            e = self.election_epoch
            self.state = ELECTING
            self.leader_rank = None
            # standing is a promise to ourselves at this epoch: we
            # must not also ack another candidate at the same epoch
            self.promised_rank = self.rank
            # stagger retries by rank so the lowest reachable rank
            # converges first instead of livelocking
            self._next_election = time.monotonic() + \
                self.election_timeout * (1 + 0.5 * self.rank
                                         + 0.2 * random.random())
        acks = 1
        infos = [{"rank": self.rank,
                  "last_committed": self.mon.last_committed()}]
        uncommitted = []
        peer_epoch = 0
        with self._lock:
            self._persist_locked()  # durable promise for our own round
            if self.uncommitted is not None:
                uncommitted.append(self.uncommitted)
        for r, addr in self._others():
            try:
                rep = self.mon.msgr.call(
                    addr, {"type": "mon_propose", "e": e,
                           "rank": self.rank},
                    timeout=self.call_timeout)
            except (OSError, TimeoutError):
                continue
            peer_epoch = max(peer_epoch, int(rep.get("epoch", 0)))
            if rep.get("ack"):
                acks += 1
                infos.append({"rank": r,
                              "last_committed":
                                  rep.get("last_committed", 0)})
                if rep.get("uncommitted"):
                    uncommitted.append(rep["uncommitted"])
        with self._lock:
            if self.election_epoch != e or self.state != ELECTING:
                return  # a newer round superseded this one
            if acks < self.majority:
                if peer_epoch >= e:
                    # reachable peers nacked at a round at least as
                    # new as ours: an asymmetrically cut candidate
                    # (its proposes arrive, the replies home but the
                    # leader's leases never do) would otherwise
                    # re-propose forever, deposing the live leader on
                    # every retry.  Adopt the standing epoch and drop
                    # to PROBING — the probe rejoins the standing
                    # quorum as a peon WITHOUT another epoch bump.
                    if peer_epoch > e:
                        self.promised_rank = None
                    self.election_epoch = peer_epoch
                    self.state = PROBING
                    self._persist_locked()
                return  # retry (or probe) at the staggered deadline
        # the ack majority IS the collect majority: every ack carried
        # last_committed + any staged entry, so the intersection
        # argument holds without a second best-effort round
        self._win(e, infos, uncommitted)

    def _h_propose(self, msg: Dict) -> Dict:
        e, r = int(msg["e"]), int(msg["rank"])
        with self._lock:
            if e < self.election_epoch:
                return {"ack": False, "epoch": self.election_epoch}
            if e > self.election_epoch:
                self.election_epoch = e
                self.promised_rank = None  # new epoch, new promise
                # a new round invalidates current leadership
                if self.state in (LEADER, PEON):
                    self.state = ELECTING
                    self.leader_rank = None
            # one promise per epoch: two same-epoch candidates must
            # never both collect majorities (they would each replicate
            # a different entry at the same version)
            ack = r < self.rank and \
                self.promised_rank in (None, r)
            if ack:
                self.promised_rank = r
                # the promise must be durable before it leaves: a
                # restarted peon that forgot this epoch could ack a
                # deposed leader's accept at the same version
                self._persist_locked()
            else:
                # I outrank the proposer and I'm alive: stand myself
                self._next_election = time.monotonic()
            return {"ack": ack, "epoch": self.election_epoch,
                    "last_committed": self.mon.last_committed(),
                    "uncommitted": self.uncommitted}

    def _win(self, e: int, infos: List[Dict],
             uncommitted: List[Dict]) -> None:
        """Sync to the newest majority state, then declare victory.

        ``infos`` (rank, last_committed) and ``uncommitted`` come from
        the MAJORITY of propose acks — the durable collect phase — so
        the newest committed version and every possibly-majority-staged
        entry are in hand before leadership is declared."""
        best_lc = self.mon.last_committed()
        best_peer = None
        for row in infos:
            if row["rank"] != self.rank and \
                    int(row["last_committed"]) > best_lc:
                best_lc = int(row["last_committed"])
                best_peer = self.addrs[row["rank"]]
        if best_peer is not None:
            self._fetch_from(best_peer, best_lc)

        with self._lock:
            if self.election_epoch != e:
                return
            self.state = LEADER
            self.leader_rank = self.rank
            self.lease_expiry = time.monotonic() + self.lease * 3
        for r, addr in self._others():
            try:
                self.mon.msgr.call(addr,
                                   {"type": "mon_victory", "e": e,
                                    "leader": self.rank},
                                   timeout=self.call_timeout)
            except (OSError, TimeoutError):
                pass
        self.mon.log.dout(1, f"mon.{self.rank}: leader at election "
                             f"epoch {e}, last_committed {best_lc}")
        self.mon.on_leader(
            self._pick_uncommitted(uncommitted, best_lc))

    def _pick_uncommitted(self, entries: List[Dict],
                          lc: int) -> Optional[Dict]:
        """The next-in-log staged entry with the highest election
        epoch, if any (Paxos: re-propose the highest accepted value)."""
        best = None
        for u in entries:
            if int(u["v"]) != lc + 1:
                continue
            if best is None or int(u["e"]) > int(best["e"]):
                best = u
        return best

    def _fetch_from(self, addr: Addr, to_v: int) -> None:
        """Pull committed entries (last_committed, to_v] and apply."""
        frm = self.mon.last_committed()
        try:
            rep = self.mon.msgr.call(
                addr, {"type": "mon_fetch", "from_v": frm,
                       "to_v": to_v},
                timeout=self.call_timeout * 2)
        except (OSError, TimeoutError):
            return
        for row in rep.get("entries", []):
            if int(row["v"]) == self.mon.last_committed() + 1:
                self.mon.apply_committed(int(row["v"]), row["entry"])

    def _h_victory(self, msg: Dict) -> Dict:
        e, leader = int(msg["e"]), int(msg["leader"])
        with self._lock:
            if e < self.election_epoch:
                return {"ok": False, "epoch": self.election_epoch}
            if e > self.election_epoch:
                self.promised_rank = None
            self.election_epoch = e
            self.state = PEON if leader != self.rank else LEADER
            self.leader_rank = leader
            self.lease_expiry = time.monotonic() + self.lease * 3
            self._persist_locked()
        return {"ok": True,
                "last_committed": self.mon.last_committed()}

    # -- leases -----------------------------------------------------------
    def _send_leases(self) -> None:
        """Lease round as request/ack (Paxos.cc lease / lease_ack): the
        leader's OWN lease is extended only when a majority of members
        (self included) acked this round — an isolated leader stops
        being one at its next lease expiry instead of ticking itself
        alive forever."""
        with self._lock:
            e = self.election_epoch
            if self.state != LEADER:
                return
        msg = {"type": "mon_lease", "e": e, "leader": self.rank,
               "last_committed": self.mon.last_committed()}
        acks = 1
        timeout = min(self.call_timeout, max(self.lease / 2, 0.2))
        for r, addr in self._others():
            try:
                rep = self.mon.msgr.call(addr, msg, timeout=timeout)
            except (OSError, TimeoutError):
                continue
            if rep and rep.get("ok"):
                acks += 1
        if acks >= self.majority:
            with self._lock:
                if self.state == LEADER and self.election_epoch == e:
                    self.lease_expiry = time.monotonic() + \
                        self.lease * 3

    def _h_lease(self, msg: Dict) -> Dict:
        e, leader = int(msg["e"]), int(msg["leader"])
        with self._lock:
            if e < self.election_epoch:
                return {"ok": False, "epoch": self.election_epoch}
            if e > self.election_epoch or self.leader_rank != leader:
                if e > self.election_epoch:
                    self.promised_rank = None
                self.election_epoch = e
                self.leader_rank = leader
                self.state = PEON if leader != self.rank else LEADER
                self._persist_locked()
            self.lease_expiry = time.monotonic() + self.lease * 3
            leader_addr = self.addrs[leader]
        # catch up on committed entries we missed (dropped mon_commit) —
        # off-thread so a long fetch cannot stall the leader's lease
        # round into a false demotion.  Single-flight: leases arrive
        # every lease/3 and concurrent fetch threads would race
        # check-then-apply in apply_committed.
        lc = int(msg.get("last_committed", 0))
        if lc > self.mon.last_committed():
            with self._lock:
                spawn = not self._lease_fetching
                self._lease_fetching = True
            if spawn:
                threading.Thread(
                    target=self._lease_fetch, args=(leader_addr, lc),
                    daemon=True,
                    name=f"mon{self.rank}-leasefetch").start()
        return {"ok": True,
                "last_committed": self.mon.last_committed()}

    def _lease_fetch(self, addr: Addr, to_v: int) -> None:
        try:
            self._fetch_from(addr, to_v)
        finally:
            with self._lock:
                self._lease_fetching = False

    # -- replication ------------------------------------------------------
    def replicate(self, v: int, entry: Dict) -> bool:
        """Leader path: stage on a majority, then commit everywhere.
        Returns False (caller rolls back + abdicates) on lost quorum."""
        with self._lock:
            if self.state != LEADER:
                return False
            e = self.election_epoch
        acks = 1
        for r, addr in self._others():
            try:
                rep = self.mon.msgr.call(
                    addr, {"type": "mon_accept", "e": e, "v": v,
                           "entry": entry},
                    timeout=self.call_timeout)
            except (OSError, TimeoutError):
                continue
            if rep.get("ack"):
                acks += 1
        if acks < self.majority:
            return False
        with self._lock:
            if self.state != LEADER or self.election_epoch != e:
                return False
        for r, addr in self._others():
            self.mon.msgr.send(addr, {"type": "mon_commit", "e": e,
                                      "v": v})
        return True

    def _h_accept(self, msg: Dict) -> Dict:
        e, v = int(msg["e"]), int(msg["v"])
        with self._lock:
            if e < self.election_epoch or self.state == LEADER:
                return {"ack": False, "epoch": self.election_epoch}
            if v != self.mon.last_committed() + 1:
                return {"ack": False,
                        "last_committed": self.mon.last_committed()}
            self.uncommitted = {"v": v, "e": e, "entry": msg["entry"]}
            # the stage must hit the store before the ack: with it, a
            # leader crash + staged-peon restart still leaves the entry
            # recoverable by the next election's collect majority
            self._persist_locked()
            return {"ack": True}

    def _h_commit(self, msg: Dict) -> None:
        v = int(msg["v"])
        with self._lock:
            u = self.uncommitted
            if u is None or int(u["v"]) != v:
                return None
            self.uncommitted = None
            entry = u["entry"]
        if v == self.mon.last_committed() + 1:
            self.mon.apply_committed(v, entry)
        # durably clear the stage only AFTER the entry itself is
        # durable: clearing first opens a crash window where a
        # majority-staged entry vanishes from every surviving store.
        # The reverse order is safe — a stale staged copy of an
        # already-applied entry is filtered by the v == lc+1 pick.
        with self._lock:
            self._persist_locked()
        return None

    def _h_fetch(self, msg: Dict) -> Dict:
        frm, to = int(msg["from_v"]), int(msg["to_v"])
        return {"entries": self.mon.committed_entries(frm, to)}

    def abdicate(self) -> None:
        """Step down after a failed replication (lost majority)."""
        with self._lock:
            if self.state == LEADER:
                self.state = ELECTING
                self.leader_rank = None
                self._next_election = time.monotonic()
