"""Peer heartbeat plane — the OSD-side failure detector.

The role of ``OSD::heartbeat`` / ``OSD::maybe_update_heartbeat_peers``
(src/osd/OSD.cc:5487): every OSD pings the peers it shares PGs with
over the messenger control lane, keeps a per-peer last-ack clock plus
an EWMA of ping latency, and reports a peer past its (latency-adapted)
grace to the monitors as an ``osd_failure`` — the raw material of
``OSDMonitor::check_failure``'s reporter quorums.  The direct OSD→mon
beacon survives only as liveness-of-last-resort with the much longer
``mon_osd_report_timeout``, so a cut mon↔OSD link alone can no longer
kill a healthy OSD that its peers still hear.

Pings are fire-and-forget both ways (MOSDPing PING / PING_REPLY): the
sender stamps a monotonic clock, the receiver echoes it back in its
own fire-and-forget reply, and the sender's reply handler turns the
echo into an RTT sample.  Nothing in the ping path ever blocks on a
dead peer — that is the point of a failure detector.

The peer set is recomputed on every map-epoch install (the
``maybe_update_heartbeat_peers`` hook in ``_post_map_install``): for
each PG this OSD is in the up or acting set of, every other member is
a heartbeat peer.  The latency EWMA adapts the effective grace
(``grace + 4×ewma``) so a loaded-but-alive peer whose scheduling
latency grows is not storm-reported (the reference's
``mon_osd_adjust_heartbeat_grace`` idea, done sender-side).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

from ..analysis.asyncheck import nonblocking
from ..analysis.lockdep import make_lock
from ..analysis.racecheck import guarded_by

# EWMA smoothing for ping RTT and its weight in the effective grace:
# eff_grace = grace + GRACE_LAT_FACTOR * ewma.  On a loopback cluster
# ewma is sub-millisecond and the bound stays ~grace; under full-suite
# CPU load the inflated RTTs buy loaded peers headroom automatically.
EWMA_ALPHA = 0.3
GRACE_LAT_FACTOR = 4.0

# dump_osd_network / OSD_SLOW_PING_TIME window spans, seconds — the
# reference's 1/5/15-minute ping-time averages (osd_mon_heartbeat_
# stat_stale windows in OSD::heartbeat_check).  A ring of 4096
# timestamped samples covers 15 min at the default 0.5s interval with
# room for a few peers' worth of bursts.
WINDOWS = ((60.0, "1min"), (300.0, "5min"), (900.0, "15min"))
_RTT_RING = 4096


class _Peer:
    """Per-peer clock state (one heartbeat_info_t)."""

    __slots__ = ("last_ack", "ewma", "rtts")

    def __init__(self, now: float):
        # a fresh peer gets a full grace window from discovery — it
        # has never been asked, so it cannot already be overdue
        self.last_ack = now
        self.ewma = 0.0
        # (monotonic stamp, rtt_s) ring — the window averages behind
        # dump_osd_network and the OSD_SLOW_PING_TIME breach report
        self.rtts: collections.deque = collections.deque(
            maxlen=_RTT_RING)

    def window_avgs_ms(self, now: float) -> Dict[str, float]:
        """Mean RTT (ms) per lookback window over the sample ring."""
        sums = [0.0] * len(WINDOWS)
        ns = [0] * len(WINDOWS)
        for t, rtt in self.rtts:
            age = now - t
            for i, (span, _label) in enumerate(WINDOWS):
                if age <= span:
                    sums[i] += rtt
                    ns[i] += 1
        return {label: round(1e3 * sums[i] / ns[i], 3)
                if ns[i] else 0.0
                for i, (_span, label) in enumerate(WINDOWS)}


@guarded_by("osd::hb", "_peers")
class HeartbeatPlane:
    """One OSD's peer-ping plane.  Owned by OSDService: constructed
    with it (registers its two control-lane handlers), started after
    the first map install, peers recomputed per epoch."""

    def __init__(self, svc) -> None:
        self.svc = svc
        self.log = svc.log
        conf = svc.ctx.conf
        self.interval: float = conf["osd_heartbeat_interval"]
        self.grace: float = conf["osd_heartbeat_grace"]
        self.ping_threshold_ms: float = \
            conf["osd_heartbeat_ping_threshold_ms"]
        self._lock = make_lock("osd::hb")
        self._peers: Dict[int, _Peer] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        pc = self.pc = svc.ctx.perf.create(f"osd.hb.{svc.id}")
        for key in ("pings", "acks", "failures_reported"):
            pc.add_u64_counter(key)
        pc.add_u64("peers")
        pc.add_time("ping_time")
        pc.add_histogram("ping_lat")
        svc.msgr.register("osd_ping", self._h_ping, control=True)
        svc.msgr.register("osd_ping_reply", self._h_ping_reply,
                          control=True)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"osd{self.svc.id}-hb")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- peer selection (maybe_update_heartbeat_peers) -----------------
    def update_peers(self) -> None:
        """Recompute the peer set from the installed map: every other
        member of every PG this OSD is in the up or acting set of."""
        svc = self.svc
        with svc._lock:
            m = svc.map
        if m is None:
            return
        me = svc.id
        want = set()
        for pool_id, pool in list(m.pools.items()):
            for ps in range(pool.pg_num):
                up, _p, acting, _ap = svc.pg_up_acting(pool_id, ps)
                # >= 0 drops CRUSH_ITEM_NONE placeholders (EC pools
                # keep positional holes for unmapped shards)
                members = {o for o in set(up) | set(acting) if o >= 0}
                if me in members:
                    want |= members - {me}
        # pad sparse PG overlap (small pools, pool-less clusters) with
        # other up osds — the osd_heartbeat_min_peers role — walking
        # ids cyclically FROM our own so padding coverage spreads
        # instead of piling onto the lowest ids
        min_peers = svc.ctx.conf["osd_heartbeat_min_peers"]
        if len(want) < min_peers:
            others = sorted(
                (o for o in range(m.max_osd)
                 if o != me and o not in want and m.exists(o)
                 and m.is_up(o)),
                key=lambda o: (o - me) % max(m.max_osd, 1))
            want.update(others[:min_peers - len(want)])
        now = time.monotonic()
        with self._lock:
            for osd in list(self._peers):
                if osd not in want:
                    del self._peers[osd]
            for osd in want:
                if osd not in self._peers:
                    self._peers[osd] = _Peer(now)
            self.pc.set("peers", len(self._peers))

    # -- the ping loop -------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._tick()
            except Exception as e:
                self.log.derr(f"osd.{self.svc.id} hb tick: {e!r}")

    @nonblocking
    def _tick(self) -> None:
        svc = self.svc
        now = time.monotonic()
        with self._lock:
            peers = {o: (p.last_ack, p.ewma)
                     for o, p in self._peers.items()}
        with svc._lock:
            m = svc.map
            addrs = dict(svc.osd_addrs)
        overdue = []
        for osd, (last_ack, ewma) in peers.items():
            addr = addrs.get(osd)
            if addr is None:
                continue  # can't ping -> no basis to condemn; the
                # mon's beacon timeout owns an osd we can't even dial
            svc.msgr.send(tuple(addr), {  # block-ok: lossless send is deadline-bounded (2s sequencing-lock timeout, fire-and-forget frame) — a dead peer costs a bounded stall, never a wedge
                "type": "osd_ping", "osd": svc.id,
                "addr": list(svc.addr), "stamp": now})
            self.pc.inc("pings")
            eff_grace = self.grace + GRACE_LAT_FACTOR * ewma
            if now - last_ack > eff_grace and m is not None and \
                    m.is_up(osd):
                overdue.append((osd, now - last_ack))
        for osd, failed_for in overdue:
            # re-sent every interval while the peer stays silent and
            # up in our map: the monitor's reports DECAY, so a live
            # claim must keep refreshing until check_failure acts
            svc.mon_send({"type": "osd_failure", "osd": osd,  # block-ok: fire-and-forget mon report over the bounded lossless send path (2s sequencing timeout)
                          "frm_osd": svc.id,
                          "failed_for": round(failed_for, 3)})
            self.pc.inc("failures_reported")

    # -- handlers (both fire-and-forget, control lane) -----------------
    @nonblocking
    def _h_ping(self, msg: Dict) -> None:
        # echo the stamp back to the pinger's listening address; our
        # own send is fire-and-forget too, so a half-dead link drops
        # the reply instead of wedging this handler
        addr = msg.get("addr")
        if addr:
            self.svc.msgr.send(tuple(addr), {  # block-ok: fire-and-forget echo on the bounded lossless send path (2s sequencing timeout); a half-dead link drops the reply, never wedges the handler
                "type": "osd_ping_reply", "osd": self.svc.id,
                "stamp": msg.get("stamp", 0.0)})
        return None

    @nonblocking
    def _h_ping_reply(self, msg: Dict) -> None:
        now = time.monotonic()
        rtt = max(0.0, now - float(msg.get("stamp", now)))
        osd = int(msg["osd"])
        with self._lock:
            peer = self._peers.get(osd)
            if peer is None:
                return None
            peer.last_ack = now
            peer.ewma = rtt if peer.ewma == 0.0 else (
                EWMA_ALPHA * rtt + (1.0 - EWMA_ALPHA) * peer.ewma)
            peer.rtts.append((now, rtt))
        self.pc.inc("acks")
        self.pc.tinc("ping_time", rtt)
        self.pc.hist_add("ping_lat", rtt)
        return None

    # -- the network-health surface (dump_osd_network) -----------------
    def dump_network(self,
                     threshold_ms: Optional[float] = None) -> Dict:
        """Per-peer RTT window averages, worst first — the `ceph
        daemon osd.N dump_osd_network` payload.  Only peers whose
        worst window average reaches ``threshold_ms`` are listed
        (0 lists everything); the default threshold is the
        OSD_SLOW_PING_TIME knob, so the dump shows exactly the peers
        the health check would complain about."""
        if threshold_ms is None:
            threshold_ms = self.ping_threshold_ms
        now = time.monotonic()
        with self._lock:
            peers = {o: (p.window_avgs_ms(now),
                         list(p.rtts)[-1][1] if p.rtts else None)
                     for o, p in self._peers.items()}
        entries = []
        for osd, (avgs, last) in peers.items():
            worst = max(avgs.values()) if avgs else 0.0
            e = {"peer": osd, "worst_ms": worst,
                 "last_ms": round(1e3 * last, 3)
                 if last is not None else None}
            e.update(avgs)
            entries.append(e)
        entries.sort(key=lambda e: e["worst_ms"], reverse=True)
        shown = [e for e in entries
                 if threshold_ms <= 0 or e["worst_ms"] >= threshold_ms]
        return {"osd": self.svc.id,
                "threshold_ms": threshold_ms,
                "total_peers": len(entries),
                "entries": shown}

    def ping_breaches(self) -> List[Dict]:
        """Peers whose worst window average crosses the threshold —
        the compact list the OSD beacon carries so the monitor can
        raise OSD_SLOW_PING_TIME with per-pair attribution."""
        dump = self.dump_network()
        return [{"peer": e["peer"], "avg_ms": e["worst_ms"]}
                for e in dump["entries"]
                if e["worst_ms"] >= dump["threshold_ms"] > 0]

    def wire(self, admin_socket) -> None:
        def _dump(args: Dict) -> Dict:
            thr = args.get("threshold_ms")
            return self.dump_network(
                float(thr) if thr is not None else None)

        admin_socket.register(
            "dump_osd_network", _dump,
            "heartbeat RTT window averages per peer (worst first)")
