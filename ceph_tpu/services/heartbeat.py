"""Peer heartbeat plane — the OSD-side failure detector.

The role of ``OSD::heartbeat`` / ``OSD::maybe_update_heartbeat_peers``
(src/osd/OSD.cc:5487): every OSD pings the peers it shares PGs with
over the messenger control lane, keeps a per-peer last-ack clock plus
an EWMA of ping latency, and reports a peer past its (latency-adapted)
grace to the monitors as an ``osd_failure`` — the raw material of
``OSDMonitor::check_failure``'s reporter quorums.  The direct OSD→mon
beacon survives only as liveness-of-last-resort with the much longer
``mon_osd_report_timeout``, so a cut mon↔OSD link alone can no longer
kill a healthy OSD that its peers still hear.

Pings are fire-and-forget both ways (MOSDPing PING / PING_REPLY): the
sender stamps a monotonic clock, the receiver echoes it back in its
own fire-and-forget reply, and the sender's reply handler turns the
echo into an RTT sample.  Nothing in the ping path ever blocks on a
dead peer — that is the point of a failure detector.

The peer set is recomputed on every map-epoch install (the
``maybe_update_heartbeat_peers`` hook in ``_post_map_install``): for
each PG this OSD is in the up or acting set of, every other member is
a heartbeat peer.  The latency EWMA adapts the effective grace
(``grace + 4×ewma``) so a loaded-but-alive peer whose scheduling
latency grows is not storm-reported (the reference's
``mon_osd_adjust_heartbeat_grace`` idea, done sender-side).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..analysis.lockdep import make_lock

# EWMA smoothing for ping RTT and its weight in the effective grace:
# eff_grace = grace + GRACE_LAT_FACTOR * ewma.  On a loopback cluster
# ewma is sub-millisecond and the bound stays ~grace; under full-suite
# CPU load the inflated RTTs buy loaded peers headroom automatically.
EWMA_ALPHA = 0.3
GRACE_LAT_FACTOR = 4.0


class _Peer:
    """Per-peer clock state (one heartbeat_info_t)."""

    __slots__ = ("last_ack", "ewma")

    def __init__(self, now: float):
        # a fresh peer gets a full grace window from discovery — it
        # has never been asked, so it cannot already be overdue
        self.last_ack = now
        self.ewma = 0.0


class HeartbeatPlane:
    """One OSD's peer-ping plane.  Owned by OSDService: constructed
    with it (registers its two control-lane handlers), started after
    the first map install, peers recomputed per epoch."""

    def __init__(self, svc) -> None:
        self.svc = svc
        self.log = svc.log
        conf = svc.ctx.conf
        self.interval: float = conf["osd_heartbeat_interval"]
        self.grace: float = conf["osd_heartbeat_grace"]
        self._lock = make_lock("osd::hb")
        self._peers: Dict[int, _Peer] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        pc = self.pc = svc.ctx.perf.create(f"osd.hb.{svc.id}")
        for key in ("pings", "acks", "failures_reported"):
            pc.add_u64_counter(key)
        pc.add_u64("peers")
        pc.add_time("ping_time")
        pc.add_histogram("ping_lat")
        svc.msgr.register("osd_ping", self._h_ping, control=True)
        svc.msgr.register("osd_ping_reply", self._h_ping_reply,
                          control=True)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"osd{self.svc.id}-hb")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- peer selection (maybe_update_heartbeat_peers) -----------------
    def update_peers(self) -> None:
        """Recompute the peer set from the installed map: every other
        member of every PG this OSD is in the up or acting set of."""
        svc = self.svc
        with svc._lock:
            m = svc.map
        if m is None:
            return
        me = svc.id
        want = set()
        for pool_id, pool in list(m.pools.items()):
            for ps in range(pool.pg_num):
                up, _p, acting, _ap = svc.pg_up_acting(pool_id, ps)
                # >= 0 drops CRUSH_ITEM_NONE placeholders (EC pools
                # keep positional holes for unmapped shards)
                members = {o for o in set(up) | set(acting) if o >= 0}
                if me in members:
                    want |= members - {me}
        # pad sparse PG overlap (small pools, pool-less clusters) with
        # other up osds — the osd_heartbeat_min_peers role — walking
        # ids cyclically FROM our own so padding coverage spreads
        # instead of piling onto the lowest ids
        min_peers = svc.ctx.conf["osd_heartbeat_min_peers"]
        if len(want) < min_peers:
            others = sorted(
                (o for o in range(m.max_osd)
                 if o != me and o not in want and m.exists(o)
                 and m.is_up(o)),
                key=lambda o: (o - me) % max(m.max_osd, 1))
            want.update(others[:min_peers - len(want)])
        now = time.monotonic()
        with self._lock:
            for osd in list(self._peers):
                if osd not in want:
                    del self._peers[osd]
            for osd in want:
                if osd not in self._peers:
                    self._peers[osd] = _Peer(now)
            self.pc.set("peers", len(self._peers))

    # -- the ping loop -------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._tick()
            except Exception as e:
                self.log.derr(f"osd.{self.svc.id} hb tick: {e!r}")

    def _tick(self) -> None:
        svc = self.svc
        now = time.monotonic()
        with self._lock:
            peers = {o: (p.last_ack, p.ewma)
                     for o, p in self._peers.items()}
        with svc._lock:
            m = svc.map
            addrs = dict(svc.osd_addrs)
        overdue = []
        for osd, (last_ack, ewma) in peers.items():
            addr = addrs.get(osd)
            if addr is None:
                continue  # can't ping -> no basis to condemn; the
                # mon's beacon timeout owns an osd we can't even dial
            svc.msgr.send(tuple(addr), {
                "type": "osd_ping", "osd": svc.id,
                "addr": list(svc.addr), "stamp": now})
            self.pc.inc("pings")
            eff_grace = self.grace + GRACE_LAT_FACTOR * ewma
            if now - last_ack > eff_grace and m is not None and \
                    m.is_up(osd):
                overdue.append((osd, now - last_ack))
        for osd, failed_for in overdue:
            # re-sent every interval while the peer stays silent and
            # up in our map: the monitor's reports DECAY, so a live
            # claim must keep refreshing until check_failure acts
            svc.mon_send({"type": "osd_failure", "osd": osd,
                          "frm_osd": svc.id,
                          "failed_for": round(failed_for, 3)})
            self.pc.inc("failures_reported")

    # -- handlers (both fire-and-forget, control lane) -----------------
    def _h_ping(self, msg: Dict) -> None:
        # echo the stamp back to the pinger's listening address; our
        # own send is fire-and-forget too, so a half-dead link drops
        # the reply instead of wedging this handler
        addr = msg.get("addr")
        if addr:
            self.svc.msgr.send(tuple(addr), {
                "type": "osd_ping_reply", "osd": self.svc.id,
                "stamp": msg.get("stamp", 0.0)})
        return None

    def _h_ping_reply(self, msg: Dict) -> None:
        now = time.monotonic()
        rtt = max(0.0, now - float(msg.get("stamp", now)))
        osd = int(msg["osd"])
        with self._lock:
            peer = self._peers.get(osd)
            if peer is None:
                return None
            peer.last_ack = now
            peer.ewma = rtt if peer.ewma == 0.0 else (
                EWMA_ALPHA * rtt + (1.0 - EWMA_ALPHA) * peer.ewma)
        self.pc.inc("acks")
        self.pc.tinc("ping_time", rtt)
        self.pc.hist_add("ping_lat", rtt)
        return None
