"""OSD service — the storage daemon analogue.

The role of src/osd (OSD.cc dispatch + PrimaryLogPG + ECBackend),
single-host scale: MemStore-backed shard storage per PG collection,
EC-positional shard writes/reads (the ECBackend sub-op surface,
ECBackend.cc:934/1015), mon boot + heartbeats (ceph_osd.cc:544), map
subscriptions, and the mark-down→remap→recover flow: on every map
epoch the service scans the PGs it serves, and backfills any shard it
should hold but doesn't by fetching surviving shards from peers and
EC-decoding (ECBackend::recover_object / continue_recovery_op shape,
:757/589 — minimum_to_decode, fetch, decode, store).

Every PG collection keeps a PG log object (omap seq → op record) —
the PGLog analogue that makes writes auditable and recovery
explainable (SURVEY §5 checkpoint row); backfill consults the peer's
object listing (the backfill path) with the log as provenance.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..common.context import Context
from ..common.throttle import Throttle
from ..ec.registry import profile_factory
from ..msg.messenger import Addr, Messenger
from ..os.memstore import MemStore
from ..os.objectstore import Transaction
from ..osdmap.osdmap import OSDMap, POOL_TYPE_ERASURE


def pg_cid(pool_id: int, ps: int) -> str:
    return f"{pool_id}.{ps}"


from .map_follower import MapFollower


class OSDService(MapFollower):
    def __init__(self, ctx: Context, osd_id: int, mon_addr: Addr,
                 host: str = "127.0.0.1", port: int = 0, keyring=None,
                 data_dir: Optional[str] = None):
        self.ctx = ctx
        self.id = osd_id
        self.log = ctx.logger("osd")
        self._init_mons(mon_addr)  # one addr or the quorum list
        # data_dir = the OSD's persistent volume (superblock + data):
        # a restart remounts the checkpoint instead of backfilling
        # everything from peers (the reference's restart-replay flow)
        self.data_dir = data_dir
        self.store = self._mount()
        self.msgr = Messenger(f"osd.{osd_id}", host, port,
                              keyring=keyring)
        self.addr = self.msgr.addr
        self.map: Optional[OSDMap] = None
        self.epoch = 0
        self.osd_addrs: Dict[int, Addr] = {}
        self.ec_profiles: Dict[str, Dict[str, str]] = {}
        self._codes: Dict[str, object] = {}
        self._lock = threading.RLock()
        self._running = False
        self._beat_thread: Optional[threading.Thread] = None
        self._recover_thread: Optional[threading.Thread] = None
        self._recover_wake = threading.Event()
        self.backfill_throttle = Throttle(
            "backfill", ctx.conf["osd_max_backfills"])
        from ..common.op_tracker import OpTracker

        self.optracker = OpTracker()
        self.pc = ctx.perf.create(f"osd.{osd_id}")
        for key in ("ops_w", "ops_r", "recovered_objects",
                    "map_epochs"):
            self.pc.add_u64_counter(key)

        for t, h in (("shard_write", self._h_shard_write),
                     ("shard_read", self._h_shard_read),
                     ("pg_list", self._h_pg_list),
                     ("pg_scrub", self._h_pg_scrub),
                     ("shard_remove", self._h_shard_remove),
                     ("map_update", self._h_map_update),
                     ("map_inc", self._h_map_inc),
                     ("status", self._h_status)):
            self.msgr.register(t, h)

    # -- persistence (superblock/restart-replay role) -------------------
    def _mount(self):
        """Without a data_dir the OSD is a pure in-RAM daemon
        (MemStore); with one, it runs the crash-consistent WALStore —
        every acked transaction survives kill -9, and a restart
        remounts checkpoint+WAL instead of backfilling from peers (the
        reference's BlueStore+superblock restart-replay flow)."""
        if self.data_dir is None:
            return MemStore()
        import os

        from ..os.wal_store import WALStore

        path = os.path.join(self.data_dir, f"osd.{self.id}.wal")
        st = WALStore(path)
        if not os.path.exists(os.path.join(path, "checkpoint")):
            st.mkfs()
        st.mount()
        return st

    def _flush(self) -> None:
        from ..os.wal_store import WALStore

        if isinstance(self.store, WALStore):
            self.store.umount()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self.msgr.start()
        self._running = True
        boot = self.mon_call({"type": "boot", "osd": self.id,
                              "addr": list(self.addr)}, tries=10)
        payload = self.subscribe_all(f"osd.{self.id}")
        self._install_map(payload)
        self.log.dout(1, f"osd.{self.id} up (boot epoch "
                         f"{boot.get('epoch')})")
        self._beat_thread = threading.Thread(
            target=self._beat_loop, daemon=True,
            name=f"osd{self.id}-beat")
        self._beat_thread.start()
        self._recover_thread = threading.Thread(
            target=self._recover_loop, daemon=True,
            name=f"osd{self.id}-recover")
        self._recover_thread.start()

    def shutdown(self) -> None:
        self._running = False
        self._recover_wake.set()
        self.msgr.shutdown()
        try:
            self._flush()
        except OSError as e:
            self.log.derr(f"checkpoint flush failed: {e}")

    # -- map handling (install/inc-apply live in MapFollower) ----------
    def _post_map_install(self) -> None:
        with self._lock:
            wrongly_down = self._running and self.map is not None \
                and not self.map.is_up(self.id)
            epoch = self.epoch
        self.pc.inc("map_epochs")
        if wrongly_down:
            # we observed our own markdown but we're alive: re-boot to
            # the mon (the reference OSD's "map says I'm down" flow)
            self.log.dout(1, f"osd.{self.id} marked down in epoch "
                             f"{epoch}; re-booting to mon")
            self.mon_send({"type": "boot", "osd": self.id,
                           "addr": list(self.addr)})
        self._recover_wake.set()

    def _h_map_update(self, msg: Dict) -> None:
        self._install_map(msg["payload"])
        return None

    def _code_for(self, pool) -> Optional[object]:
        if pool.pool_type != POOL_TYPE_ERASURE:
            return None
        name = pool.erasure_code_profile
        code = self._codes.get(name)
        if code is None:
            code = profile_factory(dict(self.ec_profiles[name]))
            self._codes[name] = code
        return code

    # -- op handlers (the ECBackend sub-op surface) --------------------
    def _h_shard_write(self, msg: Dict) -> Dict:
        from ..ec.stripe import crc32c

        cid = pg_cid(msg["pool"], msg["ps"])
        oid = f"{msg['oid']}.s{msg['shard']}"
        with self.optracker.create(
                "osd_op", f"write {cid}/{oid} from "
                          f"{msg.get('frm')}") as op:
            txn = Transaction()
            if not self.store.collection_exists(cid):
                txn.create_collection(cid)
            data = bytes.fromhex(msg["data"])
            txn.write(cid, oid, 0, data)
            txn.setattr(cid, oid, "size", str(msg["size"]).encode())
            txn.setattr(cid, oid, "crc", str(crc32c(data)).encode())
            seq = str(time.time_ns())
            txn.omap_setkeys(cid, "pglog", {
                seq: f'{{"op":"write","oid":"{msg["oid"]}",'
                     f'"shard":{msg["shard"]},"epoch":{self.epoch}}}'
                     .encode()})
            op.mark_event("queued_for_store")
            self.store.queue_transaction(txn)
            op.mark_event("commit")
            self.pc.inc("ops_w")
        return {"ok": True, "epoch": self.epoch}

    def _h_shard_read(self, msg: Dict) -> Dict:
        cid = pg_cid(msg["pool"], msg["ps"])
        oid = f"{msg['oid']}.s{msg['shard']}"
        with self.optracker.create("osd_op",
                                   f"read {cid}/{oid}"):
            try:
                data = self.store.read(cid, oid)
            except KeyError:
                return {"error": "enoent"}
            size = self.store.getattr(cid, oid, "size") or b"0"
            self.pc.inc("ops_r")
            return {"data": data.hex(), "size": int(size)}

    def _h_pg_list(self, msg: Dict) -> Dict:
        cid = pg_cid(msg["pool"], msg["ps"])
        out: Dict[str, int] = {}
        for name in self.store.list_objects(cid):
            if name == "pglog" or ".s" not in name:
                continue
            oid, _, shard = name.rpartition(".s")
            size = self.store.getattr(cid, name, "size") or b"0"
            out[oid] = int(size)
        return {"objects": out}

    def _h_pg_scrub(self, msg: Dict) -> Dict:
        """Deep scrub of one PG: recompute every local shard's crc32c
        and compare with the stored write-time digest (the
        HashInfo-backed scrub of the reference's deep-scrub flow)."""
        from ..ec.stripe import crc32c

        cid = pg_cid(msg["pool"], msg["ps"])
        inconsistent: List[str] = []
        digests: Dict[str, int] = {}
        if self.store.collection_exists(cid):
            for name in self.store.list_objects(cid):
                if name == "pglog":
                    continue
                data = self.store.read(cid, name)
                got = crc32c(data)
                stored = self.store.getattr(cid, name, "crc")
                digests[name] = got
                if stored is not None and int(stored) != got:
                    inconsistent.append(name)
        return {"osd": self.id, "inconsistent": inconsistent,
                "digests": digests}

    def _h_shard_remove(self, msg: Dict) -> Dict:
        """Drop a (corrupt) shard so recovery rebuilds it — the repair
        half of scrub (test-erasure-eio.sh flow)."""
        cid = pg_cid(msg["pool"], msg["ps"])
        name = f"{msg['oid']}.s{msg['shard']}"
        if self.store.stat(cid, name) is not None:
            self.store.queue_transaction(
                Transaction().remove(cid, name))
        self._recover_wake.set()
        return {"ok": True}

    def _h_status(self, _msg: Dict) -> Dict:
        with self._lock:
            return {"osd": self.id, "epoch": self.epoch,
                    "collections": self.store.list_collections(),
                    "perf": self.pc.dump(),
                    "historic_ops": self.optracker.dump_historic_ops()}

    # -- heartbeats ----------------------------------------------------
    def _beat_loop(self) -> None:
        interval = self.ctx.conf["osd_heartbeat_interval"]
        while self._running:
            # mon_send reaches every quorum member: peons forward to
            # the leader, so liveness survives any single monitor death
            self.mon_send({"type": "heartbeat", "osd": self.id})
            time.sleep(interval)

    # -- recovery (mark-down -> remap -> recover) ----------------------
    def _recover_loop(self) -> None:
        retry_pending = False
        while self._running:
            fired = self._recover_wake.wait(timeout=5.0)
            self._recover_wake.clear()
            if not self._running:
                break
            if not fired and not retry_pending:
                continue  # idle: no epoch change, nothing pending
            try:
                self._check_recovery()
                retry_pending = False
            except Exception as e:
                self.log.derr(f"recovery pass failed: {e}")
                retry_pending = True  # peers may come back; retry

    def _alive(self, osd: int) -> bool:
        return self.map is not None and self.map.is_up(osd) \
            and osd in self.osd_addrs

    def _check_recovery(self) -> None:
        with self._lock:
            m = self.map
        if m is None:
            return
        for pool_id, pool in m.pools.items():
            for ps in range(pool.pg_num):
                up, _p, _a, _ap = m.pg_to_up_acting_osds(pool_id, ps)
                if self.id not in up:
                    continue
                self._recover_pg(m, pool_id, pool, ps, up)

    def _recover_pg(self, m, pool_id: int, pool, ps: int,
                    up: List[int]) -> None:
        cid = pg_cid(pool_id, ps)
        code = self._code_for(pool)
        # replicated pools store the full object as shard 0 on every
        # replica; EC pools are positional
        shard = up.index(self.id) if code is not None else 0
        have: Set[str] = set()
        if self.store.collection_exists(cid):
            for name in self.store.list_objects(cid):
                if name.endswith(f".s{shard}"):
                    have.add(name.rpartition(".s")[0])
        # authoritative listing from any live peer of this pg
        peers = [o for o in up if o != self.id and self._alive(o)]
        missing: Dict[str, int] = {}
        for peer in peers:
            try:
                got = self.msgr.call(
                    self.osd_addrs[peer],
                    {"type": "pg_list", "pool": pool_id, "ps": ps},
                    timeout=5)
            except (TimeoutError, OSError):
                continue
            for oid, size in got.get("objects", {}).items():
                if oid not in have:
                    missing[oid] = max(missing.get(oid, 0), size)
        if not missing:
            return
        for oid, size in missing.items():
            if not self.backfill_throttle.get(timeout=5):
                return
            try:
                self._recover_object(m, pool_id, pool, ps, up, shard,
                                     oid, size, code)
            finally:
                self.backfill_throttle.put()

    def _recover_object(self, m, pool_id, pool, ps, up, shard, oid,
                        size, code) -> None:
        """ECBackend::recover_object: fetch survivors, decode, store."""
        cid = pg_cid(pool_id, ps)
        if code is None:
            # replicated: copy the full object from any live peer
            for peer in up:
                if peer == self.id or not self._alive(peer):
                    continue
                got = self.msgr.call(
                    self.osd_addrs[peer],
                    {"type": "shard_read", "pool": pool_id, "ps": ps,
                     "oid": oid, "shard": 0}, timeout=5)
                if "data" in got:
                    self._store_shard(cid, oid, 0, bytes.fromhex(
                        got["data"]), got["size"])
                    self.pc.inc("recovered_objects")
                    return
            return
        import numpy as np

        n = code.get_chunk_count()
        chunks: Dict[int, np.ndarray] = {}
        for pos, peer in enumerate(up):
            if len(chunks) >= code.get_data_chunk_count():
                break
            if peer == self.id or not self._alive(peer):
                continue
            try:
                got = self.msgr.call(
                    self.osd_addrs[peer],
                    {"type": "shard_read", "pool": pool_id, "ps": ps,
                     "oid": oid, "shard": pos}, timeout=5)
            except (TimeoutError, OSError):
                continue
            if "data" in got:
                chunks[pos] = np.frombuffer(
                    bytes.fromhex(got["data"]), np.uint8)
        if len(chunks) < code.get_data_chunk_count():
            self.log.derr(f"pg {cid} {oid}: not enough shards to "
                          f"recover ({len(chunks)})")
            return
        out = code.decode({shard}, chunks)
        self._store_shard(cid, oid, shard,
                          np.asarray(out[shard], np.uint8).tobytes(),
                          size)
        self.pc.inc("recovered_objects")
        self.log.dout(5, f"recovered {cid}/{oid} shard {shard}")

    def _store_shard(self, cid: str, oid: str, shard: int,
                     data: bytes, size: int) -> None:
        txn = Transaction()
        if not self.store.collection_exists(cid):
            txn.create_collection(cid)
        name = f"{oid}.s{shard}"
        txn.write(cid, name, 0, data)
        txn.setattr(cid, name, "size", str(size).encode())
        txn.omap_setkeys(cid, "pglog", {
            str(time.time_ns()):
                f'{{"op":"recover","oid":"{oid}","shard":{shard},'
                f'"epoch":{self.epoch}}}'.encode()})
        self.store.queue_transaction(txn)
