"""OSD service — the storage daemon analogue.

The role of src/osd (OSD.cc dispatch + PrimaryLogPG + ECBackend),
single-host scale: MemStore/WALStore-backed shard storage per PG
collection, EC-positional shard writes/reads (the ECBackend sub-op
surface, ECBackend.cc:934/1015), mon boot + heartbeats
(ceph_osd.cc:544), map subscriptions, and primary-driven peering +
recovery.

Peering (the PeeringState.cc / PGLog.h role, redesigned around
versioned objects instead of a log-offset state machine): every write
carries a totally-ordered version (map epoch + timestamp, identical on
every shard of the object), and every PG keeps a version-keyed log
with delete tombstones.  On each map change the PG's primary collects
``pg_info`` (last_update + per-object version map, folded from the
log) from every reachable member of the up and acting sets, merges
them into the authoritative per-object state — exactly the result the
reference reaches by electing the authoritative log and merging
divergent entries (PeeringState::choose_acting /
PGLog::merge_log) — computes each member's missing set, and drives
recovery: pull what the primary lacks, push what replicas lack,
propagate deletes.  Divergent histories (A took writes while B was
down, then roles flipped) reconcile to newest-version-wins, which the
reference guarantees through past-intervals + log election.

While the primary is itself behind, it installs a ``pg_temp`` overlay
at the monitor mapping the PG to the best-covered holder
(OSDMap.cc:2590 acting override) so reads keep being served, and
clears it once clean — the serving-continuity half of peering.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..analysis import faults
from ..analysis.lockdep import make_lock, make_rlock
from ..analysis.racecheck import guarded_by
from ..common import copytrack
from ..common.backoff import Backoff
from ..common.context import Context
from ..common.throttle import Throttle
from ..ec.registry import profile_factory
from ..msg.messenger import Addr, Messenger
from ..os.memstore import MemStore
from ..os.objectstore import Transaction
from ..osdmap.osdmap import OSDMap, POOL_TYPE_ERASURE


from ..common.encoding import MalformedInput
from ..common.op_queue import Requeue
from ..common.version import NULL_VERSION, bump, make_version
from .pg_log import PgLogEntry
from .recovery import HelperLedger, ReservationBook


def pg_cid(pool_id: int, ps: int) -> str:
    return f"{pool_id}.{ps}"


from .map_follower import MapFollower


@guarded_by("osd::state", "_pg_states", "_watchers", "_strays")
@guarded_by("osd::pg_io", "_pg_io")
@guarded_by("osd::pg_guard", "_pg_locks")
class OSDService(MapFollower):
    def __init__(self, ctx: Context, osd_id: int, mon_addr: Addr,
                 host: str = "127.0.0.1", port: int = 0, keyring=None,
                 data_dir: Optional[str] = None):
        self.ctx = ctx
        self.id = osd_id
        self.log = ctx.logger("osd")
        self._init_mons(mon_addr)  # one addr or the quorum list
        # data_dir = the OSD's persistent volume (superblock + data):
        # a restart remounts the checkpoint instead of backfilling
        # everything from peers (the reference's restart-replay flow)
        self.data_dir = data_dir
        self.store = self._mount()
        # lossless policy (osd↔osd sub-ops survive reconnects) and the
        # per-type byte throttle bounding in-flight client write bytes
        # (the osd_client_message_size_cap role, ceph_osd.cc:582-588)
        self.tracer = ctx.tracer  # shared with the messenger: handler
        # spans parent service spans (ec.encode under handle:ec_write)
        self.msgr = Messenger(
            f"osd.{osd_id}", host, port, keyring=keyring,
            lossless=True,
            throttles={"shard_write": Throttle(
                "msgr-write-bytes", 64 << 20)},
            tracer=self.tracer, perf=ctx.perf)
        self.addr = self.msgr.addr
        self.map: Optional[OSDMap] = None
        self.epoch = 0
        self.osd_addrs: Dict[int, Addr] = {}
        self.ec_profiles: Dict[str, Dict[str, str]] = {}
        self._codes: Dict[str, object] = {}
        self._lock = make_rlock("osd::state")
        self._running = False
        self._beat_thread: Optional[threading.Thread] = None
        self._recover_thread: Optional[threading.Thread] = None
        self._recover_wake = threading.Event()
        # set by shutdown(): the beat loop waits on THIS between
        # beacons (not a fixed sleep), so teardown never stalls a
        # full heartbeat interval behind a sleeping thread
        self._shutdown_ev = threading.Event()
        self.backfill_throttle = Throttle(
            "backfill", ctx.conf["osd_max_backfills"])
        # per-PG serialization: RMW coordination AND the local
        # check-then-write path (reentrant: the RMW coordinator's
        # self-push re-enters its own PG lock).  All PG locks share
        # the "osd::pg" lockdep node: cross-PG nesting on one thread
        # never happens (a PG has one primary; pushes to OTHER PGs go
        # over the wire), so same-name nesting stays un-edged
        self._pg_locks: Dict[Tuple[int, int], object] = {}
        self._pg_locks_guard = make_lock("osd::pg_guard")
        from ..common.op_queue import OpScheduler
        from ..common.op_tracker import OpTracker

        # the SLOW_OPS knob: one threshold feeds both the historic-
        # slow ring and the slow-op count the beacon reports to the
        # monitor's health fold
        self.optracker = OpTracker(
            history_slow_threshold=ctx.conf["osd_op_complaint_time"])
        # cross-thread EC encode coalescing: concurrent same-pool
        # writes share one batched engine dispatch (ec/batcher.py)
        from ..ec.batcher import EncodeBatcher

        self._ec_batcher = EncodeBatcher(
            max_delay_us=ctx.conf["ec_encode_batch_max_delay_us"])
        # (cid, oid) -> {watcher name: addr}: the Watch/Notify state
        # (src/osd/Watch.cc role).  In-memory: clients re-watch on map
        # changes, exactly like librados re-watches on reconnect.
        self._watchers: Dict[Tuple[str, str], Dict[str, Addr]] = {}
        # (pool, ps) -> stray holders that reported data for a PG this
        # osd is primary of (the MOSDPGNotify stray flow): peering
        # queries them so shards that remapped AWAY from the up set
        # stay reachable, and purges them once the PG is clean
        self._strays: Dict[Tuple[int, int], Set[int]] = {}
        # (pool, ps) -> monotonic time of the last scheduled deep
        # scrub this primary ran (PG::sched_scrub role); the semaphore
        # is the osd_max_scrubs=1 concurrency cap
        self._last_scrub: Dict[Tuple[int, int], float] = {}
        self._scrub_slots = threading.Semaphore(1)
        # dmClock QoS at the store door: client vs recovery vs scrub
        # ops are served in tag order by a small worker pool (4: a
        # window of pipelined client writes must overlap their
        # store commits, not serialize two at a time)
        self.sched = OpScheduler(n_workers=4)
        self.pc = ctx.perf.create(f"osd.{osd_id}")
        for key in ("ops_w", "ops_r", "degraded_reads",
                    "recovered_objects", "recovery_bytes",
                    "map_epochs", "pg_stat_beacons"):
            self.pc.add_u64_counter(key)
        # the byte-copy ledger (common/copytrack.py): EC input
        # assembly and recovery pushes book their host copies here
        self._copy_pc = copytrack.ledger(ctx.perf)
        # the recovery engine's own counter family (osd.recovery.*):
        # pipeline shape, helper fan-out/exclusions, reservation
        # back-pressure, and per-unit repair-strategy bookkeeping
        pc = self.rec_pc = ctx.perf.create(f"osd.recovery.{osd_id}")
        for key in ("pipelined_batches", "serial_batches",
                    "helper_reads", "helper_bytes",
                    "helper_bytes_saved", "helper_eio_excluded",
                    "replans", "strategy_full", "strategy_lrc",
                    "strategy_clay", "reservation_waits",
                    "remote_denials"):
            pc.add_u64_counter(key)
        # helper-read load balancing + per-object failure exclusions,
        # and the AsyncReserver-lite slot pool shared by local recovery
        # work and grants to remote primaries
        self.rec_ledger = HelperLedger()
        self.rec_reserver = ReservationBook(
            ctx.conf["osd_max_recovery_ops"])
        # per-PG cumulative io/recovery counters (the pg_stat_t
        # io/recovery sums): client read/write ops+bytes, EC encode
        # volume, recovery pushes — piggybacked on pg_stats beacons
        # for the monitor's PGMap per-pool aggregation
        self._pg_io: Dict[Tuple[int, int], Dict[str, float]] = {}
        self._pg_io_lock = make_lock("osd::pg_io")
        # (pool, ps) -> last peering verdict this PRIMARY computed
        # (state string, object/degraded counts): what the periodic
        # beacons re-send between peering passes
        self._pg_states: Dict[Tuple[int, int], Dict] = {}

        # map pushes and peering probes ride the control lane: a burst
        # of 16 queued shard writes holds every op-pool worker in the
        # object store, and failure detection / remapping must not
        # head-of-line-block behind it
        control = {"map_update", "map_inc", "pg_info", "pg_poke",
                   "pg_stray", "recovery_reserve"}
        for t, h in (("shard_write", self._h_shard_write),
                     ("shard_read", self._h_shard_read),
                     ("pg_list", self._h_pg_list),
                     ("pg_info", self._h_pg_info),
                     ("pg_scrub", self._h_pg_scrub),
                     ("shard_remove", self._h_shard_remove),
                     ("obj_delete", self._h_obj_delete),
                     ("ec_write", self._h_ec_write),
                     ("rep_write", self._h_rep_write),
                     ("watch", self._h_watch),
                     ("unwatch", self._h_unwatch),
                     ("notify", self._h_notify),
                     ("pg_poke", self._h_pg_poke),
                     ("pg_stray", self._h_pg_stray),
                     ("pg_log_trim", self._h_pg_log_trim),
                     ("recovery_reserve", self._h_recovery_reserve),
                     ("pg_purge", self._h_pg_purge),
                     ("map_update", self._h_map_update),
                     ("map_inc", self._h_map_inc),
                     ("status", self._h_status)):
            self.msgr.register(t, h, control=t in control)

        # the peer failure detector (OSD::heartbeat role): registers
        # its osd_ping/osd_ping_reply control-lane handlers here;
        # started with the daemon, peers recomputed per map install
        from .heartbeat import HeartbeatPlane

        self.hb = HeartbeatPlane(self)

    # -- persistence (superblock/restart-replay role) -------------------
    def _mount(self):
        """Without a data_dir the OSD is a pure in-RAM daemon
        (MemStore); with one, it runs the crash-consistent WALStore —
        every acked transaction survives kill -9, and a restart
        remounts checkpoint+WAL instead of backfilling from peers (the
        reference's BlueStore+superblock restart-replay flow)."""
        if self.data_dir is None:
            return MemStore(copy_coll=self.ctx.perf)
        import os

        from ..os.wal_store import WALStore

        path = os.path.join(self.data_dir, f"osd.{self.id}.wal")
        st = WALStore(path, group_commit_max_delay_us=self.ctx.conf[
            "wal_group_commit_max_delay_us"],
            copy_coll=self.ctx.perf)
        if not os.path.exists(os.path.join(path, "checkpoint")):
            st.mkfs()
        st.mount()
        return st

    def _flush(self) -> None:
        from ..os.wal_store import WALStore

        if isinstance(self.store, WALStore):
            self.store.umount()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self.ctx.conf["admin_socket"]:
            # the daemon's introspection plane: perf dump (own +
            # shared library counters), dump_tracing, op tracker,
            # dump_blocked — what ceph_tpu.tools.telemetry polls
            sock = self.ctx.start_admin_socket()
            self.optracker.wire(sock)
            self.tracer.wire(sock)
            self.msgr.wire(sock)   # dump_messenger
            self.hb.wire(sock)     # dump_osd_network
        self.msgr.start()
        self._running = True
        boot = self.mon_call({"type": "boot", "osd": self.id,
                              "addr": list(self.addr)}, tries=10)
        payload = self.subscribe_all(f"osd.{self.id}")
        self._install_map(payload)
        self.log.dout(1, f"osd.{self.id} up (boot epoch "
                         f"{boot.get('epoch')})")
        self._beat_thread = threading.Thread(
            target=self._beat_loop, daemon=True,
            name=f"osd{self.id}-beat")
        self._beat_thread.start()
        self._recover_thread = threading.Thread(
            target=self._recover_loop, daemon=True,
            name=f"osd{self.id}-recover")
        self._recover_thread.start()
        self.hb.update_peers()
        self.hb.start()

    def shutdown(self) -> None:
        self._running = False
        self._shutdown_ev.set()
        self.hb.stop()
        self._recover_wake.set()
        pool = getattr(self, "_fanout_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)
        pool = getattr(self, "_recover_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)
        self.sched.shutdown()
        self.msgr.shutdown()
        self.ctx.shutdown()  # admin socket + config observers
        try:
            self._flush()
        except OSError as e:
            self.log.derr(f"checkpoint flush failed: {e}")

    # -- map handling (install/inc-apply live in MapFollower) ----------
    def _post_map_install(self) -> None:
        with self._lock:
            wrongly_down = self._running and self.map is not None \
                and not self.map.is_up(self.id)
            epoch = self.epoch
        self.pc.inc("map_epochs")
        if wrongly_down:
            # we observed our own markdown but we're alive: re-boot to
            # the mon (the reference OSD's "map says I'm down" flow)
            self.log.dout(1, f"osd.{self.id} marked down in epoch "
                             f"{epoch}; re-booting to mon")
            self.mon_send({"type": "boot", "osd": self.id,
                           "addr": list(self.addr)})
        self.hb.update_peers()
        self._recover_wake.set()

    def _h_map_update(self, msg: Dict) -> None:
        self._install_map(msg["payload"])
        return None

    def _code_for(self, pool) -> Optional[object]:
        if pool.pool_type != POOL_TYPE_ERASURE:
            return None
        name = pool.erasure_code_profile
        code = self._codes.get(name)
        if code is None:
            code = profile_factory(dict(self.ec_profiles[name]))
            self._codes[name] = code
        return code

    # -- op handlers (the ECBackend sub-op surface) --------------------
    def _qos_class(self, msg: Dict) -> str:
        cls = msg.get("qos_class")
        return cls if cls in ("client", "recovery", "scrub") \
            else "client"

    # -- per-PG io/recovery accounting (pg_stat_t sums role) -----------
    _IO_KEYS = ("rd_ops", "rd_bytes", "wr_ops", "wr_bytes",
                "degraded_reads", "ec_encode_ops", "ec_encode_bytes")
    _RECOVERY_KEYS = ("objects_recovered", "bytes_recovered")

    def _account_io(self, pool_id: int, ps: int, **deltas) -> None:
        with self._pg_io_lock:
            rec = self._pg_io.setdefault(
                (pool_id, ps),
                {k: 0 for k in self._IO_KEYS + self._RECOVERY_KEYS})
            for k, v in deltas.items():
                rec[k] = rec.get(k, 0) + v

    def _send_pg_stats(self, pool_id: int, ps: int) -> None:
        """One pg_stats beacon: cached peering state (when this OSD is
        the PG's primary) + cumulative io/recovery counters.  Any
        shard holder reports io (EC reads land on every member, not
        the primary); only primary beacons carry state, so the
        monitor's staleness clock tracks primaries."""
        key = (pool_id, ps)
        with self._pg_io_lock:
            io = dict(self._pg_io.get(key) or {})
        with self._lock:
            state = self._pg_states.get(key)
        msg: Dict = {"type": "pg_stats", "pool": pool_id, "ps": ps,
                     "osd": self.id, "epoch": self.epoch,
                     "io": {k: io.get(k, 0) for k in self._IO_KEYS}}
        if state is not None:
            msg.update({"state": state["state"],
                        "objects": state["objects"],
                        "primary": self.id,
                        "degraded_objects": state["degraded_objects"],
                        "recovery": {k: io.get(k, 0)
                                     for k in self._RECOVERY_KEYS}})
        else:
            msg["io_only"] = True
        self.mon_send(msg)
        self.pc.inc("pg_stat_beacons")

    def _stat_beacon_pass(self) -> None:
        """Periodic pg_stats beacons (the mgr stats-report cadence):
        re-send every PG this OSD has state or io for, dropping state
        cache entries for PGs it no longer leads."""
        with self._pg_io_lock:
            keys = set(self._pg_io)
        with self._lock:
            keys |= set(self._pg_states)
            m = self.map
        for pool_id, ps in sorted(keys):
            if m is not None and pool_id not in m.pools:
                # the pool is gone: its counters go with it (a stale
                # key must not abort every later beacon pass)
                with self._pg_io_lock:
                    self._pg_io.pop((pool_id, ps), None)
                with self._lock:
                    self._pg_states.pop((pool_id, ps), None)
                continue
            # membership check under the state lock: the unlocked
            # read raced _h_pg_remove's pop from a dispatch thread
            # (caught by racecheck's empty-lockset report)
            with self._lock:
                leads = (pool_id, ps) in self._pg_states
            if m is not None and leads:
                up, _p, acting, _ap = self.pg_up_acting(pool_id, ps)
                members = acting if acting else up
                prim = next((o for o in members if self._alive(o)),
                            None)
                if prim != self.id:
                    with self._lock:
                        self._pg_states.pop((pool_id, ps), None)
            self._send_pg_stats(pool_id, ps)

    def _h_shard_write(self, msg: Dict) -> Dict:
        # the scheduler worker adopts this handler's span, so the
        # store-commit span lands under handle:shard_write instead of
        # orphaning when the op crosses the queue
        parent_span = self.tracer.current()

        def run():
            with self.tracer.scope(parent_span):
                return self._do_shard_write(msg)

        return self.sched.submit(self._qos_class(msg), run)

    def _do_shard_write(self, msg: Dict) -> Dict:
        from ..ec.stripe import crc32c

        if faults._ACTIVE:  # one bool test when nothing is armed
            if faults.fires("osd.kill_before_commit",
                            f"osd.{self.id}"):
                # died before the WAL commit: no data, no ack — the
                # sender's retry must land cleanly
                raise faults.InjectedKill("before WAL commit")
        cid = pg_cid(msg["pool"], msg["ps"])
        v = msg.get("v") or make_version(self.epoch)
        oid = f"{msg['oid']}.s{msg['shard']}"
        with self.optracker.create(
                "osd_op", f"write {cid}/{oid} from "
                          f"{msg.get('frm')}") as op:
            if faults._ACTIVE:
                # the slow-disk delay, BEFORE the PG lock (a slow op
                # must stall itself, not everything queued behind the
                # lock) but INSIDE the tracked scope: the op ages
                # visibly in dump_ops_in_flight and the SLOW_OPS
                # beacon while it sleeps, as a real slow disk would
                faults.sleep_if("osd.slow_op", f"osd.{self.id}")
            # per-PG lock, not the global one: a WALStore fsync per
            # write must never serialize the whole daemon or stall map
            # handling behind the write stream.  Bounded: a miss
            # requeues instead of pinning the scheduler worker.
            with self._pg_lock_bounded(msg["pool"], msg["ps"]):
                # a newer version (a divergent-history reconciliation
                # or a racing later write) must never be clobbered by
                # an older one arriving late
                cur = self.store.getattr(cid, oid, "v") \
                    if self.store.collection_exists(cid) else None
                rollback = False
                if cur is not None and cur.decode() > v:
                    if not msg.get("force") or (
                            msg.get("expect") is not None
                            and cur.decode() != msg["expect"]):
                        # `cur` lets the writer re-stamp past the
                        # stored version (clock-skew repair) instead
                        # of mistaking the discard for success
                        return {"ok": True, "superseded": True,
                                "cur": cur.decode(),
                                "epoch": self.epoch}
                    # authoritative rollback of a torn (never-acked)
                    # higher-version shard: fall through and overwrite
                    rollback = True
                txn = Transaction()
                if not self.store.collection_exists(cid):
                    txn.create_collection(cid)
                # buffer-protocol payload (a view into the frame's
                # pooled recv segment): staged zero-copy — the store
                # materialises it into its own image inside
                # queue_transaction, before this handler returns
                data = msg["data"]
                txn.write(cid, oid, 0, data)
                # a shorter rewrite must never leave a stale tail:
                # chunk boundaries shift and EC decode would interleave
                # old bytes into the new object
                txn.truncate(cid, oid, len(data))
                txn.setattr(cid, oid, "size",
                            str(msg["size"]).encode())
                txn.setattr(cid, oid, "crc",
                            str(crc32c(data)).encode())
                txn.setattr(cid, oid, "v", v.encode())
                if rollback:
                    # the torn entries must leave the log too, or the
                    # per-object "newest record" (what peering and
                    # trim consume) keeps resurrecting the rolled-back
                    # version (PGLog::rewind_divergent)
                    drop = self._log_keys_above(cid, msg["oid"], v)
                    if drop:
                        txn.omap_rmkeys(cid, "pglog", drop)
                txn.omap_setkeys(cid, "pglog", {
                    f"{v}|{msg['shard']}": PgLogEntry(
                        op="write", oid=msg["oid"],
                        shard=msg["shard"], v=v,
                        size=msg["size"]).encode_blob()})
                op.mark_event("queued_for_store")
                # the WAL stage: queue_transaction through the
                # group-commit fsync ack (attribution stage "wal")
                with self.tracer.start_span(
                        "store.commit", require_parent=True,
                        tags={"bytes": len(data)}):
                    self.store.queue_transaction(txn)
            op.mark_event("commit")
            if faults._ACTIVE and faults.fires(
                    "osd.kill_after_commit", f"osd.{self.id}"):
                # died after the WAL commit: data durable, ack lost —
                # the retry's rewrite must be idempotent (same data,
                # version floor keeps newer state safe)
                raise faults.InjectedKill("after WAL commit")
            self.pc.inc("ops_w")
        return {"ok": True, "epoch": self.epoch}

    def _h_shard_read(self, msg: Dict) -> Dict:
        parent_span = self.tracer.current()

        def run():
            with self.tracer.scope(parent_span):
                return self._do_shard_read(msg)

        return self.sched.submit(self._qos_class(msg), run)

    def _do_shard_read(self, msg: Dict) -> Dict:
        from ..ec.stripe import crc32c

        cid = pg_cid(msg["pool"], msg["ps"])
        oid = f"{msg['oid']}.s{msg['shard']}"
        with self.optracker.create("osd_op",
                                   f"read {cid}/{oid}"):
            try:
                if faults.fires("osd.shard_read_eio",
                                f"osd.{self.id}"):
                    raise OSError("injected shard read error")
                data = self.store.read(cid, oid)
                stored = self.store.getattr(cid, oid, "crc")
                if stored is not None and int(stored) != crc32c(data):
                    # silent bit rot (store.bit_rot class): the store
                    # returned success but the bytes are not what the
                    # write-time digest covers — same degrade path as
                    # an EIO'd sector
                    raise OSError("shard crc mismatch")
            except KeyError:
                return {"error": "enoent"}
            except OSError:
                # a bad sector under a shard (os.read_eio, bit rot, or
                # the injected arm above): the op must DEGRADE, not
                # fail — the reader decodes from survivors ("eio"
                # counts as reachable-but-unusable in the client's
                # shard math), and the shard is dropped so recovery
                # re-decodes it (the test-erasure-eio.sh flow)
                self.pc.inc("degraded_reads")
                self._account_io(int(msg["pool"]), int(msg["ps"]),
                                 degraded_reads=1)
                self._mark_shard_bad(int(msg["pool"]), int(msg["ps"]),
                                     msg["oid"], msg["shard"])
                return {"error": "eio"}
            size = self.store.getattr(cid, oid, "size") or b"0"
            ver = self.store.getattr(cid, oid, "v") or b""
            self.pc.inc("ops_r")
            if self._qos_class(msg) == "client":
                self._account_io(int(msg["pool"]), int(msg["ps"]),
                                 rd_ops=1, rd_bytes=len(data))
            out = bytes(data)
            if msg.get("ranges"):
                # server-side sub-chunk slicing (the CLAY bandwidth
                # repair's network win: only the repair sub-chunks
                # cross the wire); crc verification above always ran
                # over the FULL shard
                out = b"".join(out[int(off):int(off) + int(ln)]
                               for off, ln in msg["ranges"])
            return {"data": out, "size": int(size),
                    "v": ver.decode(), "chunk_len": len(data),
                    # scheduler depth: the load signal recovery
                    # primaries feed their helper ledger with
                    "load": sum(self.sched.depths().values())}

    def _h_obj_delete(self, msg: Dict) -> Dict:
        """Remove every local shard of an object and tombstone the
        log, so the delete wins over older writes at peering time."""
        cid = pg_cid(msg["pool"], msg["ps"])
        v = msg.get("v") or make_version(self.epoch)
        if msg.get("restamp"):
            # CLIENT deletes re-stamp at this daemon's current epoch
            # (interval floor, like the write paths) so the tombstone
            # dominates any version a currently-down holder minted in
            # an earlier interval.  Peering-driven deletes propagate
            # an exact authoritative version and must NOT be raised.
            now_v = make_version(self.epoch)
            if v < now_v:
                v = now_v
        with self._pg_lock(msg["pool"], msg["ps"]):
            txn = Transaction()
            if not self.store.collection_exists(cid):
                txn.create_collection(cid)
            else:
                prefix = f"{msg['oid']}.s"
                if not msg.get("force"):
                    # local version floor (same clock-skew repair as
                    # the write path): a client delete must tombstone
                    # ABOVE whatever is stored, or a lagging clock
                    # leaves the object readable after an acked delete
                    for name in self.store.list_objects(cid):
                        if name.startswith(prefix):
                            cur = self.store.getattr(cid, name, "v")
                            if cur is not None and cur.decode() >= v:
                                v = bump(cur.decode())
                torn_cleanup = False
                for name in self.store.list_objects(cid):
                    if not name.startswith(prefix):
                        continue
                    # same newer-wins guard as the write path: a stale
                    # delete (late retry racing a newer put) must not
                    # clobber the newer write's shards — the tombstone
                    # still logs, and version merge orders them.  A
                    # peering-driven FORCE delete removes a torn
                    # higher-version shard too, CAS-guarded on the
                    # version peering observed.
                    cur = self.store.getattr(cid, name, "v")
                    if cur is not None and cur.decode() > v:
                        if not msg.get("force") or (
                                msg.get("expect") is not None
                                and cur.decode() != msg["expect"]):
                            continue
                        torn_cleanup = True
                    txn.remove(cid, name)
                if torn_cleanup:
                    drop = self._log_keys_above(cid, msg["oid"], v)
                    if drop:
                        txn.omap_rmkeys(cid, "pglog", drop)
            txn.omap_setkeys(cid, "pglog", {
                f"{v}|d": PgLogEntry(op="delete", oid=msg["oid"],
                                     v=v).encode_blob()})
            self.store.queue_transaction(txn)
        return {"ok": True, "epoch": self.epoch}

    # -- EC partial-stripe overwrite (primary-coordinated RMW) ---------
    @contextlib.contextmanager
    def _pg_lock_bounded(self, pool_id: int, ps: int,
                         timeout: float = 0.25):
        """PG lock with a bounded wait for SCHEDULER-run ops: a miss
        raises Requeue, freeing the worker for other PGs while peering
        holds this one (ShardedOpWQ's requeue-on-lock-miss behavior —
        two writes to a peering PG must not starve the whole op pool)."""
        lk = self._pg_lock(pool_id, ps)
        if not lk.acquire(timeout=timeout):
            raise Requeue()
        try:
            yield
        finally:
            lk.release()

    def _pg_lock(self, pool_id: int, ps: int):
        with self._pg_locks_guard:
            lk = self._pg_locks.get((pool_id, ps))
            if lk is None:
                lk = self._pg_locks[(pool_id, ps)] = \
                    make_rlock("osd::pg")
            return lk

    def _h_ec_write(self, msg: Dict) -> Dict:
        # the RMW coordinator is control logic, NOT a store op: running
        # it on the worker pool would deadlock (its own sub-ops submit
        # to the same pool, and two RMWs gathering from each other's
        # OSDs would hold every worker).  Its shard reads/writes are
        # the scheduled, QoS-governed ops.
        return self._do_ec_write(msg)

    def _fanout(self):
        """Persistent replica fan-out pool (per-op thread spawn was a
        measurable slice of write latency)."""
        with self._lock:
            pool = getattr(self, "_fanout_pool", None)
            if pool is None:
                from concurrent.futures import ThreadPoolExecutor

                pool = self._fanout_pool = ThreadPoolExecutor(
                    max_workers=16,
                    thread_name_prefix=f"osd{self.id}-fanout")
            return pool

    def _map_for_op(self, msg: Dict):
        """Epoch-tagged op handling (the reference OSD requests newer
        maps when an op's client epoch exceeds its own,
        OSD::require_same_or_newer_map): if the sender has seen a
        newer epoch, catch up before deciding primariness/pools —
        otherwise a freshly created pool 'does not exist' here until
        the next push arrives."""
        e = int(msg.get("epoch", 0))
        if e > self.epoch:
            self._catch_up(e, {})
        with self._lock:
            return self.map

    def _h_rep_write(self, msg: Dict) -> Dict:
        """Primary-coordinated replicated write (the PrimaryLogPG
        do_op -> ReplicatedBackend submit_transaction -> MOSDRepOp
        fan-out): ONE client round trip; the primary stamps the
        version under the PG lock and pushes replicas in PARALLEL.
        Replaces the client writing each replica itself — which cost
        size serial RTTs and left version stamping at the client's
        wall clock."""
        pool_id, ps = int(msg["pool"]), int(msg["ps"])
        oid = msg["oid"]
        data = bytes(msg["data"])
        m = self._map_for_op(msg)
        if m is None:
            return {"error": "no map"}
        pool = m.pools.get(pool_id)
        if pool is None:
            return {"error": f"no pool {pool_id}"}
        up, _p, acting, _ap = self.pg_up_acting(pool_id, ps)
        members = acting if acting else up
        prim = next((o for o in members if self._alive(o)), None)
        if prim != self.id:
            return {"error": "not primary", "primary": prim,
                    "epoch": self.epoch}

        with self._pg_lock(pool_id, ps):
            v = msg.get("v") or make_version(self.epoch)
            # the serving primary's epoch is the PG's interval
            # authority (the reference stamps eversion_t at the
            # primary): a client proposing a stale-epoch version must
            # never mint one that loses to data already written in a
            # newer interval whose holders happen to be down right
            # now — that acks a write which a later revive+peering
            # pass silently rolls back (thrash acked-write loss)
            now_v = make_version(self.epoch)
            if v < now_v:
                v = now_v
            cid = pg_cid(pool_id, ps)
            curb = self.store.getattr(cid, f"{oid}.s0", "v") \
                if self.store.collection_exists(cid) else None
            if curb is not None and v <= curb.decode():
                v = bump(curb.decode())
            targets = [o for o in dict.fromkeys(members)
                       if o >= 0 and (o == self.id or self._alive(o))]
            # fan-out workers adopt this handler's span so every
            # replica push joins the op's trace
            parent_span = self.tracer.current()
            for _restamp in range(3):
                replies: Dict[int, Optional[Dict]] = {}

                def push(o):
                    with self.tracer.scope(parent_span):
                        replies[o] = self._push_shard(
                            pool_id, ps, o, oid, 0, data, len(data),
                            v, qos="client")

                others = [o for o in targets if o != self.id]
                futs = [self._fanout().submit(push, o)
                        for o in others]
                push(self.id)  # local write on this thread
                for f in futs:
                    try:
                        f.result(timeout=8)
                    except Exception:
                        pass
                landed, newest = 0, None
                for o, rep in replies.items():
                    if rep is None or not rep.get("ok"):
                        continue
                    if rep.get("superseded"):
                        newest = max(newest or "",
                                     rep.get("cur") or "")
                    else:
                        landed += 1
                if newest is None:
                    break
                v = bump(newest)
            if landed < min(pool.min_size, len(targets)):
                return {"error": f"only {landed} of "
                                 f"{pool.min_size} required replicas "
                                 f"persisted"}
            if landed < len(targets):
                # min_size acked (any full replica can serve the
                # data, unlike EC shards) — but a member missed the
                # write: re-replicate now, not at the next periodic
                # recovery pass
                self._recover_wake.set()
            self.pc.inc("ops_w")
            self._account_io(pool_id, ps, wr_ops=1,
                             wr_bytes=len(data))
            return {"ok": True, "v": v,
                    "degraded": landed < pool.size}

    def _do_ec_write(self, msg: Dict) -> Dict:
        """The ECBackend::start_rmw role (ECBackend.cc:1876-1976 +
        ECTransaction.cc:202 overwrite): the PG PRIMARY serializes
        partial writes under the PG lock — read the affected object
        (any k shards, degraded reads included), merge the byte range,
        re-encode every position at a fresh version, distribute.  The
        per-object version total order doubles as the PG-log
        serialization of the op."""
        import numpy as np

        pool_id, ps = int(msg["pool"]), int(msg["ps"])
        oid = msg["oid"]
        offset = int(msg["offset"])
        # zero-copy staging: a view into the pooled recv segment is
        # fine here — every use below copies it into the merge buffer
        # before this handler (and thus the segment's lifetime) ends
        data = msg["data"]
        m = self._map_for_op(msg)
        if m is None:
            return {"error": "no map"}
        pool = m.pools.get(pool_id)
        if pool is None:
            return {"error": f"no pool {pool_id}"}
        up, _p, acting, _ap = self.pg_up_acting(pool_id, ps)
        members = acting if acting else up
        prim = next((o for o in members if self._alive(o)), None)
        if prim != self.id:
            # stale client map: tell it where the primary is
            return {"error": "not primary", "primary": prim,
                    "epoch": self.epoch}
        code = self._code_for(pool)
        if code is None:
            return {"error": "not an ec pool"}

        with self._pg_lock(pool_id, ps):
            if msg.get("full"):
                # whole-object write: replaces content, no read-merge
                buf = bytearray(data)
                size = len(buf)
            else:
                base = self._gather_object(pool_id, ps, oid, up, code)
                size = max(len(base), offset + len(data))
                buf = bytearray(size)  # zero-fill holes
                buf[:len(base)] = base
                buf[offset:offset + len(data)] = data
            v = msg.get("v") or make_version(self.epoch)
            # primary-epoch floor, as in the replicated path: a
            # stale-epoch client proposal must not undercut versions
            # minted in a newer interval (down-holder rollback class)
            now_v = make_version(self.epoch)
            if v < now_v:
                v = now_v
            # PRIMARY-side version floor: the stamped version must
            # exceed what is stored, or a client with a lagging clock
            # writes a version that loses last-writer-wins to data it
            # itself read (the reference stamps eversion_t at the
            # primary for the same reason).  The primary's own shard
            # is the floor source — it holds the newest acked version
            # whenever it is not itself degraded.
            mypos = next((p for p, o in enumerate(up)
                          if o == self.id), None)
            if mypos is not None:
                cid = pg_cid(pool_id, ps)
                curb = self.store.getattr(
                    cid, f"{oid}.s{mypos}", "v") \
                    if self.store.collection_exists(cid) else None
                if curb is not None and v <= curb.decode():
                    v = bump(curb.decode())
            n = code.get_chunk_count()
            k = code.get_data_chunk_count()
            # traced as a child of handle:ec_write when the client op
            # carries trace context — the per-stage latency the EC
            # characterization literature needs visible
            with self.tracer.start_span(
                    "ec.encode", require_parent=True,
                    tags={"bytes": len(buf), "k": k, "m": n - k}):
                # through the coalescer: concurrent writes to other
                # PGs of this pool share one batched dispatch
                chunks = self._ec_batcher.encode(code, range(n), buf)
                payloads = [np.asarray(chunks[p], np.uint8).tobytes()
                            for p in range(n)]
            # EC input-assembly copies: the mutable merge buffer (the
            # engine wraps it zero-copy via np.frombuffer) and one
            # device->host tobytes() per chunk — each a deliberate,
            # booked materialisation; the former bytes(buf) handoff
            # copy is gone (ROADMAP item 2)
            copytrack.book_pc(
                self._copy_pc, "ec_assembly",
                len(buf) + sum(len(p) for p in payloads),
                copies=1 + n)
            # distribute; a `superseded` reply means some holder has a
            # NEWER stored version our floor probe missed (our own
            # shard degraded) — counting it as landed would ack a
            # write that readers never see.  Re-stamp past the
            # reported version and redistribute.
            for _restamp in range(3):
                landed, newest, failed = 0, None, 0
                for pos, osd in enumerate(up):
                    if not (osd == self.id or self._alive(osd)):
                        continue  # peering recovers it at version v
                    rep = self._push_shard(pool_id, ps, osd, oid, pos,
                                           payloads[pos], size, v,
                                           qos="client")
                    if rep is None or not rep.get("ok"):
                        failed += 1
                        continue
                    if rep.get("superseded"):
                        newest = max(newest or "",
                                     rep.get("cur") or "")
                    else:
                        landed += 1
                if newest is None:
                    break
                v = bump(newest)
            if failed:
                # a reachable member missed its shard: the acked
                # version is down to (or near) zero erasure margin,
                # and the in-place overwrite already consumed the
                # previous version on the positions that DID land.
                # The reference fails the whole op here (ECBackend
                # waits out every sub-op) — but it can afford to: its
                # PG log carries rollback info, so the landed
                # sub-writes unwind on peering.  Without rollback,
                # erroring would send the client through retry rounds
                # that each land MORE in-place partials (every write
                # during a dead-but-map-up member window fails), and
                # it is those stacked partials that erase the last
                # acked version's >= k coverage.  So: ack at >= k,
                # and wake recovery NOW to re-decode the missing
                # shard and restore the margin.
                self._recover_wake.set()
            if landed < k:
                return {"error": f"only {landed} of {k} required "
                                 f"shards persisted"}
            self.pc.inc("ops_w")
            self._account_io(
                pool_id, ps, wr_ops=1, wr_bytes=len(buf),
                ec_encode_ops=1,
                ec_encode_bytes=sum(len(p) for p in payloads))
            return {"ok": True, "v": v, "size": size,
                    "degraded": landed < n}

    def _gather_object(self, pool_id: int, ps: int, oid: str,
                       up: List[int], code) -> bytes:
        """Read the full current object: any k positional shards at
        the newest mutually-consistent version, decoded and trimmed —
        the read-before-overwrite of ECBackend.cc:1963.  Returns b""
        for a not-yet-existing object."""
        import numpy as np

        cid = pg_cid(pool_id, ps)
        k = code.get_data_chunk_count()
        got: Dict[int, Tuple[str, bytes, int]] = {}
        for pos, osd in enumerate(up):
            rep = self._read_shard_from(osd, pool_id, ps, oid, pos,
                                        qos="client")
            if rep is not None:
                got[pos] = rep
        if not got:
            return b""
        best_v = max(v for v, _d, _s in got.values())
        chunks = {pos: np.frombuffer(d, np.uint8)
                  for pos, (v, d, s) in got.items() if v == best_v}
        size = next(s for v, _d, s in got.values() if v == best_v)
        if len(chunks) < k:
            raise OSError(f"pg {cid} {oid}: only {len(chunks)} of "
                          f"{k} shards readable for rmw")
        out = code.decode(set(range(k)), chunks)
        data = np.concatenate([np.asarray(out[i], np.uint8)
                               for i in range(k)]).tobytes()
        return data[:size]


    def _read_shard_from(self, osd: int, pool_id: int, ps: int,
                         oid: str, pos: int,
                         qos: str = "recovery",
                         ranges: Optional[List[Tuple[int, int]]]
                         = None):
        """One shard read, local store or peer RPC — the single fetch
        primitive behind RMW gathers and both recovery paths.
        ``ranges`` asks for a concatenation of (offset, length) slices
        of the shard (the CLAY repair-sub-chunk read).  Returns
        (version, data, size) or None."""
        from ..ec.stripe import crc32c

        cid = pg_cid(pool_id, ps)
        if osd == self.id:
            try:
                data = self.store.read(cid, f"{oid}.s{pos}")
            except (KeyError, OSError):
                return None
            stored = self.store.getattr(cid, f"{oid}.s{pos}", "crc")
            if stored is not None and int(stored) != crc32c(data):
                # local bit rot: unusable as a decode input — drop it
                # for repair like the remote read path does
                self._mark_shard_bad(pool_id, ps, oid, pos)
                return None
            v = (self.store.getattr(cid, f"{oid}.s{pos}", "v")
                 or b"").decode()
            size = int(self.store.getattr(cid, f"{oid}.s{pos}",
                                          "size") or b"0")
            if ranges:
                data = b"".join(bytes(data[off:off + ln])
                                for off, ln in ranges)
            return v, data, size
        if not self._alive(osd):
            return None
        msg = {"type": "shard_read", "pool": pool_id, "ps": ps,
               "oid": oid, "shard": pos, "qos_class": qos}
        if ranges:
            msg["ranges"] = [[int(off), int(ln)]
                             for off, ln in ranges]
        try:
            got = self.msgr.call(self.osd_addrs[osd], msg, timeout=5)
        except (TimeoutError, OSError):
            return None
        if "load" in got:
            # the helper's scheduler depth rides every reply: the
            # ledger's remote half of the load signal
            self.rec_ledger.note_load(osd, got["load"])
        if "data" in got:
            return (got.get("v") or "", bytes(got["data"]),
                    int(got.get("size", 0)))
        return None

    def _pg_local_info(self, pool_id: int, ps: int) -> Dict:
        """Fold the PG log + store into the pg_info_t this OSD reports
        during peering: last_update, and per object its newest logged
        version, tombstone flag, size, and ``shards`` — which shard
        POSITIONS this OSD actually holds and at which version.  The
        position map is what makes peering correct across remaps: an
        EC member that moved from position 3 to 2 still holds (and can
        serve) its old s3 while missing s2."""
        cid = pg_cid(pool_id, ps)
        objects: Dict[str, Dict] = {}
        last_update = NULL_VERSION
        if self.store.collection_exists(cid):
            for key, raw in sorted(
                    self.store.omap_get(cid, "pglog").items()):
                try:
                    rec = PgLogEntry.decode_blob(raw)
                except MalformedInput:
                    continue
                v = rec.v or NULL_VERSION
                if not rec.oid:
                    continue
                oid = rec.oid
                cur = objects.get(oid)
                if cur is None or v >= cur["v"]:
                    objects[oid] = {
                        "v": v,
                        "deleted": rec.deleted,
                        "size": rec.size, "shards": {}}
                if v > last_update:
                    last_update = v
            # what the store actually holds, per position and version
            # (the log may claim shards scrub-repair dropped, and may
            # miss objects imported without log entries)
            for name in self.store.list_objects(cid):
                if name == "pglog" or ".s" not in name:
                    continue
                oid, _, pos = name.rpartition(".s")
                ver = self.store.getattr(cid, name, "v")
                vpos = ver.decode() if ver else NULL_VERSION
                if oid not in objects:
                    size = self.store.getattr(cid, name, "size") \
                        or b"0"
                    objects[oid] = {"v": vpos, "deleted": False,
                                    "size": int(size), "shards": {}}
                objects[oid]["shards"][pos] = vpos
        return {"osd": self.id, "epoch": self.epoch,
                "last_update": last_update, "objects": objects}

    def _h_pg_info(self, msg: Dict) -> Dict:
        return self._pg_local_info(int(msg["pool"]), int(msg["ps"]))

    def _log_keys_above(self, cid: str, oid: str, v: str):
        """PG-log keys recording ``oid`` at versions above ``v`` (the
        torn entries an authoritative rollback must erase)."""
        drop = []
        if not self.store.collection_exists(cid):
            return drop
        for key, raw in self.store.omap_get(cid, "pglog").items():
            try:
                rec = PgLogEntry.decode_blob(raw)
            except MalformedInput:
                continue
            if rec.oid == oid and rec.v > v:
                drop.append(key)
        return drop

    def _h_pg_log_trim(self, msg: Dict) -> None:
        """Drop log entries superseded by a newer entry for the same
        object (PGLog::trim): the per-object newest record — tombstones
        included — is what peering consumes; history behind it is dead
        weight in omap space."""
        pool_id, ps = int(msg["pool"]), int(msg["ps"])
        cid = pg_cid(pool_id, ps)
        with self._pg_lock(pool_id, ps):
            if not self.store.collection_exists(cid):
                return None
            log = self.store.omap_get(cid, "pglog")
            newest: Dict[str, str] = {}
            for key, raw in log.items():
                try:
                    rec = PgLogEntry.decode_blob(raw)
                except MalformedInput:
                    continue
                if rec.oid and rec.v >= newest.get(rec.oid, ""):
                    newest[rec.oid] = rec.v
            drop = []
            for key, raw in log.items():
                try:
                    rec = PgLogEntry.decode_blob(raw)
                except MalformedInput:
                    drop.append(key)
                    continue
                if rec.v < newest.get(rec.oid, ""):
                    drop.append(key)
            if drop:
                txn = Transaction()
                txn.omap_rmkeys(cid, "pglog", drop)
                self.store.queue_transaction(txn)
        return None

    def _h_pg_poke(self, _msg: Dict) -> None:
        """A peer lost a shard (scrub repair) or wants re-peering."""
        self._recover_wake.set()
        return None

    def _h_recovery_reserve(self, msg: Dict) -> Dict:
        """Remote recovery reservation (the AsyncReserver
        remote_reserver surface, MRecoveryReserve role): a primary
        about to push recovery writes at this OSD asks for a slot
        first, so concurrent recoveries onto one OSD stay bounded by
        ``osd_max_recovery_ops``.  Rides the control lane — a full op
        pool must not deadlock reservation traffic."""
        if msg.get("release"):
            self.rec_reserver.release()
            return {"ok": True}
        if self.rec_reserver.try_acquire():
            return {"ok": True, "granted": True}
        self.rec_pc.inc("remote_denials")
        return {"ok": True, "granted": False}

    # -- stray PGs (MOSDPGNotify role) ---------------------------------
    def _h_pg_stray(self, msg: Dict) -> None:
        """A former member still holds this PG's data: include it in
        peering so remapped-away shards stay reachable."""
        key = (int(msg["pool"]), int(msg["ps"]))
        with self._lock:
            self._strays.setdefault(key, set()).add(int(msg["osd"]))
        self._recover_wake.set()
        return None

    def _h_pg_purge(self, msg: Dict) -> Dict:
        """The primary declared the PG clean: this stray's copy is no
        longer needed (PG removal)."""
        cid = pg_cid(msg["pool"], msg["ps"])
        with self._lock:
            m = self.map
        if m is None:
            # without a map this osd cannot know its membership — a
            # late/duplicate purge must never delete a PG it is about
            # to serve
            return {"ok": False, "error": "no map yet"}
        up, _p, acting, _ap = m.pg_to_up_acting_osds(
            int(msg["pool"]), int(msg["ps"]))
        if self.id in up or self.id in acting:
            return {"ok": False, "error": "still a member"}
        self._drop_pg_collection(int(msg["pool"]), int(msg["ps"]))
        return {"ok": True}

    def _drop_pg_collection(self, pool_id: int, ps: int) -> None:
        """Remove a whole PG (objects first: ObjectStore refuses to
        drop non-empty collections) under the PG lock."""
        cid = pg_cid(pool_id, ps)
        with self._pg_lock(pool_id, ps):
            if not self.store.collection_exists(cid):
                return
            txn = Transaction()
            for name in self.store.list_objects(cid):
                txn.remove(cid, name)
            txn.remove_collection(cid)
            self.store.queue_transaction(txn)

    def _report_strays(self, m) -> None:
        """Per epoch: any local PG collection this osd no longer
        serves gets announced to the PG's current primary."""
        for cid in self.store.list_collections():
            try:
                pool_s, ps_s = cid.split(".", 1)
                pool_id, ps = int(pool_s), int(ps_s)
            except ValueError:
                continue
            if pool_id not in m.pools:
                # the pool was deleted: its PGs go with it (the
                # reference's PG removal on pool delete)
                self._drop_pg_collection(pool_id, ps)
                continue
            up, _p, acting, _ap = m.pg_to_up_acting_osds(pool_id, ps)
            if self.id in up or self.id in acting:
                continue
            prim = next((o for o in up if self._alive(o)), None)
            if prim is not None and prim != self.id:
                self.msgr.send(self.osd_addrs[prim],
                               {"type": "pg_stray", "pool": pool_id,
                                "ps": ps, "osd": self.id})

    # -- watch/notify (librados watch/notify, src/osd/Watch.cc) --------
    def _h_watch(self, msg: Dict) -> Dict:
        key = (pg_cid(msg["pool"], msg["ps"]), msg["oid"])
        with self._lock:
            ws = self._watchers.setdefault(key, {})
            ws[msg["watcher"]] = tuple(msg["addr"])
            count = len(ws)  # under the lock: a racing unwatch may
            # pop the key before we return
        return {"ok": True, "watchers": count}

    def _h_unwatch(self, msg: Dict) -> Dict:
        key = (pg_cid(msg["pool"], msg["ps"]), msg["oid"])
        with self._lock:
            ws = self._watchers.get(key, {})
            ws.pop(msg["watcher"], None)
            if not ws:
                self._watchers.pop(key, None)
        return {"ok": True}

    def _h_notify(self, msg: Dict) -> Dict:
        """Fan the notify out to every watcher and collect acks within
        the timeout — the rados_notify round-trip contract."""
        key = (pg_cid(msg["pool"], msg["ps"]), msg["oid"])
        with self._lock:
            watchers = dict(self._watchers.get(key, {}))
        acks, missed = [], []
        note = {"type": "watch_notify", "pool": msg["pool"],
                "ps": msg["ps"], "oid": msg["oid"],
                "payload": msg.get("payload"),
                "notifier": msg.get("frm")}
        deadline = time.monotonic() + float(msg.get("timeout", 5.0))
        for name, addr in watchers.items():
            left = max(0.2, deadline - time.monotonic())
            try:
                rep = self.msgr.call(addr, dict(note),
                                     timeout=min(5.0, left))
                (acks if rep.get("ok") else missed).append(name)
            except TimeoutError:
                missed.append(name)  # slow != gone: keep the watch
            except OSError:
                missed.append(name)
                # connection refused = the watcher is gone; a pruned
                # live client re-watches on the next map epoch
                with self._lock:
                    self._watchers.get(key, {}).pop(name, None)
        return {"ok": True, "acks": acks, "missed": missed}

    def _h_pg_list(self, msg: Dict) -> Dict:
        cid = pg_cid(msg["pool"], msg["ps"])
        out: Dict[str, int] = {}
        for name in self.store.list_objects(cid):
            if name == "pglog" or ".s" not in name:
                continue
            oid, _, shard = name.rpartition(".s")
            size = self.store.getattr(cid, name, "size") or b"0"
            out[oid] = int(size)
        return {"objects": out}

    def _h_pg_scrub(self, msg: Dict) -> Dict:
        return self.sched.submit("scrub",
                                 lambda: self._do_pg_scrub(msg))

    def _do_pg_scrub(self, msg: Dict) -> Dict:
        """Deep scrub of one PG: recompute every local shard's crc32c
        and compare with the stored write-time digest (the
        HashInfo-backed scrub of the reference's deep-scrub flow).
        Each object's (data, crc) pair reads under the PG lock: a
        racing write commits both in one transaction, and reading them
        torn would flag — and auto-repair would DROP — a healthy
        shard."""
        from ..ec.stripe import crc32c

        cid = pg_cid(msg["pool"], msg["ps"])
        inconsistent: List[str] = []
        digests: Dict[str, int] = {}
        with self._pg_lock_bounded(int(msg["pool"]), int(msg["ps"])):
            if self.store.collection_exists(cid):
                for name in self.store.list_objects(cid):
                    if name == "pglog":
                        continue
                    data = self.store.read(cid, name)
                    got = crc32c(data)
                    stored = self.store.getattr(cid, name, "crc")
                    digests[name] = got
                    if stored is not None and int(stored) != got:
                        inconsistent.append(name)
        return {"osd": self.id, "inconsistent": inconsistent,
                "digests": digests}

    def _h_shard_remove(self, msg: Dict) -> Dict:
        """Drop a (corrupt) shard so recovery rebuilds it — the repair
        half of scrub (test-erasure-eio.sh flow).  Recovery is
        primary-driven, so poke the PG's primary to re-peer."""
        cid = pg_cid(msg["pool"], msg["ps"])
        name = f"{msg['oid']}.s{msg['shard']}"
        if self.store.stat(cid, name) is not None:
            self.store.queue_transaction(
                Transaction().remove(cid, name))
        self._recover_wake.set()
        with self._lock:
            m = self.map
        if m is not None:
            up, _p, _a, _ap = m.pg_to_up_acting_osds(
                int(msg["pool"]), int(msg["ps"]))
            prim = next((o for o in up if self._alive(o)), None)
            if prim is not None and prim != self.id:
                self.msgr.send(self.osd_addrs[prim],
                               {"type": "pg_poke"})
        return {"ok": True}

    def _mark_shard_bad(self, pool_id: int, ps: int, oid: str,
                        shard: int) -> None:
        """An unreadable shard is marked for repair: drop it (its
        bytes can no longer be trusted) and poke the PG's primary so
        recovery re-decodes it from the survivors — the degraded read
        already served the client; this closes the loop on the
        damage."""
        try:
            self._h_shard_remove({"pool": pool_id, "ps": ps,
                                  "oid": oid, "shard": shard})
        except Exception as e:
            # best-effort: a failed repair mark leaves the shard for
            # the next scrub pass, it must not fail the read that
            # already degraded cleanly
            self.log.dout(5, f"mark-bad {pool_id}.{ps}/{oid}."
                             f"s{shard} failed: {e!r}")

    def _h_status(self, _msg: Dict) -> Dict:
        with self._lock:
            return {"osd": self.id, "epoch": self.epoch,
                    "collections": self.store.list_collections(),
                    "perf": self.pc.dump(),
                    "qos_served": dict(self.sched.served),
                    "qos_depths": self.sched.depths(),
                    "historic_ops": self.optracker.dump_historic_ops()}

    # -- heartbeats ----------------------------------------------------
    def _beat_loop(self) -> None:
        interval = self.ctx.conf["osd_heartbeat_interval"]
        stat_interval = self.ctx.conf["osd_pg_stat_report_interval"]
        last_stats = 0.0
        while self._running:
            # mon_send reaches every quorum member: peons forward to
            # the leader, so liveness survives any single monitor death
            # — carrying this daemon's SLO state: in-flight ops past
            # osd_op_complaint_time and heartbeat-RTT threshold
            # breaches, the raw material of the monitor's SLOW_OPS /
            # OSD_SLOW_PING_TIME health folds
            beat: Dict = {"type": "heartbeat", "osd": self.id}
            try:
                slow = self.optracker.slow_summary()
                if slow["count"]:
                    beat["slow_ops"] = slow
                pings = self.hb.ping_breaches()
                if pings:
                    beat["slow_pings"] = pings
            except Exception as e:
                # the beacon is liveness first; SLO cargo never gets
                # to break it
                self.log.dout(5, f"slo beacon cargo failed: {e}")
            self.mon_send(beat)
            # a monitor that deferred our boot (markdown dampening) or
            # marked us down while our re-boot raced a commit leaves
            # the map showing us down with no new epoch to react to:
            # keep re-booting at beacon cadence until the map agrees
            with self._lock:
                down = self.map is not None \
                    and not self.map.is_up(self.id)
            if down:
                self.mon_send({"type": "boot", "osd": self.id,
                               "addr": list(self.addr)})
            # the continuous-stats cadence rides the beat thread: PG
            # io/recovery counters reach the monitors between peering
            # passes, so pool rates resolve at beacon granularity
            if stat_interval > 0 and \
                    time.monotonic() - last_stats >= stat_interval:
                last_stats = time.monotonic()
                try:
                    self._stat_beacon_pass()
                except Exception as e:
                    self.log.dout(5, f"stat beacon pass failed: {e}")
            # waits on the shutdown event rather than sleeping: a
            # teardown mid-interval returns immediately instead of
            # holding shutdown() hostage for up to a full beat
            if self._shutdown_ev.wait(interval):
                return

    # -- recovery (mark-down -> remap -> recover) ----------------------
    def _recover_loop(self) -> None:
        retry_pending = False
        last_pass = 0.0
        while self._running:
            fired = self._recover_wake.wait(timeout=5.0)
            self._recover_wake.clear()
            if not self._running:
                break
            if not fired and not retry_pending and \
                    time.monotonic() - last_pass < 20.0:
                continue  # idle; a periodic pass still runs every
                # ~20s so pg_stats reach monitors that joined late
                # and missed pokes self-heal
            try:
                self._check_recovery()
                retry_pending = False
                last_pass = time.monotonic()
            except Exception as e:
                self.log.derr(f"recovery pass failed: {e}")
                retry_pending = True  # peers may come back; retry

    def _alive(self, osd: int) -> bool:
        return osd >= 0 and self.map is not None \
            and self.map.is_up(osd) and osd in self.osd_addrs

    def _check_recovery(self) -> None:
        with self._lock:
            m = self.map
        if m is None:
            return
        self._report_strays(m)
        for pool_id, pool in m.pools.items():
            for ps in range(pool.pg_num):
                up, _p, acting, _ap = m.pg_to_up_acting_osds(pool_id,
                                                             ps)
                members = [o for o in up if self._alive(o)]
                if not members or members[0] != self.id:
                    continue  # peering + recovery are the primary's job
                self._peer_pg(m, pool_id, pool, ps, up, acting)
                self._maybe_scrub(pool_id, ps, up)

    def _maybe_scrub(self, pool_id: int, ps: int,
                     up: List[int]) -> None:
        """Scheduled deep scrub (PG::sched_scrub / osd_scrub_* role):
        the primary periodically asks every member to recompute shard
        digests; mismatching shards are dropped (auto-repair) so the
        next peering pass re-decodes them from survivors."""
        interval = self.ctx.conf["osd_scrub_interval"]
        if interval <= 0:
            return
        key = (pool_id, ps)
        now = time.monotonic()
        if key not in self._last_scrub:
            # jittered first deadline: without it every PG scrubs on
            # the first pass after (re)start and the whole cluster
            # stays phase-aligned forever (the reference randomizes
            # scrub deadlines for the same reason)
            import random

            self._last_scrub[key] = now - random.random() * interval
            return
        if now - self._last_scrub[key] < interval:
            return
        # one sweep at a time (osd_max_scrubs role), claimed BEFORE
        # spawning: a backlog of due PGs stays due (unstamped) instead
        # of piling up blocked threads that later run with stale
        # membership
        if not self._scrub_slots.acquire(blocking=False):
            return
        self._last_scrub[key] = now
        # off the recovery thread: a slow member's 10s scrub RPC must
        # never delay re-peering of other PGs
        try:
            threading.Thread(target=self._scrub_pg,
                             args=(pool_id, ps, list(up)),
                             daemon=True,
                             name=f"osd{self.id}-scrub").start()
        except RuntimeError:
            # thread exhaustion: give the slot back or scrubbing would
            # be disabled forever
            self._scrub_slots.release()
            self._last_scrub.pop(key, None)
            raise

    def _scrub_pg(self, pool_id: int, ps: int,
                  up: List[int]) -> None:
        try:
            self._scrub_pg_inner(pool_id, ps, up)
        except Exception as e:
            self.log.derr(f"scrub pg {pool_id}.{ps} failed: {e!r}")
            # retry at the next pass, not a full interval later
            interval = self.ctx.conf["osd_scrub_interval"]
            self._last_scrub[(pool_id, ps)] = \
                time.monotonic() - interval
        finally:
            self._scrub_slots.release()

    def _scrub_pg_inner(self, pool_id: int, ps: int,
                        up: List[int]) -> None:
        repair = self.ctx.conf["osd_scrub_auto_repair"]
        for o in up:
            if o == self.id:
                # through the scheduler like remote scrubs: scrub I/O
                # is dmClock-tagged on every member equally
                got = self._h_pg_scrub({"pool": pool_id, "ps": ps})
            elif self._alive(o):
                try:
                    got = self.msgr.call(
                        self.osd_addrs[o],
                        {"type": "pg_scrub", "pool": pool_id,
                         "ps": ps}, timeout=10)
                except (TimeoutError, OSError):
                    continue
            else:
                continue
            for name in got.get("inconsistent", []):
                self.log.derr(f"scrub: pg {pool_id}.{ps} {name} "
                              f"crc mismatch on osd.{o}")
                if not repair:
                    continue
                oid, _, shard = name.rpartition(".s")
                msg = {"type": "shard_remove", "pool": pool_id,
                       "ps": ps, "oid": oid, "shard": int(shard)}
                try:
                    if o == self.id:
                        self._h_shard_remove(msg)
                    else:
                        self.msgr.call(self.osd_addrs[o], msg,
                                       timeout=5)
                except (TimeoutError, OSError):
                    pass
                self._recover_wake.set()

    # -- peering (PeeringState / PGLog roles) --------------------------
    def _peer_pg(self, m, pool_id: int, pool, ps: int,
                 up: List[int], acting: List[int]) -> None:
        """Collect infos, merge to the authoritative per-object state,
        drive pulls/pushes/deletes, manage the pg_temp overlay.

        Holds the PG lock for the whole pass: client EC ops route
        through the primary and take the same lock, so peering's
        rollback decisions can never interleave with a half-landed
        write (the reference gates ops on peering state the same
        way).  Cross-daemon shard pushes take only the REMOTE pg
        lock transiently — per-(osd, pg) locks cannot cycle because a
        PG has one primary."""
        # gather infos OUTSIDE the PG lock: up to members*5s of RPC
        # must not stall client ops; the lock-protected phase re-checks
        # the epoch and every mutation is CAS-guarded, so stale infos
        # degrade to no-ops, never to wrong rollbacks
        epoch_at_gather = self.epoch
        with self._lock:
            strays = set(self._strays.get((pool_id, ps), set()))
        members = sorted({o for o in (list(up) + list(acting)
                                      + list(strays))
                          if o == self.id or self._alive(o)})
        infos: Dict[int, Dict] = {}
        for o in members:
            if o == self.id:
                infos[o] = self._pg_local_info(pool_id, ps)
                continue
            try:
                infos[o] = self.msgr.call(
                    self.osd_addrs[o],
                    {"type": "pg_info", "pool": pool_id, "ps": ps},
                    timeout=5)
            except (TimeoutError, OSError):
                continue
            if int(infos[o].get("epoch", 0)) > self.epoch:
                # a member runs a newer map: this primary may already
                # be deposed — abort; the map install re-wakes peering
                # (shrinks the dual-primary window during transitions)
                self._recover_wake.set()
                return
        with self._pg_lock(pool_id, ps):
            if self.epoch != epoch_at_gather:
                self._recover_wake.set()  # re-peer on the new map
                return
            # local state may have advanced while gathering (a client
            # write completed): refresh our own info under the lock
            infos[self.id] = self._pg_local_info(pool_id, ps)
            self._peer_pg_locked(m, pool_id, pool, ps, up, acting,
                                 members, strays, infos)

    def _peer_pg_locked(self, m, pool_id: int, pool, ps: int,
                        up: List[int], acting: List[int],
                        members, strays, infos) -> None:
        cid = pg_cid(pool_id, ps)
        code = self._code_for(pool)
        # merge: newest version wins per object (delete tombstones
        # included) — the result of authoritative-log election + merge
        merged: Dict[str, Dict] = {}
        for o, info in infos.items():
            for oid, rec in info.get("objects", {}).items():
                cur = merged.get(oid)
                if cur is None or rec["v"] > cur["v"]:
                    merged[oid] = dict(rec)
        my = infos.get(self.id, {}).get("objects", {})

        # the degraded state must be VISIBLE, not just transited: a
        # small recovery completes within one pass, and only reporting
        # the end-of-pass verdict would hide the whole
        # degraded->recovering->clean arc from the PGMap/progress
        # plane.  Estimate the pre-pass deficit and beacon it before
        # any recovery work (the estimate may count a torn write the
        # pass then rolls back — transient, corrected by the final
        # beacon below).
        pre_degraded = 0
        for oid, rec in merged.items():
            if rec.get("deleted"):
                continue
            positions = enumerate(up) if code is not None \
                else [(0, o) for o in up]
            if any(self._shard_v_of(infos, o, oid, pos) != rec["v"]
                   for pos, o in positions):
                pre_degraded += 1
        if pre_degraded:
            n_live = len([o for o in up if self._alive(o)])
            pre_states = ["active"]
            if n_live < len(up):
                pre_states.append("undersized")
            pre_states += ["degraded", "recovering"]
            with self._lock:
                self._pg_states[(pool_id, ps)] = {
                    "state": "+".join(pre_states),
                    "objects": len([1 for r in merged.values()
                                    if not r.get("deleted")]),
                    "degraded_objects": pre_degraded}
            self._send_pg_stats(pool_id, ps)

        def shard_v(osd: int, oid: str, pos: int) -> str:
            return self._shard_v_of(infos, osd, oid, pos)

        # serving continuity: if this (new) primary is missing data,
        # point the PG at the best-covered holder via pg_temp while we
        # catch up
        i_am_behind = any(
            (not rec["deleted"])
            and shard_v(self.id, oid, 0) < rec["v"]
            for oid, rec in merged.items()) if code is None else False
        if i_am_behind and code is None:
            best = max((o for o in infos if o != self.id),
                       key=lambda o: infos[o].get("last_update",
                                                  NULL_VERSION),
                       default=None)
            if best is not None and \
                    infos[best].get("last_update", NULL_VERSION) > \
                    infos.get(self.id, {}).get("last_update",
                                               NULL_VERSION):
                # full acting set, best-covered holder first: reads
                # find the data, and writes during backfill keep the
                # pool's replication factor (and keep landing on up
                # members, so the next peering round sees them)
                acting_set = [best] + [o for o in up
                                       if o != best and self._alive(o)]
                self._set_pg_temp(pool_id, ps, acting_set)

        clean = True
        degraded_objs = 0  # objects needing recovery work this pass
        ec_groups: Dict[Tuple, List[Tuple[str, Dict]]] = {}
        rep_items: List[Tuple[str, Dict]] = []
        for oid, rec in merged.items():
            if code is not None:
                # EC: the authoritative version is the newest
                # RECOVERABLE one — >= k positions hold it somewhere.
                # A torn partial write (higher version, < k shards —
                # never acked) is ROLLED BACK, the reference's
                # divergent-entry rollback (PGLog::rewind_divergent).
                k = code.get_data_chunk_count()
                cover: Dict[str, Set[int]] = {}
                tombs: List[str] = []
                for o, info in infos.items():
                    orec = info.get("objects", {}).get(oid)
                    if not orec:
                        continue
                    if orec.get("deleted"):
                        tombs.append(orec["v"])
                    for pos_s, pv in orec.get("shards", {}).items():
                        if pv != NULL_VERSION:
                            cover.setdefault(pv, set()).add(
                                int(pos_s))
                best_write = max(
                    (v for v, poss in cover.items()
                     if len(poss) >= k), default=None)
                best_tomb = max(tombs, default=None)
                if best_tomb is not None and (
                        best_write is None or best_tomb > best_write):
                    for o, info in infos.items():
                        lrec = info.get("objects", {}).get(oid)
                        if not lrec or lrec.get("deleted"):
                            continue
                        if lrec["v"] < best_tomb:
                            self._send_delete(pool_id, ps, o, oid,
                                              best_tomb)
                        else:
                            # torn never-acked shards above the
                            # tombstone: CAS force-delete so the
                            # delete actually wins (finishing next
                            # pass keeps clean honest)
                            self._send_delete(
                                pool_id, ps, o, oid, best_tomb,
                                force=True, expect=lrec["v"])
                            clean = False
                    continue
                if best_write is None:
                    if cover:
                        clean = False
                        degraded_objs += 1
                        self.log.derr(
                            f"pg {cid} {oid}: no recoverable "
                            f"version (coverage "
                            f"{ {v: len(p) for v, p in cover.items()} })")
                    continue
                need = tuple(sorted(
                    pos for pos, o in enumerate(up)
                    if shard_v(o, oid, pos) != best_write))
                if not need:
                    continue
                degraded_objs += 1
                avail = tuple(sorted(cover[best_write]))
                rec = dict(rec, v=best_write)
                ec_groups.setdefault((need, avail, best_write),
                                     []).append((oid, rec))
                continue
            if rec["deleted"]:
                # propagate the tombstone: anyone still holding an
                # older live version drops it
                for o, info in infos.items():
                    lrec = info.get("objects", {}).get(oid)
                    if lrec and not lrec.get("deleted") \
                            and lrec["v"] < rec["v"]:
                        self._send_delete(pool_id, ps, o, oid,
                                          rec["v"])
                continue
            if any(shard_v(o, oid, 0) != rec["v"] for o in up):
                degraded_objs += 1
                rep_items.append((oid, rec))
        if rep_items or ec_groups:
            clean &= self._run_recovery(m, pool_id, pool, ps, up,
                                        rep_items, ec_groups, infos,
                                        shard_v, code)
        # PG state for the monitor's PGMap/health surface
        n_alive = len([o for o in up if self._alive(o)])
        want = len(up)
        states = ["active"]
        if n_alive < want:
            states.append("undersized")
        if not clean:
            states.append("degraded")
        else:
            states.append("clean")
        n_objects = len([1 for _oid, rec in merged.items()
                         if not rec.get("deleted")])
        with self._lock:
            self._pg_states[(pool_id, ps)] = {
                "state": "+".join(states), "objects": n_objects,
                "degraded_objects": 0 if clean else degraded_objs}
        self._send_pg_stats(pool_id, ps)
        if clean:
            self._set_pg_temp(pool_id, ps, [])
            # history behind each object's newest log record is dead
            # weight: trim it everywhere (PGLog::trim on clean)
            for o in members:
                msg_t = {"type": "pg_log_trim", "pool": pool_id,
                         "ps": ps}
                if o == self.id:
                    self._h_pg_log_trim(msg_t)
                elif self._alive(o):
                    self.msgr.send(self.osd_addrs[o], msg_t)
            # every up member holds everything: strays may drop their
            # copies (PG removal after clean)
            for o in strays:
                if o in up or o in acting or not self._alive(o):
                    continue
                try:
                    rep = self.msgr.call(
                        self.osd_addrs[o],
                        {"type": "pg_purge", "pool": pool_id,
                         "ps": ps}, timeout=5)
                    if rep.get("ok"):
                        with self._lock:
                            self._strays.get((pool_id, ps),
                                             set()).discard(o)
                except (TimeoutError, OSError):
                    pass

    @staticmethod
    def _shard_v_of(infos: Dict, osd: int, oid: str,
                    pos: int) -> str:
        return infos.get(osd, {}).get("objects", {}) \
            .get(oid, {}).get("shards", {}) \
            .get(str(pos), NULL_VERSION)

    # -- the recovery engine (reserved, pipelined, load-balanced) ------
    def _run_recovery(self, m, pool_id, pool, ps, up, rep_items,
                      ec_groups, infos, shard_v, code) -> bool:
        """One PG's recovery work for this peering pass, under the
        reservation/throttle plane: acquire a recovery slot on every
        alive push target (local slot + remote ``recovery_reserve``
        grants, the AsyncReserver local/remote pair) so concurrent
        primaries recovering onto one OSD stay bounded and client p99
        holds; then drive replicated pulls and the pipelined EC engine
        under the backfill throttle.  A reservation miss backs off
        briefly (jittered) and defers the PG to the next pass —
        recovery yields, it never stalls."""
        pc = self.rec_pc
        targets = sorted({o for o in list(up) + [self.id]
                          if o == self.id or self._alive(o)})
        granted = self._reserve_recovery(targets)
        bo = Backoff(base=0.05, cap=0.4, deadline=1.5)
        while granted is None:
            pc.inc("reservation_waits")
            if not bo.sleep():
                return False  # contended: the periodic pass retries
            granted = self._reserve_recovery(targets)
        try:
            ok = True
            for oid, rec in rep_items:
                if not self.backfill_throttle.get(timeout=5):
                    return False
                try:
                    ok &= self._recover_object(
                        m, pool_id, pool, ps, up, oid, rec, infos,
                        shard_v, code)
                finally:
                    self.backfill_throttle.put()
            if ec_groups:
                if not self.backfill_throttle.get(timeout=5):
                    return False
                try:
                    ok &= self._recover_ec_groups(
                        pool_id, ps, up, ec_groups, infos, shard_v,
                        code)
                finally:
                    self.backfill_throttle.put()
            return ok
        finally:
            self._release_recovery(granted)

    def _reserve_recovery(self, targets) -> Optional[List[int]]:
        """All-or-nothing slot acquisition in ascending OSD order
        (two primaries reserving each other cannot deadlock: failure
        releases everything and backs off).  An unreachable target is
        skipped — its pushes fail on their own; reservation must not
        stall the reachable rest."""
        granted: List[int] = []
        for o in targets:
            if o == self.id:
                if self.rec_reserver.try_acquire():
                    granted.append(o)
                    continue
                self._release_recovery(granted)
                return None
            try:
                rep = self.msgr.call(
                    self.osd_addrs[o],
                    {"type": "recovery_reserve", "osd": self.id},
                    timeout=5)
            except (TimeoutError, OSError):
                continue
            if rep.get("granted"):
                granted.append(o)
            else:
                self._release_recovery(granted)
                return None
        return granted

    def _release_recovery(self, granted) -> None:
        for o in granted:
            if o == self.id:
                self.rec_reserver.release()
                continue
            try:
                self.msgr.send(self.osd_addrs[o],
                               {"type": "recovery_reserve",
                                "osd": self.id, "release": True})
            except (KeyError, OSError):
                pass

    def _recovery_executor(self):
        """Dedicated small pool for pipelined helper gathers — NOT
        the replica fan-out pool: a gather submitting into the pool
        its caller occupies would deadlock at depth."""
        with self._lock:
            ex = getattr(self, "_recover_pool", None)
            if ex is None:
                from concurrent.futures import ThreadPoolExecutor

                ex = self._recover_pool = ThreadPoolExecutor(
                    max_workers=4,
                    thread_name_prefix=f"osd{self.id}-rec")
            return ex

    def _recover_ec_groups(self, pool_id, ps, up, ec_groups, infos,
                           shard_v, code) -> bool:
        """Pipelined multi-object EC recovery (RapidRAID's streaming
        model, arXiv:1207.6744): erasure-pattern groups split into
        bounded units of ``osd_recovery_batch_max_objects``; helper
        shard reads for unit N+1 stream on the gather pool while unit
        N's stripes decode and push on this thread.  Depth <= 1
        degrades to serial gather-then-decode (the drill's baseline
        knob)."""
        import itertools
        from collections import deque

        conf = self.ctx.conf
        pc = self.rec_pc
        depth = int(conf["osd_recovery_pipeline_depth"])
        batch_max = max(1, int(conf["osd_recovery_batch_max_objects"]))
        pace = float(conf["osd_recovery_sleep"])
        cid = pg_cid(pool_id, ps)
        ok = True
        units = []
        for (need, avail, v), items in ec_groups.items():
            strategy, plan = self._choose_ec_strategy(
                code, need, avail, items[0][0], v, infos, shard_v)
            if plan is None:
                self.log.derr(
                    f"pg {cid}: {len(items)} objects undecodable, "
                    f"pattern need={need} avail={avail}")
                ok = False
                continue
            for i in range(0, len(items), batch_max):
                units.append((need, avail, v, strategy, plan,
                              items[i:i + batch_max]))

        def gather(unit):
            return self._gather_ec_unit(pool_id, ps, unit, infos,
                                        shard_v, code)

        if depth <= 1:
            for unit in units:
                ok &= self._decode_push_ec_unit(
                    pool_id, ps, up, unit, gather(unit), infos,
                    shard_v, code)
                pc.inc("serial_batches")
                if pace > 0:
                    time.sleep(pace)  # the
                    # osd_recovery_sleep pacing knob, not retry pacing
            return ok
        ex = self._recovery_executor()
        pending: deque = deque()
        it = iter(units)
        for unit in itertools.islice(it, depth):
            pending.append((unit, ex.submit(gather, unit)))
        while pending:
            unit, fut = pending.popleft()
            nxt = next(it, None)
            if nxt is not None:
                # keep `depth` gathers in flight BEFORE decoding: the
                # next unit's helper reads overlap this unit's decode
                pending.append((nxt, ex.submit(gather, nxt)))
            try:
                gathered = fut.result(timeout=60)
            except Exception as e:
                self.log.derr(f"pg {cid}: recovery gather failed: "
                              f"{e!r}")
                ok = False
                continue
            ok &= self._decode_push_ec_unit(
                pool_id, ps, up, unit, gathered, infos, shard_v, code)
            pc.inc("pipelined_batches")
            if pace > 0:
                time.sleep(pace)  # fault-ok: the osd_recovery_sleep
                # pacing knob, not retry pacing
        return ok

    def _pos_load(self, oid: str, v: str, pos: int, infos,
                  shard_v) -> float:
        holders = [o for o in infos if shard_v(o, oid, pos) == v]
        if not holders:
            return float("inf")
        return min(self.rec_ledger.load(o) for o in holders)

    def _choose_ec_strategy(self, code, need, avail, rep_oid, v,
                            infos, shard_v):
        """Pick the repair strategy for one erasure-pattern group:
        CLAY 1/q-bandwidth repair when the profile and loss pattern
        allow it, LRC local-group repair when the layered minimum
        stays under k, full decode otherwise — and for full decode,
        prefer the k LEAST-LOADED feasible survivors over the
        first-k-up default.  Returns (strategy, plan): the plan is a
        sorted position list for full/lrc, the sub-chunk read plan
        dict for clay, or None when the pattern is undecodable."""
        k = code.get_data_chunk_count()
        want, have = set(need), set(avail)
        try:
            sub = code.get_sub_chunk_count()
        except Exception:
            sub = 1
        if len(want) == 1 and sub > 1 and hasattr(code, "is_repair"):
            try:  # wire-ok: EC plan math (minimum_to_decode), not a wire decode
                if code.is_repair(want, have):
                    return "clay", code.minimum_to_decode(want, have)
            except Exception:
                pass
        try:
            plan = code.minimum_to_decode(want, have)
        except Exception:
            return "full", None
        if len(plan) < k:
            return "lrc", sorted(plan)
        use = self._plan_full_use(code, want, have, rep_oid, v, infos,
                                  shard_v)
        return "full", use if use is not None else sorted(plan)[:k]

    def _plan_full_use(self, code, want, have, rep_oid, v, infos,
                       shard_v) -> Optional[List[int]]:
        """Least-loaded feasible survivor set for a full decode: rank
        positions by their best holder's ledger load and expand from
        the cheapest k until the code accepts the candidate set (MDS
        codes accept immediately; layered codes may need more)."""
        k = code.get_data_chunk_count()
        order = sorted(have, key=lambda p: (self._pos_load(
            rep_oid, v, p, infos, shard_v), p))
        if hasattr(code, "is_repair"):
            # MDS by construction: any k survivors decode, and
            # minimum_to_decode would re-route to the repair plan
            return order[:k] if len(order) >= k else None
        for cut in range(k, len(order) + 1):
            try:  # wire-ok: EC plan math (minimum_to_decode), not a wire decode
                return sorted(code.minimum_to_decode(
                    want, set(order[:cut])))
            except Exception:
                continue
        return None

    def _gather_ec_unit(self, pool_id, ps, unit, infos, shard_v,
                        code):
        """Fetch one unit's helper shards (runs on the gather pool
        under the pipeline).  Per object: ("batch", oid, rec, chunks)
        for concat-decode, ("clay", oid, rec, repair) for bandwidth
        repair, or None when no feasible plan survived this pass."""
        need, avail, v, strategy, plan, items = unit
        out = []
        for oid, rec in items:
            if strategy == "clay":
                got = self._gather_clay_object(
                    pool_id, ps, oid, rec, v, plan, infos, shard_v,
                    code)
                if got is not None:
                    out.append(("clay", oid, rec, got))
                    continue
                # sub-chunk repair unavailable for THIS object
                # (helper loss / misaligned chunk): full decode
                use = self._plan_full_use(code, set(need), set(avail),
                                          oid, v, infos, shard_v)
                if use is None:
                    out.append(None)
                    continue
            else:
                use = list(plan)
            chunks = self._gather_ec_object(
                pool_id, ps, oid, rec, v, use, avail, need, infos,
                shard_v, code)
            out.append(("batch", oid, rec, chunks)
                       if chunks is not None else None)
        return out

    def _rec_holders(self, key, oid, v, pos, infos, shard_v):
        """Candidate holders for one shard, failure-excluded and
        sorted least-loaded-first."""
        excl = self.rec_ledger.excluded(key)
        holders = [o for o in infos
                   if o not in excl and shard_v(o, oid, pos) == v]
        return sorted(holders,
                      key=lambda o: (self.rec_ledger.load(o), o))

    def _fetch_pos(self, key, pool_id, ps, oid, rec, v, pos, infos,
                   shard_v, ranges=None):
        """One position's shard from its least-loaded holder.  A
        failed or stale read EXCLUDES that holder for this object's
        remaining attempts (across passes — the retry-duplication
        fix) and falls through to the next candidate."""
        import numpy as np

        led = self.rec_ledger
        pc = self.rec_pc
        for o in self._rec_holders(key, oid, v, pos, infos, shard_v):
            led.start(o)
            try:
                rep = self._read_shard_from(o, pool_id, ps, oid, pos,
                                            ranges=ranges)
            finally:
                led.finish(o)
            if rep is not None and rep[0] == v:
                pc.inc("helper_reads")
                pc.inc("helper_bytes", len(rep[1]))
                # the object size travels with the shard: the info
                # record's size may describe a newer torn version
                rec["size"] = rep[2]
                return np.frombuffer(rep[1], np.uint8)
            led.exclude(key, o)
            pc.inc("helper_eio_excluded")
        return None

    def _gather_ec_object(self, pool_id, ps, oid, rec, v, use, avail,
                          need, infos, shard_v, code):
        """One object's survivor chunks for a full/lrc decode.  When
        a position runs out of non-excluded holders, RE-PLAN the
        decode from the remaining survivors (jitter-paced within the
        osd_recovery_helper_deadline budget) instead of stalling the
        object on the failed helper."""
        key = (pool_id, ps, oid)
        bo = Backoff(base=0.02, cap=0.25,
                     deadline=self.ctx.conf[
                         "osd_recovery_helper_deadline"])
        pending = list(use)
        chunks: Dict[int, object] = {}
        while pending:
            pos = pending.pop(0)
            arr = self._fetch_pos(key, pool_id, ps, oid, rec, v, pos,
                                  infos, shard_v)
            if arr is not None:
                chunks[pos] = arr
                continue
            self.rec_pc.inc("replans")
            feasible = {p for p in avail
                        if p in chunks or self._rec_holders(
                            key, oid, v, p, infos, shard_v)}
            try:
                newplan = code.minimum_to_decode(set(need), feasible)
            except Exception:
                return None  # not decodable this pass; retried later
            newuse = sorted(newplan)
            chunks = {p: c for p, c in chunks.items() if p in newuse}
            pending = [p for p in newuse if p not in chunks]
            if not bo.sleep():
                return None
        return chunks

    def _gather_clay_object(self, pool_id, ps, oid, rec, v, plan,
                            infos, shard_v, code):
        """CLAY 1/q-bandwidth repair gather: the first helper reads
        FULL (establishing the chunk length), the remaining d-1 read
        only their repair sub-chunk ranges server-side — the network
        never carries the bytes a full decode would have."""
        import numpy as np

        key = (pool_id, ps, oid)
        helpers = sorted(plan)
        sub = code.get_sub_chunk_count()
        first = helpers[0]
        arr = self._fetch_pos(key, pool_id, ps, oid, rec, v, first,
                              infos, shard_v)
        if arr is None:
            return None
        chunk_len = len(arr)
        if chunk_len == 0 or chunk_len % sub != 0:
            return None
        scs = chunk_len // sub
        got: Dict[int, object] = {}
        read_bytes = chunk_len
        for c in helpers:
            ranges = [(int(i) * scs, int(cnt) * scs)
                      for i, cnt in plan[c]]
            want_len = sum(ln for _off, ln in ranges)
            if c == first:
                got[c] = np.concatenate(
                    [arr[off:off + ln] for off, ln in ranges])
                continue
            sl = self._fetch_pos(key, pool_id, ps, oid, rec, v, c,
                                 infos, shard_v, ranges=ranges)
            if sl is None or len(sl) != want_len:
                return None
            got[c] = sl
            read_bytes += want_len
        k = code.get_data_chunk_count()
        return {"helpers": got, "chunk_len": chunk_len,
                "saved": max(0, k * chunk_len - read_bytes)}

    def _decode_push_ec_unit(self, pool_id, ps, up, unit, gathered,
                             infos, shard_v, code) -> bool:
        """Decode one gathered unit and push the rebuilt shards.
        Batch entries sharing a survivor set concatenate along the
        byte axis into ONE decode launch (recover_stripes' execution
        model; the codes are bytewise-linear, so decode(concat) ==
        concat of per-object decodes); clay entries repair
        per-object with chunk_size routing into the code's
        sub-chunk `_repair` path."""
        import numpy as np

        need, avail, v, strategy, plan, items = unit
        pc = self.rec_pc
        cid = pg_cid(pool_id, ps)
        k = code.get_data_chunk_count()
        ok = True
        batch = []
        for entry in gathered:
            if entry is None:
                ok = False
                continue
            if entry[0] == "clay":
                _kind, oid, rec, got = entry
                try:
                    out = code.decode(set(need),
                                      dict(got["helpers"]),
                                      chunk_size=got["chunk_len"])
                except Exception as e:
                    self.log.derr(f"pg {cid}: clay repair of {oid} "
                                  f"failed: {e!r}")
                    ok = False
                    continue
                pos = next(iter(need))
                shard = np.asarray(out[pos], np.uint8)
                ok &= self._push_rebuilt(pool_id, ps, up, oid, rec, v,
                                         {pos: shard}, shard_v)
                pc.inc("strategy_clay")
                pc.inc("helper_bytes_saved", got["saved"])
            else:
                batch.append(entry[1:])
        # bucket by survivor set: re-planned objects may have deviated
        # from the unit's plan and need their own decode launch
        buckets: Dict[frozenset, List] = {}
        for oid, rec, chunks in batch:
            buckets.setdefault(frozenset(chunks), []).append(
                (oid, rec, chunks))
        for useset, objs in buckets.items():
            offsets, total = [], 0
            for _oid, _rec, chunks in objs:
                ln = len(next(iter(chunks.values())))
                offsets.append((total, ln))
                total += ln
            surviving = {
                pos: np.concatenate([c[pos] for _o, _r, c in objs])
                for pos in useset}
            try:
                out = code.decode(set(need), surviving)
            except Exception as e:
                self.log.derr(f"pg {cid}: batched decode failed "
                              f"(use={sorted(useset)}): {e!r}")
                ok = False
                continue
            lrc_win = len(useset) < k
            for (oid, rec, _c), (off, ln) in zip(objs, offsets):
                shards = {
                    pos: np.asarray(out[pos], np.uint8)[off:off + ln]
                    for pos in need}
                ok &= self._push_rebuilt(pool_id, ps, up, oid, rec,
                                         v, shards, shard_v)
                if lrc_win:
                    pc.inc("strategy_lrc")
                    pc.inc("helper_bytes_saved",
                           (k - len(useset)) * ln)
                else:
                    pc.inc("strategy_full")
        return ok

    def _push_rebuilt(self, pool_id, ps, up, oid, rec, v, shards,
                      shard_v) -> bool:
        """Push one object's rebuilt shards to their up members.
        force+expect: the authoritative version may be LOWER than a
        torn never-acked shard on a member — roll it back, but only
        if the shard is still exactly what peering observed (a racing
        newer client write wins)."""
        ok = True
        for pos, shard in shards.items():
            osd = up[pos]
            if osd != self.id and not self._alive(osd):
                ok = False
                continue
            self._push_shard(pool_id, ps, osd, oid, pos,
                             shard.tobytes(), rec.get("size", 0), v,
                             force=True,
                             expect=shard_v(osd, oid, pos))
        self.pc.inc("recovered_objects")
        self._account_io(pool_id, ps, objects_recovered=1)
        return ok

    def _send_delete(self, pool_id, ps, osd, oid, v, force=False,
                     expect=None) -> None:
        msg = {"type": "obj_delete", "pool": pool_id, "ps": ps,
               "oid": oid, "v": v}
        if force:
            msg["force"] = True
            msg["expect"] = expect
        try:
            if osd == self.id:
                self._h_obj_delete(msg)
            else:
                self.msgr.call(self.osd_addrs[osd], msg, timeout=5)
        except (TimeoutError, OSError):
            pass

    def _recover_object(self, m, pool_id, pool, ps, up, oid, rec,
                        infos, shard_v, code) -> bool:
        """Primary-driven REPLICATED object recovery at the
        authoritative version (ReplicatedBackend push-pull): returns
        True when every up member holds oid@v.  EC objects never reach
        here — _peer_pg_locked routes them through the torn-write-aware
        pipelined path (_recover_ec_groups)."""
        import numpy as np

        assert code is None, "EC recovery goes through the batch path"
        cid = pg_cid(pool_id, ps)
        v, size = rec["v"], rec.get("size", 0)
        need = [o for o in up if shard_v(o, oid, 0) != v]
        if not need:
            return True
        data = None
        for o in infos:
            if shard_v(o, oid, 0) != v:
                continue
            rep = self._read_shard_from(o, pool_id, ps, oid, 0)
            if rep is not None and rep[0] == v:
                data = np.frombuffer(rep[1], np.uint8)
                size = rep[2]
                break
        if data is None:
            self.log.derr(f"pg {cid} {oid}@{v}: no reachable holder")
            return False
        ok = True
        for o in need:
            if o != self.id and not self._alive(o):
                ok = False
                continue
            self._push_shard(pool_id, ps, o, oid, 0, data.tobytes(),
                             size, v)
        self.pc.inc("recovered_objects")
        self._account_io(pool_id, ps, objects_recovered=1)
        return ok

    def _push_shard(self, pool_id, ps, osd, oid, shard, data, size,
                    v, qos: str = "recovery", force: bool = False,
                    expect: Optional[str] = None) -> Optional[Dict]:
        """One shard write, local or remote.  Returns the holder's
        reply (so callers can distinguish `superseded` — the holder
        kept its newer version — from a genuine persist) or None on
        transport failure."""
        # every caller hands a stable bytes payload (a device->host
        # tobytes() or an already-materialised shard) — no defensive
        # re-copy here
        msg = {"type": "shard_write", "pool": pool_id, "ps": ps,
               "oid": oid, "shard": shard, "data": data,
               "size": size, "v": v, "qos_class": qos}
        if force:
            msg["force"] = True
            msg["expect"] = expect
        try:
            if osd == self.id:
                # direct: the caller is already a scheduled worker or
                # the RMW coordinator — re-submitting would deadlock
                # the worker pool
                rep = self._do_shard_write(msg)
            else:
                # 5s: long enough for a loaded replica's fsync+queue,
                # but a push often runs under the PG lock, so a dead
                # peer must stop blocking the whole PG quickly (the
                # messenger fails even faster once its resync gives
                # the peer up)
                rep = self.msgr.call(self.osd_addrs[osd], msg,
                                     timeout=5)
        except (TimeoutError, OSError):
            return None
        if qos == "recovery" and rep is not None and rep.get("ok"):
            self.pc.inc("recovery_bytes", len(msg["data"]))
            # recovery-push copy: the decoded shard is materialised
            # once (the caller's device->host tobytes()) for the push
            copytrack.book_pc(self._copy_pc, "recovery_push",
                              len(msg["data"]), copies=1)
            self._account_io(pool_id, ps,
                             bytes_recovered=len(msg["data"]))
        return rep

    def _set_pg_temp(self, pool_id: int, ps: int,
                     osds: List[int]) -> None:
        """Install/clear the acting override at the monitor; no-op when
        the map already agrees (avoids commit churn every pass)."""
        with self._lock:
            cur = self.map.pg_temp.get((pool_id, ps), []) \
                if self.map is not None else []
        if list(cur) == list(osds):
            return
        try:
            self.mon_call({"type": "pg_temp_set", "pool": pool_id,
                           "ps": ps, "osds": list(osds)}, timeout=5,
                          tries=1)
        except Exception as e:
            self.log.dout(5, f"pg_temp_set failed: {e}")
