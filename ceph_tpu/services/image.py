"""Image — the librbd analogue: a block device striped over objects.

The role of src/librbd at this framework's scope: an image is a
fixed-size virtual block device carved into stripe pieces
(``services.striper`` layout) over a pool, with a header object
carrying geometry and the snapshot table, random-offset read/write via
read-modify-write on the backing pieces, resize (shrink discards
truncated data, as the block-device contract requires), and
point-in-time snapshots with rollback.  Snapshots remember their size,
so a later shrink doesn't truncate history.

Divergence note: the reference snapshots in place via RADOS
self-managed snaps (object clones inside the same PG); here a snapshot
materializes copies under ``name@snap`` piece names — the user-visible
semantics (immutable point-in-time view, rollback, independent reads)
are preserved; the storage cost differs.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..common import encoding
from .client import Client, ObjectNotFound
from .striper import Striper, _piece_name

# wire/disk version of the header object (wirecheck entry
# rbd.image_header).  Writer v0 = the pre-envelope raw-dict era;
# decode stays lenient so existing images keep opening.
HEADER_V = 1


def encode_header(header: Dict) -> bytes:
    return encoding.encode(dict(header), HEADER_V, 1).encode()


def decode_header(raw: bytes) -> Dict:
    v, d = encoding.decode_any(raw, supported=HEADER_V,
                               struct="rbd.image_header")
    if not isinstance(d, dict):
        raise encoding.MalformedInput(
            f"rbd.image_header v{v}: payload is not an object")
    return d


def _header_oid(name: str) -> str:
    return f"rbd_header.{name}"


class ImageError(Exception):
    pass


class Image:
    def __init__(self, client: Client, pool_id: int, name: str,
                 header: Dict):
        self.client = client
        self.pool_id = pool_id
        self.name = name
        self._h = header
        self._parent_img: Optional["Image"] = None
        self.striper = Striper(client,
                               stripe_unit=header["stripe_unit"],
                               stripe_count=header["stripe_count"],
                               object_size=header["object_size"])

    # -- lifecycle ------------------------------------------------------
    @classmethod
    def create(cls, client: Client, pool_id: int, name: str,
               size: int, stripe_unit: int = 4096,
               stripe_count: int = 4,
               object_size: int = 1 << 16) -> "Image":
        try:
            client.get(pool_id, _header_oid(name), notfound_retries=0)
        except ObjectNotFound:
            pass  # the only evidence the image does NOT exist;
            # transient errors (TimeoutError/OSError) propagate so a
            # degraded moment can never silently clobber a header
        else:
            raise ImageError(f"image {name!r} exists")
        header = {"size": size, "stripe_unit": stripe_unit,
                  "stripe_count": stripe_count,
                  "object_size": object_size, "snaps": [],
                  "parent": None, "children": []}
        client.put(pool_id, _header_oid(name), encode_header(header))
        return cls(client, pool_id, name, header)

    @classmethod
    def open(cls, client: Client, pool_id: int, name: str) -> "Image":
        try:
            raw = client.get(pool_id, _header_oid(name))
        except ObjectNotFound:
            raise ImageError(f"no image {name!r}")
        return cls(client, pool_id, name, decode_header(raw))

    def _save_header(self) -> None:
        self.client.put(self.pool_id, _header_oid(self.name),
                        encode_header(self._h))

    def _reload_header(self) -> None:
        """The header lives in RADOS; another handle (a clone's
        flatten, a second opener) may have changed it — snapshot/clone
        bookkeeping re-reads before deciding."""
        raw = self.client.get(self.pool_id, _header_oid(self.name))
        self._h = decode_header(raw)

    # -- geometry -------------------------------------------------------
    @property
    def size(self) -> int:
        return self._h["size"]

    def resize(self, size: int) -> None:
        """Grow or shrink.  Shrinking zeroes exactly the truncated
        extents so a later grow reads zeros there (the block-device
        contract).  Striping interleaves live and truncated stripe
        units within one backing object, so truncation must patch
        per-extent — never drop whole objects."""
        old = self.size
        if size < old:
            # within one backing object, logical offsets grow with
            # obj_off, so the truncated region is a contiguous TAIL:
            # keep [0, min truncated obj_off) and drop the rest.  A
            # boundary of 0 means the whole object goes — no read
            # needed (large shrinks don't transfer the tail back).
            boundary: Dict[int, int] = {}
            for objectno, obj_off, _log_off, _run in \
                    self.striper.extent_map(size, old - size):
                cur = boundary.get(objectno)
                if cur is None or obj_off < cur:
                    boundary[objectno] = obj_off
            for objectno, keep in sorted(boundary.items()):
                piece = b"" if keep == 0 else \
                    self._piece(self.name, objectno)[:keep]
                self.client.put(self.pool_id,
                                _piece_name(self.name, objectno),
                                piece.rstrip(b"\0"))
        self._h["size"] = size
        p = self._h.get("parent")
        if p and size < p["overlap"]:
            # shrink trims the COW window: a later grow reads zeros,
            # never stale parent bytes (librbd overlap semantics)
            p["overlap"] = size
        self._save_header()

    def snaps(self) -> List[str]:
        return [s["name"] for s in self._h["snaps"]]

    def _snap(self, snap: str) -> Dict:
        for s in self._h["snaps"]:
            if s["name"] == snap:
                return s
        raise ImageError(f"no snap {snap!r}")

    # -- data path (read-modify-write over stripe pieces) ---------------
    def _piece(self, data_name: str, objectno: int) -> bytes:
        try:
            # sparse images miss pieces constantly: definitive ENOENT,
            # no backfill-race retries on this path
            return self.client.get(self.pool_id,
                                   _piece_name(data_name, objectno),
                                   notfound_retries=0)
        except ObjectNotFound:
            if data_name == self.name and self._h.get("parent"):
                return self._parent_piece(objectno)
            return b""  # sparse: unwritten pieces read as zeros

    def _parent_piece(self, objectno: int) -> bytes:
        """COW fallthrough (librbd parent overlap reads): an unwritten
        child piece reads from the parent snapshot, trimmed to the
        overlap window (shrink-then-grow must expose zeros, not stale
        parent bytes)."""
        p = self._h["parent"]
        if self._parent_img is None:
            self._parent_img = Image.open(self.client, p["pool"],
                                          p["name"])
        cache = getattr(self, "_overlap_keep", None)
        if cache is None or cache[0] != p["overlap"]:
            # one extent-map walk per overlap value, not per read
            keeps: Dict[int, int] = {}
            for objn, obj_off, _log, run in \
                    self.striper.extent_map(0, p["overlap"]):
                keeps[objn] = max(keeps.get(objn, 0), obj_off + run)
            cache = (p["overlap"], keeps)
            self._overlap_keep = cache
        keep = cache[1].get(objectno, 0)
        if keep == 0:
            return b""
        piece = self._parent_img._piece(
            f"{p['name']}@{p['snap']}", objectno)
        return piece[:keep]

    def write(self, offset: int, data: bytes) -> int:
        if offset + len(data) > self.size:
            raise ImageError("write past end of image")
        touched: Dict[int, bytearray] = {}
        for objectno, obj_off, log_off, run in \
                self.striper.extent_map(offset, len(data)):
            buf = touched.get(objectno)
            if buf is None:
                buf = bytearray(self._piece(self.name, objectno))
                touched[objectno] = buf
            if len(buf) < obj_off + run:
                buf.extend(b"\0" * (obj_off + run - len(buf)))
            buf[obj_off:obj_off + run] = \
                data[log_off - offset:log_off - offset + run]
        for objectno, buf in sorted(touched.items()):
            self.client.put(self.pool_id,
                            _piece_name(self.name, objectno),
                            bytes(buf))
        return len(data)

    def _read_pieces(self, data_name: str, offset: int, length: int,
                     limit: int) -> bytes:
        length = max(0, min(length, limit - offset))
        if not length:
            return b""
        out = bytearray(length)  # unwritten extents read as zeros
        cache: Dict[int, bytes] = {}
        for objectno, obj_off, log_off, run in \
                self.striper.extent_map(offset, length):
            piece = cache.get(objectno)
            if piece is None:
                piece = self._piece(data_name, objectno)
                cache[objectno] = piece
            chunk = piece[obj_off:obj_off + run]
            out[log_off - offset:log_off - offset + len(chunk)] = chunk
        return bytes(out)

    def read(self, offset: int, length: int) -> bytes:
        return self._read_pieces(self.name, offset, length, self.size)

    # -- snapshots -------------------------------------------------------
    def _pieces_in_use(self, size: int) -> List[int]:
        objs = set()
        for objectno, _o, _l, _r in self.striper.extent_map(0, size):
            objs.add(objectno)
        return sorted(objs)

    def snapshot(self, snap: str) -> None:
        if any(s["name"] == snap for s in self._h["snaps"]):
            raise ImageError(f"snap {snap!r} exists")
        for objectno in self._pieces_in_use(self.size):
            piece = self._piece(self.name, objectno)
            if piece:
                self.client.put(
                    self.pool_id,
                    _piece_name(f"{self.name}@{snap}", objectno),
                    piece)
        self._h["snaps"].append({"name": snap, "size": self.size})
        self._save_header()

    def read_snap(self, snap: str, offset: int, length: int) -> bytes:
        info = self._snap(snap)
        return self._read_pieces(f"{self.name}@{snap}", offset,
                                 length, info["size"])

    def rollback(self, snap: str) -> None:
        """Restore the image data (and size) to the snapshot's state."""
        info = self._snap(snap)
        for objectno in self._pieces_in_use(
                max(info["size"], self.size)):
            piece = self._piece(f"{self.name}@{snap}", objectno)
            self.client.put(self.pool_id,
                            _piece_name(self.name, objectno), piece)
        self._h["size"] = info["size"]
        self._save_header()

    # -- clones (librbd COW clone / protect / flatten) -------------------
    def protect_snap(self, snap: str) -> None:
        """Clones may only hang off protected snapshots — otherwise a
        snap removal would orphan children (librbd's protect rule)."""
        self._reload_header()
        self._snap(snap)["protected"] = True
        self._save_header()

    def unprotect_snap(self, snap: str) -> None:
        self._reload_header()
        info = self._snap(snap)
        kids = [c for c in self._h.get("children", [])
                if c["snap"] == snap]
        if kids:
            raise ImageError(
                f"snap {snap!r} has children: "
                f"{[c['name'] for c in kids]}")
        info["protected"] = False
        self._save_header()

    def clone(self, snap: str, clone_name: str) -> "Image":
        """COW clone: the child shares the parent snapshot's data and
        copies nothing; child writes land on child pieces only, child
        reads fall through to the parent inside the overlap window."""
        self._reload_header()  # a sibling clone's children record
        # must never be clobbered by a stale cached header
        info = self._snap(snap)
        if not info.get("protected"):
            raise ImageError(f"snap {snap!r} is not protected")
        child = Image.create(
            self.client, self.pool_id, clone_name, info["size"],
            stripe_unit=self._h["stripe_unit"],
            stripe_count=self._h["stripe_count"],
            object_size=self._h["object_size"])
        child._h["parent"] = {"pool": self.pool_id,
                              "name": self.name, "snap": snap,
                              "overlap": info["size"]}
        child._save_header()
        self._h.setdefault("children", []).append(
            {"name": clone_name, "snap": snap})
        self._save_header()
        return child

    def flatten(self) -> None:
        """Copy every parent-backed extent into the child and detach —
        after this the parent snapshot can be unprotected."""
        p = self._h.get("parent")
        if not p:
            return
        for objectno in self._pieces_in_use(
                min(self.size, p["overlap"]) or self.size):
            try:
                self.client.get(
                    self.pool_id, _piece_name(self.name, objectno),
                    notfound_retries=0)
            except ObjectNotFound:
                piece = self._parent_piece(objectno)
                if piece:
                    self.client.put(
                        self.pool_id,
                        _piece_name(self.name, objectno), piece)
        parent = Image.open(self.client, p["pool"], p["name"])
        parent._h["children"] = [
            c for c in parent._h.get("children", [])
            if not (c["name"] == self.name and c["snap"] == p["snap"])]
        parent._save_header()
        self._h["parent"] = None
        self._parent_img = None
        self._save_header()
