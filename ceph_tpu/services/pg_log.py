"""PG log entries — the pg_log_entry_t wire/disk form.

The role of src/osd/osd_types.h pg_log_entry_t: each write/delete
appends one record to the PG's omap-resident log; peering consumes the
per-object newest record (tombstones included) to compute missing
sets, and trim drops superseded history.  Before this module the OSD
serialized these records as ad-hoc ``json.dumps`` dicts — no version,
no compat floor, no registry entry — exactly the drift class the
wirecheck layer exists to close.

Records now travel through the versioned envelope (wirecheck entry
``osd.pg_log_entry``); archived raw-dict records (writer v0 — every
store written before this PR) still decode via the lenient path, so a
remounted OSD data_dir replays its history unchanged.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

from ..common import encoding
from ..common.encoding import MalformedInput, Versioned


@dataclass
class PgLogEntry(Versioned):
    """One log record: op kind, object, version stamp, and (for
    writes) the shard position and logical size."""

    STRUCT_V = 1
    COMPAT_V = 1

    op: str = "write"        # "write" | "delete"
    oid: str = ""
    v: str = ""              # the version stamp (common.version)
    shard: int = -1          # -1: not a shard-positional record
    size: int = 0

    def to_dict(self) -> dict:
        return {"op": self.op, "oid": self.oid, "v": self.v,
                "shard": self.shard, "size": self.size}

    @classmethod
    def from_dict(cls, d: dict) -> "PgLogEntry":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @property
    def deleted(self) -> bool:
        return self.op == "delete"

    # -- omap value form ----------------------------------------------
    def encode_blob(self) -> bytes:
        return self.encode_versioned().encode()

    @classmethod
    def decode_blob(cls, raw: bytes) -> "PgLogEntry":
        """Lenient: pre-envelope raw-dict records (writer v0) decode
        with the same field defaults."""
        v, d = encoding.decode_any(raw, supported=cls.STRUCT_V,
                                   struct="osd.pg_log_entry")
        if not isinstance(d, dict):
            raise MalformedInput(
                f"osd.pg_log_entry v{v}: payload is not an object")
        try:
            return cls.from_dict(cls.upgrade(max(v, 1), d))
        except (KeyError, TypeError, ValueError) as e:
            raise MalformedInput(
                f"osd.pg_log_entry v{v}: bad payload: {e!r}")
