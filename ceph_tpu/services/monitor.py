"""Monitor — the cluster-map authority and failure detector.

The role of src/mon (Monitor.cc / OSDMonitor.cc / MonitorDBStore.h):
it owns the OSDMap, bumps epochs on every state change, retains full
maps per epoch (the MonitorDBStore analogue — any daemon can resume at
any epoch), tracks osd boot/heartbeat liveness, and marks osds down
after ``osd_heartbeat_grace`` without a beat (OSD::handle_osd_ping →
OSDMonitor flow, src/osd/OSD.cc:5487 / ceph_osd.cc:544).  Map changes
push to subscribers (MonClient subscription role) through per-peer
queues so one hung subscriber can never stall the commit path.

Runs standalone (a single authority) or as one of N quorum members:
``set_peers(rank, addrs)`` before ``start()`` attaches the election +
replicated-log layer (services/quorum.py — the ElectionLogic/Paxos
role).  In quorum mode every epoch is majority-replicated before it
becomes visible, write commands are forwarded to the leader, reads and
subscriptions are served by any member, and only the leader runs
failure detection.  (SURVEY §2.5 Monitor row.)
"""

from __future__ import annotations

import collections
import json
import os
import queue
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

from ..analysis import faults
from ..analysis.asyncheck import nonblocking
from ..analysis.lockdep import make_lock, make_rlock
from ..analysis.racecheck import guarded_by
from ..common import encoding
from ..common.context import Context
from ..common.op_tracker import OpTracker
from ..msg.messenger import Addr, Messenger
from ..osdmap.osdmap import OSDMap, PgPool
from .quorum import Quorum

# the epoch-store payload format (MonitorDBStore full-map rows,
# wirecheck entry mon.epoch_payload): one envelope around
# {epoch, map, osd_addrs, ec_profiles}.  Files written before the
# migration are raw dicts (writer v0) and keep decoding, so a monitor
# resumes from an old store_dir unchanged.
EPOCH_PAYLOAD_V = 1


def encode_epoch_payload(payload: Dict) -> str:
    return encoding.encode(payload, EPOCH_PAYLOAD_V, 1)


def decode_epoch_payload(blob) -> Dict:
    v, d = encoding.decode_any(blob, supported=EPOCH_PAYLOAD_V,
                               struct="mon.epoch_payload")
    if not isinstance(d, dict):
        raise encoding.MalformedInput(
            f"mon.epoch_payload v{v}: payload is not an object")
    return d


@guarded_by("mon::state", "_pg_stats", "_osd_slo", "_subscribers")
class Monitor:
    def __init__(self, ctx: Context, osdmap: OSDMap,
                 host: str = "127.0.0.1", port: int = 0,
                 store_dir: Optional[str] = None, keyring=None):
        self.ctx = ctx
        self.log = ctx.logger("mon")
        self.map = osdmap
        self.tracer = ctx.tracer
        # lossless policy: mon↔mon quorum traffic and mon↔osd control
        # frames are sequenced and replayed across reconnects
        self.msgr = Messenger("mon", host, port, keyring=keyring,
                              lossless=True, tracer=self.tracer,
                              perf=ctx.perf)
        self.addr: Addr = self.msgr.addr
        self.store_dir = store_dir
        self._epochs: Dict[int, str] = {}  # epoch -> map json
        # epoch -> Incremental dict (map distribution is O(change):
        # subscribers apply deltas, fetching a full map only on a gap)
        self._incs: Dict[int, Dict] = {}
        self._prev_map: Optional[OSDMap] = None
        self._osd_addrs: Dict[int, Addr] = {}
        self._last_beat: Dict[int, float] = {}
        self._down_since: Dict[int, float] = {}
        # OSDMonitor::check_failure state: failed osd -> {reporter
        # osd: mono stamp of its latest osd_failure report}.  Reports
        # DECAY (reporters re-send every heartbeat interval while the
        # peer stays silent), so a burst from one partitioned corner
        # of the cluster cannot linger forever as half a quorum.
        self._failure_reports: Dict[int, Dict[int, float]] = {}
        # osd -> mono stamp of its last accepted boot: a failure
        # report whose silence window STARTED before the boot is
        # evidence against the previous incarnation, not this one
        # (check_failure's failed_since >= up_from rule)
        self._up_from: Dict[int, float] = {}
        # the osd_markdown_log role: osd -> markdown stamps within
        # osd_max_markdown_period; crossing osd_max_markdown_count
        # dampens the daemon (boot deferred + auto-out) and raises
        # the OSD_FLAPPING health check
        self._markdown_log: Dict[int, Deque[float]] = {}
        # osd -> last time we pushed the map at a beating-but-down
        # daemon (rate limit for the wrongly-marked-down nudge)
        self._down_nudge: Dict[int, float] = {}
        # osd -> the SLO cargo its last beacon carried (slow-op count
        # + oldest age, heartbeat-RTT threshold breaches) with receipt
        # stamp: what _h_health folds into SLOW_OPS /
        # OSD_SLOW_PING_TIME, aged out with the stats grace so a dead
        # daemon's stale complaint can't pin health at WARN
        self._osd_slo: Dict[int, Dict] = {}
        # osd -> pre-out weight, for osds the MONITOR outed (auto-out);
        # restored on boot, unlike an admin mark_out which sticks
        self._auto_out: Dict[int, int] = {}
        self._subscribers: Dict[str, Addr] = {}
        self._pushers: Dict[str, "_SubPusher"] = {}
        self._lock = make_rlock("mon::state")
        self._commit_serial = make_lock("mon::commit")
        self._committed_epoch = 0
        self._ticker: Optional[threading.Thread] = None
        self._running = False
        self.quorum: Optional[Quorum] = None
        self.rank = 0  # quorum rank (set_peers); 0 standalone
        self.ec_profiles: Dict[str, Dict[str, str]] = {}
        self.pc = ctx.perf.create("mon")
        self.pc.add_u64_counter("epochs")
        self.pc.add_u64_counter("beats")
        self.pc.add_u64_counter("markdowns")
        self.pc.add_u64_counter("failure_reports")
        self.pc.add_u64_counter("markdowns_dampened")
        self.pc.add_u64_counter("pg_stat_reports")
        self.pc.add_u64("stale_pgs")
        self.pc.add_histogram("commit_lat")
        self.pc.add_time("commit_time")
        # write commands register here (the leader-side op surface);
        # dump_ops_in_flight / dump_historic_ops over the admin socket
        # — slow threshold on the same knob as the osds' SLOW_OPS
        self.optracker = OpTracker(
            history_slow_threshold=ctx.conf["osd_op_complaint_time"])

        # write commands mutate the map: leader-only in quorum mode
        # (forwarded there); reads are served by any member
        # heartbeats and map reads ride the messenger's control lane:
        # failure detection must never queue behind a burst of client
        # write commands holding every op-pool worker
        for t, h, ctl in (("boot", self._fwd(self._h_boot), False),
                          ("heartbeat", self._fwd(self._h_heartbeat,
                                                  fire_forget=True),
                           True),
                          ("osd_failure",
                           self._fwd(self._h_osd_failure,
                                     fire_forget=True), True),
                          ("get_map", self._h_get_map, True),
                          ("get_inc", self._h_get_inc, True),
                          ("subscribe", self._h_subscribe, False),
                          ("mark_down", self._fwd(self._h_mark_down),
                           False),
                          ("mark_out", self._fwd(self._h_mark_out),
                           False),
                          ("pool_create",
                           self._fwd(self._h_pool_create), False),
                          ("pool_delete",
                           self._fwd(self._h_pool_delete), False),
                          ("reweight", self._fwd(self._h_reweight),
                           False),
                          ("pg_temp_set",
                           self._fwd(self._h_pg_temp_set), False),
                          ("pg_upmap_items_set",
                           self._fwd(self._h_pg_upmap_items_set),
                           False),
                          ("mgr_health_report",
                           self._h_mgr_health_report, False),
                          ("ec_profile_set",
                           self._fwd(self._h_ec_profile_set), False),
                          ("pg_stats", self._h_pg_stats, False),
                          ("pool_stats", self._h_pool_stats, False),
                          ("progress", self._h_progress, False),
                          ("health", self._h_health, False),
                          ("status", self._h_status, False)):
            self.msgr.register(t, h, control=ctl)
        # PGMap role (src/mon/MgrStatMonitor / PGMap.cc): latest
        # primary-reported state per PG — observability state, NOT part
        # of the replicated epoch log (exactly as in the reference);
        # OSDs broadcast stats to every member, so any mon can serve
        # health without quorum traffic
        self._pg_stats: Dict[Tuple[int, int], Dict] = {}
        # ((pool, ps), reporter osd) -> {"io": cumulative block,
        # "last_report": mono}: any shard HOLDER reports io (EC reads
        # land on every member), so pool sums cover the whole set
        self._pg_io: Dict[Tuple[Tuple[int, int], int], Dict] = {}
        # per-pool stat-sample ring (the PGMap delta ring the
        # `pool-stats` rate series derives from) + the mgr-progress
        # event surface (open per pool, completed bounded)
        self._pool_stat_ring: Dict[int, Deque[Dict]] = {}
        self._progress_open: Dict[int, Dict] = {}
        self._progress_done: Deque[Dict] = collections.deque(
            maxlen=32)
        self._progress_seq = 0
        # latest mgr-module health report (mgr broadcasts to every
        # member); folded into _h_health while within the grace
        self._mgr_health: Optional[Dict] = None

    # -- quorum ---------------------------------------------------------
    def set_peers(self, rank: int, addrs: List[Addr]) -> None:
        """Join an N-monitor quorum (call before start()).  ``addrs``
        is the rank-ordered list of every member including self."""
        self.rank = rank
        # rank-qualified wire identity: every frame's ``frm`` carries
        # it, so the net.partition fault plane can scope a single
        # rank ("mon.2") while "mon" still prefix-matches them all
        self.msgr.name = f"mon.{rank}"
        self.quorum = Quorum(
            self, rank, addrs,
            lease=self.ctx.conf["mon_lease"],
            election_timeout=self.ctx.conf["mon_election_timeout"])

    def _fwd(self, handler, fire_forget: bool = False):
        """Leader-only write handler: executed locally on the leader,
        forwarded to it from peons (Monitor::forward_request role)."""

        def h(msg: Dict):
            q = self.quorum
            if q is None or q.is_leader():
                with self.optracker.create(
                        "mon_cmd",
                        f"{msg.get('type', '?')} from "
                        f"{msg.get('frm', '?')}"):
                    return handler(msg)
            la = q.leader_addr()
            if la is None:
                return {"error": "no quorum"}
            fwd = {k: v for k, v in msg.items()
                   if k not in ("tid", "mac", "frm")}
            if fire_forget:
                self.msgr.send(la, fwd)
                return None
            return self.msgr.call(la, fwd, timeout=5.0)

        return h

    def last_committed(self) -> int:
        with self._lock:
            return self._committed_epoch

    def committed_entries(self, frm: int, to: int) -> List[Dict]:
        """Committed (version, entry) rows in (frm, to] that are still
        retained — the quorum catch-up feed.  (A member further behind
        than the retention window cannot catch up incrementally; with
        mon_max_map_epochs=500 that does not happen in practice.)"""
        out = []
        with self._lock:
            for v in range(frm + 1, to + 1):
                pay = self._epochs.get(v)
                if pay is None:
                    continue
                out.append({"v": v,
                            "entry": {"payload": pay,
                                      "inc": self._incs.get(v)}})
        return out

    def apply_committed(self, v: int, entry: Dict) -> None:
        """Install a majority-committed epoch (peon apply / leader
        sync): replace live state from the full payload, store, push."""
        p = decode_epoch_payload(entry["payload"])
        with self._lock:
            if v != self._committed_epoch + 1:
                # duplicate/stale delivery (racing catch-up paths must
                # never roll the visible state backwards)
                return
            self.map = OSDMap.from_dict(p["map"])
            self._osd_addrs = {int(k): tuple(a)
                               for k, a in p["osd_addrs"].items()}
            self.ec_profiles = dict(p["ec_profiles"])
            self._store_committed(v, entry["payload"],
                                  entry.get("inc"))
        self.pc.inc("epochs")
        self._push_maps()

    def on_leader(self, uncommitted: Optional[Dict]) -> None:
        """Quorum callback after winning + syncing an election."""
        with self._lock:
            # surviving osds get a full grace window to re-beat before
            # the new leader may mark them down
            now = time.monotonic()
            for o in range(self.map.max_osd):
                if self.map.exists(o) and self.map.is_up(o):
                    self._last_beat.setdefault(o, now)
        if uncommitted is not None and \
                int(uncommitted["v"]) == self.last_committed() + 1:
            # Paxos re-propose: an accepted-but-uncommitted entry that
            # may have reached a majority must survive the failover
            v = int(uncommitted["v"])
            if self.quorum.replicate(v, uncommitted["entry"]):
                self.apply_committed(v, uncommitted["entry"])
        if self.last_committed() == 0:
            try:
                self._commit("genesis")
            except RuntimeError:
                pass  # lost quorum immediately; next leader retries

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self.ctx.conf["admin_socket"]:
            sock = self.ctx.start_admin_socket()
            self.optracker.wire(sock)
            self.tracer.wire(sock)
            self.msgr.wire(sock)   # dump_messenger
        self._load_store()
        self.msgr.start()
        self._running = True
        self._ticker = threading.Thread(target=self._tick_loop,
                                        daemon=True, name="mon-tick")
        self._ticker.start()
        if self.quorum is not None:
            self.quorum.start()
        elif self._committed_epoch == 0:
            self._commit("genesis")

    def _load_store(self) -> None:
        """MonitorDBStore reload: a restarted monitor resumes from its
        persisted epochs instead of resetting to genesis (which would
        freeze daemons already holding newer epochs).  Quorum members
        also benefit: a rejoin starts from the local tail and syncs
        only the delta."""
        if not self.store_dir or not os.path.isdir(self.store_dir):
            return
        epochs = []
        for name in os.listdir(self.store_dir):
            if name.startswith("osdmap.") and name.endswith(".json"):
                try:
                    epochs.append(int(name.split(".")[1]))
                except ValueError:
                    continue
        if not epochs:
            return
        keep = self.ctx.conf["mon_max_map_epochs"]
        with self._lock:
            for e in sorted(epochs)[-keep:]:
                try:
                    self._epochs[e] = open(os.path.join(
                        self.store_dir, f"osdmap.{e}.json")).read()
                except OSError:
                    continue
            newest = max(self._epochs)
            p = decode_epoch_payload(self._epochs[newest])
            self.map = OSDMap.from_dict(p["map"])
            self._osd_addrs = {int(k): tuple(a)
                               for k, a in p["osd_addrs"].items()}
            self.ec_profiles = dict(p["ec_profiles"])
            self._prev_map = OSDMap.from_dict(p["map"])
            self._committed_epoch = newest
        self.log.dout(1, f"resumed from stored epoch {newest}")

    def shutdown(self) -> None:
        self._running = False
        if self.quorum is not None:
            self.quorum.shutdown()
        if self._ticker:
            self._ticker.join(timeout=2)
        for p in self._pushers.values():
            p.stop()
        self.msgr.shutdown()
        self.ctx.shutdown()  # admin socket + config observers

    # -- the epoch store (MonitorDBStore role) --------------------------
    def _commit(self, why: str) -> int:
        """Bump the epoch, retain the full map AND its delta, persist,
        notify.  In quorum mode the entry is majority-replicated BEFORE
        it is stored or pushed anywhere; a leader that cannot reach a
        majority rolls back and abdicates, so epochs never fork."""
        from ..osdmap.incremental import diff_maps

        t_commit = time.monotonic()
        with self._commit_serial:
            with self._lock:
                self.map.epoch += 1
                v = self.map.epoch
                payload = encode_epoch_payload(self._map_payload())
                inc_d = None
                if self._prev_map is not None:
                    inc = diff_maps(self._prev_map, self.map)
                    inc.epoch = v
                    inc_d = inc.to_dict()
            if self.quorum is not None:
                if not self.quorum.replicate(
                        v, {"payload": payload, "inc": inc_d}):
                    self._restore_committed()
                    self.quorum.abdicate()
                    raise RuntimeError(
                        "mon: lost quorum; commit aborted")
            self._store_committed(v, payload, inc_d)
        self.pc.inc("epochs")
        dt = time.monotonic() - t_commit
        self.pc.hist_add("commit_lat", dt)
        self.pc.tinc("commit_time", dt)
        self.log.dout(5, f"new epoch {v} ({why})")
        self._push_maps()
        return v

    def _store_committed(self, v: int, payload: str,
                         inc_d: Optional[Dict]) -> None:
        with self._lock:
            self._epochs[v] = payload
            if inc_d is not None:
                self._incs[v] = inc_d
            self._prev_map = OSDMap.from_dict(
                decode_epoch_payload(payload)["map"])
            self._committed_epoch = v
            keep = self.ctx.conf["mon_max_map_epochs"]
            for e in sorted(self._epochs)[:-keep]:
                del self._epochs[e]
                self._incs.pop(e, None)
                if self.store_dir:
                    try:
                        os.unlink(os.path.join(
                            self.store_dir, f"osdmap.{e}.json"))
                    except OSError:
                        pass
            # a deleted pool's PGs must leave the PGMap too, or stale
            # states poison health checks forever
            for pgid in [g for g in self._pg_stats
                         if g[0] not in self.map.pools]:
                del self._pg_stats[pgid]
            for key in [k for k in self._pg_io
                        if k[0][0] not in self.map.pools]:
                del self._pg_io[key]
            for pid in [p for p in self._pool_stat_ring
                        if p not in self.map.pools]:
                del self._pool_stat_ring[pid]
                self._progress_open.pop(pid, None)
            if self.store_dir:
                os.makedirs(self.store_dir, exist_ok=True)
                with open(os.path.join(
                        self.store_dir, f"osdmap.{v}.json"), "w") as f:
                    f.write(payload)

    # Paxos durability (Paxos.cc persistent accepted_pn + uncommitted
    # value via MonitorDBStore): the quorum layer writes its promise
    # epoch and any staged-but-uncommitted entry here BEFORE acking, so
    # restarts cannot lose a majority-staged entry or un-promise.
    def store_quorum_state(self, state: Dict) -> None:
        if not self.store_dir:
            return
        os.makedirs(self.store_dir, exist_ok=True)
        tmp = os.path.join(self.store_dir, ".quorum.json.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.store_dir, "quorum.json"))

    def load_quorum_state(self) -> Optional[Dict]:
        if not self.store_dir:
            return None
        try:
            return json.load(open(os.path.join(self.store_dir,
                                               "quorum.json")))
        except (OSError, ValueError):
            return None

    def _restore_committed(self) -> None:
        """Roll live state back to the last committed entry (a failed
        quorum replication left only in-memory mutations)."""
        with self._lock:
            if self._committed_epoch == 0:
                self.map.epoch = 0
                return
            p = decode_epoch_payload(self._epochs[self._committed_epoch])
            self.map = OSDMap.from_dict(p["map"])
            self._osd_addrs = {int(k): tuple(a)
                               for k, a in p["osd_addrs"].items()}
            self.ec_profiles = dict(p["ec_profiles"])

    def _map_payload(self) -> Dict:
        return {"epoch": self.map.epoch,
                "map": self.map.to_dict(),
                "osd_addrs": {str(k): list(v)
                              for k, v in self._osd_addrs.items()},
                "ec_profiles": self.ec_profiles}

    def get_epoch_payload(self, epoch: int) -> Optional[Dict]:
        with self._lock:
            raw = self._epochs.get(epoch)
        return decode_epoch_payload(raw) if raw else None

    def _wire_full(self, payload: Dict) -> Dict:
        """Full-map payload for the WIRE: the map travels as its
        versioned binary encode (OSDMap::encode role — ~200 KB for a
        10k-OSD map vs ~3 MB of JSON), cached per epoch since every
        subscriber gets the same bytes.  The JSON form stays in the
        epoch STORE (debuggable, quorum-fetchable)."""
        epoch = payload.get("epoch")
        with self._lock:
            cached = getattr(self, "_wire_cache", None)
        if cached is not None and cached[0] == epoch:
            map_bin = cached[1]
        else:
            from ..osdmap.bincode_maps import osdmap_to_bytes

            map_bin = osdmap_to_bytes(OSDMap.from_dict(
                payload["map"]))
            with self._lock:
                self._wire_cache = (epoch, map_bin)
        p = {k: v for k, v in payload.items() if k != "map"}
        p["map_bin"] = map_bin
        return p

    def _push_maps(self) -> None:
        """Queue the newest committed epoch to every subscriber.  Each
        subscriber has its own pusher thread + bounded queue, so a hung
        or slow peer delays only itself, never the commit path (the
        round-3 review's push-isolation gap)."""
        with self._lock:
            epoch = self._committed_epoch
            if epoch == 0:
                return
            inc = self._incs.get(epoch)
            payload = None if inc is not None else \
                decode_epoch_payload(self._epochs[epoch])
            extras = {"osd_addrs": {str(k): list(v) for k, v in
                                    self._osd_addrs.items()},
                      "ec_profiles": dict(self.ec_profiles)}
            pushers = list(self._pushers.values())
        if inc is not None:
            msg = {"type": "map_inc", "inc": inc, **extras}
        else:
            msg = {"type": "map_update",
                   "payload": self._wire_full(payload)}
        for p in pushers:
            p.push(msg)

    @nonblocking
    def _h_get_inc(self, msg: Dict) -> Dict:
        with self._lock:
            got = self._incs.get(int(msg["epoch"]))
        return {"inc": got} if got is not None else \
            {"error": f"no incremental for epoch {msg['epoch']}"}

    # -- handlers --------------------------------------------------------
    def _h_boot(self, msg: Dict) -> Dict:
        osd = int(msg["osd"])
        addr = tuple(msg["addr"])
        with self._lock:
            now = time.monotonic()
            if self.map.exists(osd) and not self.map.is_up(osd) \
                    and self._is_dampened(osd, now):
                # osd_markdown_log dampening: a daemon that flapped
                # through the markdown budget stays down until its
                # oldest markdown ages out of the window (the delayed
                # re-boot role); it keeps re-beating boot and gets in
                # once the log drains
                self._last_beat[osd] = now  # alive, just dampened
                return {"epoch": self.map.epoch, "dampened": True}
            addr_changed = self._osd_addrs.get(osd) != addr
            self._osd_addrs[osd] = addr
            self._last_beat[osd] = now
            # a booting incarnation starts with a clean slate: stale
            # peer reports against the previous incarnation must not
            # insta-kill it (the markdown/boot oscillation guard)
            self._failure_reports.pop(osd, None)
            self._up_from[osd] = now
            was_up = self.map.exists(osd) and self.map.is_up(osd)
            # weight policy on boot (OSDMonitor::prepare_boot): an osd
            # the monitor auto-outed comes back in; an osd an admin
            # marked out (weight 0 via mark_out) STAYS out; a known osd
            # keeps whatever weight it had
            if self.map.exists(osd):
                weight = self.map.osd_weight[osd]
                if osd in self._auto_out:
                    weight = self._auto_out[osd]
            else:
                weight = msg.get("weight", 0x10000)
            changed = (not was_up) or \
                weight != (self.map.osd_weight[osd]
                           if self.map.exists(osd) else None)
            self._auto_out.pop(osd, None)
            self.map.add_osd(osd, weight=weight)
        if changed or addr_changed:
            # a fast reboot keeps the osd "up" but rebinds its socket:
            # the new address must reach every peer via a new epoch;
            # any weight/up change must also land in the epoch store
            self._commit(f"osd.{osd} boot")
        self.log.dout(1, f"osd.{osd} booted at {msg['addr']}")
        return {"epoch": self.map.epoch}

    @nonblocking
    def _h_heartbeat(self, msg: Dict) -> None:
        osd = int(msg["osd"])
        push = None
        with self._lock:
            now = time.monotonic()
            self._last_beat[osd] = now
            # SLO cargo: overwrite each beat, so a beacon WITHOUT the
            # keys (ops drained, pings recovered) clears the daemon's
            # entry and the health checks fall away with it
            self._osd_slo[osd] = {
                "ts": now,
                "slow_ops": msg.get("slow_ops"),
                "slow_pings": msg.get("slow_pings")}
            if self.map.exists(osd) and not self.map.is_up(osd) \
                    and self._committed_epoch \
                    and now - self._down_nudge.get(osd, 0.0) > 1.0:
                pusher = self._pushers.get(f"osd.{osd}")
                if pusher is not None:
                    self._down_nudge[osd] = now
                    payload = decode_epoch_payload(
                        self._epochs[self._committed_epoch])
                    push = (pusher, payload)
        if push is not None:
            # a beat from an osd the map says is DOWN: the daemon is
            # alive but missed its own markdown epoch (a healed
            # partition dropped the push without replay) — shove the
            # committed map at it so it can see itself down, request
            # a re-boot, and rejoin without waiting for an unrelated
            # commit to come along
            push[0].push({"type": "map_update",
                          "payload": self._wire_full(push[1])})
        self.pc.inc("beats")
        return None

    @nonblocking
    def _h_get_map(self, msg: Dict) -> Dict:
        epoch = msg.get("epoch")
        if epoch is not None:
            got = self.get_epoch_payload(int(epoch))
            return self._wire_full(got) if got is not None else \
                {"error": f"no epoch {epoch}"}
        with self._lock:
            if self._committed_epoch == 0:
                return {"error": "no committed map yet"}
            payload = decode_epoch_payload(self._epochs[self._committed_epoch])
        return self._wire_full(payload)

    def _h_subscribe(self, msg: Dict) -> Dict:
        name, addr = msg["name"], tuple(msg["addr"])
        with self._lock:
            old = self._subscribers.get(name)
            self._subscribers[name] = addr
            if old != addr:
                stale = self._pushers.pop(name, None)
                self._pushers[name] = _SubPusher(self.msgr, addr)
            else:
                stale = None
            if self._committed_epoch == 0:
                reply = {"error": "no committed map yet"}
            else:
                reply = decode_epoch_payload(self._epochs[self._committed_epoch])
        if stale is not None:
            stale.stop()
        return self._wire_full(reply) if "map" in reply else reply

    def _h_mark_down(self, msg: Dict) -> Dict:
        return {"epoch": self.mark_down(int(msg["osd"]))}

    def _h_mark_out(self, msg: Dict) -> Dict:
        osd = int(msg["osd"])
        with self._lock:
            self.map.osd_weight[osd] = 0
            self._auto_out.pop(osd, None)  # admin out sticks
        return {"epoch": self._commit(f"osd.{osd} out")}

    def _h_pg_temp_set(self, msg: Dict) -> Dict:
        """Primary-requested acting override (OSDMonitor pg_temp flow):
        keeps a PG served by its data holders while the new up set
        backfills; an empty list clears the override."""
        pgid = (int(msg["pool"]), int(msg["ps"]))
        osds = [int(o) for o in msg.get("osds", [])]
        with self._lock:
            cur = self.map.pg_temp.get(pgid)
            if osds:
                if cur == osds:
                    return {"epoch": self.map.epoch}
                self.map.pg_temp[pgid] = osds
            else:
                if cur is None:
                    return {"epoch": self.map.epoch}
                del self.map.pg_temp[pgid]
        return {"epoch": self._commit(f"pg_temp {pgid}")}

    def _h_pg_upmap_items_set(self, msg: Dict) -> Dict:
        """Balancer-proposed remap pairs (the OSDMonitor
        osd pg-upmap-items flow, OSDMonitor.cc:13736): install the
        PG's ``pg_upmap_items`` exception list and commit — the change
        rides the incremental's new_pg_upmap_items delta to every
        subscriber.  An empty list clears the entry."""
        pgid = (int(msg["pool"]), int(msg["ps"]))
        items = [(int(f), int(t)) for f, t in msg.get("items", [])]
        with self._lock:
            pool = self.map.pools.get(pgid[0])
            if pool is None:
                return {"error": f"no pool {pgid[0]}"}
            if pgid[1] >= pool.pg_num:
                return {"error": f"ps {pgid[1]} >= pg_num "
                                 f"{pool.pg_num}"}
            if len(items) > pool.size:
                # the reference monitor rejects wider-than-pool entry
                # lists (and the batched pipeline's fixed result
                # width could not hold them)
                return {"error": f"{len(items)} pairs > pool size "
                                 f"{pool.size}"}
            cur = self.map.pg_upmap_items.get(pgid)
            if items:
                if cur == items:
                    return {"epoch": self.map.epoch}
                self.map.pg_upmap_items[pgid] = items
            else:
                if cur is None:
                    return {"epoch": self.map.epoch}
                del self.map.pg_upmap_items[pgid]
        return {"epoch": self._commit(f"pg_upmap_items {pgid}")}

    def _h_mgr_health_report(self, msg: Dict) -> None:
        """Mgr-module health checks (the MMgrBeacon health payload
        role): kept beside the PGMap observability state — NOT part
        of the replicated epoch log — and folded into ``_h_health``
        while fresh.  The mgr broadcasts to every member, so any mon
        serves the same fold."""
        checks = {str(k): str(v)
                  for k, v in (msg.get("checks") or {}).items()}
        with self._lock:
            self._mgr_health = {
                "name": msg.get("name", "mgr"),
                "checks": checks,
                "ts": time.monotonic()}
        return None

    def _h_pool_create(self, msg: Dict) -> Dict:
        pool_id = int(msg["pool_id"])
        with self._lock:
            self.map.pools[pool_id] = PgPool(**msg["pool"])
        return {"epoch": self._commit(f"pool {pool_id} create")}

    def _h_pool_delete(self, msg: Dict) -> Dict:
        """Pool removal (OSDMonitor prepare_pool_op delete): rides the
        incremental's old_pools delta; daemons drop the pool's PGs on
        the next map."""
        pool_id = int(msg["pool_id"])
        with self._lock:
            if pool_id not in self.map.pools:
                return {"error": f"no pool {pool_id}"}
            del self.map.pools[pool_id]
            for pgid in [g for g in self.map.pg_temp
                         if g[0] == pool_id]:
                del self.map.pg_temp[pgid]
        return {"epoch": self._commit(f"pool {pool_id} delete")}

    def _h_reweight(self, msg: Dict) -> Dict:
        """`ceph osd reweight` (0.0-1.0 override weight)."""
        osd = int(msg["osd"])
        w = int(msg["weight"])  # 16.16 fixed point
        with self._lock:
            if not self.map.exists(osd):
                return {"error": f"no osd.{osd}"}
            self.map.osd_weight[osd] = max(0, min(0x10000, w))
            self._auto_out.pop(osd, None)
        return {"epoch": self._commit(f"osd.{osd} reweight")}

    def _h_ec_profile_set(self, msg: Dict) -> Dict:
        with self._lock:
            self.ec_profiles[msg["name"]] = dict(msg["profile"])
        return {"epoch": self._commit(f"ec profile {msg['name']}")}

    _IO_KEYS = ("rd_ops", "rd_bytes", "wr_ops", "wr_bytes",
                "degraded_reads", "ec_encode_ops", "ec_encode_bytes")

    def _h_pg_stats(self, msg: Dict) -> None:
        """One pg_stats beacon.  Io blocks are recorded per reporting
        OSD (EC reads land on every holder, not the primary); PG
        state/recovery only from primary beacons, which also refresh
        the per-PG staleness clock (the STALE_PG_STATS input)."""
        if faults._ACTIVE and faults.fires("mon.drop_pg_stats",
                                           f"mon.{self.rank}"):
            return None  # beacon lost on the floor: staleness clock
            # keeps ticking toward STALE_PG_STATS
        pgid = (int(msg["pool"]), int(msg["ps"]))
        now = time.monotonic()
        self.pc.inc("pg_stat_reports")
        reporter = int(msg.get("osd", msg.get("primary", -1)))
        with self._lock:
            if isinstance(msg.get("io"), dict):
                self._pg_io[(pgid, reporter)] = {
                    "io": {k: float(msg["io"].get(k, 0))
                           for k in self._IO_KEYS},
                    "last_report": now}
            if msg.get("io_only"):
                return None
            cur = self._pg_stats.get(pgid)
            if cur is None or int(msg.get("epoch", 0)) >= \
                    int(cur.get("epoch", 0)):
                self._pg_stats[pgid] = {
                    "state": msg.get("state", "unknown"),
                    "objects": int(msg.get("objects", 0)),
                    "primary": int(msg.get("primary", -1)),
                    "epoch": int(msg.get("epoch", 0)),
                    "degraded_objects": int(
                        msg.get("degraded_objects", 0)),
                    "recovery": {
                        k: float((msg.get("recovery") or {})
                                 .get(k, 0))
                        for k in ("objects_recovered",
                                  "bytes_recovered")},
                    "last_report": now}
                # progress events open ON RECEIPT of a degraded
                # report, not on the sampling tick: a small recovery
                # can complete inside one tick interval, and the
                # event must still exist to complete at 1.0
                if "degraded" in msg.get("state", ""):
                    self._open_progress(pgid[0], time.time())
        return None

    def _open_progress(self, pool_id: int, wall: float) -> None:
        """Open (or bump the peak of) the pool's recovery event
        (call under self._lock)."""
        cur = sum(1 for g, st in self._pg_stats.items()
                  if g[0] == pool_id
                  and "degraded" in st.get("state", ""))
        ev = self._progress_open.get(pool_id)
        if ev is None:
            self._progress_seq += 1
            ev = {"id": f"recovery-{pool_id}-{self._progress_seq}",
                  "pool": pool_id,
                  "message": f"Recovery: pool {pool_id}",
                  "started_at": wall, "updated_at": wall,
                  "peak_degraded_pgs": max(1, cur),
                  "degraded_pgs": cur,
                  "fraction": 0.0, "rate_bps": 0.0, "done": False}
            self._progress_open[pool_id] = ev
            self.log.dout(1, f"progress: {ev['id']} started "
                             f"({cur} pgs degraded)")
        else:
            ev["peak_degraded_pgs"] = max(ev["peak_degraded_pgs"],
                                          cur)
            ev["degraded_pgs"] = cur
            ev["updated_at"] = wall

    def _pg_summary(self) -> Dict:
        """PGMap aggregation (call under self._lock)."""
        by_state: Dict[str, int] = {}
        objects = 0
        degraded_pgs = 0
        for st in self._pg_stats.values():
            by_state[st["state"]] = by_state.get(st["state"], 0) + 1
            objects += st["objects"]
            if "degraded" in st["state"]:
                degraded_pgs += 1
        total = sum(p.pg_num for p in self.map.pools.values())
        return {"pgs_total": total,
                "pgs_reported": len(self._pg_stats),
                "by_state": by_state, "objects": objects,
                "degraded_pgs": degraded_pgs}

    # -- the continuous stats plane (PGMap ring / mgr progress) --------
    def _observability_tick(self, now: float) -> None:
        """Every monitor tick (leader or peon — this is local
        observability state, not replicated): fold the per-PG reports
        into per-pool stat samples, drive recovery progress events,
        and age out stale pg_stats entries."""
        grace = self.ctx.conf["mon_pg_stats_stale_grace"]
        retention = self.ctx.conf["mon_pool_stats_retention"]
        wall = time.time()
        with self._lock:
            # age out entries no primary has refreshed (a PG whose
            # every holder died must not poison health forever);
            # STALE is the intermediate, surfaced state
            expiry = 4 * grace
            stale = 0
            for pgid in list(self._pg_stats):
                age = now - self._pg_stats[pgid].get("last_report",
                                                    now)
                if age > expiry:
                    del self._pg_stats[pgid]
                elif age > grace:
                    stale += 1
            self.pc.set("stale_pgs", stale)
            for key in list(self._pg_io):
                if now - self._pg_io[key].get("last_report", now) \
                        > expiry:
                    del self._pg_io[key]
            for pool_id in self.map.pools:
                sample = {"ts": wall}
                for k in self._IO_KEYS:
                    sample[k] = sum(
                        rec["io"].get(k, 0)
                        for (pgid, _o), rec in self._pg_io.items()
                        if pgid[0] == pool_id)
                sample["objects_recovered"] = 0.0
                sample["bytes_recovered"] = 0.0
                sample["degraded_objects"] = 0
                sample["degraded_pgs"] = 0
                sample["objects"] = 0
                for pgid, st in self._pg_stats.items():
                    if pgid[0] != pool_id:
                        continue
                    rec = st.get("recovery") or {}
                    sample["objects_recovered"] += rec.get(
                        "objects_recovered", 0)
                    sample["bytes_recovered"] += rec.get(
                        "bytes_recovered", 0)
                    sample["degraded_objects"] += st.get(
                        "degraded_objects", 0)
                    sample["objects"] += st.get("objects", 0)
                    if "degraded" in st.get("state", ""):
                        sample["degraded_pgs"] += 1
                ring = self._pool_stat_ring.get(pool_id)
                if ring is None or ring.maxlen != retention:
                    ring = collections.deque(
                        ring or (), maxlen=max(2, int(retention)))
                    self._pool_stat_ring[pool_id] = ring
                ring.append(sample)
                self._update_progress(pool_id, sample, wall)

    def _update_progress(self, pool_id: int, sample: Dict,
                         wall: float) -> None:
        """mgr progress-module role (call under self._lock): a pool
        entering degraded state opens a recovery event; completion
        fraction tracks degraded PGs recovered vs the peak; the event
        completes at fraction 1.0 when the pool is clean again."""
        cur = sample["degraded_pgs"]
        ev = self._progress_open.get(pool_id)
        if ev is None:
            if cur > 0:
                self._open_progress(pool_id, wall)
            return
        ev["peak_degraded_pgs"] = max(ev["peak_degraded_pgs"], cur)
        ev["degraded_pgs"] = cur
        ev["updated_at"] = wall
        ring = self._pool_stat_ring.get(pool_id)
        if ring is not None and len(ring) >= 2:
            a, b = ring[-2], ring[-1]
            dt = max(1e-9, b["ts"] - a["ts"])
            ev["rate_bps"] = max(0.0, (b["bytes_recovered"]
                                       - a["bytes_recovered"]) / dt)
        if cur <= 0:
            ev["fraction"] = 1.0
            ev["done"] = True
            ev["ended_at"] = wall
            self._progress_done.append(ev)
            del self._progress_open[pool_id]
            self.log.dout(1, f"progress: {ev['id']} complete")
        else:
            ev["fraction"] = round(
                1.0 - cur / max(1, ev["peak_degraded_pgs"]), 4)

    def _h_pool_stats(self, msg: Dict) -> Dict:
        """`ceph_cli pool-stats`: per-pool rate SERIES derived from
        the sample ring at read time (deltas clamped at 0: a primary
        change resets cumulative counters)."""
        want = msg.get("pool")
        with self._lock:
            rings = {pid: list(ring) for pid, ring in
                     self._pool_stat_ring.items()
                     if want is None or pid == int(want)}
        pools: Dict[str, Dict] = {}
        rate_keys = (("wr_bps", "wr_bytes"), ("rd_bps", "rd_bytes"),
                     ("wr_ops_s", "wr_ops"), ("rd_ops_s", "rd_ops"),
                     ("ec_encode_bps", "ec_encode_bytes"),
                     ("recovery_bps", "bytes_recovered"),
                     ("recovery_objs_s", "objects_recovered"))
        for pid, samples in rings.items():
            series = []
            for a, b in zip(samples, samples[1:]):
                dt = max(1e-9, b["ts"] - a["ts"])
                row = {"ts": b["ts"], "dt": round(dt, 3),
                       "degraded_pgs": b["degraded_pgs"],
                       "degraded_objects": b["degraded_objects"]}
                for out_k, in_k in rate_keys:
                    row[out_k] = max(0.0, (b.get(in_k, 0)
                                           - a.get(in_k, 0)) / dt)
                series.append(row)
            pools[str(pid)] = {
                "series": series,
                "current": dict(samples[-1]) if samples else {}}
        return {"pools": pools}

    def _h_progress(self, _msg: Dict) -> Dict:
        """`ceph_cli progress`: open + recently completed recovery
        events (the mgr progress-module surface)."""
        with self._lock:
            events = [dict(e) for e in
                      self._progress_open.values()]
            events += [dict(e) for e in self._progress_done]
        events.sort(key=lambda e: e.get("started_at", 0))
        return {"events": events}

    def _h_health(self, _msg: Dict) -> Dict:
        """HEALTH_OK / HEALTH_WARN with typed, coded reasons — the
        `ceph health` surface (src/mon/HealthMonitor.cc role).  Each
        check is "CODE: summary"; the machine-readable code list rides
        alongside as ``check_codes``."""
        now = time.monotonic()
        grace = self.ctx.conf["mon_pg_stats_stale_grace"]
        slow_grace = self.ctx.conf["mon_slow_recovery_grace"]
        with self._lock:
            # down-AND-IN osds (the reference's OSD_DOWN scope): an
            # osd the cluster already marked out has been remapped
            # around — it no longer degrades service, so it must not
            # pin health at WARN after recovery completes
            down = [o for o in range(self.map.max_osd)
                    if self.map.exists(o) and not self.map.is_up(o)
                    and self.map.osd_weight[o] > 0]
            # sorted() snapshots the keys: _is_dampened prunes (and
            # may delete) log entries while we iterate
            flapping = [o for o in sorted(self._markdown_log)
                        if self._is_dampened(o, now)]
            pgs = self._pg_summary()
            stale = [pgid for pgid, st in self._pg_stats.items()
                     if now - st.get("last_report", now) > grace]
            recovering = [dict(e) for e in
                          self._progress_open.values()]
            slow = [e for e in recovering
                    if time.time() - e.get("started_at", 0)
                    > slow_grace]
            mgr_checks: Dict[str, str] = {}
            if self._mgr_health is not None and \
                    now - self._mgr_health["ts"] < grace:
                mgr_checks = dict(self._mgr_health["checks"])
            # fresh per-daemon SLO cargo from the beacons: slow ops
            # (SLOW_OPS) and heartbeat-RTT breaches
            # (OSD_SLOW_PING_TIME); entries past the grace are a dead
            # or wedged reporter's last words, not live state
            slow_ops: Dict[int, Dict] = {}
            slow_pings: Dict[int, list] = {}
            for osd, e in list(self._osd_slo.items()):
                if now - e["ts"] > 4 * grace:
                    del self._osd_slo[osd]
                    continue
                if now - e["ts"] > grace:
                    continue
                so = e.get("slow_ops")
                if so and so.get("count"):
                    slow_ops[osd] = so
                sp = e.get("slow_pings")
                if sp:
                    slow_pings[osd] = sp
        checks = []
        if slow_ops:
            # the reference's `N slow ops, oldest one blocked for X
            # sec, daemons [osd.a,osd.b] have slow ops.` summary line
            total = sum(int(s.get("count", 0))
                        for s in slow_ops.values())
            oldest = max(float(s.get("oldest_age", 0.0))
                         for s in slow_ops.values())
            daemons = [f"osd.{o}" for o in sorted(slow_ops)]
            checks.append(
                f"SLOW_OPS: {total} slow ops, oldest one blocked "
                f"for {oldest:.1f} sec, daemons {daemons} have "
                f"slow ops.")
        if slow_pings:
            pairs = sorted(
                ((o, int(b["peer"]), float(b["avg_ms"]))
                 for o, bs in slow_pings.items() for b in bs),
                key=lambda p: p[2], reverse=True)
            worst = ", ".join(f"osd.{a}->osd.{b} {ms:.0f}ms"
                              for a, b, ms in pairs[:8])
            checks.append(
                f"OSD_SLOW_PING_TIME: {len(pairs)} slow osd "
                f"heartbeat pings (worst first): {worst}")
        if down:
            checks.append(f"OSD_DOWN: {len(down)} osds down: {down}")
        if flapping:
            # dampened daemons are auto-outed (not counted by
            # OSD_DOWN's weight>0 scope), so flapping gets its own
            # coded check and clears when the markdown log drains
            checks.append(f"OSD_FLAPPING: {len(flapping)} osd(s) "
                          f"flapping (markdown-dampened): {flapping}")
        if pgs["degraded_pgs"] or recovering:
            # an OPEN recovery event counts: a fast recovery's
            # degraded beacons may be superseded between two health
            # polls, but the cluster WAS degraded until the event
            # completes (mirrors the reference, where PG_DEGRADED
            # clears only when recovery finishes)
            n = max(pgs["degraded_pgs"],
                    max((e["degraded_pgs"] for e in recovering),
                        default=0), 1)
            checks.append(f"PG_DEGRADED: {n} pgs degraded "
                          f"(recovery in progress)")
        not_clean = {s: n for s, n in pgs["by_state"].items()
                     if "clean" not in s}
        if not_clean:
            checks.append(f"pgs not clean: {not_clean}")
        if stale:
            checks.append(
                f"STALE_PG_STATS: {len(stale)} pgs have had no "
                f"primary report for >{grace:.0f}s: "
                f"{sorted(stale)[:8]}")
        for ev in slow:
            age = time.time() - ev["started_at"]
            checks.append(
                f"SLOW_RECOVERY: {ev['id']} open {age:.0f}s at "
                f"fraction {ev['fraction']} "
                f"({ev['rate_bps']:.0f} B/s)")
        if pgs["pgs_reported"] < pgs["pgs_total"]:
            checks.append(
                f"{pgs['pgs_total'] - pgs['pgs_reported']} pgs never "
                f"reported by a primary")
        for code in sorted(mgr_checks):
            checks.append(f"{code}: {mgr_checks[code]}")
        return {"status": "HEALTH_OK" if not checks else "HEALTH_WARN",
                "checks": checks,
                "check_codes": sorted({c.split(":", 1)[0]
                                       for c in checks if ":" in c
                                       and c.split(":", 1)[0].isupper()
                                       }),
                "pgmap": pgs}

    def _h_status(self, _msg: Dict) -> Dict:
        with self._lock:
            up = [o for o in range(self.map.max_osd)
                  if self.map.is_up(o)]
            return {"epoch": self.map.epoch, "up_osds": up,
                    "num_pools": len(self.map.pools),
                    "pgmap": self._pg_summary(),
                    "subscribers": sorted(self._subscribers)}

    # -- failure detection ------------------------------------------------
    def _reporter_subtree(self, osd: int) -> int:
        """CRUSH node id of the reporter's failure-domain subtree at
        ``mon_osd_reporter_subtree_level`` (check_failure's reporter
        dedup: two osds on one host are ONE witness).  An osd not
        placed in the crush tree is its own subtree."""
        from ..crush.wrapper import DEFAULT_TYPES

        level = self.ctx.conf["mon_osd_reporter_subtree_level"]
        want = next((t for t, n in DEFAULT_TYPES.items()
                     if n == level), 1)
        node, hops = osd, 0
        while hops < 16:  # cycle guard; real trees are depth ~4
            hops += 1
            b = next((b for b in self.map.crush.buckets.values()
                      if node in b.items), None)
            if b is None:
                return node
            if b.type >= want:
                return b.id
            node = b.id
        return node

    @nonblocking
    def _h_osd_failure(self, msg: Dict) -> None:
        """OSDMonitor::check_failure — a peer's osd_failure report.
        Mark down only once reports arrive from enough DISTINCT
        failure-domain subtrees: a cut link to one host (or to this
        monitor) can no longer kill a healthy osd on its own."""
        failed = int(msg["osd"])
        reporter = int(msg["frm_osd"])
        self.pc.inc("failure_reports")
        grace = self.ctx.conf["osd_heartbeat_grace"]
        need = self.ctx.conf["mon_osd_min_down_reporters"]
        now = time.monotonic()
        with self._lock:
            if failed == reporter or not self.map.exists(failed):
                return None
            if not self.map.is_up(failed):
                # already down: late reports are stale, not evidence
                # against the NEXT incarnation
                self._failure_reports.pop(failed, None)
                return None
            failed_for = float(msg.get("failed_for", 0.0))
            if now - failed_for < self._up_from.get(failed, 0.0):
                # the reporter's silence window opened before this
                # incarnation booted: stale evidence (the
                # failed_since >= up_from rule) — without it a cut
                # link would re-kill a re-booting osd every beat
                # instead of after a fresh full grace
                return None
            reps = self._failure_reports.setdefault(failed, {})
            reps[reporter] = now
            for r, ts in list(reps.items()):
                if now - ts > 2 * grace:  # report decay
                    del reps[r]
            subtrees = {self._reporter_subtree(r) for r in reps}
            enough = len(subtrees) >= need
            reporters = sorted(reps)
        if enough:
            self.log.dout(
                1, f"osd.{failed} failed by {len(subtrees)} "
                   f"subtree(s), reporters {reporters}")
            try:
                self.mark_down(failed)  # block-ok: markdown commits synchronously by design — epoch order would break if deferred; replicate is deadline-bounded (5s call timeout, dead peons skipped) and the store write is a local rename
            except RuntimeError as e:
                self.log.derr(f"failure markdown aborted: {e}")
        return None

    def _is_dampened(self, osd: int, now: float) -> bool:
        """True while the osd's markdown log crosses
        ``osd_max_markdown_count`` within ``osd_max_markdown_period``
        (caller holds the lock).  Prunes the log as a side effect."""
        log = self._markdown_log.get(osd)
        if not log:
            return False
        period = self.ctx.conf["osd_max_markdown_period"]
        while log and now - log[0] > period:
            log.popleft()
        if not log:
            del self._markdown_log[osd]
            return False
        return len(log) >= self.ctx.conf["osd_max_markdown_count"]

    def mark_down(self, osd: int) -> int:
        from ..osdmap.osdmap import OSD_EXISTS

        with self._lock:
            if not self.map.is_up(osd):
                return self.map.epoch
            self.map.osd_state[osd] = OSD_EXISTS  # up bit cleared
            self._last_beat.pop(osd, None)
            self._down_since[osd] = time.monotonic()
            # consumed: the reports did their job; a fresh incarnation
            # must be condemned by fresh evidence, not leftovers
            self._failure_reports.pop(osd, None)
            now = time.monotonic()
            mdl = self._markdown_log.setdefault(
                osd, collections.deque())
            mdl.append(now)
            dampened = self._is_dampened(osd, now)
            if dampened and self.map.osd_weight[osd] > 0:
                # flapping: don't wait out mon_osd_down_out_interval —
                # remap around the unstable daemon NOW (auto-out, so
                # a stable re-boot restores the weight)
                self._auto_out[osd] = self.map.osd_weight[osd]
                self.map.osd_weight[osd] = 0
                self._down_since.pop(osd, None)
        self.pc.inc("markdowns")
        if dampened:
            self.pc.inc("markdowns_dampened")
            self.log.dout(1, f"osd.{osd} marked down (flapping: "
                             f"dampened + auto-out)")
        else:
            self.log.dout(1, f"osd.{osd} marked down")
        return self._commit(f"osd.{osd} down")

    def _tick_loop(self) -> None:
        grace = self.ctx.conf["osd_heartbeat_grace"]
        interval = self.ctx.conf["osd_heartbeat_interval"]
        out_interval = self.ctx.conf["mon_osd_down_out_interval"]
        # the direct osd->mon beacon is liveness-of-last-resort only:
        # peer osd_failure reports (check_failure) are the primary
        # detector, so a beacon gap alone — a cut mon link, a loaded
        # beat thread — gets a MUCH longer rope before the monitor
        # acts unilaterally (the mon_osd_report_timeout role)
        report_timeout = self.ctx.conf["mon_osd_report_timeout"] \
            or 5 * grace
        while self._running:
            time.sleep(interval / 2)  # fault-ok: failure-detection
            # tick cadence, not retry pacing against a failing peer
            # the stats plane ticks on EVERY member (observability is
            # local state; any mon serves pool-stats/progress/health)
            try:
                self._observability_tick(time.monotonic())
            except Exception as e:
                self.log.derr(f"observability tick failed: {e}")
            if self.quorum is not None and not self.quorum.is_leader():
                continue  # failure detection is the leader's job
            now = time.monotonic()
            stale = []
            to_out = []
            with self._lock:
                for osd, last in self._last_beat.items():
                    if now - last > report_timeout and \
                            self.map.is_up(osd):
                        stale.append(osd)
                # down -> out after the grace window: clearing the
                # in/out weight is what makes CRUSH remap the osd's
                # positions so backfill can begin (the reference's
                # mon_osd_down_out_interval flow)
                for osd, since in list(self._down_since.items()):
                    if self.map.is_up(osd):
                        del self._down_since[osd]
                    elif now - since > out_interval and \
                            self.map.osd_weight[osd] > 0:
                        to_out.append(osd)
                        del self._down_since[osd]
            # a lost quorum mid-commit raises; the tick thread must
            # survive it (the next leader retries the mark-down)
            try:
                for osd in stale:
                    self.log.dout(1, f"osd.{osd} heartbeat stale")
                    self.mark_down(osd)
                for osd in to_out:
                    self.log.dout(1, f"osd.{osd} auto-out")
                    with self._lock:
                        self._auto_out[osd] = self.map.osd_weight[osd]
                        self.map.osd_weight[osd] = 0
                    self._commit(f"osd.{osd} auto-out")
            except RuntimeError as e:
                self.log.derr(f"tick commit aborted: {e}")


class _SubPusher:
    """One subscriber's map-push lane: a bounded queue drained by its
    own thread.  A peer that stops reading fills only its own queue
    (oldest entries dropped — it will catch up via incrementals or a
    full fetch) and can never stall the monitor's commit path."""

    def __init__(self, msgr: Messenger, addr: Addr, depth: int = 64):
        self.msgr = msgr
        self.addr = tuple(addr)
        self.q: "queue.Queue[Optional[Dict]]" = queue.Queue(depth)
        self._th = threading.Thread(target=self._run, daemon=True,
                                    name=f"mon-push:{addr[1]}")
        self._th.start()

    def push(self, msg: Dict) -> None:
        while True:
            try:
                self.q.put_nowait(msg)
                return
            except queue.Full:
                try:
                    self.q.get_nowait()  # drop-oldest
                except queue.Empty:
                    pass

    def _run(self) -> None:
        while True:
            msg = self.q.get()
            if msg is None:
                return
            self.msgr.send(self.addr, msg)

    def stop(self) -> None:
        try:
            self.q.put_nowait(None)
        except queue.Full:
            pass  # drain beats a leak; the daemon thread dies with us
