"""Monitor — the cluster-map authority and failure detector.

The role of src/mon (Monitor.cc / OSDMonitor.cc / MonitorDBStore.h):
it owns the OSDMap, bumps epochs on every state change, retains full
maps per epoch (the MonitorDBStore analogue — any daemon can resume at
any epoch), tracks osd boot/heartbeat liveness, and marks osds down
after ``osd_heartbeat_grace`` without a beat (OSD::handle_osd_ping →
OSDMonitor flow, src/osd/OSD.cc:5487 / ceph_osd.cc:544).  Map changes
push to subscribers (MonClient subscription role) through per-peer
queues so one hung subscriber can never stall the commit path.

Runs standalone (a single authority) or as one of N quorum members:
``set_peers(rank, addrs)`` before ``start()`` attaches the election +
replicated-log layer (services/quorum.py — the ElectionLogic/Paxos
role).  In quorum mode every epoch is majority-replicated before it
becomes visible, write commands are forwarded to the leader, reads and
subscriptions are served by any member, and only the leader runs
failure detection.  (SURVEY §2.5 Monitor row.)
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..analysis.lockdep import make_lock, make_rlock
from ..common import encoding
from ..common.context import Context
from ..common.op_tracker import OpTracker
from ..msg.messenger import Addr, Messenger
from ..osdmap.osdmap import OSDMap, PgPool
from .quorum import Quorum

# the epoch-store payload format (MonitorDBStore full-map rows,
# wirecheck entry mon.epoch_payload): one envelope around
# {epoch, map, osd_addrs, ec_profiles}.  Files written before the
# migration are raw dicts (writer v0) and keep decoding, so a monitor
# resumes from an old store_dir unchanged.
EPOCH_PAYLOAD_V = 1


def encode_epoch_payload(payload: Dict) -> str:
    return encoding.encode(payload, EPOCH_PAYLOAD_V, 1)


def decode_epoch_payload(blob) -> Dict:
    v, d = encoding.decode_any(blob, supported=EPOCH_PAYLOAD_V,
                               struct="mon.epoch_payload")
    if not isinstance(d, dict):
        raise encoding.MalformedInput(
            f"mon.epoch_payload v{v}: payload is not an object")
    return d


class Monitor:
    def __init__(self, ctx: Context, osdmap: OSDMap,
                 host: str = "127.0.0.1", port: int = 0,
                 store_dir: Optional[str] = None, keyring=None):
        self.ctx = ctx
        self.log = ctx.logger("mon")
        self.map = osdmap
        self.tracer = ctx.tracer
        # lossless policy: mon↔mon quorum traffic and mon↔osd control
        # frames are sequenced and replayed across reconnects
        self.msgr = Messenger("mon", host, port, keyring=keyring,
                              lossless=True, tracer=self.tracer,
                              perf=ctx.perf)
        self.addr: Addr = self.msgr.addr
        self.store_dir = store_dir
        self._epochs: Dict[int, str] = {}  # epoch -> map json
        # epoch -> Incremental dict (map distribution is O(change):
        # subscribers apply deltas, fetching a full map only on a gap)
        self._incs: Dict[int, Dict] = {}
        self._prev_map: Optional[OSDMap] = None
        self._osd_addrs: Dict[int, Addr] = {}
        self._last_beat: Dict[int, float] = {}
        self._down_since: Dict[int, float] = {}
        # osd -> pre-out weight, for osds the MONITOR outed (auto-out);
        # restored on boot, unlike an admin mark_out which sticks
        self._auto_out: Dict[int, int] = {}
        self._subscribers: Dict[str, Addr] = {}
        self._pushers: Dict[str, "_SubPusher"] = {}
        self._lock = make_rlock("mon::state")
        self._commit_serial = make_lock("mon::commit")
        self._committed_epoch = 0
        self._ticker: Optional[threading.Thread] = None
        self._running = False
        self.quorum: Optional[Quorum] = None
        self.ec_profiles: Dict[str, Dict[str, str]] = {}
        self.pc = ctx.perf.create("mon")
        self.pc.add_u64_counter("epochs")
        self.pc.add_u64_counter("beats")
        self.pc.add_u64_counter("markdowns")
        self.pc.add_histogram("commit_lat")
        self.pc.add_time("commit_time")
        # write commands register here (the leader-side op surface);
        # dump_ops_in_flight / dump_historic_ops over the admin socket
        self.optracker = OpTracker()

        # write commands mutate the map: leader-only in quorum mode
        # (forwarded there); reads are served by any member
        # heartbeats and map reads ride the messenger's control lane:
        # failure detection must never queue behind a burst of client
        # write commands holding every op-pool worker
        for t, h, ctl in (("boot", self._fwd(self._h_boot), False),
                          ("heartbeat", self._fwd(self._h_heartbeat,
                                                  fire_forget=True),
                           True),
                          ("get_map", self._h_get_map, True),
                          ("get_inc", self._h_get_inc, True),
                          ("subscribe", self._h_subscribe, False),
                          ("mark_down", self._fwd(self._h_mark_down),
                           False),
                          ("mark_out", self._fwd(self._h_mark_out),
                           False),
                          ("pool_create",
                           self._fwd(self._h_pool_create), False),
                          ("pool_delete",
                           self._fwd(self._h_pool_delete), False),
                          ("reweight", self._fwd(self._h_reweight),
                           False),
                          ("pg_temp_set",
                           self._fwd(self._h_pg_temp_set), False),
                          ("ec_profile_set",
                           self._fwd(self._h_ec_profile_set), False),
                          ("pg_stats", self._h_pg_stats, False),
                          ("health", self._h_health, False),
                          ("status", self._h_status, False)):
            self.msgr.register(t, h, control=ctl)
        # PGMap role (src/mon/MgrStatMonitor / PGMap.cc): latest
        # primary-reported state per PG — observability state, NOT part
        # of the replicated epoch log (exactly as in the reference);
        # OSDs broadcast stats to every member, so any mon can serve
        # health without quorum traffic
        self._pg_stats: Dict[Tuple[int, int], Dict] = {}

    # -- quorum ---------------------------------------------------------
    def set_peers(self, rank: int, addrs: List[Addr]) -> None:
        """Join an N-monitor quorum (call before start()).  ``addrs``
        is the rank-ordered list of every member including self."""
        self.quorum = Quorum(
            self, rank, addrs,
            lease=self.ctx.conf["mon_lease"],
            election_timeout=self.ctx.conf["mon_election_timeout"])

    def _fwd(self, handler, fire_forget: bool = False):
        """Leader-only write handler: executed locally on the leader,
        forwarded to it from peons (Monitor::forward_request role)."""

        def h(msg: Dict):
            q = self.quorum
            if q is None or q.is_leader():
                with self.optracker.create(
                        "mon_cmd",
                        f"{msg.get('type', '?')} from "
                        f"{msg.get('frm', '?')}"):
                    return handler(msg)
            la = q.leader_addr()
            if la is None:
                return {"error": "no quorum"}
            fwd = {k: v for k, v in msg.items()
                   if k not in ("tid", "mac", "frm")}
            if fire_forget:
                self.msgr.send(la, fwd)
                return None
            return self.msgr.call(la, fwd, timeout=5.0)

        return h

    def last_committed(self) -> int:
        with self._lock:
            return self._committed_epoch

    def committed_entries(self, frm: int, to: int) -> List[Dict]:
        """Committed (version, entry) rows in (frm, to] that are still
        retained — the quorum catch-up feed.  (A member further behind
        than the retention window cannot catch up incrementally; with
        mon_max_map_epochs=500 that does not happen in practice.)"""
        out = []
        with self._lock:
            for v in range(frm + 1, to + 1):
                pay = self._epochs.get(v)
                if pay is None:
                    continue
                out.append({"v": v,
                            "entry": {"payload": pay,
                                      "inc": self._incs.get(v)}})
        return out

    def apply_committed(self, v: int, entry: Dict) -> None:
        """Install a majority-committed epoch (peon apply / leader
        sync): replace live state from the full payload, store, push."""
        p = decode_epoch_payload(entry["payload"])
        with self._lock:
            if v != self._committed_epoch + 1:
                # duplicate/stale delivery (racing catch-up paths must
                # never roll the visible state backwards)
                return
            self.map = OSDMap.from_dict(p["map"])
            self._osd_addrs = {int(k): tuple(a)
                               for k, a in p["osd_addrs"].items()}
            self.ec_profiles = dict(p["ec_profiles"])
            self._store_committed(v, entry["payload"],
                                  entry.get("inc"))
        self.pc.inc("epochs")
        self._push_maps()

    def on_leader(self, uncommitted: Optional[Dict]) -> None:
        """Quorum callback after winning + syncing an election."""
        with self._lock:
            # surviving osds get a full grace window to re-beat before
            # the new leader may mark them down
            now = time.monotonic()
            for o in range(self.map.max_osd):
                if self.map.exists(o) and self.map.is_up(o):
                    self._last_beat.setdefault(o, now)
        if uncommitted is not None and \
                int(uncommitted["v"]) == self.last_committed() + 1:
            # Paxos re-propose: an accepted-but-uncommitted entry that
            # may have reached a majority must survive the failover
            v = int(uncommitted["v"])
            if self.quorum.replicate(v, uncommitted["entry"]):
                self.apply_committed(v, uncommitted["entry"])
        if self.last_committed() == 0:
            try:
                self._commit("genesis")
            except RuntimeError:
                pass  # lost quorum immediately; next leader retries

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self.ctx.conf["admin_socket"]:
            sock = self.ctx.start_admin_socket()
            self.optracker.wire(sock)
            self.tracer.wire(sock)
        self._load_store()
        self.msgr.start()
        self._running = True
        self._ticker = threading.Thread(target=self._tick_loop,
                                        daemon=True, name="mon-tick")
        self._ticker.start()
        if self.quorum is not None:
            self.quorum.start()
        elif self._committed_epoch == 0:
            self._commit("genesis")

    def _load_store(self) -> None:
        """MonitorDBStore reload: a restarted monitor resumes from its
        persisted epochs instead of resetting to genesis (which would
        freeze daemons already holding newer epochs).  Quorum members
        also benefit: a rejoin starts from the local tail and syncs
        only the delta."""
        if not self.store_dir or not os.path.isdir(self.store_dir):
            return
        epochs = []
        for name in os.listdir(self.store_dir):
            if name.startswith("osdmap.") and name.endswith(".json"):
                try:
                    epochs.append(int(name.split(".")[1]))
                except ValueError:
                    continue
        if not epochs:
            return
        keep = self.ctx.conf["mon_max_map_epochs"]
        with self._lock:
            for e in sorted(epochs)[-keep:]:
                try:
                    self._epochs[e] = open(os.path.join(
                        self.store_dir, f"osdmap.{e}.json")).read()
                except OSError:
                    continue
            newest = max(self._epochs)
            p = decode_epoch_payload(self._epochs[newest])
            self.map = OSDMap.from_dict(p["map"])
            self._osd_addrs = {int(k): tuple(a)
                               for k, a in p["osd_addrs"].items()}
            self.ec_profiles = dict(p["ec_profiles"])
            self._prev_map = OSDMap.from_dict(p["map"])
            self._committed_epoch = newest
        self.log.dout(1, f"resumed from stored epoch {newest}")

    def shutdown(self) -> None:
        self._running = False
        if self.quorum is not None:
            self.quorum.shutdown()
        if self._ticker:
            self._ticker.join(timeout=2)
        for p in self._pushers.values():
            p.stop()
        self.msgr.shutdown()
        self.ctx.shutdown()  # admin socket + config observers

    # -- the epoch store (MonitorDBStore role) --------------------------
    def _commit(self, why: str) -> int:
        """Bump the epoch, retain the full map AND its delta, persist,
        notify.  In quorum mode the entry is majority-replicated BEFORE
        it is stored or pushed anywhere; a leader that cannot reach a
        majority rolls back and abdicates, so epochs never fork."""
        from ..osdmap.incremental import diff_maps

        t_commit = time.monotonic()
        with self._commit_serial:
            with self._lock:
                self.map.epoch += 1
                v = self.map.epoch
                payload = encode_epoch_payload(self._map_payload())
                inc_d = None
                if self._prev_map is not None:
                    inc = diff_maps(self._prev_map, self.map)
                    inc.epoch = v
                    inc_d = inc.to_dict()
            if self.quorum is not None:
                if not self.quorum.replicate(
                        v, {"payload": payload, "inc": inc_d}):
                    self._restore_committed()
                    self.quorum.abdicate()
                    raise RuntimeError(
                        "mon: lost quorum; commit aborted")
            self._store_committed(v, payload, inc_d)
        self.pc.inc("epochs")
        dt = time.monotonic() - t_commit
        self.pc.hist_add("commit_lat", dt)
        self.pc.tinc("commit_time", dt)
        self.log.dout(5, f"new epoch {v} ({why})")
        self._push_maps()
        return v

    def _store_committed(self, v: int, payload: str,
                         inc_d: Optional[Dict]) -> None:
        with self._lock:
            self._epochs[v] = payload
            if inc_d is not None:
                self._incs[v] = inc_d
            self._prev_map = OSDMap.from_dict(
                decode_epoch_payload(payload)["map"])
            self._committed_epoch = v
            keep = self.ctx.conf["mon_max_map_epochs"]
            for e in sorted(self._epochs)[:-keep]:
                del self._epochs[e]
                self._incs.pop(e, None)
                if self.store_dir:
                    try:
                        os.unlink(os.path.join(
                            self.store_dir, f"osdmap.{e}.json"))
                    except OSError:
                        pass
            # a deleted pool's PGs must leave the PGMap too, or stale
            # states poison health checks forever
            for pgid in [g for g in self._pg_stats
                         if g[0] not in self.map.pools]:
                del self._pg_stats[pgid]
            if self.store_dir:
                os.makedirs(self.store_dir, exist_ok=True)
                with open(os.path.join(
                        self.store_dir, f"osdmap.{v}.json"), "w") as f:
                    f.write(payload)

    # Paxos durability (Paxos.cc persistent accepted_pn + uncommitted
    # value via MonitorDBStore): the quorum layer writes its promise
    # epoch and any staged-but-uncommitted entry here BEFORE acking, so
    # restarts cannot lose a majority-staged entry or un-promise.
    def store_quorum_state(self, state: Dict) -> None:
        if not self.store_dir:
            return
        os.makedirs(self.store_dir, exist_ok=True)
        tmp = os.path.join(self.store_dir, ".quorum.json.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.store_dir, "quorum.json"))

    def load_quorum_state(self) -> Optional[Dict]:
        if not self.store_dir:
            return None
        try:
            return json.load(open(os.path.join(self.store_dir,
                                               "quorum.json")))
        except (OSError, ValueError):
            return None

    def _restore_committed(self) -> None:
        """Roll live state back to the last committed entry (a failed
        quorum replication left only in-memory mutations)."""
        with self._lock:
            if self._committed_epoch == 0:
                self.map.epoch = 0
                return
            p = decode_epoch_payload(self._epochs[self._committed_epoch])
            self.map = OSDMap.from_dict(p["map"])
            self._osd_addrs = {int(k): tuple(a)
                               for k, a in p["osd_addrs"].items()}
            self.ec_profiles = dict(p["ec_profiles"])

    def _map_payload(self) -> Dict:
        return {"epoch": self.map.epoch,
                "map": self.map.to_dict(),
                "osd_addrs": {str(k): list(v)
                              for k, v in self._osd_addrs.items()},
                "ec_profiles": self.ec_profiles}

    def get_epoch_payload(self, epoch: int) -> Optional[Dict]:
        with self._lock:
            raw = self._epochs.get(epoch)
        return decode_epoch_payload(raw) if raw else None

    def _wire_full(self, payload: Dict) -> Dict:
        """Full-map payload for the WIRE: the map travels as its
        versioned binary encode (OSDMap::encode role — ~200 KB for a
        10k-OSD map vs ~3 MB of JSON), cached per epoch since every
        subscriber gets the same bytes.  The JSON form stays in the
        epoch STORE (debuggable, quorum-fetchable)."""
        epoch = payload.get("epoch")
        with self._lock:
            cached = getattr(self, "_wire_cache", None)
        if cached is not None and cached[0] == epoch:
            map_bin = cached[1]
        else:
            from ..osdmap.bincode_maps import osdmap_to_bytes

            map_bin = osdmap_to_bytes(OSDMap.from_dict(
                payload["map"]))
            with self._lock:
                self._wire_cache = (epoch, map_bin)
        p = {k: v for k, v in payload.items() if k != "map"}
        p["map_bin"] = map_bin
        return p

    def _push_maps(self) -> None:
        """Queue the newest committed epoch to every subscriber.  Each
        subscriber has its own pusher thread + bounded queue, so a hung
        or slow peer delays only itself, never the commit path (the
        round-3 review's push-isolation gap)."""
        with self._lock:
            epoch = self._committed_epoch
            if epoch == 0:
                return
            inc = self._incs.get(epoch)
            payload = None if inc is not None else \
                decode_epoch_payload(self._epochs[epoch])
            extras = {"osd_addrs": {str(k): list(v) for k, v in
                                    self._osd_addrs.items()},
                      "ec_profiles": dict(self.ec_profiles)}
            pushers = list(self._pushers.values())
        if inc is not None:
            msg = {"type": "map_inc", "inc": inc, **extras}
        else:
            msg = {"type": "map_update",
                   "payload": self._wire_full(payload)}
        for p in pushers:
            p.push(msg)

    def _h_get_inc(self, msg: Dict) -> Dict:
        with self._lock:
            got = self._incs.get(int(msg["epoch"]))
        return {"inc": got} if got is not None else \
            {"error": f"no incremental for epoch {msg['epoch']}"}

    # -- handlers --------------------------------------------------------
    def _h_boot(self, msg: Dict) -> Dict:
        osd = int(msg["osd"])
        addr = tuple(msg["addr"])
        with self._lock:
            addr_changed = self._osd_addrs.get(osd) != addr
            self._osd_addrs[osd] = addr
            self._last_beat[osd] = time.monotonic()
            was_up = self.map.exists(osd) and self.map.is_up(osd)
            # weight policy on boot (OSDMonitor::prepare_boot): an osd
            # the monitor auto-outed comes back in; an osd an admin
            # marked out (weight 0 via mark_out) STAYS out; a known osd
            # keeps whatever weight it had
            if self.map.exists(osd):
                weight = self.map.osd_weight[osd]
                if osd in self._auto_out:
                    weight = self._auto_out[osd]
            else:
                weight = msg.get("weight", 0x10000)
            changed = (not was_up) or \
                weight != (self.map.osd_weight[osd]
                           if self.map.exists(osd) else None)
            self._auto_out.pop(osd, None)
            self.map.add_osd(osd, weight=weight)
        if changed or addr_changed:
            # a fast reboot keeps the osd "up" but rebinds its socket:
            # the new address must reach every peer via a new epoch;
            # any weight/up change must also land in the epoch store
            self._commit(f"osd.{osd} boot")
        self.log.dout(1, f"osd.{osd} booted at {msg['addr']}")
        return {"epoch": self.map.epoch}

    def _h_heartbeat(self, msg: Dict) -> None:
        with self._lock:
            self._last_beat[int(msg["osd"])] = time.monotonic()
        self.pc.inc("beats")
        return None

    def _h_get_map(self, msg: Dict) -> Dict:
        epoch = msg.get("epoch")
        if epoch is not None:
            got = self.get_epoch_payload(int(epoch))
            return self._wire_full(got) if got is not None else \
                {"error": f"no epoch {epoch}"}
        with self._lock:
            if self._committed_epoch == 0:
                return {"error": "no committed map yet"}
            payload = decode_epoch_payload(self._epochs[self._committed_epoch])
        return self._wire_full(payload)

    def _h_subscribe(self, msg: Dict) -> Dict:
        name, addr = msg["name"], tuple(msg["addr"])
        with self._lock:
            old = self._subscribers.get(name)
            self._subscribers[name] = addr
            if old != addr:
                stale = self._pushers.pop(name, None)
                self._pushers[name] = _SubPusher(self.msgr, addr)
            else:
                stale = None
            if self._committed_epoch == 0:
                reply = {"error": "no committed map yet"}
            else:
                reply = decode_epoch_payload(self._epochs[self._committed_epoch])
        if stale is not None:
            stale.stop()
        return self._wire_full(reply) if "map" in reply else reply

    def _h_mark_down(self, msg: Dict) -> Dict:
        return {"epoch": self.mark_down(int(msg["osd"]))}

    def _h_mark_out(self, msg: Dict) -> Dict:
        osd = int(msg["osd"])
        with self._lock:
            self.map.osd_weight[osd] = 0
            self._auto_out.pop(osd, None)  # admin out sticks
        return {"epoch": self._commit(f"osd.{osd} out")}

    def _h_pg_temp_set(self, msg: Dict) -> Dict:
        """Primary-requested acting override (OSDMonitor pg_temp flow):
        keeps a PG served by its data holders while the new up set
        backfills; an empty list clears the override."""
        pgid = (int(msg["pool"]), int(msg["ps"]))
        osds = [int(o) for o in msg.get("osds", [])]
        with self._lock:
            cur = self.map.pg_temp.get(pgid)
            if osds:
                if cur == osds:
                    return {"epoch": self.map.epoch}
                self.map.pg_temp[pgid] = osds
            else:
                if cur is None:
                    return {"epoch": self.map.epoch}
                del self.map.pg_temp[pgid]
        return {"epoch": self._commit(f"pg_temp {pgid}")}

    def _h_pool_create(self, msg: Dict) -> Dict:
        pool_id = int(msg["pool_id"])
        with self._lock:
            self.map.pools[pool_id] = PgPool(**msg["pool"])
        return {"epoch": self._commit(f"pool {pool_id} create")}

    def _h_pool_delete(self, msg: Dict) -> Dict:
        """Pool removal (OSDMonitor prepare_pool_op delete): rides the
        incremental's old_pools delta; daemons drop the pool's PGs on
        the next map."""
        pool_id = int(msg["pool_id"])
        with self._lock:
            if pool_id not in self.map.pools:
                return {"error": f"no pool {pool_id}"}
            del self.map.pools[pool_id]
            for pgid in [g for g in self.map.pg_temp
                         if g[0] == pool_id]:
                del self.map.pg_temp[pgid]
        return {"epoch": self._commit(f"pool {pool_id} delete")}

    def _h_reweight(self, msg: Dict) -> Dict:
        """`ceph osd reweight` (0.0-1.0 override weight)."""
        osd = int(msg["osd"])
        w = int(msg["weight"])  # 16.16 fixed point
        with self._lock:
            if not self.map.exists(osd):
                return {"error": f"no osd.{osd}"}
            self.map.osd_weight[osd] = max(0, min(0x10000, w))
            self._auto_out.pop(osd, None)
        return {"epoch": self._commit(f"osd.{osd} reweight")}

    def _h_ec_profile_set(self, msg: Dict) -> Dict:
        with self._lock:
            self.ec_profiles[msg["name"]] = dict(msg["profile"])
        return {"epoch": self._commit(f"ec profile {msg['name']}")}

    def _h_pg_stats(self, msg: Dict) -> None:
        pgid = (int(msg["pool"]), int(msg["ps"]))
        with self._lock:
            cur = self._pg_stats.get(pgid)
            if cur is None or int(msg.get("epoch", 0)) >= \
                    int(cur.get("epoch", 0)):
                self._pg_stats[pgid] = {
                    "state": msg.get("state", "unknown"),
                    "objects": int(msg.get("objects", 0)),
                    "primary": int(msg.get("primary", -1)),
                    "epoch": int(msg.get("epoch", 0))}
        return None

    def _pg_summary(self) -> Dict:
        """PGMap aggregation (call under self._lock)."""
        by_state: Dict[str, int] = {}
        objects = 0
        for st in self._pg_stats.values():
            by_state[st["state"]] = by_state.get(st["state"], 0) + 1
            objects += st["objects"]
        total = sum(p.pg_num for p in self.map.pools.values())
        return {"pgs_total": total,
                "pgs_reported": len(self._pg_stats),
                "by_state": by_state, "objects": objects}

    def _h_health(self, _msg: Dict) -> Dict:
        """HEALTH_OK / HEALTH_WARN with reasons — the `ceph health`
        surface (src/mon/HealthMonitor.cc role)."""
        with self._lock:
            down = [o for o in range(self.map.max_osd)
                    if self.map.exists(o) and not self.map.is_up(o)]
            pgs = self._pg_summary()
        checks = []
        if down:
            checks.append(f"{len(down)} osds down: {down}")
        not_clean = {s: n for s, n in pgs["by_state"].items()
                     if "clean" not in s}
        if not_clean:
            checks.append(f"pgs not clean: {not_clean}")
        if pgs["pgs_reported"] < pgs["pgs_total"]:
            checks.append(
                f"{pgs['pgs_total'] - pgs['pgs_reported']} pgs never "
                f"reported by a primary")
        return {"status": "HEALTH_OK" if not checks else "HEALTH_WARN",
                "checks": checks, "pgmap": pgs}

    def _h_status(self, _msg: Dict) -> Dict:
        with self._lock:
            up = [o for o in range(self.map.max_osd)
                  if self.map.is_up(o)]
            return {"epoch": self.map.epoch, "up_osds": up,
                    "num_pools": len(self.map.pools),
                    "pgmap": self._pg_summary(),
                    "subscribers": sorted(self._subscribers)}

    # -- failure detection ------------------------------------------------
    def mark_down(self, osd: int) -> int:
        from ..osdmap.osdmap import OSD_EXISTS

        with self._lock:
            if not self.map.is_up(osd):
                return self.map.epoch
            self.map.osd_state[osd] = OSD_EXISTS  # up bit cleared
            self._last_beat.pop(osd, None)
            self._down_since[osd] = time.monotonic()
        self.pc.inc("markdowns")
        self.log.dout(1, f"osd.{osd} marked down")
        return self._commit(f"osd.{osd} down")

    def _tick_loop(self) -> None:
        grace = self.ctx.conf["osd_heartbeat_grace"]
        interval = self.ctx.conf["osd_heartbeat_interval"]
        out_interval = self.ctx.conf["mon_osd_down_out_interval"]
        while self._running:
            time.sleep(interval / 2)
            if self.quorum is not None and not self.quorum.is_leader():
                continue  # failure detection is the leader's job
            now = time.monotonic()
            stale = []
            to_out = []
            with self._lock:
                for osd, last in self._last_beat.items():
                    if now - last > grace and self.map.is_up(osd):
                        stale.append(osd)
                # down -> out after the grace window: clearing the
                # in/out weight is what makes CRUSH remap the osd's
                # positions so backfill can begin (the reference's
                # mon_osd_down_out_interval flow)
                for osd, since in list(self._down_since.items()):
                    if self.map.is_up(osd):
                        del self._down_since[osd]
                    elif now - since > out_interval and \
                            self.map.osd_weight[osd] > 0:
                        to_out.append(osd)
                        del self._down_since[osd]
            # a lost quorum mid-commit raises; the tick thread must
            # survive it (the next leader retries the mark-down)
            try:
                for osd in stale:
                    self.log.dout(1, f"osd.{osd} heartbeat stale")
                    self.mark_down(osd)
                for osd in to_out:
                    self.log.dout(1, f"osd.{osd} auto-out")
                    with self._lock:
                        self._auto_out[osd] = self.map.osd_weight[osd]
                        self.map.osd_weight[osd] = 0
                    self._commit(f"osd.{osd} auto-out")
            except RuntimeError as e:
                self.log.derr(f"tick commit aborted: {e}")


class _SubPusher:
    """One subscriber's map-push lane: a bounded queue drained by its
    own thread.  A peer that stops reading fills only its own queue
    (oldest entries dropped — it will catch up via incrementals or a
    full fetch) and can never stall the monitor's commit path."""

    def __init__(self, msgr: Messenger, addr: Addr, depth: int = 64):
        self.msgr = msgr
        self.addr = tuple(addr)
        self.q: "queue.Queue[Optional[Dict]]" = queue.Queue(depth)
        self._th = threading.Thread(target=self._run, daemon=True,
                                    name=f"mon-push:{addr[1]}")
        self._th.start()

    def push(self, msg: Dict) -> None:
        while True:
            try:
                self.q.put_nowait(msg)
                return
            except queue.Full:
                try:
                    self.q.get_nowait()  # drop-oldest
                except queue.Empty:
                    pass

    def _run(self) -> None:
        while True:
            msg = self.q.get()
            if msg is None:
                return
            self.msgr.send(self.addr, msg)

    def stop(self) -> None:
        try:
            self.q.put_nowait(None)
        except queue.Full:
            pass  # drain beats a leak; the daemon thread dies with us
