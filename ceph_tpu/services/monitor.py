"""Monitor — the cluster-map authority and failure detector.

The role of src/mon (Monitor.cc / OSDMonitor.cc / MonitorDBStore.h),
single-instance: it owns the OSDMap, bumps epochs on every state
change, retains full maps per epoch (the MonitorDBStore analogue — any
daemon can resume at any epoch), tracks osd boot/heartbeat liveness,
and marks osds down after ``osd_heartbeat_grace`` without a beat
(OSD::handle_osd_ping → OSDMonitor flow, src/osd/OSD.cc:5487 /
ceph_osd.cc:544).  Map changes push to subscribers (MonClient
subscription role).

Paxos is consciously replaced by the single authority: the reference
runs 3+ mons for its OWN availability; the map semantics downstream
(epochs, incremental catch-up, subscriptions) are what the rest of the
system consumes and are preserved here.  (SURVEY §2.5 Monitor row.)
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common.context import Context
from ..msg.messenger import Addr, Messenger
from ..osdmap.osdmap import OSDMap, PgPool


class Monitor:
    def __init__(self, ctx: Context, osdmap: OSDMap,
                 host: str = "127.0.0.1", port: int = 0,
                 store_dir: Optional[str] = None, keyring=None):
        self.ctx = ctx
        self.log = ctx.logger("mon")
        self.map = osdmap
        self.msgr = Messenger("mon", host, port, keyring=keyring)
        self.addr: Addr = self.msgr.addr
        self.store_dir = store_dir
        self._epochs: Dict[int, str] = {}  # epoch -> map json
        # epoch -> Incremental dict (map distribution is O(change):
        # subscribers apply deltas, fetching a full map only on a gap)
        self._incs: Dict[int, Dict] = {}
        self._prev_map: Optional[OSDMap] = None
        self._osd_addrs: Dict[int, Addr] = {}
        self._last_beat: Dict[int, float] = {}
        self._down_since: Dict[int, float] = {}
        # osd -> pre-out weight, for osds the MONITOR outed (auto-out);
        # restored on boot, unlike an admin mark_out which sticks
        self._auto_out: Dict[int, int] = {}
        self._subscribers: Dict[str, Addr] = {}
        self._lock = threading.RLock()
        self._ticker: Optional[threading.Thread] = None
        self._running = False
        self.ec_profiles: Dict[str, Dict[str, str]] = {}
        self.pc = ctx.perf.create("mon")
        self.pc.add_u64_counter("epochs")
        self.pc.add_u64_counter("beats")
        self.pc.add_u64_counter("markdowns")

        for t, h in (("boot", self._h_boot),
                     ("heartbeat", self._h_heartbeat),
                     ("get_map", self._h_get_map),
                     ("get_inc", self._h_get_inc),
                     ("subscribe", self._h_subscribe),
                     ("mark_down", self._h_mark_down),
                     ("mark_out", self._h_mark_out),
                     ("pool_create", self._h_pool_create),
                     ("ec_profile_set", self._h_ec_profile_set),
                     ("status", self._h_status)):
            self.msgr.register(t, h)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._commit("genesis")
        self.msgr.start()
        self._running = True
        self._ticker = threading.Thread(target=self._tick_loop,
                                        daemon=True, name="mon-tick")
        self._ticker.start()

    def shutdown(self) -> None:
        self._running = False
        if self._ticker:
            self._ticker.join(timeout=2)
        self.msgr.shutdown()

    # -- the epoch store (MonitorDBStore role) --------------------------
    def _commit(self, why: str) -> int:
        """Bump the epoch, retain the full map AND its delta, persist,
        notify."""
        from ..osdmap.incremental import diff_maps

        with self._lock:
            self.map.epoch += 1
            payload = json.dumps(self._map_payload())
            self._epochs[self.map.epoch] = payload
            if self._prev_map is not None:
                inc = diff_maps(self._prev_map, self.map)
                inc.epoch = self.map.epoch
                self._incs[self.map.epoch] = inc.to_dict()
            self._prev_map = OSDMap.from_dict(self.map.to_dict())
            keep = self.ctx.conf["mon_max_map_epochs"]
            for e in sorted(self._epochs)[:-keep]:
                del self._epochs[e]
                self._incs.pop(e, None)
            if self.store_dir:
                os.makedirs(self.store_dir, exist_ok=True)
                with open(os.path.join(
                        self.store_dir,
                        f"osdmap.{self.map.epoch}.json"), "w") as f:
                    f.write(payload)
            epoch = self.map.epoch
        self.pc.inc("epochs")
        self.log.dout(5, f"new epoch {epoch} ({why})")
        self._push_maps()
        return epoch

    def _map_payload(self) -> Dict:
        return {"epoch": self.map.epoch,
                "map": self.map.to_dict(),
                "osd_addrs": {str(k): list(v)
                              for k, v in self._osd_addrs.items()},
                "ec_profiles": self.ec_profiles}

    def get_epoch_payload(self, epoch: int) -> Optional[Dict]:
        with self._lock:
            raw = self._epochs.get(epoch)
        return json.loads(raw) if raw else None

    def _push_maps(self) -> None:
        with self._lock:
            epoch = self.map.epoch
            inc = self._incs.get(epoch)
            payload = None if inc is not None else \
                json.loads(self._epochs[epoch])
            extras = {"osd_addrs": {str(k): list(v) for k, v in
                                    self._osd_addrs.items()},
                      "ec_profiles": dict(self.ec_profiles)}
            subs = list(self._subscribers.values())
        for addr in subs:
            if inc is not None:
                self.msgr.send(addr, {"type": "map_inc", "inc": inc,
                                      **extras})
            else:
                self.msgr.send(addr, {"type": "map_update",
                                      "payload": payload})

    def _h_get_inc(self, msg: Dict) -> Dict:
        with self._lock:
            got = self._incs.get(int(msg["epoch"]))
        return {"inc": got} if got is not None else \
            {"error": f"no incremental for epoch {msg['epoch']}"}

    # -- handlers --------------------------------------------------------
    def _h_boot(self, msg: Dict) -> Dict:
        osd = int(msg["osd"])
        addr = tuple(msg["addr"])
        with self._lock:
            addr_changed = self._osd_addrs.get(osd) != addr
            self._osd_addrs[osd] = addr
            self._last_beat[osd] = time.monotonic()
            was_up = self.map.exists(osd) and self.map.is_up(osd)
            # weight policy on boot (OSDMonitor::prepare_boot): an osd
            # the monitor auto-outed comes back in; an osd an admin
            # marked out (weight 0 via mark_out) STAYS out; a known osd
            # keeps whatever weight it had
            if self.map.exists(osd):
                weight = self.map.osd_weight[osd]
                if osd in self._auto_out:
                    weight = self._auto_out[osd]
            else:
                weight = msg.get("weight", 0x10000)
            changed = (not was_up) or \
                weight != (self.map.osd_weight[osd]
                           if self.map.exists(osd) else None)
            self._auto_out.pop(osd, None)
            self.map.add_osd(osd, weight=weight)
        if changed or addr_changed:
            # a fast reboot keeps the osd "up" but rebinds its socket:
            # the new address must reach every peer via a new epoch;
            # any weight/up change must also land in the epoch store
            self._commit(f"osd.{osd} boot")
        self.log.dout(1, f"osd.{osd} booted at {msg['addr']}")
        return {"epoch": self.map.epoch}

    def _h_heartbeat(self, msg: Dict) -> None:
        with self._lock:
            self._last_beat[int(msg["osd"])] = time.monotonic()
        self.pc.inc("beats")
        return None

    def _h_get_map(self, msg: Dict) -> Dict:
        epoch = msg.get("epoch")
        if epoch is not None:
            got = self.get_epoch_payload(int(epoch))
            return got if got is not None else \
                {"error": f"no epoch {epoch}"}
        with self._lock:
            return json.loads(self._epochs[self.map.epoch])

    def _h_subscribe(self, msg: Dict) -> Dict:
        with self._lock:
            self._subscribers[msg["name"]] = tuple(msg["addr"])
            return json.loads(self._epochs[self.map.epoch])

    def _h_mark_down(self, msg: Dict) -> Dict:
        return {"epoch": self.mark_down(int(msg["osd"]))}

    def _h_mark_out(self, msg: Dict) -> Dict:
        osd = int(msg["osd"])
        with self._lock:
            self.map.osd_weight[osd] = 0
            self._auto_out.pop(osd, None)  # admin out sticks
        return {"epoch": self._commit(f"osd.{osd} out")}

    def _h_pool_create(self, msg: Dict) -> Dict:
        pool_id = int(msg["pool_id"])
        with self._lock:
            self.map.pools[pool_id] = PgPool(**msg["pool"])
        return {"epoch": self._commit(f"pool {pool_id} create")}

    def _h_ec_profile_set(self, msg: Dict) -> Dict:
        with self._lock:
            self.ec_profiles[msg["name"]] = dict(msg["profile"])
        return {"epoch": self._commit(f"ec profile {msg['name']}")}

    def _h_status(self, _msg: Dict) -> Dict:
        with self._lock:
            up = [o for o in range(self.map.max_osd)
                  if self.map.is_up(o)]
            return {"epoch": self.map.epoch, "up_osds": up,
                    "num_pools": len(self.map.pools),
                    "subscribers": sorted(self._subscribers)}

    # -- failure detection ------------------------------------------------
    def mark_down(self, osd: int) -> int:
        from ..osdmap.osdmap import OSD_EXISTS

        with self._lock:
            if not self.map.is_up(osd):
                return self.map.epoch
            self.map.osd_state[osd] = OSD_EXISTS  # up bit cleared
            self._last_beat.pop(osd, None)
            self._down_since[osd] = time.monotonic()
        self.pc.inc("markdowns")
        self.log.dout(1, f"osd.{osd} marked down")
        return self._commit(f"osd.{osd} down")

    def _tick_loop(self) -> None:
        grace = self.ctx.conf["osd_heartbeat_grace"]
        interval = self.ctx.conf["osd_heartbeat_interval"]
        out_interval = self.ctx.conf["mon_osd_down_out_interval"]
        while self._running:
            time.sleep(interval / 2)
            now = time.monotonic()
            stale = []
            to_out = []
            with self._lock:
                for osd, last in self._last_beat.items():
                    if now - last > grace and self.map.is_up(osd):
                        stale.append(osd)
                # down -> out after the grace window: clearing the
                # in/out weight is what makes CRUSH remap the osd's
                # positions so backfill can begin (the reference's
                # mon_osd_down_out_interval flow)
                for osd, since in list(self._down_since.items()):
                    if self.map.is_up(osd):
                        del self._down_since[osd]
                    elif now - since > out_interval and \
                            self.map.osd_weight[osd] > 0:
                        to_out.append(osd)
                        del self._down_since[osd]
            for osd in stale:
                self.log.dout(1, f"osd.{osd} heartbeat stale")
                self.mark_down(osd)
            for osd in to_out:
                self.log.dout(1, f"osd.{osd} auto-out")
                with self._lock:
                    self._auto_out[osd] = self.map.osd_weight[osd]
                    self.map.osd_weight[osd] = 0
                self._commit(f"osd.{osd} auto-out")
