"""Recovery-engine support state: helper-load ledger + reservations.

Two small, lock-protected books behind the OSD's pipelined recovery
path (osd_service._run_recovery):

``HelperLedger`` — the per-OSD in-flight ledger the helper-read
fan-out consults to pick the LEAST-LOADED survivor instead of always
reading the first k up shards (the rateless load-balancing analysis,
arXiv:1804.10331: recovery time is dominated by the hottest helper).
Load is this primary's own in-flight helper reads against an OSD plus
the last scheduler depth that OSD reported in a shard_read reply (the
heartbeat/pg-stats-plane feed).  It also keeps the per-object
exclusion table: a helper whose read failed (EIO'd via
``osd.shard_read_eio``, timed out, or returned a stale version) is
excluded from that object's remaining attempts — across recovery
passes, so the next pass does not re-request from the same bad OSD —
with a doubling TTL so a *transient* EIO cannot permanently strand an
object on a small cluster where every survivor eventually
misbehaves once.

``ReservationBook`` — the AsyncReserver-lite (the reference's
local_reserver/remote_reserver pair, osd/scheduler + AsyncReserver.h):
one slot pool of ``osd_max_recovery_ops`` shared by this OSD's own
recovery work and the grants it hands to remote primaries
(``recovery_reserve`` RPC), so a burst of primaries recovering onto
one OSD is bounded and client p99 holds under active recovery.
"""

from __future__ import annotations

import time
from typing import Dict, List, Set, Tuple

from ..analysis.lockdep import make_lock

# exclusion TTLs: first failure sidelines a helper for EXCLUDE_BASE_S,
# each repeat doubles up to EXCLUDE_CAP_S (decorrelated enough for a
# toy cluster; a real bad disk keeps re-earning its exclusion)
EXCLUDE_BASE_S = 1.0
EXCLUDE_CAP_S = 30.0

# one in-flight read from this primary weighs as much as this many
# queued ops on the remote scheduler when ranking helpers
INFLIGHT_WEIGHT = 2.0


class HelperLedger:
    """Per-OSD helper-read load + per-object failure exclusions."""

    def __init__(self):
        self._lock = make_lock("osd::rec_ledger")
        self._inflight: Dict[int, int] = {}
        self._remote_load: Dict[int, float] = {}
        # (pool, ps, oid) -> {osd: (expiry_monotonic, ttl)}
        self._excluded: Dict[Tuple, Dict[int, Tuple[float, float]]] = {}

    # -- in-flight / reported load -------------------------------------
    def start(self, osd: int) -> None:
        with self._lock:
            self._inflight[osd] = self._inflight.get(osd, 0) + 1

    def finish(self, osd: int) -> None:
        with self._lock:
            n = self._inflight.get(osd, 0) - 1
            if n > 0:
                self._inflight[osd] = n
            else:
                self._inflight.pop(osd, None)

    def note_load(self, osd: int, load: float) -> None:
        """A shard_read reply carried the helper's scheduler depth."""
        with self._lock:
            self._remote_load[osd] = float(load)

    def load(self, osd: int) -> float:
        with self._lock:
            return (self._inflight.get(osd, 0) * INFLIGHT_WEIGHT
                    + self._remote_load.get(osd, 0.0))

    # -- per-object exclusions -----------------------------------------
    def exclude(self, key: Tuple, osd: int) -> None:
        """Sideline ``osd`` for object ``key``; repeats double the
        TTL (capped), so the exclusion outlives the next recovery
        passes while a genuinely transient fault ages out."""
        now = time.monotonic()
        with self._lock:
            ent = self._excluded.setdefault(key, {})
            prev = ent.get(osd)
            ttl = EXCLUDE_BASE_S if prev is None \
                else min(EXCLUDE_CAP_S, prev[1] * 2.0)
            ent[osd] = (now + ttl, ttl)

    def excluded(self, key: Tuple) -> Set[int]:
        """Currently-excluded OSDs for an object (expired entries are
        pruned in place)."""
        now = time.monotonic()
        with self._lock:
            ent = self._excluded.get(key)
            if not ent:
                return set()
            dead = [o for o, (exp, _ttl) in ent.items() if exp <= now]
            for o in dead:
                del ent[o]
            if not ent:
                self._excluded.pop(key, None)
                return set()
            return set(ent)

    def dump(self) -> Dict:
        with self._lock:
            return {
                "inflight": dict(self._inflight),
                "remote_load": dict(self._remote_load),
                "excluded": {repr(k): sorted(v)
                             for k, v in self._excluded.items()},
            }


class ReservationBook:
    """One recovery slot pool shared by local work and remote grants
    (the AsyncReserver local+remote pair, collapsed: both sides draw
    from ``osd_max_recovery_ops``)."""

    def __init__(self, slots: int):
        self._lock = make_lock("osd::rec_reserve")
        self._slots = max(1, int(slots))
        self._held = 0

    def try_acquire(self) -> bool:
        with self._lock:
            if self._held < self._slots:
                self._held += 1
                return True
            return False

    def release(self) -> None:
        with self._lock:
            if self._held > 0:
                self._held -= 1

    @property
    def held(self) -> int:
        with self._lock:
            return self._held

    @property
    def slots(self) -> int:
        return self._slots
