"""Client — the librados/Objecter analogue.

Placement is CLIENT-SIDE and stateless, exactly as in the reference
(Objecter::_calc_target, src/osdc/Objecter.cc:2688): the client holds
its own OSDMap copy, computes object→PG→OSD mappings locally
(pg_to_up_acting_osds), EC-encodes on write and fans shards out to the
up set positionally; reads gather any k shards and decode.  On a stale
map (peer down / remapped), it refreshes from the mon and retries —
the map-epoch retry loop every RADOS op runs.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..analysis.lockdep import make_rlock
from ..common.backoff import Backoff
from ..common.op_tracker import OpTracker
from ..common.perf_counters import collection
from ..common.tracing import Tracer
from ..common.version import make_version
from ..msg.messenger import Addr, Messenger
from ..osdmap.osdmap import OSDMap, POOL_TYPE_ERASURE
from ..ec.registry import profile_factory


class ObjectNotFound(KeyError):
    """Every reachable shard holder answered ENOENT — the object does
    not exist (distinct from transient unreachability, which raises
    TimeoutError/OSError and is retried)."""


class AioCompletion:
    """librados ``rados_completion_t`` analogue: handed out by
    ``aio_put``/``aio_write``; ``wait()`` re-raises the op's failure
    on the caller's thread."""

    __slots__ = ("_done", "error")

    def __init__(self):
        self._done = threading.Event()
        self.error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> None:
        if not self._done.wait(timeout):
            raise TimeoutError("aio op still in flight")
        if self.error is not None:
            raise self.error


def object_to_ps(oid: str) -> int:
    """object name -> placement seed.  The reference uses
    ceph_str_hash_rjenkins (object_locator_to_pg); any fixed 32-bit
    hash yields the same placement *semantics* — this one is
    sha256-low32, framework-defined and stable."""
    return int.from_bytes(
        hashlib.sha256(oid.encode()).digest()[:4], "little")


from .map_follower import MapFollower


class Client(MapFollower):
    def __init__(self, name: str, mon_addr: Addr,
                 host: str = "127.0.0.1", keyring=None, ctx=None):
        self.name = name
        self.ctx = ctx  # optional Context: librados' own admin socket
        # role — perf dump / dump_tracing / dump_ops_in_flight for the
        # CLIENT side of an op, polled by the telemetry tool
        self._init_mons(mon_addr)  # one addr or the quorum list
        if ctx is not None:
            self.tracer = ctx.tracer
            self.pc = ctx.perf.create(f"client.{name}")
        else:
            self.tracer = Tracer(f"client.{name}")
            self.pc = collection().create(f"client.{name}")
        for key in ("ops_put", "ops_get", "ops_write", "ops_delete",
                    "op_errors", "ops_aio_put", "ops_aio_write"):
            self.pc.add_u64_counter(key)
        self.pc.add_histogram("op_lat")
        self.pc.add_time("op_time")
        # in-flight window occupancy at each aio submit — proves the
        # pipeline actually keeps the OSD queues full
        self.pc.add_histogram("aio_depth", min_value=1)
        # -- pipelined I/O (the librados aio_* window) ---------------
        from ..common.throttle import Throttle

        window = (ctx.conf["client_aio_window"] if ctx is not None
                  else 16)
        self._aio_window = max(1, int(window))
        self._aio_throttle = Throttle(f"client-aio-{name}",
                                      self._aio_window)
        self._aio_pool = None  # lazy: sync-only clients never pay it
        self._aio_inflight: set = set()
        self.optracker = OpTracker(
            history_slow_threshold=ctx.conf["osd_op_complaint_time"]
            if ctx is not None else 0.5)
        if ctx is not None and ctx.conf["admin_socket"]:
            sock = ctx.start_admin_socket()
            self.optracker.wire(sock)
            self.tracer.wire(sock)
        self.msgr = Messenger(f"client.{name}", host, 0,
                              keyring=keyring, tracer=self.tracer,
                              perf=ctx.perf if ctx is not None
                              else None)
        # map pushes on the control lane: a client retrying ops into a
        # dead primary must still learn the new map promptly
        self.msgr.register("map_update", self._h_map_update,
                           control=True)
        self.msgr.register("map_inc", self._h_map_inc, control=True)
        self.msgr.register("watch_notify", self._h_watch_notify)
        # (pool, oid) -> callback; re-registered with the (possibly
        # new) primary on every map change, like librados re-watch
        self._watches: Dict[tuple, object] = {}
        self.msgr.start()
        self.map: Optional[OSDMap] = None
        self.epoch = 0
        self.osd_addrs: Dict[int, Addr] = {}
        self.ec_profiles: Dict[str, Dict[str, str]] = {}
        self._codes: Dict[str, object] = {}
        self._lock = make_rlock("client::state")
        self._install_map(self.subscribe_all(f"client.{name}"))

    def shutdown(self) -> None:
        with self._lock:
            pool, self._aio_pool = self._aio_pool, None
        if pool is not None:
            # no wait: in-flight aio ops fail fast once the messenger
            # drops its sockets below; their workers then exit
            pool.shutdown(wait=False)
        self.msgr.shutdown()
        if self.ctx is not None:
            self.ctx.shutdown()

    # -- pipelined I/O (aio_put/aio_write/flush) -----------------------
    def aio_put(self, pool_id: int, oid: str, data: bytes,
                retries: int = 3,
                on_complete=None) -> AioCompletion:
        """Async ``put`` with a bounded in-flight window: blocks only
        while the window (``client_aio_window``, default 16) is full,
        so callers keep the OSD queues full instead of ping-ponging
        one op at a time.  Durability/ack semantics are ``put``'s —
        the completion fires when the primary acked the write.
        ``on_complete(comp)`` runs on the worker thread right after."""
        return self._aio_submit("put", on_complete, self.put,
                                pool_id, oid, bytes(data), retries)

    def aio_write(self, pool_id: int, oid: str, offset: int,
                  data: bytes, retries: int = 3,
                  on_complete=None) -> AioCompletion:
        """Async partial ``write`` under the same in-flight window."""
        return self._aio_submit("write", on_complete, self.write,
                                pool_id, oid, offset, bytes(data),
                                retries)

    def _aio_submit(self, kind: str, on_complete, fn,
                    *args) -> AioCompletion:
        self._aio_throttle.get()  # the bounded window (backpressure)
        comp = AioCompletion()
        with self._lock:
            pool = self._aio_pool
            if pool is None:
                from concurrent.futures import ThreadPoolExecutor

                pool = self._aio_pool = ThreadPoolExecutor(
                    max_workers=self._aio_window,
                    thread_name_prefix=f"aio:{self.name}")
            self._aio_inflight.add(comp)
        self.pc.hist_add("aio_depth",
                         self._aio_throttle.get_current())
        self.pc.inc(f"ops_aio_{kind}")

        def run():
            try:
                fn(*args)
            except BaseException as e:
                comp.error = e
            finally:
                with self._lock:
                    self._aio_inflight.discard(comp)
                self._aio_throttle.put()
                comp._done.set()
                if on_complete is not None:
                    try:
                        on_complete(comp)
                    except Exception:
                        pass  # a callback bug must not kill the pool

        try:
            pool.submit(run)
        except RuntimeError:  # racing shutdown
            with self._lock:
                self._aio_inflight.discard(comp)
            self._aio_throttle.put()
            comp.error = OSError(f"client.{self.name} shut down")
            comp._done.set()
        return comp

    def flush(self, timeout: float = 60.0) -> None:
        """Wait for every outstanding aio op (librados
        rados_aio_flush): returns once the window is empty; re-raises
        the FIRST failed op's error after all have settled."""
        deadline = time.monotonic() + timeout
        with self._lock:
            comps = list(self._aio_inflight)
        first: Optional[BaseException] = None
        for c in comps:
            try:
                c.wait(max(0.0, deadline - time.monotonic()))
            except TimeoutError as e:
                if not c.done():
                    raise  # the flush window itself expired
                if first is None:  # the OP failed with TimeoutError
                    first = e
            except BaseException as e:
                if first is None:
                    first = e
        self._aio_throttle.wait_until_drained(
            max(0.0, deadline - time.monotonic()))
        if first is not None:
            raise first

    # -- op instrumentation (the librados op latency surface) ----------
    @contextlib.contextmanager
    def _op(self, kind: str, pool_id: int, oid: str):
        """Root span + tracked op + latency counters around one client
        op (retries included — the latency a caller actually sees)."""
        t0 = time.monotonic()
        with self.tracer.start_span(
                f"client.{kind}",
                tags={"pool": pool_id, "oid": oid}) as span:
            with self.optracker.create(
                    "client_op", f"{kind} {pool_id}/{oid}") as op:
                try:
                    yield span, op
                except BaseException:
                    self.pc.inc("op_errors")
                    raise
                finally:
                    dt = time.monotonic() - t0
                    self.pc.hist_add("op_lat", dt)
                    self.pc.tinc("op_time", dt)
        self.pc.inc(f"ops_{kind}")

    def _retry_backoff(self) -> Backoff:
        """One jittered-backoff budget per op: retry pacing grows
        decorrelated-exponentially (no retry storms when a primary
        dies under N clients) and the TOTAL sleep across retries is
        bounded by ``client_retry_deadline`` — once spent, the op
        re-raises its last error instead of pacing another attempt."""
        dl = (self.ctx.conf["client_retry_deadline"]
              if self.ctx is not None else 10.0)
        return Backoff(base=0.1, cap=1.0, deadline=dl)

    # -- map -----------------------------------------------------------
    def _h_map_update(self, msg: Dict) -> None:
        self._install_map(msg["payload"])
        return None

    def refresh_map(self) -> None:
        self._install_map(self.mon_call({"type": "get_map"}))

    def _code_for(self, pool):
        if pool.pool_type != POOL_TYPE_ERASURE:
            return None
        name = pool.erasure_code_profile
        code = self._codes.get(name)
        if code is None:
            code = profile_factory(dict(self.ec_profiles[name]))
            self._codes[name] = code
        return code

    def _up(self, pool_id: int, oid: str):
        """Route to the ACTING set (pg_temp overlay included): during
        backfill the acting members hold the data and take the IO —
        the serving-continuity contract of peering (OSDMap.cc:2590)."""
        pool = self.map.pools[pool_id]
        ps = object_to_ps(oid) % pool.pg_num
        up, _p, acting, _ap = self.pg_up_acting(pool_id, ps)
        return pool, ps, (acting if acting else up)

    # -- data path -------------------------------------------------------
    def put(self, pool_id: int, oid: str, data: bytes,
            retries: int = 3) -> None:
        """EVERY write routes through the PG primary (the reference
        sends all ops to the primary, Objecter::_calc_target) — ONE
        client round trip; the primary stamps the version under the
        PG lock (eversion_t at the primary: immune to client clock
        skew) and fans replicas/shards out in parallel."""
        with self._op("put", pool_id, oid) as (_span, op):
            bo = self._retry_backoff()
            for attempt in range(retries):
                v = make_version(self.epoch)  # proposal; primary may
                # bump
                try:
                    # inside the retry loop: a freshly-created pool
                    # may be a map epoch away (a peon served the
                    # refresh before applying the commit) — KeyError
                    # retries like any stale-map condition
                    pool, ps, up = self._up(pool_id, oid)
                    code = self._code_for(pool)
                    if code is None:
                        req = {"type": "rep_write", "pool": pool_id,
                               "ps": ps, "oid": oid,
                               "epoch": self.epoch,
                               "data": bytes(data), "v": v}
                    else:
                        req = {"type": "ec_write", "pool": pool_id,
                               "ps": ps, "oid": oid, "offset": 0,
                               "epoch": self.epoch,
                               "data": bytes(data), "v": v,
                               "full": True}
                    prim = self._first_reachable(up)
                    if prim is None:
                        raise TimeoutError("no reachable primary")
                    got = self.msgr.call(self.osd_addrs[prim], req,
                                         timeout=20)
                    if not got.get("ok") and \
                            got.get("error") == "not primary" and \
                            got.get("primary") in self.osd_addrs:
                        got = self.msgr.call(
                            self.osd_addrs[got["primary"]],
                            dict(req), timeout=20)
                    if not got.get("ok"):
                        raise OSError(f"put via osd.{prim}: {got}")
                    return
                except (TimeoutError, OSError, KeyError):
                    if attempt + 1 == retries:
                        raise
                    op.mark_event(f"retry {attempt + 1}")
                    if not bo.sleep():
                        raise  # retry-sleep budget exhausted
                    self.refresh_map()

    def get(self, pool_id: int, oid: str, retries: int = 3,
            notfound_retries: int = 2) -> bytes:
        """``notfound_retries`` covers the read-races-backfill window:
        a just-remapped up set answers ENOENT for an object that exists
        on the old holders until recovery copies it over.  Callers that
        expect sparse misses (image pieces, existence probes) pass 0
        for fast definitive ENOENT."""
        nf_left = notfound_retries
        transient_left = retries - 1  # separate budgets: an ENOENT
        # retry must never convert into OSError('unreachable') when the
        # miss is definitive — callers branch on ObjectNotFound
        with self._op("get", pool_id, oid) as (_span, op):
            bo = self._retry_backoff()
            while True:
                try:
                    pool, ps, up = self._up(pool_id, oid)
                    code = self._code_for(pool)
                    if code is None:
                        return self._read_replicated(pool_id, ps, oid,
                                                     up)
                    return self._read_ec(pool_id, ps, oid, up, code)
                except ObjectNotFound:
                    if nf_left <= 0 or not bo.sleep():
                        raise
                    nf_left -= 1
                except (TimeoutError, OSError, KeyError):
                    if transient_left <= 0 or not bo.sleep():
                        raise
                    transient_left -= 1
                op.mark_event("retry")
                self.refresh_map()

    def _read_replicated(self, pool_id, ps, oid, up) -> bytes:
        """Version-aware: while divergent histories are still
        reconciling, replicas can disagree — the highest-version copy
        is the acked latest write, so gather all answers and keep it."""
        last: Exception = OSError("empty up set")
        enoent = 0
        reachable = 0
        best = None
        best_v = ""
        agree = 0
        for osd in up:
            try:
                got = self.msgr.call(
                    self.osd_addrs[osd],
                    {"type": "shard_read", "pool": pool_id, "ps": ps,
                     "oid": oid, "shard": 0}, timeout=5)
            except (TimeoutError, OSError, KeyError) as e:
                last = e
                continue
            reachable += 1
            if "data" in got:
                v = got.get("v") or ""
                if best is None or v > best_v:
                    best = bytes(got["data"])[:got["size"]]
                    best_v = v
                    agree = 1
                elif v == best_v:
                    agree += 1
                # two copies agreeing on the newest version seen is
                # proof enough of freshness — the healthy path stops
                # after 2 RPCs instead of querying every replica
                if agree >= 2:
                    return best
            elif got.get("error") == "enoent":
                enoent += 1
        if best is not None:
            return best
        if reachable and enoent == reachable:
            raise ObjectNotFound(oid)
        raise last

    def write(self, pool_id: int, oid: str, offset: int,
              data: bytes, retries: int = 3) -> None:
        """Partial (offset) write.  EC pools: a primary-coordinated
        read-merge-encode op (the ECBackend start_rmw flow) — the
        client sends ONE ec_write to the PG primary, which serializes
        it under the PG lock.  Replicated pools: client-side RMW over
        put (last-writer-wins at object granularity, like the
        reference's replicated offset write under a single client)."""
        with self._op("write", pool_id, oid) as (_span, op):
            bo = self._retry_backoff()
            for attempt in range(retries):
                try:
                    pool, ps, up = self._up(pool_id, oid)
                    code = self._code_for(pool)
                    if code is None:
                        try:
                            base = self.get(pool_id, oid,
                                            notfound_retries=0)
                        except ObjectNotFound:
                            base = b""
                        size = max(len(base), offset + len(data))
                        buf = bytearray(size)
                        buf[:len(base)] = base
                        buf[offset:offset + len(data)] = data
                        self.put(pool_id, oid, bytes(buf))
                        return
                    # same liveness rule as the server's primary
                    # check: first UP member, else the op targets a
                    # dead daemon the real primary would skip
                    prim = self._first_reachable(up)
                    if prim is None:
                        raise TimeoutError("no reachable primary")
                    v = make_version(self.epoch)
                    got = self.msgr.call(
                        self.osd_addrs[prim],
                        {"type": "ec_write", "pool": pool_id,
                         "ps": ps, "oid": oid, "offset": offset,
                         "data": bytes(data), "v": v}, timeout=15)
                    if got.get("ok"):
                        return
                    if got.get("error") == "not primary" and \
                            got.get("primary") in self.osd_addrs:
                        got = self.msgr.call(
                            self.osd_addrs[got["primary"]],
                            {"type": "ec_write", "pool": pool_id,
                             "ps": ps, "oid": oid, "offset": offset,
                             "data": bytes(data), "v": v},
                            timeout=15)
                        if got.get("ok"):
                            return
                    raise OSError(f"ec_write via osd.{prim}: {got}")
                except (TimeoutError, OSError, KeyError):
                    if attempt + 1 == retries:
                        raise
                    op.mark_event(f"retry {attempt + 1}")
                    if not bo.sleep():
                        raise  # retry-sleep budget exhausted
                    self.refresh_map()

    def _first_reachable(self, up):
        """The routing invariant: first up, addressable, non-NONE
        member — the op target every primary-coordinated path uses."""
        return next((o for o in up
                     if o >= 0 and o in self.osd_addrs
                     and self.map.is_up(o)), None)

    # -- watch/notify (librados rados_watch/rados_notify) --------------
    def _primary_of(self, pool_id: int, oid: str):
        pool, ps, up = self._up(pool_id, oid)
        prim = self._first_reachable(up)
        if prim is None:
            raise TimeoutError(f"no reachable primary for {oid}")
        return ps, prim

    def watch(self, pool_id: int, oid: str, callback) -> None:
        """``callback(oid, payload, notifier)`` runs on every notify.
        The registration follows the PG primary across map changes."""
        with self._lock:
            self._watches[(pool_id, oid)] = callback
        self._register_watch(pool_id, oid)

    def _register_watch(self, pool_id: int, oid: str) -> None:
        ps, prim = self._primary_of(pool_id, oid)
        self.msgr.call(self.osd_addrs[prim],
                       {"type": "watch", "pool": pool_id, "ps": ps,
                        "oid": oid, "watcher": self.name,
                        "addr": list(self.msgr.addr)}, timeout=5)

    def unwatch(self, pool_id: int, oid: str) -> None:
        with self._lock:
            self._watches.pop((pool_id, oid), None)
        try:
            ps, prim = self._primary_of(pool_id, oid)
            self.msgr.call(self.osd_addrs[prim],
                           {"type": "unwatch", "pool": pool_id,
                            "ps": ps, "oid": oid,
                            "watcher": self.name}, timeout=5)
        except (TimeoutError, OSError, KeyError):
            pass  # the primary prunes dead watchers on notify anyway

    def notify(self, pool_id: int, oid: str, payload,
               timeout: float = 5.0) -> Dict:
        """Returns {"acks": [names], "missed": [names]}."""
        ps, prim = self._primary_of(pool_id, oid)
        return self.msgr.call(
            self.osd_addrs[prim],
            {"type": "notify", "pool": pool_id, "ps": ps,
             "oid": oid, "payload": payload, "timeout": timeout},
            timeout=timeout + 5.0)

    def _h_watch_notify(self, msg: Dict) -> Dict:
        with self._lock:
            cb = self._watches.get((msg["pool"], msg["oid"]))
        if cb is None:
            return {"ok": False}
        try:
            cb(msg["oid"], msg.get("payload"), msg.get("notifier"))
        except Exception:
            return {"ok": False}
        return {"ok": True}

    def _post_map_install(self) -> None:
        """Re-watch on every epoch: the primary may have moved."""
        with self._lock:
            watches = list(self._watches)
        if not watches:
            return

        def rewatch():
            for pool_id, oid in watches:
                try:
                    self._register_watch(pool_id, oid)
                except (TimeoutError, OSError, KeyError):
                    pass  # next epoch retries

        threading.Thread(target=rewatch, daemon=True).start()

    def delete(self, pool_id: int, oid: str, retries: int = 3) -> None:
        """Tombstoned delete: peering propagates it over older writes
        (the reference's log-entry DELETE semantics)."""
        v = make_version(self.epoch)
        with self._op("delete", pool_id, oid) as (_span, op):
            bo = self._retry_backoff()
            for attempt in range(retries):
                try:
                    pool, ps, up = self._up(pool_id, oid)
                    for osd in {o for o in up
                                if o >= 0 and o in self.osd_addrs}:
                        got = self.msgr.call(
                            self.osd_addrs[osd],
                            {"type": "obj_delete", "pool": pool_id,
                             "ps": ps, "oid": oid, "v": v,
                             "restamp": True}, timeout=10)
                        if not got.get("ok"):
                            raise OSError(f"obj_delete on osd.{osd}: "
                                          f"{got}")
                    return
                except (TimeoutError, OSError, KeyError):
                    if attempt + 1 == retries:
                        raise
                    op.mark_event(f"retry {attempt + 1}")
                    if not bo.sleep():
                        raise  # retry-sleep budget exhausted
                    self.refresh_map()

    def _read_ec(self, pool_id, ps, oid, up, code) -> bytes:
        """Gather any k shards (degraded reads ride the same path the
        reference's objects_read_and_reconstruct does).

        Chunks from different writes never decode together, so shards
        group by version and the NEWEST version with >= k chunks wins:
        a torn higher-version write (partially landed, never acked —
        peering will roll it back) must not shadow the last acked
        state."""
        k = code.get_data_chunk_count()
        m = code.get_chunk_count() - k
        by_ver: Dict[str, Dict[int, np.ndarray]] = {}
        sizes: Dict[str, int] = {}
        enoent = 0
        reachable = 0
        for pos, osd in enumerate(up):
            done = any(len(c) >= k for c in by_ver.values())
            # Early exit is only sound when m < k: an acked write
            # covers >= k positions, so at most m stale shards exist
            # and k stale chunks cannot assemble without surfacing at
            # least one newer shard (which un-satisfies the newest-
            # seen-is-decodable condition).  With m >= k a reader
            # could decode k stale shards before probing any position
            # the newest acked write landed on — probe them all.
            if done and m < k and max(by_ver) == max(
                    (v for v, c in by_ver.items() if len(c) >= k)):
                break  # the newest version seen is already decodable
            try:
                got = self.msgr.call(
                    self.osd_addrs[osd],
                    {"type": "shard_read", "pool": pool_id, "ps": ps,
                     "oid": oid, "shard": pos}, timeout=5)
            except (TimeoutError, OSError, KeyError):
                continue
            reachable += 1
            if "data" in got:
                v = got.get("v") or ""
                by_ver.setdefault(v, {})[pos] = np.frombuffer(
                    bytes(got["data"]), np.uint8)
                sizes[v] = got["size"]
            elif got.get("error") == "enoent":
                enoent += 1
        decodable = [v for v, c in by_ver.items() if len(c) >= k]
        if not decodable:
            if reachable and enoent == reachable:
                raise ObjectNotFound(oid)
            have = max((len(c) for c in by_ver.values()), default=0)
            raise TimeoutError(
                f"only {have}/{k} shards reachable for {oid}")
        best = max(decodable)
        return code.decode_concat(by_ver[best])[:sizes[best]]
