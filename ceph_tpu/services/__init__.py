"""Distributed services — the daemons layer (reference src/mon,
src/osd), single-host scale.

- ``monitor``: the cluster-map authority — versioned OSDMap epochs
  (MonitorDBStore role), osd boot/heartbeat tracking, failure
  detection (mark-down on heartbeat grace), map push to subscribers.
- ``osd_service``: the OSD analogue — MemStore-backed shard storage,
  EC data path, heartbeats, and mark-down→remap→recover backfill.
- ``client``: the librados analogue — client-side placement
  (pg_to_up_acting_osds on its own map copy), EC encode/decode.
- ``cluster``: the vstart.sh-style harness: one call brings up a mon
  and N osds on localhost sockets (many daemons, one host — the
  reference's qa/standalone model), plus the thrasher hooks.
"""
