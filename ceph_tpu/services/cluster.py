"""MiniCluster — the vstart.sh / qa/standalone harness.

The reference tests "multi-node" behavior with many daemons on one
host (src/vstart.sh, qa/standalone/ceph-helpers.sh run_mon/run_osd/
wait_for_clean).  MiniCluster is that harness: one call boots a
monitor and N OSD services on localhost sockets, builds the CRUSH
hierarchy through the facade, creates pools/EC profiles through mon
commands, and exposes the thrasher hooks (kill_osd / revive_osd /
wait_for_down / wait_for_recovery) that qa/tasks/thrashosds.py
provides in the reference.
"""

from __future__ import annotations

import shutil
import tempfile
import time
from typing import Dict, List, Optional

from ..common.backoff import Backoff
from ..common.config import Config
from ..common.context import Context
from ..crush.wrapper import CrushWrapper
from ..osdmap.osdmap import (OSDMap, PgPool, POOL_TYPE_ERASURE,
                             POOL_TYPE_REPLICATED)
from .client import Client
from .monitor import Monitor
from .osd_service import OSDService


class MiniCluster:
    def __init__(self, n_osds: int = 4, hosts: Optional[int] = None,
                 config: Optional[Config] = None, auth: bool = False,
                 data_dir: Optional[str] = None, n_mons: int = 1):
        self.conf = config or Config()
        # the out-of-band keyring every daemon/client shares (cephx)
        from ..msg.auth import Keyring
        self.keyring = Keyring.generate() if auth else None
        # when set, OSDs persist their stores under data_dir/osd<N>
        # and restarts remount instead of backfilling from scratch
        self.data_dir = data_dir
        # every daemon's admin socket binds under one per-cluster dir
        # (kept short: AF_UNIX paths cap at ~108 bytes) — the dir the
        # telemetry tool polls for the whole-cluster snapshot
        self.asok_dir = tempfile.mkdtemp(prefix="ceph-tpu-asok-")
        self.n_osds = n_osds
        hosts = hosts or n_osds
        # crush hierarchy through the facade (one host per fd bucket)
        self.wrapper = CrushWrapper()
        for d in range(n_osds):
            self.wrapper.insert_item(
                d, 0x10000, f"osd.{d}",
                {"host": f"host{d % hosts}", "root": "default"})
        self.replicated_rule = self.wrapper.add_simple_rule(
            "replicated_rule", "default", "host", "", "firstn")
        self.ec_rule = self.wrapper.add_simple_rule(
            "ec_rule", "default", "host", "", "indep", rule_type=3)

        osdmap = OSDMap(self.wrapper.crush)
        self.n_mons = n_mons
        self.mons: Dict[int, Monitor] = {}
        self._mon_osdmap = osdmap
        for rank in range(n_mons):
            self.mons[rank] = self._make_mon(rank)
        self.mon_addrs = [self.mons[r].addr for r in range(n_mons)]
        if n_mons > 1:
            for rank, mon in self.mons.items():
                mon.set_peers(rank, self.mon_addrs)
        self.osds: Dict[int, OSDService] = {}
        self.clients: List[Client] = []
        self.mgr = None

    @property
    def mon(self) -> Monitor:
        """Historical single-mon handle: the lowest-ranked LIVE monitor
        (a plain attribute would go stale after kill_mon/revive_mon)."""
        return self.mons[min(self.mons)]

    def _make_mon(self, rank: int, port: int = 0) -> Monitor:
        mon_store = None
        if self.data_dir is not None:
            import os

            mon_store = os.path.join(self.data_dir, f"mon{rank}")
        ctx = Context(f"mon.{rank}", config=self.conf,
                      admin_dir=self.asok_dir)
        return Monitor(ctx, OSDMap.from_dict(
            self._mon_osdmap.to_dict()), keyring=self.keyring,
            store_dir=mon_store, port=port)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "MiniCluster":
        for mon in self.mons.values():
            mon.start()
        if self.n_mons > 1:
            self.wait_for_quorum()
        for d in range(self.n_osds):
            self.revive_osd(d)
        return self

    def shutdown(self) -> None:
        for c in self.clients:
            c.shutdown()
        if self.mgr is not None:
            self.mgr.shutdown()
            self.mgr = None
        for svc in list(self.osds.values()):
            svc.shutdown()
        for mon in self.mons.values():
            mon.shutdown()
        shutil.rmtree(self.asok_dir, ignore_errors=True)

    def start_mgr(self, name: str = "x"):
        """Start the manager daemon (one per cluster, the ceph-mgr
        role); its admin socket binds beside the others, so
        ``ceph_cli balancer ...`` finds it via --asok-dir."""
        from ..mgr.daemon import MgrDaemon

        ctx = Context(f"mgr.{name}", config=self.conf,
                      admin_dir=self.asok_dir)
        self.mgr = MgrDaemon(ctx, name, self.mon_addrs,
                             keyring=self.keyring).start()
        return self.mgr

    def client(self, name: str = "admin") -> Client:
        ctx = Context(f"client.{name}", config=self.conf,
                      admin_dir=self.asok_dir)
        c = Client(name, self.mon_addrs, keyring=self.keyring,
                   ctx=ctx)
        self.clients.append(c)
        return c

    # -- monitor quorum hooks -------------------------------------------
    def leader(self) -> Optional[Monitor]:
        for mon in self.mons.values():
            if mon.quorum is None or mon.quorum.is_leader():
                return mon
        return None

    def wait_for_quorum(self, timeout: float = 30.0) -> Monitor:
        """Wait for the STEADY-STATE leader: the lowest live rank, with
        genesis committed.  (A higher rank can win a first round and
        lead transiently until the lowest reachable rank's candidacy
        deposes it — returning that one makes callers racy.)"""
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            ldr = self.leader()
            if ldr is not None and ldr.last_committed() > 0 and \
                    (ldr.quorum is None or
                     ldr is self.mons[min(self.mons)]):
                return ldr
            time.sleep(0.1)
        raise TimeoutError("no monitor quorum")

    def kill_mon(self, rank: int) -> None:
        mon = self.mons.pop(rank, None)
        if mon is not None:
            mon.shutdown()

    def revive_mon(self, rank: int) -> Monitor:
        # rebind the original rank port so peers and daemons reach it
        # at the address already in their quorum lists (brief retry:
        # the killed listener's socket may still be closing)
        bo = Backoff(base=0.1, cap=0.5, deadline=5.0)
        while True:
            try:
                mon = self._make_mon(rank,
                                     port=self.mon_addrs[rank][1])
                break
            except OSError:
                if not bo.sleep():
                    raise
        if self.n_mons > 1:
            mon.set_peers(rank, self.mon_addrs)
        mon.start()
        self.mons[rank] = mon
        return mon

    def set_faults(self, spec: str) -> None:
        """Arm (or disarm, spec="") failpoints cluster-wide: every
        daemon Context shares self.conf, whose ``fault_inject_spec``
        observer feeds analysis/faults.py live."""
        self.conf.set("fault_inject_spec", spec)

    def mon_command(self, msg: Dict, timeout: float = 10.0) -> Dict:
        """Send a command to the quorum via the shared failover loop."""
        from .map_follower import failover_call

        mons = list(self.mons.values())
        rep, _ = failover_call(mons[0].msgr, [m.addr for m in mons],
                               msg, timeout=timeout)
        return rep

    # -- pool / profile management (mon command surface) ---------------
    def create_replicated_pool(self, pool_id: int, pg_num: int = 8,
                               size: int = 3) -> None:
        self.mon_command({
            "type": "pool_create", "pool_id": pool_id,
            "pool": {"pool_type": POOL_TYPE_REPLICATED, "size": size,
                     "min_size": max(1, size - 1), "pg_num": pg_num,
                     "crush_rule": self.replicated_rule}})

    def create_ec_pool(self, pool_id: int, profile_name: str,
                       profile: Dict[str, str],
                       pg_num: int = 8) -> None:
        self.mon_command({
            "type": "ec_profile_set", "name": profile_name,
            "profile": profile})
        from ..ec.registry import profile_factory

        code = profile_factory(dict(profile))
        self.mon_command({
            "type": "pool_create", "pool_id": pool_id,
            "pool": {"pool_type": POOL_TYPE_ERASURE,
                     "size": code.get_chunk_count(),
                     "min_size": code.get_data_chunk_count(),
                     "pg_num": pg_num, "crush_rule": self.ec_rule,
                     "erasure_code_profile": profile_name}})

    def delete_pool(self, pool_id: int) -> None:
        self.mon_command({"type": "pool_delete", "pool_id": pool_id})

    def reweight_osd(self, osd: int, weight: float) -> None:
        """`ceph osd reweight` (0.0-1.0)."""
        self.mon_command({"type": "reweight", "osd": osd,
                          "weight": int(weight * 0x10000)})

    def scrub(self, pool_id: int) -> Dict[int, list]:
        """Deep-scrub every PG of a pool on every up OSD; returns
        {osd: [inconsistent shard names]} (non-empty = damage)."""
        payload = self.mon_command({"type": "get_map"})
        from ..osdmap.bincode_maps import payload_map

        m = payload_map(payload)
        pool = m.pools[pool_id]
        bad: Dict[int, list] = {}
        for ps in range(pool.pg_num):
            up, _p, _a, _ap = m.pg_to_up_acting_osds(pool_id, ps)
            for osd in up:
                svc = self.osds.get(osd)
                if svc is None:
                    continue
                got = svc.msgr.call(svc.addr,
                                    {"type": "pg_scrub",
                                     "pool": pool_id, "ps": ps})
                for name in got.get("inconsistent", []):
                    bad.setdefault(osd, []).append(
                        (pool_id, ps, name))
        return bad

    def repair(self, osd: int, pool_id: int, ps: int,
               shard_name: str) -> None:
        """Drop the damaged shard on ``osd``; recovery re-decodes it
        from the survivors."""
        oid, _, shard = shard_name.rpartition(".s")
        svc = self.osds[osd]
        svc.msgr.call(svc.addr, {"type": "shard_remove",
                                 "pool": pool_id, "ps": ps,
                                 "oid": oid, "shard": int(shard)})

    # -- thrasher hooks (qa/tasks/thrashosds.py role) -------------------
    def kill_osd(self, osd: int) -> None:
        svc = self.osds.pop(osd, None)
        if svc is not None:
            svc.shutdown()

    def revive_osd(self, osd: int) -> OSDService:
        ctx = Context(f"osd.{osd}", config=self.conf,
                      admin_dir=self.asok_dir)
        data_dir = None
        if self.data_dir is not None:
            import os

            data_dir = os.path.join(self.data_dir, f"osd{osd}")
        svc = OSDService(ctx, osd, self.mon_addrs,
                         keyring=self.keyring, data_dir=data_dir)
        svc.start()
        self.osds[osd] = svc
        return svc

    def status(self) -> Dict:
        return self.mon_command({"type": "status"})

    def health(self) -> Dict:
        """`ceph health` surface: HEALTH_OK/HEALTH_WARN + checks."""
        return self.mon_command({"type": "health"})

    def pool_stats(self, pool_id: Optional[int] = None) -> Dict:
        """Per-pool io/recovery rate series (the PGMap `pool-stats`
        surface)."""
        msg: Dict = {"type": "pool_stats"}
        if pool_id is not None:
            msg["pool"] = pool_id
        return self.mon_command(msg)

    def progress(self) -> Dict:
        """Open + completed recovery events (mgr progress role)."""
        return self.mon_command({"type": "progress"})

    def wait_for_health_ok(self, timeout: float = 30.0) -> Dict:
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            last = self.health()
            if last.get("status") == "HEALTH_OK":
                return last
            time.sleep(0.3)
        raise TimeoutError(f"health never OK: {last}")

    def wait_for_down(self, osd: int, timeout: float = 15.0) -> None:
        self._wait(lambda: osd not in self.status()["up_osds"],
                   timeout, f"osd.{osd} still up")

    def wait_for_up(self, osd: int, timeout: float = 15.0) -> None:
        self._wait(lambda: osd in self.status()["up_osds"],
                   timeout, f"osd.{osd} still down")

    def wait_for_recovery(self, pool_id: int, objects: Dict[str, int],
                          timeout: float = 30.0) -> None:
        """wait_for_clean: every up-set shard of every object present
        on the OSD that should hold it."""
        def clean() -> bool:
            payload = self.mon_command({"type": "get_map"})
            from ..osdmap.bincode_maps import payload_map

            m = payload_map(payload)
            pool = m.pools[pool_id]
            from .client import object_to_ps
            for oid in objects:
                ps = object_to_ps(oid) % pool.pg_num
                up, _p, _a, _ap = m.pg_to_up_acting_osds(pool_id, ps)
                for pos, osd in enumerate(up):
                    svc = self.osds.get(osd)
                    if svc is None:
                        return False
                    shard = pos if pool.pool_type == \
                        POOL_TYPE_ERASURE else 0
                    cid = f"{pool_id}.{ps}"
                    if svc.store.stat(cid, f"{oid}.s{shard}") is None:
                        return False
            return True

        self._wait(clean, timeout, "recovery incomplete")

    @staticmethod
    def _wait(cond, timeout: float, what: str) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return
            time.sleep(0.2)
        raise TimeoutError(what)
