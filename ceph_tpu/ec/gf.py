"""GF(2^8) arithmetic and Reed-Solomon matrix algebra (host side).

The reference delegates all Galois-field math to vendored libraries
(jerasure/gf-complete for the jerasure plugin, isa-l asm for the isa
plugin — both git submodules, absent from the checkout; see
src/erasure-code/jerasure/ErasureCodeJerasure.cc:156 and
src/erasure-code/isa/ErasureCodeIsa.cc:369 for how they are consumed).
This module is the from-scratch replacement: table-driven GF(2^8) on the
standard AES-adjacent polynomial 0x11d (the gf-complete/isa-l default for
w=8), plus the matrix constructions the plugins need:

- systematic Vandermonde generator (reed_sol_van semantics,
  ErasureCodeJerasure.cc:156-204 / isa-l gf_gen_rs_matrix)
- Cauchy generator (cauchy_good semantics, ErasureCodeJerasure.cc:259-336)
- Gauss-Jordan inversion for decode matrices
  (ErasureCodeIsa.cc:227-304 erasure-signature → table flow)

Everything here is numpy host code: tiny matrices, run once per
profile/erasure-pattern and cached.  The bulk data path lives in
``rs_jax.py`` as bit-plane matmuls on the MXU.
"""

from __future__ import annotations

import numpy as np

GF_POLY = 0x11D
GF_SIZE = 256

# -- tables -----------------------------------------------------------------


def _build_tables():
    exp = np.zeros(512, np.int32)
    log = np.zeros(256, np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    log[0] = 0  # never used: guard zero explicitly
    return exp, log


GF_EXP, GF_LOG = _build_tables()

# full 256x256 multiplication table (64 KiB) — the gather-kernel operand
GF_MUL = np.zeros((256, 256), np.uint8)
_nz = np.arange(1, 256)
GF_MUL[1:, 1:] = GF_EXP[(GF_LOG[_nz][:, None] + GF_LOG[_nz][None, :]) % 255]


def gf_mul(a, b):
    """Elementwise GF(2^8) multiply of arrays/scalars."""
    a = np.asarray(a, np.uint8)
    b = np.asarray(b, np.uint8)
    return GF_MUL[a, b]


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF inverse of 0")
    return int(GF_EXP[255 - GF_LOG[a]])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] * n) % 255])


def gf_matmul(A, B):
    """GF(2^8) matrix product (small host matrices)."""
    A = np.asarray(A, np.uint8)
    B = np.asarray(B, np.uint8)
    out = np.zeros((A.shape[0], B.shape[1]), np.uint8)
    for i in range(A.shape[0]):
        acc = np.zeros(B.shape[1], np.uint8)
        for t in range(A.shape[1]):
            acc ^= GF_MUL[A[i, t], B[t]]
        out[i] = acc
    return out


def gf_inv_matrix(M):
    """Gauss-Jordan inversion over GF(2^8); raises if singular."""
    M = np.asarray(M, np.uint8)
    n = M.shape[0]
    assert M.shape == (n, n)
    aug = np.concatenate([M.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = None
        for r in range(col, n):
            if aug[r, col]:
                piv = r
                break
        if piv is None:
            raise np.linalg.LinAlgError("singular GF matrix")
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        inv = gf_inv(int(aug[col, col]))
        aug[col] = GF_MUL[np.uint8(inv), aug[col]]
        for r in range(n):
            if r != col and aug[r, col]:
                aug[r] ^= GF_MUL[aug[r, col], aug[col]]
    return aug[:, n:].copy()


# -- generator matrices -----------------------------------------------------


def rs_vandermonde_matrix(k: int, m: int) -> np.ndarray:
    """Systematic RS generator: (k+m) x k, top k rows = identity.

    Built as a raw Vandermonde V[i,j] = i^j, then right-multiplied by the
    inverse of its top square so the code is systematic — the classical
    construction behind reed_sol_van (ErasureCodeJerasure.cc:156) and
    isa-l's gf_gen_rs_matrix (ErasureCodeIsa.cc:377).
    """
    if k + m > GF_SIZE:
        raise ValueError("k+m must be <= 256 for GF(2^8)")
    V = np.zeros((k + m, k), np.uint8)
    for i in range(k + m):
        for j in range(k):
            V[i, j] = gf_pow(i, j) if i else (1 if j == 0 else 0)
    top_inv = gf_inv_matrix(V[:k])
    G = gf_matmul(V, top_inv)
    assert np.array_equal(G[:k], np.eye(k, dtype=np.uint8))
    return G


def rs_cauchy_matrix(k: int, m: int) -> np.ndarray:
    """Systematic Cauchy generator: identity over a Cauchy block
    a[i,j] = 1/(x_i ^ y_j) (cauchy_orig/cauchy_good semantics,
    ErasureCodeJerasure.cc:259; isa-l gf_gen_cauchy1_matrix)."""
    if k + m > GF_SIZE:
        raise ValueError("k+m must be <= 256 for GF(2^8)")
    G = np.zeros((k + m, k), np.uint8)
    G[:k] = np.eye(k, dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            G[k + i, j] = gf_inv((k + i) ^ j)
    return G


# -- bit-matrix expansion (the MXU-native representation) -------------------

# mul-by-c over GF(2^8) is GF(2)-linear on the 8 bit planes; column s of
# the 8x8 bit matrix is the bits of c * 2^s.  A full (k+m,k) GF generator
# therefore expands to an (8m, 8k) 0/1 matrix, and encode becomes a plain
# mod-2 integer matmul — which is exactly what the MXU does best.  This is
# the same algebra as jerasure's bitmatrix/"schedule" technique
# (ErasureCodeJerasure.cc:259-336) recast as a dense matmul instead of an
# XOR schedule.

def gf_const_bitmatrix(c: int) -> np.ndarray:
    """8x8 0/1 matrix B with: bits(c*x) = B @ bits(x) mod 2 (bit 0 = LSB)."""
    B = np.zeros((8, 8), np.uint8)
    for s in range(8):
        prod = gf_mul(c, 1 << s)
        for b in range(8):
            B[b, s] = (int(prod) >> b) & 1
    return B


def expand_bitmatrix(M) -> np.ndarray:
    """Expand an (r, c) GF matrix into the (8r, 8c) GF(2) bit matrix."""
    M = np.asarray(M, np.uint8)
    r, c = M.shape
    out = np.zeros((8 * r, 8 * c), np.uint8)
    for i in range(r):
        for j in range(c):
            out[8 * i:8 * i + 8, 8 * j:8 * j + 8] = \
                gf_const_bitmatrix(int(M[i, j]))
    return out


# -- numpy reference encode/decode (the executable spec for rs_jax) ---------


def encode_ref(G, data):
    """data: uint8[k, L] → parity uint8[m, L] using coding rows of G."""
    G = np.asarray(G, np.uint8)
    k = G.shape[1]
    coding = G[k:]
    out = np.zeros((coding.shape[0], data.shape[1]), np.uint8)
    for i in range(coding.shape[0]):
        for j in range(k):
            out[i] ^= GF_MUL[coding[i, j], data[j]]
    return out


def decode_matrix(G, present_rows, k: int) -> np.ndarray:
    """Rows of G for k surviving chunks, inverted: recovers data chunks.
    ``present_rows``: indices (into k+m) of the k survivors used."""
    G = np.asarray(G, np.uint8)
    sub = G[np.asarray(present_rows, np.int64)]
    return gf_inv_matrix(sub)


def decode_ref(G, chunks, erasures, k: int):
    """Reference decode: ``chunks`` dict chunk_index->uint8[L]; returns
    the reconstructed full data array uint8[k, L]."""
    present = sorted(i for i in chunks if i not in erasures)[:k]
    if len(present) < k:
        raise ValueError("not enough chunks to decode")
    inv = decode_matrix(G, present, k)
    stack = np.stack([chunks[i] for i in present])
    out = np.zeros((k, stack.shape[1]), np.uint8)
    for i in range(k):
        for t in range(k):
            out[i] ^= GF_MUL[inv[i, t], stack[t]]
    return out
