"""Native GF(2^8) matmul — the EC engine's CPU twin.

The isa-l role on the host: RS encode/decode as table-driven GF(2^8)
matrix application (native/crush_host.cpp gf8_matmul, OpenMP over
rows).  The TPU path stays the MXU bit-matmul (engine.BitCode /
pallas_kernels); this backs the bench's CPU fallback and host tools so
the EC throughput number is a real engine on every platform.

Parity is identical to the array engines by construction: both apply
the SAME generator matrices (gf.py) over the same field (poly 0x11D),
pinned by tests.
"""

from __future__ import annotations

import ctypes
from typing import Dict, Sequence

import numpy as np

from . import gf
from ..crush.native import ensure_built

_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_wired = False


def _lib():
    global _wired
    lib = ensure_built()
    if lib is None:
        return None
    if not _wired:
        lib.gf8_matmul.restype = ctypes.c_int
        lib.gf8_matmul.argtypes = [
            ctypes.c_int, ctypes.c_int, _u8p, _u8p, _u8p,
            ctypes.c_int64,
        ]
        _wired = True
    return lib


def available() -> bool:
    return _lib() is not None


def gf8_matmul(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """(rows, k) GF(2^8) matrix @ u8[k, L] -> u8[rows, L]."""
    lib = _lib()
    if lib is None:
        raise RuntimeError("native gf engine unavailable")
    mat = np.ascontiguousarray(mat, np.uint8)
    data = np.ascontiguousarray(data, np.uint8)
    rows, k = mat.shape
    assert data.shape[0] == k
    out = np.zeros((rows, data.shape[1]), np.uint8)
    lib.gf8_matmul(rows, k, mat, data, out,
                   np.int64(data.shape[1]))
    return out


class NativeRS:
    """RS(k, m) on the native engine — mirrors rs_jax.RSCode's array
    API for host-side callers."""

    def __init__(self, k: int, m: int, technique: str = "reed_sol_van"):
        self.k, self.m = k, m
        if technique in ("reed_sol_van", "vandermonde"):
            self.G = gf.rs_vandermonde_matrix(k, m)
        else:
            self.G = gf.rs_cauchy_matrix(k, m)
        self._dec_cache: Dict[tuple, np.ndarray] = {}

    def encode(self, data: np.ndarray) -> np.ndarray:
        return gf8_matmul(np.asarray(self.G[self.k:], np.uint8), data)

    def all_chunks(self, data: np.ndarray) -> np.ndarray:
        return np.concatenate([np.asarray(data, np.uint8),
                               self.encode(data)], axis=0)

    def decode(self, chunks: Dict[int, np.ndarray],
               erasures: Sequence[int]) -> np.ndarray:
        present = tuple(sorted(
            i for i in chunks if i not in set(erasures)))[:self.k]
        if len(present) < self.k:
            raise ValueError("need at least k chunks")
        dm = self._dec_cache.get(present)
        if dm is None:
            dm = np.asarray(
                gf.decode_matrix(self.G, list(present), self.k),
                np.uint8)
            self._dec_cache[present] = dm
        stack = np.stack([np.asarray(chunks[i], np.uint8)
                          for i in present])
        return gf8_matmul(dm, stack)
