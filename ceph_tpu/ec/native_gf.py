"""Native GF(2^8) matmul — the EC engine's CPU twin.

The isa-l role on the host: RS encode/decode as table-driven GF(2^8)
matrix application (native/crush_host.cpp gf8_matmul, OpenMP over
rows).  Two consumers:

- the bench's CPU EC figure and host tools;
- the plugin registry's w=8 matrix techniques (jerasure RS, isa),
  via :class:`NativeMatrixCode` — the OSD/client data path operates
  on per-op chunks far below the size where accelerator dispatch
  pays for itself, so the host engine is the default there EVEN on
  a TPU host (CEPH_TPU_EC_ENGINE=bitplane opts back into the
  array/Pallas engine, which remains the large-batch bench path).

Parity is identical to the array engines by construction: both apply
the SAME generator matrices (gf.py) over the same field (poly 0x11D),
pinned by tests (tests/test_native_gf.py cross-engine byte equality).
"""

from __future__ import annotations

import ctypes
from typing import Dict, Sequence

import numpy as np

from . import gf
from ..crush.native import ensure_built

_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_wired = False


def _lib():
    global _wired
    lib = ensure_built()
    if lib is None:
        return None
    if not _wired:
        lib.gf8_matmul.restype = ctypes.c_int
        lib.gf8_matmul.argtypes = [
            ctypes.c_int, ctypes.c_int, _u8p, _u8p, _u8p,
            ctypes.c_int64,
        ]
        _wired = True
    return lib


def available() -> bool:
    return _lib() is not None


def gf8_matmul(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """(rows, k) GF(2^8) matrix @ u8[k, L] -> u8[rows, L]."""
    lib = _lib()
    if lib is None:
        raise RuntimeError("native gf engine unavailable")
    mat = np.ascontiguousarray(mat, np.uint8)
    data = np.ascontiguousarray(data, np.uint8)
    rows, k = mat.shape
    assert data.shape[0] == k
    out = np.zeros((rows, data.shape[1]), np.uint8)
    lib.gf8_matmul(rows, k, mat, data, out,
                   np.int64(data.shape[1]))
    return out


ENGINES = ("native", "bitplane", "pallas-fused")


def engine_choice(profile_engine: str = "") -> str:
    """Which engine the plugin registry should put behind w=8 MATRIX
    techniques: 'native' (the GF(2^8) table engine — the isa-l role,
    7-40x the portable bit-plane engine on CPU) unless overridden or
    the native library is unavailable.  Mirrors the reference's
    plugin-selection rationale (src/erasure-code/isa/
    ErasureCodeIsa.cc:333-336: pick the fastest verified engine for
    the shape).

    ``profile_engine`` is the pool profile's ``engine=`` key and wins
    over the process-wide CEPH_TPU_EC_ENGINE env override.  Choices:
    'native', 'bitplane' (the array/XLA engine), and 'pallas-fused'
    (the fused unpack→MXU→pack kernel — compiled on TPU, interpret
    mode on CPU; byte-identical to bitplane by the corpus tests)."""
    import os

    forced = profile_engine or os.environ.get("CEPH_TPU_EC_ENGINE", "")
    if forced and forced not in ENGINES:
        raise RuntimeError(
            f"unknown EC engine {forced!r}; have {list(ENGINES)}")
    if forced in ("bitplane", "pallas-fused"):
        return forced
    if forced == "native":
        if not available():
            raise RuntimeError(
                "EC engine 'native' requested but the native GF "
                "engine failed to build/load — unset it or fix the "
                "toolchain")
        return "native"
    return "native" if available() else "bitplane"


class NativeMatrixCode:
    """BitCode-compatible facade over the native GF(2^8) engine for
    w=8 matrix techniques (jerasure reed_sol_van/reed_sol_r6_op w=8,
    every isa technique).

    Same generator matrices as the bit-plane engine — parity bytes are
    identical by construction (pinned by the EC corpus tests); only
    the execution engine differs.  Interface mirrors engine.BitCode:
    encode / all_chunks / decode_data / decode."""

    def __init__(self, k: int, m: int, coding_rows: np.ndarray):
        self.k, self.m = k, m
        rows = np.asarray(coding_rows, np.uint8)
        assert rows.shape == (m, k), rows.shape
        self.G = np.concatenate(
            [np.eye(k, dtype=np.uint8), rows], axis=0)
        self._dec_cache: Dict[tuple, np.ndarray] = {}

    def encode(self, data) -> np.ndarray:
        import time

        from .engine import _account

        data = np.asarray(data, np.uint8)
        assert data.shape[0] == self.k
        t0 = time.monotonic()
        out = gf8_matmul(self.G[self.k:], data)
        _account("encode", (), time.monotonic() - t0,
                 int(data.size), jitted=False)
        return out

    def all_chunks(self, data) -> np.ndarray:
        data = np.asarray(data, np.uint8)
        return np.concatenate([data, self.encode(data)], axis=0)

    def decode_data(self, chunks: Dict[int, np.ndarray]) -> np.ndarray:
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise ValueError("need at least k chunks")
        present = tuple(avail[:self.k])
        dm = self._dec_cache.get(present)
        if dm is None:
            dm = np.asarray(gf.decode_matrix(self.G, list(present),
                                             self.k), np.uint8)
            if len(self._dec_cache) >= 512:  # IsaTableCache-style bound
                self._dec_cache.pop(next(iter(self._dec_cache)))
            self._dec_cache[present] = dm
        import time

        from .engine import _account

        stack = np.stack([np.asarray(chunks[i], np.uint8)
                          for i in present])
        t0 = time.monotonic()
        out = gf8_matmul(dm, stack)
        _account("decode", (), time.monotonic() - t0,
                 int(stack.size), jitted=False)
        return out

    def decode(self, want: Sequence[int],
               chunks: Dict[int, np.ndarray]) -> Dict[int, np.ndarray]:
        have = {i: np.asarray(c, np.uint8) for i, c in chunks.items()}
        missing = [i for i in want if i not in have]
        if missing:
            data = self.decode_data(have)
            for i in range(self.k):
                if i not in have:
                    have[i] = data[i]
            par_missing = [i for i in missing if i >= self.k]
            if par_missing:
                parity = self.encode(data)
                for i in par_missing:
                    have[i] = parity[i - self.k]
        return {i: have[i] for i in want}


class NativeRS(NativeMatrixCode):
    """RS(k, m) on the native engine — mirrors rs_jax.RSCode's array
    API for host-side callers (a thin facade over NativeMatrixCode:
    one decode-cache implementation to keep in sync, not two)."""

    def __init__(self, k: int, m: int, technique: str = "reed_sol_van"):
        if technique in ("reed_sol_van", "vandermonde"):
            G = gf.rs_vandermonde_matrix(k, m)
        else:
            G = gf.rs_cauchy_matrix(k, m)
        super().__init__(k, m, np.asarray(G[k:], np.uint8))

    # rs_jax.RSCode decode signature: (chunks, erasures) -> data rows
    def decode(self, chunks: Dict[int, np.ndarray],  # type: ignore[override]
               erasures: Sequence[int]) -> np.ndarray:
        avail = {i: c for i, c in chunks.items()
                 if i not in set(erasures)}
        return self.decode_data(avail)
