"""The isa-equivalent plugin: RS over GF(2^8) with isa-l's generators.

Mirrors src/erasure-code/isa/ErasureCodeIsa.{h,cc}: the same two
techniques (``reed_sol_van`` = isa-l gf_gen_rs_matrix Vandermonde,
``cauchy`` = gf_gen_cauchy1_matrix), the same defaults (k=7, m=3,
ErasureCodeIsa.cc:46-47), the same Vandermonde MDS-safety clamps
(:331-360) and 32-byte chunk alignment (xor_op.h:28, get_chunk_size
:66-79).  Where isa-l runs table-driven SSE/AVX GF multiplies
(ec_encode_data, :129) with an LRU decode-table cache (:227-304), this
plugin expands the generator to a GF(2) bit matrix once and runs the
MXU mod-2 matmul engine — the decode-matrix-per-erasure-signature cache
lives in ``engine.BitCode`` (the IsaTableCache flow).  The m=1 /
single-erasure region_xor fast paths (:125-127) need no special case:
an all-ones generator row IS the XOR as a matmul.
"""

from __future__ import annotations

from . import matrices as M
from .engine import BitCode, Layout
from .gfw import GFW
from .interface import ErasureCode, ErasureCodeError, ErasureCodeProfile

EC_ISA_ADDRESS_ALIGNMENT = 32  # xor_op.h:28

DEFAULT_K = 7
DEFAULT_M = 3


class ErasureCodeIsa(ErasureCode):
    """Both isa techniques; ``technique`` selects the generator."""

    def __init__(self, technique: str = "reed_sol_van"):
        super().__init__()
        self.technique = technique
        self.k = 0
        self.m = 0
        self.engine = ""
        self._code: BitCode | None = None

    def init(self, profile: ErasureCodeProfile) -> None:
        profile["technique"] = self.technique
        self.parse(profile)
        self.prepare()
        super().init(profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        self.k = self.to_int("k", profile, DEFAULT_K)
        self.m = self.to_int("m", profile, DEFAULT_M)
        self.sanity_check_k_m(self.k, self.m)
        # per-pool engine selection (isa is always a w=8 byte layout,
        # so every engine applies); wins over CEPH_TPU_EC_ENGINE
        from .native_gf import ENGINES

        self.engine = profile.get("engine", "")
        if self.engine and self.engine not in ENGINES:
            raise ErasureCodeError(
                -22, f"engine={self.engine} must be one of "
                     f"{list(ENGINES)}")
        if self.technique == "reed_sol_van":
            # isa-l's Vandermonde construction is not MDS everywhere;
            # clamp to the verified-safe region (ErasureCodeIsa.cc:331)
            if self.k > 32:
                raise ErasureCodeError(
                    -22, f"Vandermonde: k={self.k} must be <= 32")
            if self.m > 4:
                raise ErasureCodeError(
                    -22, f"Vandermonde: m={self.m} must be < 5 for MDS")
            if self.m == 4 and self.k > 21:
                raise ErasureCodeError(
                    -22, f"Vandermonde: k={self.k} must be < 22 at m=4")

    def prepare(self) -> None:
        if self.technique == "cauchy":
            full = M.isa_gf_gen_cauchy1_matrix(self.k, self.m)
        else:
            full = M.isa_gf_gen_rs_matrix(self.k, self.m)
        coding = full[self.k:]
        from .native_gf import NativeMatrixCode, engine_choice

        choice = engine_choice(self.engine)
        if choice == "native":
            # the ec_encode_data role on its native engine (isa-l is
            # GF(2^8) table asm; this is the same math via the C++
            # OpenMP kernel) — same bytes as the bit-plane engine
            self._code = NativeMatrixCode(self.k, self.m, coding)
            return
        cb = GFW(8).expand_bitmatrix(coding)
        self._code = BitCode(self.k, self.m, cb, Layout(8),
                             force_fused=choice == "pallas-fused")

    # -- geometry (ErasureCodeIsa.cc:66-79) ---------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        return EC_ISA_ADDRESS_ALIGNMENT

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.get_alignment()
        chunk_size = (object_size + self.k - 1) // self.k
        modulo = chunk_size % alignment
        if modulo:
            chunk_size += alignment - modulo
        return chunk_size

    # -- data path (same engine as jerasure) --------------------------
    def encode_chunks(self, want_to_encode, chunks) -> None:
        import numpy as np

        data = np.stack([np.asarray(chunks[self.chunk_index(i)],
                                    np.uint8)
                         for i in range(self.k)])
        parity = np.asarray(self._code.encode(data))
        for i in range(self.m):
            chunks[self.chunk_index(self.k + i)] = parity[i]

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        import numpy as np

        # encoded-position -> internal remap, symmetric with encode
        n = self.k + self.m
        inv = {self.chunk_index(i): i for i in range(n)}
        avail = {inv[c]: np.asarray(v, np.uint8)
                 for c, v in chunks.items()}
        erased = [i for i in range(n) if i not in avail]
        out = self._code.decode(erased, avail)
        for i, buf in out.items():
            decoded[self.chunk_index(i)] = np.asarray(buf)


def make_isa(profile: ErasureCodeProfile) -> ErasureCodeIsa:
    """Plugin factory (ErasureCodePluginIsa.cc:41-55 flow)."""
    technique = profile.get("technique", "reed_sol_van")
    if technique not in ("reed_sol_van", "cauchy"):
        raise ErasureCodeError(
            -2, f"technique={technique} must be reed_sol_van or cauchy")
    inst = ErasureCodeIsa(technique)
    inst.init(profile)
    return inst
