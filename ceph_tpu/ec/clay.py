"""The CLAY plugin — coupled-layer MSR regenerating codes.

Mirrors src/erasure-code/clay/ErasureCodeClay.{h,cc}: chunks split into
``sub_chunk_no = q^t`` sub-chunks arranged on a (q x t) node grid;
encode/decode work plane by plane through pairwise-coupling transforms
(a tiny k=2,m=2 "pft" code), with a scalar MDS code (jerasure/isa/shec)
across each plane's uncoupled values.  Single-node repair reads only
d helpers x (1/q of each chunk) — bandwidth-optimal (the
minimum_to_repair path, :324-363).

Ported 1:1 from the reference flow: parse/q/t/nu geometry (:188-300),
is_repair (:302-322), get_repair_subchunks (:365-380), repair +
repair_one_lost_chunk (:404-645), decode_layered / decode_erasures /
decode_uncoupled (:648-760), the type-1/coupled/uncoupled pair
transforms (:776-875), plane ordering (:763-773, :877-888).  Where the
reference aliases bufferlists (substr_of views mutated in place), this
port uses numpy slice views with explicit copy-back after each inner
decode.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

import numpy as np

from .interface import ErasureCode, ErasureCodeError, ErasureCodeProfile

DEFAULT_K = 4
DEFAULT_M = 2


class ErasureCodeClay(ErasureCode):
    def __init__(self):
        super().__init__()
        self.k = 0
        self.m = 0
        self.d = 0
        self.q = 0
        self.t = 0
        self.nu = 0
        self.sub_chunk_no = 0
        self.mds: ErasureCode | None = None
        self.pft: ErasureCode | None = None

    # -- profile (:188-300) -------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        super().init(profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        from .registry import factory

        self.k = self.to_int("k", profile, DEFAULT_K)
        self.m = self.to_int("m", profile, DEFAULT_M)
        self.sanity_check_k_m(self.k, self.m)
        self.d = self.to_int("d", profile, self.k + self.m - 1)

        plugin = profile.get("scalar_mds", "") or "jerasure"
        if plugin not in ("jerasure", "isa", "shec"):
            raise ErasureCodeError(
                -22, f"scalar_mds {plugin} not supported; use "
                     f"jerasure, isa or shec")
        tech = profile.get("technique", "")
        if not tech:
            tech = "reed_sol_van" if plugin in ("jerasure", "isa") \
                else "single"
        allowed = {
            "jerasure": ("reed_sol_van", "reed_sol_r6_op",
                         "cauchy_orig", "cauchy_good", "liber8tion"),
            "isa": ("reed_sol_van", "cauchy"),
            "shec": ("single", "multiple"),
        }[plugin]
        if tech not in allowed:
            raise ErasureCodeError(
                -22, f"technique {tech} not supported for {plugin}")

        if self.d < self.k or self.d > self.k + self.m - 1:
            raise ErasureCodeError(
                -22, f"value of d {self.d} must be within "
                     f"[{self.k},{self.k + self.m - 1}]")
        self.q = self.d - self.k + 1
        self.nu = (self.q - (self.k + self.m) % self.q) \
            if (self.k + self.m) % self.q else 0
        if self.k + self.m + self.nu > 254:
            raise ErasureCodeError(-22, "k+m+nu must be <= 254")

        mds_profile = {"plugin": plugin, "technique": tech,
                       "k": str(self.k + self.nu), "m": str(self.m),
                       "w": "8"}
        pft_profile = {"plugin": plugin, "technique": tech,
                       "k": "2", "m": "2", "w": "8"}
        if plugin == "shec":
            mds_profile["c"] = "2"
            pft_profile["c"] = "2"
        self.mds = factory(plugin, mds_profile)
        self.pft = factory(plugin, pft_profile)

        self.t = (self.k + self.m + self.nu) // self.q
        self.sub_chunk_no = self.q ** self.t

    # -- geometry -----------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_chunk_size(self, object_size: int) -> int:
        """:90-96: aligned so each sub-chunk is a whole scalar-code
        word block."""
        align = self.sub_chunk_no * self.k * \
            self.pft.get_chunk_size(1)
        padded = ((object_size + align - 1) // align) * align
        return padded // self.k

    # -- plane helpers ------------------------------------------------
    def get_plane_vector(self, z: int) -> List[int]:
        z_vec = [0] * self.t
        for i in range(self.t):
            z_vec[self.t - 1 - i] = z % self.q
            z = z // self.q
        return z_vec

    def get_max_iscore(self, erased: Set[int]) -> int:
        weight = [0] * self.t
        score = 0
        for i in erased:
            if weight[i // self.q] == 0:
                weight[i // self.q] = 1
                score += 1
        return score

    def _plane_order(self, erased: Set[int]) -> List[int]:
        order = [0] * self.sub_chunk_no
        for z in range(self.sub_chunk_no):
            z_vec = self.get_plane_vector(z)
            order[z] = sum(1 for i in erased
                           if i % self.q == z_vec[i // self.q])
        return order

    # -- pairwise transform helper ------------------------------------
    def _pft_decode(self, erased: Set[int],
                    known: Dict[int, np.ndarray],
                    out_views: Dict[int, np.ndarray]) -> None:
        """Run the 2x2 pairwise code and copy results back into the
        aliased buffers (the reference mutates through bufferlist
        views)."""
        decoded = {}
        for i in range(4):
            decoded[i] = np.array(
                known[i] if i in known else out_views[i], np.uint8)
        self.pft.decode_chunks(erased, dict(known), decoded)
        for i in erased:
            out_views[i][:] = decoded[i]

    # -- uncoupled scalar decode (:742-760) ----------------------------
    def _decode_uncoupled(self, U: Dict[int, np.ndarray],
                          erased: Set[int], z: int,
                          sc_size: int) -> None:
        known = {}
        decoded = {}
        for i in range(self.q * self.t):
            view = U[i][z * sc_size:(z + 1) * sc_size]
            if i not in erased:
                known[i] = np.array(view)
            decoded[i] = np.array(view)
        self.mds.decode_chunks(set(erased), known, decoded)
        for i in erased:
            U[i][z * sc_size:(z + 1) * sc_size] = decoded[i]

    # -- coupled<->uncoupled transforms (:776-875) ---------------------
    def _swap_idx(self, x: int, zy: int) -> Tuple[int, int, int, int]:
        if zy > x:
            return 1, 0, 3, 2
        return 0, 1, 2, 3

    def _get_uncoupled_from_coupled(self, chunks, U, x, y, z, z_vec,
                                    sc_size) -> None:
        node_xy = y * self.q + x
        node_sw = y * self.q + z_vec[y]
        z_sw = z + (x - z_vec[y]) * self.q ** (self.t - 1 - y)
        i0, i1, i2, i3 = self._swap_idx(x, z_vec[y])
        known = {
            i0: np.array(chunks[node_xy][z * sc_size:(z + 1) * sc_size]),
            i1: np.array(
                chunks[node_sw][z_sw * sc_size:(z_sw + 1) * sc_size]),
        }
        out = {
            i2: U[node_xy][z * sc_size:(z + 1) * sc_size],
            i3: U[node_sw][z_sw * sc_size:(z_sw + 1) * sc_size],
        }
        self._pft_decode({2, 3}, known, out)

    def _get_coupled_from_uncoupled(self, chunks, U, x, y, z, z_vec,
                                    sc_size) -> None:
        node_xy = y * self.q + x
        node_sw = y * self.q + z_vec[y]
        z_sw = z + (x - z_vec[y]) * self.q ** (self.t - 1 - y)
        assert z_vec[y] < x
        known = {
            2: np.array(U[node_xy][z * sc_size:(z + 1) * sc_size]),
            3: np.array(
                U[node_sw][z_sw * sc_size:(z_sw + 1) * sc_size]),
        }
        out = {
            0: chunks[node_xy][z * sc_size:(z + 1) * sc_size],
            1: chunks[node_sw][z_sw * sc_size:(z_sw + 1) * sc_size],
        }
        self._pft_decode({0, 1}, known, out)

    def _recover_type1(self, chunks, U, x, y, z, z_vec,
                       sc_size) -> None:
        node_xy = y * self.q + x
        node_sw = y * self.q + z_vec[y]
        z_sw = z + (x - z_vec[y]) * self.q ** (self.t - 1 - y)
        i0, i1, i2, i3 = self._swap_idx(x, z_vec[y])
        known = {
            i1: np.array(
                chunks[node_sw][z_sw * sc_size:(z_sw + 1) * sc_size]),
            i2: np.array(U[node_xy][z * sc_size:(z + 1) * sc_size]),
        }
        out = {
            i0: chunks[node_xy][z * sc_size:(z + 1) * sc_size],
            i3: np.zeros(sc_size, np.uint8),
        }
        self._pft_decode({i0}, known, out)

    # -- layered decode (:648-741) -------------------------------------
    def _decode_layered(self, erased: Set[int],
                        chunks: Dict[int, np.ndarray]) -> None:
        size = len(chunks[0])
        assert size % self.sub_chunk_no == 0
        sc_size = size // self.sub_chunk_no
        erased = set(erased)
        assert erased
        # pad erasures to exactly m with shortened/parity nodes
        for i in range(self.k + self.nu, self.q * self.t):
            if len(erased) >= self.m:
                break
            erased.add(i)
        assert len(erased) == self.m

        U = {i: np.zeros(size, np.uint8)
             for i in range(self.q * self.t)}
        order = self._plane_order(erased)
        max_iscore = self.get_max_iscore(erased)

        for iscore in range(max_iscore + 1):
            for z in range(self.sub_chunk_no):
                if order[z] == iscore:
                    self._decode_erasures(erased, z, chunks, U, sc_size)
            for z in range(self.sub_chunk_no):
                if order[z] != iscore:
                    continue
                z_vec = self.get_plane_vector(z)
                for node_xy in sorted(erased):
                    x = node_xy % self.q
                    y = node_xy // self.q
                    node_sw = y * self.q + z_vec[y]
                    if z_vec[y] != x:
                        if node_sw not in erased:
                            self._recover_type1(chunks, U, x, y, z,
                                                z_vec, sc_size)
                        elif z_vec[y] < x:
                            self._get_coupled_from_uncoupled(
                                chunks, U, x, y, z, z_vec, sc_size)
                    else:
                        chunks[node_xy][z * sc_size:(z + 1) * sc_size] \
                            = U[node_xy][z * sc_size:(z + 1) * sc_size]

    def _decode_erasures(self, erased: Set[int], z: int, chunks, U,
                         sc_size: int) -> None:
        z_vec = self.get_plane_vector(z)
        for x in range(self.q):
            for y in range(self.t):
                node_xy = self.q * y + x
                node_sw = self.q * y + z_vec[y]
                if node_xy in erased:
                    continue
                if z_vec[y] < x:
                    self._get_uncoupled_from_coupled(
                        chunks, U, x, y, z, z_vec, sc_size)
                elif z_vec[y] == x:
                    U[node_xy][z * sc_size:(z + 1) * sc_size] = \
                        chunks[node_xy][z * sc_size:(z + 1) * sc_size]
                else:
                    if node_sw in erased:
                        self._get_uncoupled_from_coupled(
                            chunks, U, x, y, z, z_vec, sc_size)
        self._decode_uncoupled(U, erased, z, sc_size)

    # -- encode/decode entry points (:129-185) -------------------------
    def _grid_chunks(self, encoded: Dict[int, np.ndarray],
                     chunk_size: int) -> Dict[int, np.ndarray]:
        """Map interface chunk ids onto the q*t node grid, inserting
        zeroed shortening nodes k..k+nu."""
        chunks: Dict[int, np.ndarray] = {}
        for i in range(self.k + self.m):
            buf = np.array(np.asarray(encoded[i], np.uint8))
            chunks[i if i < self.k else i + self.nu] = buf
        for i in range(self.k, self.k + self.nu):
            chunks[i] = np.zeros(chunk_size, np.uint8)
        return chunks

    def encode_chunks(self, want_to_encode: Set[int],
                      chunks_io: Dict[int, np.ndarray]) -> None:
        chunk_size = len(np.asarray(chunks_io[self.chunk_index(0)]))
        grid_in = {i: chunks_io[self.chunk_index(i)]
                   for i in range(self.k + self.m)}
        chunks = self._grid_chunks(grid_in, chunk_size)
        parity_nodes = {i + self.nu
                        for i in range(self.k, self.k + self.m)}
        self._decode_layered(parity_nodes, chunks)
        for i in range(self.k, self.k + self.m):
            chunks_io[self.chunk_index(i)] = chunks[i + self.nu]

    def decode_chunks(self, want_to_read: Set[int],
                      chunks_avail: Dict[int, np.ndarray],
                      decoded: Dict[int, np.ndarray]) -> None:
        chunk_size = len(next(iter(decoded.values())))
        erased = set()
        grid: Dict[int, np.ndarray] = {}
        for i in range(self.k + self.m):
            node = i if i < self.k else i + self.nu
            enc = self.chunk_index(i)  # encoded-position remap
            if enc not in chunks_avail:
                erased.add(node)
            grid[node] = np.array(np.asarray(decoded[enc], np.uint8))
        for i in range(self.k, self.k + self.nu):
            grid[i] = np.zeros(chunk_size, np.uint8)
        self._decode_layered(erased, grid)
        for i in range(self.k + self.m):
            node = i if i < self.k else i + self.nu
            decoded[self.chunk_index(i)] = grid[node]

    # -- repair path (:302-645) ----------------------------------------
    def is_repair(self, want_to_read: Set[int],
                  available: Set[int]) -> bool:
        if set(want_to_read) <= set(available):
            return False
        if len(want_to_read) > 1:
            return False
        i = next(iter(want_to_read))
        lost = i if i < self.k else i + self.nu
        for x in range(self.q):
            node = (lost // self.q) * self.q + x
            node = node if node < self.k else node - self.nu
            if node != i and node not in available:
                return False
        return len(available) >= self.d

    def get_repair_subchunks(self, lost_node: int
                             ) -> List[Tuple[int, int]]:
        y_lost = lost_node // self.q
        x_lost = lost_node % self.q
        seq = self.q ** (self.t - 1 - y_lost)
        num_seq = self.q ** y_lost
        out = []
        index = x_lost * seq
        for _ in range(num_seq):
            out.append((index, seq))
            index += self.q * seq
        return out

    def get_repair_sub_chunk_count(self,
                                   want_to_read: Set[int]) -> int:
        weight = [0] * self.t
        for i in want_to_read:
            weight[i // self.q] += 1
        count = 1
        for y in range(self.t):
            count *= (self.q - weight[y])
        return self.sub_chunk_no - count

    def minimum_to_decode(self, want_to_read: Set[int],
                          available: Set[int]
                          ) -> Dict[int, List[Tuple[int, int]]]:
        """:98-104: bandwidth-optimal repair plan when possible."""
        if self.is_repair(set(want_to_read), set(available)):
            return self._minimum_to_repair(set(want_to_read),
                                           set(available))
        return super().minimum_to_decode(want_to_read, available)

    def _minimum_to_repair(self, want_to_read: Set[int],
                           available: Set[int]
                           ) -> Dict[int, List[Tuple[int, int]]]:
        i = next(iter(want_to_read))
        lost = i if i < self.k else i + self.nu
        sub_ind = self.get_repair_subchunks(lost)
        minimum: Dict[int, List[Tuple[int, int]]] = {}
        for j in range(self.q):
            if j != lost % self.q:
                rep = (lost // self.q) * self.q + j
                if rep < self.k:
                    minimum[rep] = list(sub_ind)
                elif rep >= self.k + self.nu:
                    minimum[rep - self.nu] = list(sub_ind)
        for chunk in sorted(available):
            if len(minimum) >= self.d:
                break
            minimum.setdefault(chunk, list(sub_ind))
        assert len(minimum) == self.d
        return minimum

    def decode(self, want_to_read, chunks: Dict[int, np.ndarray],
               chunk_size: int = 0):
        """:98-125: helpers holding only repair sub-chunks route to the
        repair path."""
        want = set(want_to_read)
        avail = set(chunks)
        first_len = len(np.asarray(next(iter(chunks.values()))))
        if self.is_repair(want, avail) and chunk_size > first_len:
            return self._repair(want, chunks, chunk_size)
        return self._decode(want, chunks)

    def _repair(self, want_to_read: Set[int],
                chunks: Dict[int, np.ndarray],
                chunk_size: int) -> Dict[int, np.ndarray]:
        assert len(want_to_read) == 1 and len(chunks) == self.d
        repair_sub_no = self.get_repair_sub_chunk_count(want_to_read)
        repair_blocksize = len(np.asarray(next(iter(chunks.values()))))
        assert repair_blocksize % repair_sub_no == 0
        sub_chunksize = repair_blocksize // repair_sub_no
        chunksize = self.sub_chunk_no * sub_chunksize
        assert chunksize == chunk_size

        recovered: Dict[int, np.ndarray] = {}
        helper: Dict[int, np.ndarray] = {}
        aloof: Set[int] = set()
        lost_id = -1
        repair_sub_ind: List[Tuple[int, int]] = []
        for i in range(self.k + self.m):
            node = i if i < self.k else i + self.nu
            if i in chunks:
                helper[node] = np.asarray(chunks[i], np.uint8)
            elif i != next(iter(want_to_read)):
                aloof.add(node)
            else:
                lost_id = node
                recovered[node] = np.zeros(chunksize, np.uint8)
                repair_sub_ind = self.get_repair_subchunks(node)
        for i in range(self.k, self.k + self.nu):
            helper[i] = np.zeros(repair_blocksize, np.uint8)
        assert len(helper) + len(aloof) + len(recovered) == \
            self.q * self.t

        self._repair_one_lost_chunk(recovered, aloof, helper,
                                    repair_blocksize, repair_sub_ind)
        i = next(iter(want_to_read))
        return {i: recovered[lost_id]}

    def _repair_one_lost_chunk(self, recovered, aloof, helper,
                               repair_blocksize, repair_sub_ind
                               ) -> None:
        q, t = self.q, self.t
        repair_subchunks = self.sub_chunk_no // q
        sub_sz = repair_blocksize // repair_subchunks

        ordered_planes: Dict[int, Set[int]] = {}
        repair_plane_to_ind: Dict[int, int] = {}
        plane_ind = 0
        for index, count in repair_sub_ind:
            for j in range(index, index + count):
                z_vec = self.get_plane_vector(j)
                order = sum(1 for node in recovered
                            if node % q == z_vec[node // q])
                order += sum(1 for node in aloof
                             if node % q == z_vec[node // q])
                assert order > 0
                ordered_planes.setdefault(order, set()).add(j)
                repair_plane_to_ind[j] = plane_ind
                plane_ind += 1
        assert plane_ind == repair_subchunks

        U = {i: np.zeros(self.sub_chunk_no * sub_sz, np.uint8)
             for i in range(q * t)}

        (lost_chunk,) = recovered.keys()
        erasures = {lost_chunk - lost_chunk % q + i for i in range(q)}
        erasures |= aloof

        order = 1
        while order in ordered_planes:
            for z in sorted(ordered_planes[order]):
                z_vec = self.get_plane_vector(z)
                for y in range(t):
                    for x in range(q):
                        node_xy = y * q + x
                        if node_xy in erasures:
                            continue
                        z_sw = z + (x - z_vec[y]) * q ** (t - 1 - y)
                        node_sw = y * q + z_vec[y]
                        i0, i1, i2, i3 = self._swap_idx(x, z_vec[y])
                        hslice = helper[node_xy][
                            repair_plane_to_ind[z] * sub_sz:
                            (repair_plane_to_ind[z] + 1) * sub_sz]
                        if node_sw in aloof:
                            known = {
                                i0: np.array(hslice),
                                i3: np.array(
                                    U[node_sw][z_sw * sub_sz:
                                               (z_sw + 1) * sub_sz]),
                            }
                            out = {
                                i2: U[node_xy][z * sub_sz:
                                               (z + 1) * sub_sz],
                                i1: np.zeros(sub_sz, np.uint8),
                            }
                            self._pft_decode({i2}, known, out)
                        elif z_vec[y] != x:
                            sw_slice = helper[node_sw][
                                repair_plane_to_ind[z_sw] * sub_sz:
                                (repair_plane_to_ind[z_sw] + 1)
                                * sub_sz]
                            known = {i0: np.array(hslice),
                                     i1: np.array(sw_slice)}
                            out = {
                                i2: U[node_xy][z * sub_sz:
                                               (z + 1) * sub_sz],
                                i3: np.zeros(sub_sz, np.uint8),
                            }
                            self._pft_decode({i2}, known, out)
                        else:
                            U[node_xy][z * sub_sz:(z + 1) * sub_sz] \
                                = hslice
                assert len(erasures) <= self.m
                self._decode_uncoupled(U, erasures, z, sub_sz)
                for i in sorted(erasures):
                    x, y = i % q, i // q
                    node_sw = y * q + z_vec[y]
                    z_sw = z + (x - z_vec[y]) * q ** (t - 1 - y)
                    i0, i1, i2, i3 = self._swap_idx(x, z_vec[y])
                    if i in aloof:
                        continue
                    if x == z_vec[y]:  # hole-dot pair (type 0)
                        recovered[i][z * sub_sz:(z + 1) * sub_sz] = \
                            U[i][z * sub_sz:(z + 1) * sub_sz]
                    else:
                        assert y == lost_chunk // q
                        assert node_sw == lost_chunk
                        known = {
                            i0: np.array(helper[i][
                                repair_plane_to_ind[z] * sub_sz:
                                (repair_plane_to_ind[z] + 1)
                                * sub_sz]),
                            i2: np.array(U[i][z * sub_sz:
                                              (z + 1) * sub_sz]),
                        }
                        out = {
                            i1: recovered[node_sw][
                                z_sw * sub_sz:(z_sw + 1) * sub_sz],
                            i3: np.zeros(sub_sz, np.uint8),
                        }
                        self._pft_decode({i1}, known, out)
            order += 1


def make_clay(profile: ErasureCodeProfile) -> ErasureCodeClay:
    inst = ErasureCodeClay()
    inst.init(profile)
    return inst
