"""The jerasure-equivalent plugin: six techniques on the TPU engine.

Mirrors src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}: the same
technique set (reed_sol_van, reed_sol_r6_op, cauchy_orig, cauchy_good,
liberation, blaum_roth, liber8tion), the same profile keys
(k/m/w/packetsize/jerasure-per-chunk-alignment), the same
get_chunk_size/alignment arithmetic (ErasureCodeJerasure.cc:80-104,
:174-184, :278-292) — with the vendored GF kernels replaced by
``ceph_tpu.ec.engine`` mod-2 matmuls and the generator constructions in
``ceph_tpu.ec.matrices`` (the submodules are absent from the reference
checkout; parity is pinned to the published algorithms).
"""

from __future__ import annotations

from typing import Dict, Set

import numpy as np

from . import matrices as M
from .engine import BitCode, Layout
from .gfw import GFW
from .interface import ErasureCode, ErasureCodeError, ErasureCodeProfile

LARGEST_VECTOR_WORDSIZE = 16  # ErasureCodeJerasure.cc:30

DEFAULT_K = 2
DEFAULT_M = 1
DEFAULT_W = 8
DEFAULT_PACKETSIZE = 2048

_PRIMES = {2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53,
           59, 61, 67, 71, 73, 79, 83, 89, 97, 101, 103, 107, 109, 113,
           127, 131, 137, 139, 149, 151, 157, 163, 167, 173, 179, 181,
           191, 193, 197, 199, 211, 223, 227, 229, 233, 239, 241, 251,
           257}


def is_prime(v: int) -> bool:
    return v in _PRIMES


class ErasureCodeJerasure(ErasureCode):
    """Common jerasure behavior; subclasses provide the bit code."""

    technique = "?"

    def __init__(self):
        super().__init__()
        self.k = 0
        self.m = 0
        self.w = 0
        self.engine = ""
        self.per_chunk_alignment = False
        self._code: BitCode | None = None

    # -- profile ------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        profile["technique"] = self.technique
        self.parse(profile)
        self.prepare()
        super().init(profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        self.k = self.to_int("k", profile, DEFAULT_K)
        self.m = self.to_int("m", profile, DEFAULT_M)
        self.w = self.to_int("w", profile, self.default_w())
        # profile engine= selects the execution engine per pool
        # (native GF(2^8) table / bitplane XLA / pallas-fused kernel);
        # wins over the CEPH_TPU_EC_ENGINE process override
        from .native_gf import ENGINES

        self.engine = profile.get("engine", "")
        if self.engine and self.engine not in ENGINES:
            raise ErasureCodeError(
                -22, f"engine={self.engine} must be one of "
                     f"{list(ENGINES)}")
        self._parse_mapping(profile)
        if self.chunk_mapping and \
                len(self.chunk_mapping) != self.k + self.m:
            self.chunk_mapping = []
            raise ErasureCodeError(
                -22, "mapping maps the wrong number of chunks")
        self.sanity_check_k_m(self.k, self.m)

    def default_w(self) -> int:
        return DEFAULT_W

    def prepare(self) -> None:
        raise NotImplementedError

    # -- geometry -----------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        raise NotImplementedError

    def get_chunk_size(self, object_size: int) -> int:
        """ErasureCodeJerasure.cc:80-104."""
        alignment = self.get_alignment()
        if self.per_chunk_alignment:
            chunk_size = (object_size + self.k - 1) // self.k
            modulo = chunk_size % alignment
            if modulo:
                chunk_size += alignment - modulo
            return chunk_size
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    # -- data path ----------------------------------------------------
    def encode_chunks(self, want_to_encode: Set[int],
                      chunks: Dict[int, np.ndarray]) -> None:
        data = np.stack([np.asarray(chunks[self.chunk_index(i)], np.uint8)
                         for i in range(self.k)])
        parity = np.asarray(self._code.encode(data))
        for i in range(self.m):
            chunks[self.chunk_index(self.k + i)] = parity[i]

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Dict[int, np.ndarray],
                      decoded: Dict[int, np.ndarray]) -> None:
        # chunks/decoded are keyed by ENCODED position; the engine
        # works in internal (data-first) order — remap symmetrically
        # with encode_chunks so mapping= profiles decode correctly
        n = self.k + self.m
        inv = {self.chunk_index(i): i for i in range(n)}
        avail = {inv[c]: np.asarray(v, np.uint8)
                 for c, v in chunks.items()}
        erased = [i for i in range(n) if i not in avail]
        out = self._code.decode(erased, avail)
        for i, buf in out.items():
            decoded[self.chunk_index(i)] = np.asarray(buf)


class _MatrixTechnique(ErasureCodeJerasure):
    """RS matrix codes: w in {8, 16, 32}, word layout."""

    def get_alignment(self) -> int:
        """ErasureCodeJerasure.cc:174-184."""
        if self.per_chunk_alignment:
            return self.w * LARGEST_VECTOR_WORDSIZE
        alignment = self.k * self.w * 4  # sizeof(int)
        if (self.w * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * LARGEST_VECTOR_WORDSIZE
        return alignment

    def _make_code(self, coding_rows) -> None:
        from .native_gf import NativeMatrixCode, engine_choice

        if self.w != 8:
            if self.engine in ("native", "pallas-fused"):
                raise ErasureCodeError(
                    -22, f"engine={self.engine} requires w=8 "
                         f"(byte layout), have w={self.w}")
            choice = "bitplane"
        else:
            choice = engine_choice(self.engine)
        if choice == "native":
            # w=8 RS rides the native GF(2^8) table engine (the isa-l
            # role) when present — same generator matrix, same bytes,
            # 7-40x the portable bit-plane engine on CPU
            self._code = NativeMatrixCode(self.k, self.m, coding_rows)
            return
        cb = GFW(self.w).expand_bitmatrix(coding_rows)
        self._code = BitCode(self.k, self.m, cb, Layout(self.w),
                             force_fused=choice == "pallas-fused")


class ReedSolomonVandermonde(_MatrixTechnique):
    technique = "reed_sol_van"

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        if self.w not in (8, 16, 32):
            raise ErasureCodeError(
                -22, f"reed_sol_van: w={self.w} must be in {{8,16,32}}")
        self.per_chunk_alignment = self.to_bool(
            "jerasure-per-chunk-alignment", profile, False)

    def prepare(self) -> None:
        self._make_code(
            M.reed_sol_vandermonde_coding_matrix(self.k, self.m, self.w))


class ReedSolomonRAID6(_MatrixTechnique):
    technique = "reed_sol_r6_op"

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        if self.m != 2:
            raise ErasureCodeError(-22, "reed_sol_r6_op: m must be 2")
        if self.w not in (8, 16, 32):
            raise ErasureCodeError(
                -22, f"reed_sol_r6_op: w={self.w} must be in {{8,16,32}}")

    def default_w(self) -> int:
        return 8

    def prepare(self) -> None:
        self._make_code(M.reed_sol_r6_coding_matrix(self.k, self.w))


class _PacketTechnique(ErasureCodeJerasure):
    """Bitmatrix codes over w packet-rows of packetsize bytes."""

    def __init__(self):
        super().__init__()
        self.packetsize = 0

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.packetsize = self.to_int("packetsize", profile,
                                      DEFAULT_PACKETSIZE)
        if self.engine and self.engine != "bitplane":
            raise ErasureCodeError(
                -22, f"engine={self.engine}: packet/bitmatrix "
                     f"techniques run only on the bit-plane engine")

    def get_alignment(self) -> int:
        """Cauchy/liberation alignment (ErasureCodeJerasure.cc:278-292)."""
        if self.per_chunk_alignment:
            alignment = self.w * self.packetsize
            modulo = alignment % LARGEST_VECTOR_WORDSIZE
            if modulo:
                alignment += LARGEST_VECTOR_WORDSIZE - modulo
            return alignment
        alignment = self.k * self.w * self.packetsize * 4
        if (self.w * self.packetsize * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * self.packetsize \
                * LARGEST_VECTOR_WORDSIZE
        return alignment

    def _make_bit_code(self, coding_bm: np.ndarray) -> None:
        self._code = BitCode(self.k, self.m, coding_bm,
                             Layout(self.w, self.packetsize))


class CauchyOrig(_PacketTechnique):
    technique = "cauchy_orig"

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.per_chunk_alignment = self.to_bool(
            "jerasure-per-chunk-alignment", profile, False)

    def prepare(self) -> None:
        mat = M.cauchy_original_coding_matrix(self.k, self.m, self.w)
        self._make_bit_code(GFW(self.w).expand_bitmatrix(mat))


class CauchyGood(CauchyOrig):
    technique = "cauchy_good"

    def prepare(self) -> None:
        mat = M.cauchy_good_coding_matrix(self.k, self.m, self.w)
        self._make_bit_code(GFW(self.w).expand_bitmatrix(mat))


class Liberation(_PacketTechnique):
    technique = "liberation"

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        if self.m != 2:
            raise ErasureCodeError(-22, "liberation: m must be 2")
        if self.k > self.w:
            raise ErasureCodeError(-22, "liberation: k must be <= w")
        if self.w <= 2 or not is_prime(self.w):
            raise ErasureCodeError(
                -22, f"liberation: w={self.w} must be prime > 2")
        if self.packetsize == 0:
            raise ErasureCodeError(-22, "liberation: packetsize required")
        if self.packetsize % 4:
            raise ErasureCodeError(
                -22, "liberation: packetsize must be a multiple of 4")

    def default_w(self) -> int:
        return 7

    def prepare(self) -> None:
        self._make_bit_code(
            M.liberation_coding_bitmatrix(self.k, self.w))


class BlaumRoth(Liberation):
    technique = "blaum_roth"

    def parse(self, profile: ErasureCodeProfile) -> None:
        _PacketTechnique.parse(self, profile)
        if self.m != 2:
            raise ErasureCodeError(-22, "blaum_roth: m must be 2")
        if self.k > self.w:
            raise ErasureCodeError(-22, "blaum_roth: k must be <= w")
        # w = 7 tolerated for Firefly compatibility
        # (ErasureCodeJerasure.cc:464-476)
        if self.w != 7 and (self.w <= 2 or not is_prime(self.w + 1)):
            raise ErasureCodeError(
                -22, f"blaum_roth: w+1={self.w + 1} must be prime")
        if self.packetsize == 0:
            raise ErasureCodeError(-22, "blaum_roth: packetsize required")

    def default_w(self) -> int:
        return 6

    def prepare(self) -> None:
        self._make_bit_code(
            M.blaum_roth_coding_bitmatrix(self.k, self.w))


class Liber8tion(_PacketTechnique):
    technique = "liber8tion"

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        if self.m != 2:
            raise ErasureCodeError(-22, "liber8tion: m must be 2")
        if self.w != 8:
            raise ErasureCodeError(-22, "liber8tion: w must be 8")
        if self.k > 8:
            raise ErasureCodeError(-22, "liber8tion: k must be <= 8")
        if self.packetsize == 0:
            raise ErasureCodeError(-22, "liber8tion: packetsize required")

    def default_w(self) -> int:
        return 8

    def prepare(self) -> None:
        self._make_bit_code(M.liber8tion_coding_bitmatrix(self.k))


TECHNIQUES = {
    cls.technique: cls
    for cls in (ReedSolomonVandermonde, ReedSolomonRAID6, CauchyOrig,
                CauchyGood, Liberation, BlaumRoth, Liber8tion)
}


def make_jerasure(profile: ErasureCodeProfile) -> ErasureCodeJerasure:
    """Plugin factory (ErasureCodePluginJerasure.cc:84 flow)."""
    technique = profile.get("technique", "reed_sol_van")
    cls = TECHNIQUES.get(technique)
    if cls is None:
        raise ErasureCodeError(
            -2, f"technique={technique} is not a valid coding technique")
    inst = cls()
    inst.init(profile)
    return inst
