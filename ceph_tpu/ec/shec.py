"""The SHEC plugin — shingled erasure coding.

Mirrors src/erasure-code/shec/ErasureCodeShec.{h,cc}: k data chunks,
m parity chunks, durability c — each parity covers a shingled window
of the data, trading MDS-ness for cheaper single-chunk recovery.

Ported semantics:
- generator: Vandermonde coding matrix with shingle windows zeroed
  (shec_reedsolomon_coding_matrix, :465-533), including the MULTIPLE
  technique's (m1, c1) split search minimizing recovery efficiency
  (shec_calc_recovery_efficiency1).
- decode: exhaustive parity-subset search for the smallest invertible
  square submatrix (shec_make_decoding_matrix, :535-760 — the
  determinant.c check becomes a GF inversion attempt), cached per
  (want, avails) signature (the ShecTableCache flow).
- minimum_to_decode: the same search's row set (:71-124).
- geometry: chunk alignment k*w*4 (:275-278), parse constraints
  (c <= m <= k <= 12, k+m <= 20, w in {8,16,32}, :280-345).

Execution is the shared bit-matrix engine: encode is one mod-2 matmul;
each decode submatrix inverse expands to a bit matrix applied the same
way.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from . import matrices as M
from .engine import Layout, _mod2_matmul
from .gfw import GFW
from .interface import ErasureCode, ErasureCodeError, ErasureCodeProfile

DEFAULT_K = 4
DEFAULT_M = 3
DEFAULT_C = 2
DEFAULT_W = 8

SINGLE = 0
MULTIPLE = 1


def _recovery_efficiency1(k: int, m1: int, m2: int, c1: int,
                          c2: int) -> float:
    """shec_calc_recovery_efficiency1: average chunks read to recover
    one lost data chunk under the (m1,c1)/(m2,c2) split."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [10 ** 8] * k
    r_e1 = 0.0
    for m_i, c_i in ((m1, c1), (m2, c2)):
        for rr in range(m_i):
            start = ((rr * k) // m_i) % k
            end = (((rr + c_i) * k) // m_i) % k
            span = ((rr + c_i) * k) // m_i - (rr * k) // m_i
            cc = start
            first = True
            while first or cc != end:
                first = False
                r_eff_k[cc] = min(r_eff_k[cc], span)
                cc = (cc + 1) % k
            r_e1 += span
    return r_e1 + sum(r_eff_k)


def shec_coding_matrix(k: int, m: int, c: int, w: int,
                       technique: int = MULTIPLE) -> List[List[int]]:
    """shec_reedsolomon_coding_matrix (:465-533): Vandermonde rows with
    shingle windows zeroed."""
    if technique == MULTIPLE:
        c1_best, m1_best = -1, -1
        # the reference seeds this at 100.0; inf is equivalent on every
        # configuration the parse constraints admit, and safe beyond
        min_r_e1 = float("inf")
        for c1 in range(c // 2 + 1):
            for m1 in range(m + 1):
                c2, m2 = c - c1, m - m1
                if m1 < c1 or m2 < c2:
                    continue
                if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
                    continue
                if (m1 != 0 and c1 == 0) or (m2 != 0 and c2 == 0):
                    continue
                r_e1 = _recovery_efficiency1(k, m1, m2, c1, c2)
                if r_e1 < min_r_e1:
                    min_r_e1 = r_e1
                    c1_best, m1_best = c1, m1
        m1, c1 = m1_best, c1_best
        m2, c2 = m - m1, c - c1
    else:
        m1, c1 = 0, 0
        m2, c2 = m, c

    mat = M.reed_sol_vandermonde_coding_matrix(k, m, w)
    for rr in range(m1):
        end = ((rr * k) // m1) % k
        start = (((rr + c1) * k) // m1) % k
        cc = start
        while cc != end:
            mat[rr][cc] = 0
            cc = (cc + 1) % k
    for rr in range(m2):
        end = ((rr * k) // m2) % k
        start = (((rr + c2) * k) // m2) % k
        cc = start
        while cc != end:
            mat[rr + m1][cc] = 0
            cc = (cc + 1) % k
    return mat


class ErasureCodeShec(ErasureCode):
    """technique MULTIPLE (the reference's default plugin flavor)."""

    def __init__(self, technique: int = MULTIPLE):
        super().__init__()
        self.technique = technique
        self.k = 0
        self.m = 0
        self.c = 0
        self.w = DEFAULT_W
        self.matrix: List[List[int]] = []
        self._gf: Optional[GFW] = None
        self._layout: Optional[Layout] = None
        self._enc_bm = None
        self._dec_cache: Dict[Tuple, tuple] = {}

    # -- profile (:280-345) -------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        self.prepare()
        super().init(profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        has = [x in profile for x in ("k", "m", "c")]
        if not any(has):
            self.k, self.m, self.c = DEFAULT_K, DEFAULT_M, DEFAULT_C
        elif not all(has):
            raise ErasureCodeError(-22, "k, m, c must all be chosen")
        else:
            self.k = self.to_int("k", profile, DEFAULT_K)
            self.m = self.to_int("m", profile, DEFAULT_M)
            self.c = self.to_int("c", profile, DEFAULT_C)
        if self.k <= 0 or self.m <= 0 or self.c <= 0:
            raise ErasureCodeError(-22, "k, m, c must be positive")
        if self.m < self.c:
            raise ErasureCodeError(-22, f"c={self.c} must be <= m")
        if self.k > 12:
            raise ErasureCodeError(-22, f"k={self.k} must be <= 12")
        if self.k + self.m > 20:
            raise ErasureCodeError(-22, "k+m must be <= 20")
        if self.k < self.m:
            raise ErasureCodeError(-22, f"m={self.m} must be <= k")
        self.w = self.to_int("w", profile, DEFAULT_W)
        if self.w not in (8, 16, 32):
            self.w = DEFAULT_W  # the reference falls back, not errors

    def prepare(self) -> None:
        self.matrix = shec_coding_matrix(self.k, self.m, self.c,
                                         self.w, self.technique)
        self._gf = GFW(self.w)
        self._layout = Layout(self.w)
        self._enc_bm = self._gf.expand_bitmatrix(self.matrix)
        self._dec_cache.clear()

    # -- geometry -----------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        return self.k * self.w * 4  # :275-278

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.get_alignment()
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    # -- the decoding-matrix search (:535-760) -------------------------
    def _search(self, want: List[int], avails: List[int]):
        """Returns (dup, rows, cols) — the smallest invertible square
        recovery system — plus the minimum chunk vector; None when
        unrecoverable."""
        k, m = self.k, self.m
        key = (tuple(want), tuple(avails))
        _MISS = "miss"
        hit = self._dec_cache.get(key, _MISS)
        if hit is not _MISS:  # cached None = known-unrecoverable
            return hit
        want = list(want)
        for i in range(m):
            if want[k + i] and not avails[k + i]:
                for j in range(k):
                    if self.matrix[i][j]:
                        want[j] = 1

        mindup, minp = k + 1, k + 1
        best_rows: List[int] = []
        best_cols: List[int] = []
        for pp in range(1 << m):
            p = [i for i in range(m) if pp >> i & 1]
            if len(p) > minp:
                continue
            if any(not avails[k + i] for i in p):
                continue
            tmprow = [0] * (k + m)
            tmpcol = [0] * k
            for i in range(k):
                if want[i] and not avails[i]:
                    tmpcol[i] = 1
            for i in p:
                tmprow[k + i] = 1
                for j in range(k):
                    if self.matrix[i][j]:
                        tmpcol[j] = 1
                        if avails[j]:
                            tmprow[j] = 1
            rows = [i for i in range(k + m) if tmprow[i]]
            cols = [j for j in range(k) if tmpcol[j]]
            if len(rows) != len(cols):
                continue
            dup = len(rows)
            if dup == 0:
                mindup, best_rows, best_cols = 0, [], []
                break
            if dup < mindup:
                sub = [[(1 if r == c_ else 0) if r < k
                        else self.matrix[r - k][c_] for c_ in cols]
                       for r in rows]
                try:
                    self._gf.mat_inv(sub)
                except np.linalg.LinAlgError:
                    continue
                mindup = dup
                best_rows, best_cols = rows, cols
                minp = len(p)
        if mindup == k + 1:
            self._dec_cache[key] = None
            return None

        minimum = [0] * (k + m)
        for r in best_rows:
            minimum[r] = 1
        for i in range(k):
            if want[i] and avails[i]:
                minimum[i] = 1
        for i in range(m):
            if want[k + i] and avails[k + i] and not minimum[k + i]:
                if any(self.matrix[i][j] and not want[j]
                       for j in range(k)):
                    minimum[k + i] = 1
        res = (mindup, best_rows, best_cols, minimum)
        self._dec_cache[key] = res
        return res

    def _minimum_to_decode(self, want_to_read: Set[int],
                           available: Set[int]) -> Set[int]:
        n = self.k + self.m
        want = [1 if i in want_to_read else 0 for i in range(n)]
        avails = [1 if i in available else 0 for i in range(n)]
        res = self._search(want, avails)
        if res is None:
            raise ErasureCodeError(-5, "shec: can't find recover "
                                       "matrix")
        _dup, _rows, _cols, minimum = res
        return {i for i in range(n) if minimum[i]}

    # -- data path ----------------------------------------------------
    def encode_chunks(self, want_to_encode: Set[int],
                      chunks: Dict[int, np.ndarray]) -> None:
        data = np.stack([np.asarray(chunks[self.chunk_index(i)],
                                    np.uint8) for i in range(self.k)])
        rows = self._layout.to_rows(data)
        out = _mod2_matmul(np.asarray(self._enc_bm), rows)
        parity = self._layout.from_rows(out, self.m, data.shape[1])
        parity = np.asarray(parity)
        for i in range(self.m):
            chunks[self.chunk_index(self.k + i)] = parity[i]

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Dict[int, np.ndarray],
                      decoded: Dict[int, np.ndarray]) -> None:
        """shec_matrix_decode (:757-814) on the bit engine.  Encoded
        positions remap to internal (data-first) order symmetrically
        with encode_chunks."""
        n = self.k + self.m
        inv = {self.chunk_index(i): i for i in range(n)}
        chunks = {inv[c]: v for c, v in chunks.items()}
        want_to_read = {inv[c] for c in want_to_read}
        want = [0] * n
        avails = [0] * n
        for i in want_to_read:
            want[i] = 1
        for i in range(n):
            if i in chunks:
                avails[i] = 1
        res = self._search(want, avails)
        if res is None:
            raise ErasureCodeError(-5, "shec: can't find recover "
                                       "matrix")
        dup, rows, cols, _minimum = res
        if dup:
            sub = [[(1 if r == c_ else 0) if r < self.k
                    else self.matrix[r - self.k][c_] for c_ in cols]
                   for r in rows]
            inv = self._gf.mat_inv(sub)
            need_idx = [i for i, c_ in enumerate(cols)
                        if not avails[c_]]
            dec_rows = [inv[i] for i in need_idx]
            bm = self._gf.expand_bitmatrix(dec_rows)
            stack = np.stack([np.asarray(chunks[r], np.uint8)
                              for r in rows])
            L = stack.shape[1]
            rows_b = self._layout.to_rows(stack)
            out = self._layout.from_rows(
                _mod2_matmul(np.asarray(bm), rows_b),
                len(need_idx), L)
            out = np.asarray(out)
            for idx, i in enumerate(need_idx):
                decoded[self.chunk_index(cols[i])] = out[idx]
        # re-encode WANTED erased parity from the (recovered) data it
        # touches (:807-812)
        erased_parity = [i for i in range(self.m)
                         if want[self.k + i] and not avails[self.k + i]]
        if erased_parity:
            data = np.stack(
                [np.asarray(decoded[self.chunk_index(j)], np.uint8)
                 for j in range(self.k)])
            bm = self._gf.expand_bitmatrix(
                [self.matrix[i] for i in erased_parity])
            L = data.shape[1]
            out = self._layout.from_rows(
                _mod2_matmul(np.asarray(bm),
                             self._layout.to_rows(data)),
                len(erased_parity), L)
            out = np.asarray(out)
            for idx, i in enumerate(erased_parity):
                decoded[self.chunk_index(self.k + i)] = out[idx]


def make_shec(profile: ErasureCodeProfile) -> ErasureCodeShec:
    """Plugin factory (ErasureCodePluginShec.cc flow): technique
    defaults to multiple."""
    tech = profile.get("technique", "multiple")
    if tech not in ("single", "multiple"):
        raise ErasureCodeError(
            -2, f"technique={tech} must be single or multiple")
    inst = ErasureCodeShec(SINGLE if tech == "single" else MULTIPLE)
    inst.init(profile)
    return inst
