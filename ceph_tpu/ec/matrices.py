"""Generator matrices for every EC technique the reference ships.

The reference delegates these to vendored submodules absent from its own
checkout (jerasure/gf-complete for ErasureCodeJerasure.cc:156-515,
isa-l for ErasureCodeIsa.cc:369-421).  Each constructor here re-derives
the published algorithm (Plank's jerasure 2.0 / Intel isa-l), so encode
parity is pinned to the published constructions, golden-tested by this
repo's own vectors; divergences that cannot be re-derived (search-table
codes) are documented on the function.

Matrix conventions: a "matrix code" is the m x k GF(2^w) coding block
(rows map data chunks to parity chunks); a "bitmatrix code" is the
(w*m) x (w*k) 0/1 block operating on w packet-rows per chunk
(jerasure's schedule representation, executed on TPU as a mod-2
matmul by ``ceph_tpu.ec.engine``).
"""

from __future__ import annotations

from typing import List

import numpy as np

from .gfw import GFW, poly_mul_matrix

Matrix = List[List[int]]


# -- jerasure reed_sol.c ----------------------------------------------------


def reed_sol_extended_vandermonde_matrix(rows: int, cols: int,
                                         w: int) -> Matrix:
    """Extended Vandermonde: row 0 = e_0, last row = e_{cols-1}, middle
    rows are power progressions of i — the starting point of jerasure's
    reed_sol_van (published reed_sol.c algorithm)."""
    gf = GFW(w)
    if w < 30 and ((1 << w) < rows or (1 << w) < cols):
        raise ValueError("field too small")
    V = [[0] * cols for _ in range(rows)]
    V[0][0] = 1
    if rows == 1:
        return V
    V[rows - 1][cols - 1] = 1
    for i in range(1, rows - 1):
        a = 1
        for j in range(cols):
            V[i][j] = a
            a = gf.mul(a, i)
    return V


def reed_sol_big_vandermonde_distribution_matrix(rows: int, cols: int,
                                                 w: int) -> Matrix:
    """Systematize the extended Vandermonde by column elimination, then
    normalize so coding row 0 and coding column 0 are all ones — the
    published jerasure reed_sol.c pipeline, which yields a DIFFERENT
    (and reference-compatible) generator than classical
    top-square-inversion."""
    gf = GFW(w)
    if cols >= rows:
        raise ValueError("rows must exceed cols")
    d = reed_sol_extended_vandermonde_matrix(rows, cols, w)

    for i in range(1, cols):
        # pivot row with d[j][i] != 0, swap into row i
        j = next((r for r in range(i, rows) if d[r][i]), None)
        if j is None:
            raise np.linalg.LinAlgError("singular vandermonde")
        if j != i:
            d[i], d[j] = d[j], d[i]
        # scale COLUMN i so the pivot is 1
        if d[i][i] != 1:
            f = gf.inv(d[i][i])
            for r in range(rows):
                d[r][i] = gf.mul(f, d[r][i])
        # eliminate every other column of row i via column ops
        for j in range(cols):
            e = d[i][j]
            if j != i and e:
                for r in range(rows):
                    d[r][j] ^= gf.mul(e, d[r][i])

    # make coding row 0 (row `cols`) all ones by scaling columns
    for j in range(cols):
        t = d[cols][j]
        if t and t != 1:
            f = gf.inv(t)
            for r in range(cols, rows):
                d[r][j] = gf.mul(f, d[r][j])
    # make coding column 0 all ones by scaling rows
    for i in range(cols + 1, rows):
        t = d[i][0]
        if t and t != 1:
            f = gf.inv(t)
            d[i] = [gf.mul(v, f) for v in d[i]]
    return d


def reed_sol_vandermonde_coding_matrix(k: int, m: int, w: int) -> Matrix:
    """jerasure reed_sol_van generator: the m coding rows
    (ErasureCodeJerasure.cc:204 prepare())."""
    dist = reed_sol_big_vandermonde_distribution_matrix(k + m, k, w)
    return dist[k:]


def reed_sol_r6_coding_matrix(k: int, w: int) -> Matrix:
    """RAID6: P = XOR, Q = sum 2^j d_j (reed_sol_r6_op,
    ErasureCodeJerasure.cc:256)."""
    gf = GFW(w)
    p_row = [1] * k
    q_row = [gf.pow(2, j) for j in range(k)]
    return [p_row, q_row]


# -- jerasure cauchy.c ------------------------------------------------------


def cauchy_original_coding_matrix(k: int, m: int, w: int) -> Matrix:
    """cauchy_orig: a[i][j] = 1/(i ^ (m+j)) (ErasureCodeJerasure.cc:321)."""
    gf = GFW(w)
    if w < 31 and (k + m) > (1 << w):
        raise ValueError("field too small")
    return [[gf.inv(i ^ (m + j)) for j in range(k)] for i in range(m)]


def cauchy_good_coding_matrix(k: int, m: int, w: int) -> Matrix:
    """cauchy_good: the original Cauchy matrix normalized to minimize
    bitmatrix ones — first scale columns so row 0 is all ones, then for
    each later row try every element's inverse as a row scale and keep
    the best (published improve_coding_matrix).

    Divergence note: for m=2 and small k the published jerasure uses a
    hard-coded table of searched optimal elements (cbest_*); that table
    is part of the absent submodule, so this implementation always uses
    the general improvement path.  The code remains MDS and
    self-consistent (decode uses the same matrix); XOR-schedule cost —
    which the TPU matmul path does not depend on — may differ."""
    gf = GFW(w)
    mat = cauchy_original_coding_matrix(k, m, w)
    # scale columns so row 0 is all ones
    for j in range(k):
        if mat[0][j] != 1:
            f = gf.inv(mat[0][j])
            for i in range(m):
                mat[i][j] = gf.mul(mat[i][j], f)
    # scale each later row to minimize total bitmatrix ones
    for i in range(1, m):
        best = sum(gf.n_ones(v) for v in mat[i])
        best_j = -1
        for j in range(k):
            if mat[i][j] != 1:
                f = gf.inv(mat[i][j])
                tot = sum(gf.n_ones(gf.mul(v, f)) for v in mat[i])
                if tot < best:
                    best, best_j = tot, j
        if best_j >= 0:
            f = gf.inv(mat[i][best_j])
            mat[i] = [gf.mul(v, f) for v in mat[i]]
    return mat


# -- bitmatrix (schedule) codes ---------------------------------------------


def liberation_coding_bitmatrix(k: int, w: int) -> np.ndarray:
    """Liberation RAID6 bitmatrix (Plank 2008; liberation.c): P block =
    identities; Q block for drive j = the (i, (i+j) mod w) diagonal
    permutation plus, for j>0, one extra bell bit at row
    i0 = j*(w-1)/2 mod w, column (i0+j-1) mod w.  Returns the
    (2w, k*w) coding bitmatrix.  Requires prime w > 2, k <= w."""
    if k > w:
        raise ValueError("liberation needs k <= w")
    bm = np.zeros((2 * w, k * w), np.uint8)
    for j in range(k):
        # P: identity
        for i in range(w):
            bm[i, j * w + i] = 1
        # Q: shifted diagonal
        for i in range(w):
            bm[w + i, j * w + (j + i) % w] = 1
        if j > 0:
            i0 = (j * ((w - 1) // 2)) % w
            bm[w + i0, j * w + (i0 + j - 1) % w] = 1
    return bm


def blaum_roth_coding_bitmatrix(k: int, w: int) -> np.ndarray:
    """Blaum-Roth RAID6 over the ring GF(2)[x]/M_p(x) with p = w+1
    prime, M_p(x) = 1 + x + ... + x^(w): P block = identity, Q block for
    drive j = multiply-by-x^j in the ring (the canonical Blaum-Roth 1993
    construction behind blaum_roth_coding_bitmatrix,
    ErasureCodeJerasure.cc:471).  Returns the (2w, k*w) coding block."""
    if k > w:
        raise ValueError("blaum_roth needs k <= w")
    mp = (1 << (w + 1)) - 1 >> 0  # x^w + ... + x + 1 has bits 0..w set
    bm = np.zeros((2 * w, k * w), np.uint8)
    for j in range(k):
        for i in range(w):
            bm[i, j * w + i] = 1
        bm[w:2 * w, j * w:(j + 1) * w] = poly_mul_matrix(j, w, mp)
    return bm


def liber8tion_coding_bitmatrix(k: int) -> np.ndarray:
    """liber8tion-equivalent RAID6 bitmatrix at w=8, k <= 8.

    Divergence note: the published liber8tion code is a table of
    minimal-XOR matrices found by search (part of the absent jerasure
    submodule and not re-derivable); this implementation provides the
    same contract (m=2, w=8, k<=8, MDS, bitmatrix technique) using
    multiply-by-g^j GF(2^8) blocks for the Q row.  XOR-schedule cost
    differs; the TPU matmul path does not depend on it."""
    w = 8
    if k > w:
        raise ValueError("liber8tion needs k <= 8")
    gf = GFW(8)
    bm = np.zeros((2 * w, k * w), np.uint8)
    for j in range(k):
        for i in range(w):
            bm[i, j * w + i] = 1
        bm[w:2 * w, j * w:(j + 1) * w] = gf.elem_bitmatrix(gf.pow(2, j))
    return bm


# -- isa-l ec_base.c --------------------------------------------------------


def isa_gf_gen_rs_matrix(k: int, m: int) -> Matrix:
    """isa-l gf_gen_rs_matrix semantics (ErasureCodeIsa.cc:377,
    matrixtype Vandermonde): full (k+m) x k with identity top; coding
    row i is the power progression of gen = 2^i.  NOT guaranteed MDS
    for large k+m — same caveat as isa-l; the isa plugin's default
    (k=7, m=3) is safe."""
    gf = GFW(8)
    a = [[1 if i == j else 0 for j in range(k)] for i in range(k)]
    gen = 1
    for _ in range(m):
        p = 1
        row = []
        for _j in range(k):
            row.append(p)
            p = gf.mul(p, gen)
        a.append(row)
        gen = gf.mul(gen, 2)
    return a


def isa_gf_gen_cauchy1_matrix(k: int, m: int) -> Matrix:
    """isa-l gf_gen_cauchy1_matrix semantics (ErasureCodeIsa.cc:379):
    identity top, coding element [i][j] = 1/(i ^ j) for i >= k."""
    gf = GFW(8)
    a = [[1 if i == j else 0 for j in range(k)] for i in range(k)]
    for i in range(k, k + m):
        a.append([gf.inv(i ^ j) for j in range(k)])
    return a
