"""ECUtil — stripe math and the stripe-looped EC data path.

The bridge from "codec" to "data path" (src/osd/ECUtil.{h,cc}):

- ``StripeInfo``: the logical↔chunk offset arithmetic of
  ``stripe_info_t`` (ECUtil.h:27-80) — stripe_width bytes of logical
  object data become one chunk_size slice on each of the k+m shards.
- ``encode``: ECUtil::encode (ECUtil.cc:123-162).  The reference loops
  stripes calling ``ErasureCodeInterface::encode`` once per stripe and
  appends per-shard buffers; byte lanes are independent in the GF
  engine, so here ALL stripes encode in one batched call — the
  per-shard concatenation the reference builds buffer-by-buffer is just
  a reshape.
- ``decode``: ECUtil.cc:50-121 — reconstruct the needed shards for
  every stripe at once from whatever shard slices survive.  This
  batched many-stripes decode IS the recovery shape (SURVEY §2.6
  recovery-concurrency row: ECBackend::recover_object fetching
  minimum_to_decode then decoding stripe runs).
- ``HashInfo``: cumulative per-shard crc32c (ECUtil.h:164-180), crc32c
  (Castagnoli) matching the reference's ceph_crc32c.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set

import numpy as np

from .interface import ErasureCode, ErasureCodeError


class StripeInfo:
    """stripe_info_t (ECUtil.h:27-80): ``stripe_size`` data chunks per
    stripe (k), ``stripe_width`` logical bytes per stripe."""

    def __init__(self, stripe_size: int, stripe_width: int):
        if stripe_width % stripe_size:
            raise ValueError("stripe_width must be a multiple of "
                             "stripe_size")
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // stripe_size

    def logical_offset_is_stripe_aligned(self, logical: int) -> bool:
        return logical % self.stripe_width == 0

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return ((offset + self.stripe_width - 1)
                // self.stripe_width) * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset + (self.stripe_width - rem) if rem else offset

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def offset_len_to_stripe_bounds(self, offset: int,
                                    length: int) -> tuple:
        off = self.logical_to_prev_stripe_offset(offset)
        ln = self.logical_to_next_stripe_offset((offset - off) + length)
        return off, ln


def sinfo_for(code: ErasureCode, stripe_unit: int = 4096) -> StripeInfo:
    """The OSD's stripe geometry for a code: chunk = stripe_unit bytes,
    width = k * stripe_unit (PGBackend::get_ec_stripe semantics)."""
    k = code.get_data_chunk_count()
    return StripeInfo(k, k * stripe_unit)


def encode(sinfo: StripeInfo, code: ErasureCode,
           data: bytes | np.ndarray,
           want: Iterable[int] | None = None
           ) -> Dict[int, np.ndarray]:
    """ECUtil::encode: logical buffer (multiple of stripe_width) ->
    per-shard concatenated chunk buffers — all stripes in ONE engine
    call."""
    buf = np.frombuffer(data, np.uint8) if isinstance(
        data, (bytes, bytearray)) else np.asarray(data, np.uint8).ravel()
    if len(buf) % sinfo.stripe_width:
        raise ValueError("input must be stripe-aligned "
                         "(ECUtil.cc:133 assert)")
    k = code.get_data_chunk_count()
    n = code.get_chunk_count()
    cs = sinfo.chunk_size
    nstripes = len(buf) // sinfo.stripe_width
    if want is None:
        want = range(n)
    if nstripes == 0:
        return {i: np.zeros(0, np.uint8) for i in want}

    # [stripe, chunk_j, byte] -> per-shard concatenation [chunk_j,
    # stripe*cs]: equivalent to the reference's per-stripe loop with
    # claim_append, because byte lanes are independent in the engine
    stripes = buf.reshape(nstripes, k, cs).transpose(1, 0, 2)
    shard_data = stripes.reshape(k, nstripes * cs)

    chunks: Dict[int, np.ndarray] = {
        code.chunk_index(i): shard_data[i] for i in range(k)}
    for i in range(k, n):
        chunks[code.chunk_index(i)] = np.zeros(nstripes * cs, np.uint8)
    code.encode_chunks(set(want), chunks)
    return {i: chunks[i] for i in want}


def decode(sinfo: StripeInfo, code: ErasureCode,
           to_decode: Dict[int, np.ndarray],
           need: Iterable[int]) -> Dict[int, np.ndarray]:
    """ECUtil::decode: per-shard concatenated slices in, reconstructed
    shard buffers out — every stripe decoded in one engine call."""
    need = set(need)
    avail = set(to_decode)
    lengths = {len(np.asarray(v).ravel()) for v in to_decode.values()}
    if len(lengths) != 1:
        raise ValueError("all shard buffers must be equal length")
    (length,) = lengths
    if length % sinfo.chunk_size:
        raise ValueError("shard buffers must be chunk-aligned")
    # feasibility via the code's own minimum_to_decode
    code.minimum_to_decode(need, avail)
    chunks = {i: np.asarray(v, np.uint8).ravel()
              for i, v in to_decode.items()}
    out = code.decode(need, chunks)
    return {i: np.asarray(out[i], np.uint8) for i in need}


def recover_stripes(sinfo: StripeInfo, code: ErasureCode,
                    surviving: Dict[int, np.ndarray],
                    lost: Iterable[int]) -> Dict[int, np.ndarray]:
    """The batched recovery path (ECBackend::recover_object shape,
    ECBackend.cc:757/589): reconstruct the lost shards for a run of
    stripes from the survivors, one launch."""
    return decode(sinfo, code, surviving, set(lost))


# -- crc32c (Castagnoli) — HashInfo (ECUtil.h:164-180) ----------------------
#
# The byte update s' = T[(s ^ b) & 0xFF] ^ (s >> 8) is GF(2)-LINEAR
# (T[x ^ y] = T[x] ^ T[y]), so crc(seed, block) =
# shift_B(seed) ^ crc(0, block), and crc(0, block) is an XOR of
# per-(position, byte) contributions — a numpy gather + XOR-reduce per
# block, with only one tiny table-lookup shift per block left in
# Python.  This keeps HashInfo viable on the data path (per-byte
# Python would cost seconds per multi-MiB shard).

_CRC32C_POLY = 0x82F63B78
_CRC_BLOCK = 512
_crc_tables: dict = {}


def _crc_setup():
    if _crc_tables:
        return _crc_tables
    tbl = np.zeros(256, np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        tbl[i] = c

    def shift1(v):  # advance one zero byte (vectorized)
        return tbl[v & np.uint32(0xFF)] ^ (v >> np.uint32(8))

    # pos_tbl[p, b]: crc(0, block with byte b at p, zeros elsewhere)
    pos = np.zeros((_CRC_BLOCK, 256), np.uint32)
    pos[_CRC_BLOCK - 1] = tbl
    for p in range(_CRC_BLOCK - 2, -1, -1):
        pos[p] = shift1(pos[p + 1])

    # shift_B as two 16-bit half-state tables
    basis = np.asarray([1 << i for i in range(32)], np.uint32)
    for _ in range(_CRC_BLOCK):
        basis = shift1(basis)
    idx = np.arange(1 << 16, dtype=np.uint32)
    sh_lo = np.zeros(1 << 16, np.uint32)
    sh_hi = np.zeros(1 << 16, np.uint32)
    for i in range(16):
        bit = (idx >> np.uint32(i)) & np.uint32(1)
        sh_lo ^= np.where(bit == 1, basis[i], np.uint32(0))
        sh_hi ^= np.where(bit == 1, basis[16 + i], np.uint32(0))
    _crc_tables.update(tbl=tbl, pos=pos, sh_lo=sh_lo, sh_hi=sh_hi)
    return _crc_tables


_native_crc = None


def _native_crc32c():
    """The slicing-by-8 C engine (native/crush_host.cpp crc32c_sb8) —
    the src/common/crc32c.h hot-path role; bit-equality with the
    Python table walker below is pinned by tests/test_stripe.py."""
    global _native_crc
    if _native_crc is None:
        try:
            import ctypes

            from ..crush.native import ensure_built

            lib = ensure_built()
            if lib is None:
                _native_crc = False
            else:
                lib.crc32c_sb8.restype = ctypes.c_uint32
                lib.crc32c_sb8.argtypes = [
                    ctypes.c_uint32,
                    np.ctypeslib.ndpointer(np.uint8,
                                           flags="C_CONTIGUOUS"),
                    ctypes.c_int64]
                _native_crc = lib.crc32c_sb8
        except Exception:
            _native_crc = False
    return _native_crc or None


def crc32c(data: bytes | np.ndarray, crc: int = 0xFFFFFFFF) -> int:
    """ceph_crc32c semantics (seed as passed, no final xor; the OSD
    uses -1)."""
    fn = _native_crc32c()
    if fn is not None:
        buf = np.frombuffer(data, np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)) \
            else np.ascontiguousarray(np.asarray(data, np.uint8).ravel())
        return int(fn(crc & 0xFFFFFFFF, buf, len(buf)))
    t = _crc_setup()
    buf = np.frombuffer(data, np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else \
        np.asarray(data, np.uint8).ravel()
    s = int(crc) & 0xFFFFFFFF
    nb = len(buf) // _CRC_BLOCK
    if nb:
        blocks = buf[:nb * _CRC_BLOCK].reshape(nb, _CRC_BLOCK)
        contrib = t["pos"][np.arange(_CRC_BLOCK)[None, :], blocks]
        block_crcs = np.bitwise_xor.reduce(contrib, axis=1).tolist()
        sh_lo, sh_hi = t["sh_lo"], t["sh_hi"]
        for c in block_crcs:
            s = int(sh_lo[s & 0xFFFF]) ^ int(sh_hi[s >> 16]) ^ c
    tbl = t["tbl"]
    for b in buf[nb * _CRC_BLOCK:].tobytes():
        s = int(tbl[(s ^ b) & 0xFF]) ^ (s >> 8)
    return s


class HashInfo:
    """Cumulative per-shard crc32c of everything appended
    (ECUtil.h:164-180)."""

    def __init__(self, n_shards: int):
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [0xFFFFFFFF] * n_shards

    def append(self, old_size: int,
               to_append: Dict[int, np.ndarray]) -> None:
        assert old_size == self.total_chunk_size
        sizes = {len(np.asarray(v).ravel())
                 for v in to_append.values()}
        assert len(sizes) == 1
        for shard, buf in to_append.items():
            self.cumulative_shard_hashes[shard] = crc32c(
                buf, self.cumulative_shard_hashes[shard])
        self.total_chunk_size += sizes.pop()

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]
