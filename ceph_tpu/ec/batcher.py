"""EncodeBatcher — cross-thread EC encode coalescing.

The dispatch-side twin of the WAL group commit (os/wal_store.py) and
the RapidRAID-style pipelining motivation (arXiv:1207.6744): an OSD
primary serving many concurrent EC writes pays one XLA/engine dispatch
per object, and dispatch overhead — not arithmetic — dominates small
stripes.  Concurrent ``encode`` calls queue here; the first waiter to
take the leader mutex drains the queue, groups requests by (code,
object size), and runs ONE ``encode_batched`` per group (byte-identical
to per-object encode — see ErasureCode.encode_batched), completing
every waiter.  A lone caller is its own leader: the depth-1 path is a
plain ``encode`` with no added latency.

Batches are padded up to the next power of two with zero objects (a
zero object's chunks are zero for every linear code; the pad outputs
are discarded) so the device sees a BOUNDED set of batch-shape
signatures — the PR-3 recompile-budget contract.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..analysis.lockdep import make_lock
from .engine import book_batch

MAX_BATCH = 16  # objects per batched dispatch (pow2-padded)


class _EncodeReq:
    __slots__ = ("code", "want", "raw", "done", "out", "error")

    def __init__(self, code, want, raw: bytes):
        self.code = code
        self.want = want
        self.raw = raw
        self.done = threading.Event()
        self.out: Optional[Dict] = None
        self.error: Optional[BaseException] = None


class EncodeBatcher:
    """``mesh``: an explicit device mesh threads through to
    ``ErasureCode.encode_batched`` so a coalesced dispatch shards its
    stripe batch axis across the chips; None defers to the process
    default (``parallel.placement.set_data_plane_mesh``), which is
    itself None — unsharded — unless a daemon installed one."""

    def __init__(self, max_delay_us: int = 0,
                 max_batch: int = MAX_BATCH, mesh=None):
        self._mutex = make_lock("ec::batch_leader")
        self._qlock = make_lock("ec::batch_q")
        self._q: List[_EncodeReq] = []
        self._delay = max(0, max_delay_us) / 1e6
        self._max_batch = max(1, max_batch)
        self._mesh = mesh

    def encode(self, code, want_to_encode, raw: bytes) -> Dict:
        """Drop-in for ``code.encode(want, raw)``: queue, then either
        lead a batched dispatch for everyone queued or wait for a
        concurrent leader to cover this request."""
        # raw is staged AS IS (bytes, bytearray, or a memoryview into
        # a pooled recv segment): the caller blocks on req.done until
        # its group's dispatch completes, so the buffer outlives every
        # read of it — no defensive copy
        req = _EncodeReq(code, set(want_to_encode), raw)
        with self._qlock:
            self._q.append(req)
        while not req.done.is_set():
            if self._mutex.acquire(timeout=0.05):
                try:
                    if not req.done.is_set():
                        self._drain()
                finally:
                    self._mutex.release()
        if req.error is not None:
            raise req.error
        return req.out

    def _drain(self) -> None:
        if self._delay > 0:
            # widen the batch: let concurrent writers land their
            # requests before the shared dispatch (bounded by the knob)
            time.sleep(self._delay)  # the leader mutex is the coalescing role, not a data lock; waiting here IS the batching window
        with self._qlock:
            batch, self._q = self._q, []
        if not batch:
            return
        groups: Dict[Tuple, List[_EncodeReq]] = {}
        for r in batch:
            groups.setdefault(
                (id(r.code), len(r.raw), tuple(sorted(r.want))),
                []).append(r)
        for reqs in groups.values():
            try:
                self._run_group(reqs)
            except Exception as e:
                for r in reqs:
                    r.error = e
            finally:
                for r in reqs:
                    r.done.set()

    def _run_group(self, reqs: List[_EncodeReq]) -> None:
        code = reqs[0].code
        want = reqs[0].want
        if len(reqs) == 1:
            reqs[0].out = code.encode(want, reqs[0].raw)
            book_batch(1)
            return
        for lo in range(0, len(reqs), self._max_batch):
            part = reqs[lo:lo + self._max_batch]
            raws = [r.raw for r in part]
            # pad to the next power of two with zero objects so batch
            # shapes come from a bounded set (recompile budget); the
            # pad rows cost arithmetic, not compiles, and are dropped
            pad = (1 << (len(raws) - 1).bit_length()) - len(raws)
            # copy-ok: zero pad rows are freshly allocated, not copied
            # from any payload — there is no view to keep
            raws += [bytes(len(raws[0]))] * pad
            outs = code.encode_batched(want, raws, mesh=self._mesh)
            for r, out in zip(part, outs):
                r.out = out
            book_batch(len(part))
