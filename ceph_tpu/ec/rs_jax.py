"""Reed-Solomon encode/decode — thin adapter over the one GF engine.

The flagship/bench entry point for RS(k, m) at w=8.  The execution
lives in ``ceph_tpu.ec.engine`` (bit-plane MXU matmuls with the
decode-matrix cache keyed by erasure signature — the reference's
ErasureCodeIsaTableCache flow, ErasureCodeIsa.cc:227-304); this module
only picks a generator matrix and exposes the array-level API the
bench, flagship step, and stripe layer share.  One engine, every
consumer: the interface plugins (jerasure/isa/lrc) ride the same
``BitCode``.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from . import gf
from .engine import (BitCode, Layout, _mod2_matmul, _pack_bytes,
                     _unpack_bytes)


def gf_matmul_bits(bm, data):
    """Apply an expanded GF(2) bit matrix to byte data:
    (8r, 8c) 0/1 @ u8[c, L] -> u8[r, L]."""
    return _pack_bytes(_mod2_matmul(jnp.asarray(bm),
                                    _unpack_bytes(jnp.asarray(data))))


class RSCode:
    """One compiled (k, m, technique) code instance on the engine."""

    def __init__(self, k: int, m: int, technique: str = "reed_sol_van"):
        self.k = k
        self.m = m
        self.technique = technique
        if technique in ("reed_sol_van", "vandermonde"):
            self.G = gf.rs_vandermonde_matrix(k, m)
        elif technique in ("cauchy", "cauchy_good", "cauchy_orig"):
            self.G = gf.rs_cauchy_matrix(k, m)
        else:
            raise ValueError(f"unknown technique {technique!r}")
        self._bit = BitCode(k, m, gf.expand_bitmatrix(self.G[k:]),
                            Layout(8))

    # -- encode -------------------------------------------------------
    def encode(self, data):
        """u8[k, L] -> parity u8[m, L] (device array)."""
        return self._bit.encode(data)

    def encode_np(self, data):
        return np.asarray(self.encode(data))

    # -- decode -------------------------------------------------------
    def decode(self, chunks, erasures):
        """chunks: dict chunk_index -> u8[L]; returns u8[k, L] data."""
        avail = {i: c for i, c in chunks.items()
                 if i not in set(erasures)}
        return self._bit.decode_data(avail)

    def decode_np(self, chunks, erasures):
        return np.asarray(self.decode(chunks, erasures))

    def all_chunks(self, data):
        """u8[k, L] -> u8[k+m, L]: systematic data + parity."""
        return self._bit.all_chunks(data)
