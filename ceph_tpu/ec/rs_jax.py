"""Reed-Solomon encode/decode as bit-plane matmuls — the TPU data path.

The reference's hot EC loop is ``ec_encode_data`` (isa-l asm,
ErasureCodeIsa.cc:129) / jerasure's XOR schedules: per-byte table lookups
vectorized with SSE/AVX shuffles.  TPUs have no byte-shuffle unit but they
have the MXU, and GF(2^8) multiplication by a constant is linear over
GF(2).  So instead of translating table lookups, the whole (k+m, k) code
is expanded once into an (8m, 8k) 0/1 bit matrix (gf.expand_bitmatrix)
and applied as an integer matmul mod 2:

    data u8[k, L]  → bit planes u8[8k, L]   (unpack, XLA elementwise)
    parity planes  = (BM_i8 @ planes_i8) & 1     (MXU int8 matmul)
    parity u8[m, L] ← pack bit planes

Per-element products are 0/1, so the i32 accumulator
(preferred_element_type=int32) holds at most the contraction depth
8k <= 2048 << 2^31 — exact.  Decode is the same matmul with a host-inverted matrix
(gf.decode_matrix), mirroring the reference's decode-table flow
(ErasureCodeIsa.cc:227-304) including the LRU cache keyed by erasure
signature (ErasureCodeIsaTableCache.cc).
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from . import gf

_BITS = np.arange(8, dtype=np.uint8)


def _unpack_bits(data):
    """u8[r, L] -> u8[8r, L] bit planes, plane order: row-major (row, bit),
    bit 0 (LSB) first to match gf.gf_const_bitmatrix."""
    r, L = data.shape
    planes = (data[:, None, :] >> _BITS[None, :, None]) & jnp.uint8(1)
    return planes.reshape(8 * r, L)


def _pack_bits(planes):
    """u8[8r, L] -> u8[r, L]."""
    r8, L = planes.shape
    p = planes.reshape(r8 // 8, 8, L)
    return jnp.sum(p << _BITS[None, :, None], axis=1,
                   dtype=jnp.uint8)


@functools.partial(jax.jit, static_argnames=())
def _bit_matmul(bm, planes):
    """(R8, C8) 0/1 int8 @ (C8, L) 0/1 -> mod-2 (R8, L) uint8."""
    acc = jax.lax.dot_general(
        bm.astype(jnp.int8), planes.astype(jnp.int8),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc & 1).astype(jnp.uint8)


@jax.jit
def gf_matmul_bits(bm, data):
    """Apply an expanded bit matrix to byte data: u8[rows_out, L]."""
    planes = _unpack_bits(data)
    out_planes = _bit_matmul(bm, planes)
    return _pack_bits(out_planes)


class RSCode:
    """One compiled (k, m, technique) code instance.

    Owns the generator matrix, its bit expansion on device, and an LRU of
    inverted decode matrices keyed by the erasure signature — the same
    shape as the reference's EC table cache (ErasureCodeIsaTableCache.h),
    with XLA compilation replacing table generation.
    """

    def __init__(self, k: int, m: int, technique: str = "reed_sol_van"):
        self.k = k
        self.m = m
        self.technique = technique
        if technique in ("reed_sol_van", "vandermonde"):
            self.G = gf.rs_vandermonde_matrix(k, m)
        elif technique in ("cauchy", "cauchy_good", "cauchy_orig"):
            self.G = gf.rs_cauchy_matrix(k, m)
        else:
            raise ValueError(f"unknown technique {technique!r}")
        self._enc_bm = jnp.asarray(gf.expand_bitmatrix(self.G[k:]))
        self._dec_cache = {}

    # -- encode -------------------------------------------------------
    def encode(self, data):
        """u8[k, L] -> parity u8[m, L] (device array)."""
        data = jnp.asarray(data)
        assert data.shape[0] == self.k
        return gf_matmul_bits(self._enc_bm, data)

    def encode_np(self, data):
        return np.asarray(self.encode(data))

    # -- decode -------------------------------------------------------
    def _decode_bm(self, present: Sequence[int]):
        key = tuple(present)
        bm = self._dec_cache.get(key)
        if bm is None:
            inv = gf.decode_matrix(self.G, present, self.k)
            bm = jnp.asarray(gf.expand_bitmatrix(inv))
            self._dec_cache[key] = bm
        return bm

    def decode(self, chunks, erasures):
        """chunks: dict chunk_index -> u8[L]; returns u8[k, L] data."""
        present = sorted(i for i in chunks if i not in set(erasures))
        present = present[:self.k]
        if len(present) < self.k:
            raise ValueError("need at least k chunks")
        bm = self._decode_bm(present)
        stack = jnp.stack([jnp.asarray(chunks[i]) for i in present])
        return gf_matmul_bits(bm, stack)

    def decode_np(self, chunks, erasures):
        return np.asarray(self.decode(chunks, erasures))

    def all_chunks(self, data):
        """u8[k, L] -> u8[k+m, L]: systematic data + parity."""
        data = jnp.asarray(data)
        return jnp.concatenate([data, self.encode(data)], axis=0)
