"""The ErasureCode interface contract and base-class semantics.

Mirrors the reference's load-bearing EC seam — the ~12-virtual
``ErasureCodeInterface`` (src/erasure-code/ErasureCodeInterface.h:170-462)
plus the shared behavior of the ``ErasureCode`` base class
(src/erasure-code/ErasureCode.cc:42-348): profile init, chunk
``mapping=`` remap, aligned ``encode_prepare`` padding, trivial-copy
decode, default ``minimum_to_decode``.  Chunk payloads are numpy/JAX
uint8 arrays instead of bufferlists; plugins put the math on the TPU via
``ceph_tpu.ec.engine``.

An object of size S is carved into k data chunks of
``get_chunk_size(S)`` bytes (zero-padded) plus m coding chunks; chunk i
of the *encoded* layout holds object range
[i*chunk_size, (i+1)*chunk_size) — the diagram at
ErasureCodeInterface.h:39-74.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

import numpy as np

ErasureCodeProfile = Dict[str, str]

SIMD_ALIGN = 32  # ErasureCode.cc:42 — kept for layout parity

DEFAULT_RULE_ROOT = "default"
DEFAULT_RULE_FAILURE_DOMAIN = "host"


class ErasureCodeError(Exception):
    def __init__(self, errno_: int, msg: str):
        super().__init__(msg)
        self.errno = errno_


class ErasureCode:
    """Base class: everything but the code-specific matrix."""

    def __init__(self):
        self.chunk_mapping: List[int] = []
        self._profile: ErasureCodeProfile = {}
        self.rule_root = DEFAULT_RULE_ROOT
        self.rule_failure_domain = DEFAULT_RULE_FAILURE_DOMAIN
        self.rule_device_class = ""

    # -- profile ------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        """Parse the profile; raises ErasureCodeError on bad input
        (the reference returns -EINVAL + fills *ss)."""
        self.rule_root = profile.get("crush-root", DEFAULT_RULE_ROOT)
        self.rule_failure_domain = profile.get(
            "crush-failure-domain", DEFAULT_RULE_FAILURE_DOMAIN)
        self.rule_device_class = profile.get("crush-device-class", "")
        self._parse_mapping(profile)
        self._profile = dict(profile)

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    def _parse_mapping(self, profile: ErasureCodeProfile) -> None:
        """profile ``mapping=DD_D...``: data chunks go to the 'D'
        positions, coding chunks to the rest (ErasureCode.cc:260-279)."""
        mapping = profile.get("mapping")
        if not mapping:
            return
        data_pos = [i for i, c in enumerate(mapping) if c == "D"]
        coding_pos = [i for i, c in enumerate(mapping) if c != "D"]
        self.chunk_mapping = data_pos + coding_pos

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if i < len(self.chunk_mapping) else i

    @staticmethod
    def sanity_check_k_m(k: int, m: int) -> None:
        if k < 2:
            raise ErasureCodeError(-22, f"k={k} must be >= 2")
        if m < 1:
            raise ErasureCodeError(-22, f"m={m} must be >= 1")

    # -- geometry (code-specific) --------------------------------------
    def get_chunk_count(self) -> int:
        raise NotImplementedError

    def get_data_chunk_count(self) -> int:
        raise NotImplementedError

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        return 1

    def get_chunk_size(self, object_size: int) -> int:
        raise NotImplementedError

    # -- CRUSH rule ----------------------------------------------------
    def create_rule(self, name: str, crush) -> int:
        """add_simple_rule(root, failure-domain, class, "indep")
        (ErasureCode.cc:64-82); ``crush`` is a CrushWrapper."""
        return crush.add_simple_rule(
            name, self.rule_root, self.rule_failure_domain,
            self.rule_device_class, "indep", rule_type=3)

    # -- minimum_to_decode --------------------------------------------
    def _minimum_to_decode(self, want_to_read: Set[int],
                           available: Set[int]) -> Set[int]:
        """Default: wanted chunks if all available, else the first k
        available (ErasureCode.cc:102-119)."""
        if want_to_read <= available:
            return set(want_to_read)
        k = self.get_data_chunk_count()
        if len(available) < k:
            raise ErasureCodeError(-5, "not enough chunks to decode")
        return set(sorted(available)[:k])

    def minimum_to_decode(
            self, want_to_read: Set[int], available: Set[int]
    ) -> Dict[int, List[Tuple[int, int]]]:
        """chunk id -> [(sub_chunk_offset, count)]
        (ErasureCode.cc:121-137)."""
        ids = self._minimum_to_decode(set(want_to_read), set(available))
        sub = [(0, self.get_sub_chunk_count())]
        return {i: list(sub) for i in sorted(ids)}

    def minimum_to_decode_with_cost(
            self, want_to_read: Set[int],
            available: Dict[int, int]) -> Set[int]:
        """Equal-cost default (ErasureCode.cc:139-148)."""
        return self._minimum_to_decode(set(want_to_read),
                                       set(available.keys()))

    # -- encode -------------------------------------------------------
    def encode_prepare(self, raw: bytes | np.ndarray) -> np.ndarray:
        """Split + zero-pad into k aligned data chunks
        (ErasureCode.cc:150-185).  Returns u8[k, chunk_size]."""
        # buffer-protocol inputs (bytes, bytearray, a memoryview into
        # a pooled recv segment) wrap zero-copy via np.frombuffer —
        # the one host materialisation is the padded chunk array below
        raw = np.frombuffer(raw, np.uint8) if isinstance(
            raw, (bytes, bytearray, memoryview)) \
            else np.asarray(raw, np.uint8).ravel()
        k = self.get_data_chunk_count()
        blocksize = self.get_chunk_size(len(raw))
        out = np.zeros((k, blocksize), np.uint8)
        flat = out.reshape(-1)
        flat[:len(raw)] = raw
        return out

    def encode(self, want_to_encode: Iterable[int],
               raw: bytes | np.ndarray) -> Dict[int, np.ndarray]:
        """Full encode flow (ErasureCode.cc:187-203): prepare, run the
        code, return only the wanted chunks keyed by *encoded* index
        (mapping applied)."""
        want = set(want_to_encode)
        data = self.encode_prepare(raw)
        k = self.get_data_chunk_count()
        n = self.get_chunk_count()
        chunks: Dict[int, np.ndarray] = {
            self.chunk_index(i): data[i] for i in range(k)}
        for i in range(k, n):
            chunks[self.chunk_index(i)] = np.zeros(data.shape[1], np.uint8)
        self.encode_chunks(want, chunks)
        return {i: chunks[i] for i in want if i in chunks}

    def encode_chunks(self, want_to_encode: Set[int],
                      chunks: Dict[int, np.ndarray]) -> None:
        raise NotImplementedError

    def encode_batched(self, want_to_encode: Iterable[int],
                       raws: Sequence[bytes | np.ndarray],
                       mesh=None) -> List[Dict[int, np.ndarray]]:
        """Batched full-object encode: one ``encode_chunks`` dispatch
        for B same-size objects, byte-identical to B ``encode`` calls.

        Every non-sub-chunked code in the registry is bytewise-linear
        with aligned chunk sizes, so the B objects' data chunks
        concatenate along the byte axis (chunk i of the combined =
        concat of every object's chunk i), run through the underlying
        engine ONCE, and the parities split back.  Sub-chunked codes
        (CLAY: intra-chunk coupling geometry derives from the chunk
        length, so concatenation shifts sub-chunk boundaries) and
        mixed-size batches fall back to the per-object loop — still
        byte-identical, just unbatched.

        ``mesh``: a multi-device ``jax.sharding.Mesh`` (explicit, or
        the process-default data-plane mesh when None) shards the
        stripe batch axis u8[B, k, L] across the chips via the
        engine's ``encode_batched_sharded`` — available for plugins
        whose parity math runs on a single ``BitCode`` (jerasure/isa
        matrix and packet codes); layered/sub-chunked plugins keep
        the concat path."""
        raws = list(raws)
        want = set(want_to_encode)
        if len(raws) <= 1 or self.get_sub_chunk_count() != 1 or \
                len({len(r) for r in raws}) != 1:
            return [self.encode(want, r) for r in raws]
        if mesh is None:
            from ..parallel.meshctx import get_mesh

            mesh = get_mesh()
        code = getattr(self, "_code", None)
        if mesh is not None and \
                int(np.asarray(mesh.devices).size) > 1 and \
                hasattr(code, "encode_batched_sharded"):
            return self._encode_batched_mesh(want, raws, code, mesh)
        k = self.get_data_chunk_count()
        n = self.get_chunk_count()
        parts = [self.encode_prepare(r) for r in raws]
        L = parts[0].shape[1]
        B = len(parts)
        cat = np.concatenate(parts, axis=1)  # u8[k, B*L]
        chunks: Dict[int, np.ndarray] = {
            self.chunk_index(i): cat[i] for i in range(k)}
        for i in range(k, n):
            chunks[self.chunk_index(i)] = np.zeros(B * L, np.uint8)
        self.encode_chunks(want, chunks)
        out: List[Dict[int, np.ndarray]] = []
        for b in range(B):
            sl = slice(b * L, (b + 1) * L)
            out.append({i: np.asarray(chunks[i])[sl]
                        for i in want if i in chunks})
        return out

    def _encode_batched_mesh(self, want: Set[int], raws, code,
                             mesh) -> List[Dict[int, np.ndarray]]:
        """The mesh half of ``encode_batched``: stack the prepared
        objects into the stripe batch u8[B, k, L], shard the batch
        axis across the mesh through the engine, and assemble per-
        object chunk dicts exactly as ``encode_chunks`` would (parity
        chunk j lands at ``chunk_index(k + j)``) — byte-identical to
        the per-object path."""
        parts = [self.encode_prepare(r) for r in raws]
        stripes = np.stack(parts)                       # u8[B, k, L]
        parity = np.asarray(
            code.encode_batched_sharded(stripes, mesh))  # u8[B, m, L]
        k = self.get_data_chunk_count()
        n = self.get_chunk_count()
        out: List[Dict[int, np.ndarray]] = []
        for b, data in enumerate(parts):
            chunks: Dict[int, np.ndarray] = {
                self.chunk_index(i): data[i] for i in range(k)}
            for j in range(k, n):
                chunks[self.chunk_index(j)] = parity[b, j - k]
            out.append({i: chunks[i] for i in want if i in chunks})
        return out

    # -- decode -------------------------------------------------------
    def decode(self, want_to_read: Iterable[int],
               chunks: Dict[int, np.ndarray],
               chunk_size: int = 0) -> Dict[int, np.ndarray]:
        return self._decode(set(want_to_read), chunks)

    def _decode(self, want_to_read: Set[int],
                chunks: Dict[int, np.ndarray]) -> Dict[int, np.ndarray]:
        """Trivial copy when everything wanted is present, else
        decode_chunks (ErasureCode.cc:205-241)."""
        have = set(chunks.keys())
        if want_to_read <= have:
            return {i: chunks[i] for i in want_to_read}
        blocksize = len(next(iter(chunks.values())))
        decoded = {}
        for i in range(self.get_chunk_count()):
            if i in chunks:
                decoded[i] = np.asarray(chunks[i], np.uint8)
            else:
                decoded[i] = np.zeros(blocksize, np.uint8)
        self.decode_chunks(want_to_read, chunks, decoded)
        return {i: decoded[i] for i in want_to_read}

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Dict[int, np.ndarray],
                      decoded: Dict[int, np.ndarray]) -> None:
        raise NotImplementedError

    def get_chunk_mapping(self) -> List[int]:
        return self.chunk_mapping

    def decode_concat(self, chunks: Dict[int, np.ndarray]) -> bytes:
        """Recover and concatenate the data chunks in mapping order
        (ErasureCode.cc:281-304 / ErasureCodeInterface.h:460)."""
        k = self.get_data_chunk_count()
        want = [self.chunk_index(i) for i in range(k)]
        decoded = self.decode(set(want), chunks)
        return b"".join(np.asarray(decoded[i], np.uint8).tobytes()
                        for i in want)

    # -- profile field parsing (to_int/to_bool, ErasureCode.cc:288-346)
    @staticmethod
    def to_int(name: str, profile: ErasureCodeProfile,
               default: int) -> int:
        v = profile.get(name, "")
        if v == "":
            profile[name] = str(default)
            return default
        try:
            return int(v)
        except ValueError:
            raise ErasureCodeError(
                -22, f"could not convert {name}={v} to int")

    @staticmethod
    def to_bool(name: str, profile: ErasureCodeProfile,
                default: bool) -> bool:
        v = profile.get(name, "")
        if v == "":
            return default
        return v.lower() in ("yes", "true", "1", "on")
