"""The TPU EC execution engine: every code family as one mod-2 matmul.

The reference executes EC three different ways — isa-l's table-driven
SSE/AVX GF multiplies (ErasureCodeIsa.cc:129 ec_encode_data), jerasure's
matrix loops, and jerasure's bitmatrix XOR schedules
(jerasure_schedule_encode, ErasureCodeJerasure.cc:264).  None of those
map to a TPU.  What does: every one of these codes is GF(2)-linear, so
encode/decode is a single 0/1 matrix applied over bit rows — an int8
matmul on the MXU with a mod-2 epilogue.  Three data layouts cover the
whole zoo:

- ``w8``  — GF(2^8) matrix codes: chunk bytes → 8 bit planes.
- ``w16/w32`` — GF(2^16/2^32) RS: chunk viewed as little-endian words →
  w bit planes (matches jerasure's word-in-memory convention).
- ``packet(w, psize)`` — bitmatrix/schedule codes (cauchy, liberation,
  blaum_roth, liber8tion): chunk = blocks of w packets of psize bytes;
  packet-rows are the GF(2) vector elements; bytes XOR bitwise, so the
  byte axis is unpacked to bits for the matmul and repacked after.

Encode: parity_rows = CB @ data_rows (CB = coding bitmatrix, w*m x w*k).
Decode: pick k surviving chunks, stack their row-blocks of the full
[I; CB] matrix, invert over GF(2) on host (cached per erasure
signature — the ErasureCodeIsaTableCache flow, ErasureCodeIsa.cc:227),
one matmul recovers all data rows; missing parity is re-encoded.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..common import device_metrics
from ..common.perf_counters import collection
from .gfw import gf2_mat_inv

_BITS8 = np.arange(8, dtype=np.uint8)

# -- instrumentation (process-global: the MXU kernels are shared by
# every in-process daemon; served via each daemon's `perf dump`, which
# merges the global collection).  First-call JIT compile cost books
# under jit_compiles/jit_compile_time — keyed by kernel signature, the
# same shape key XLA's own jit cache uses — so steady-state latency
# histograms are not polluted by tracing+compilation.
_pc = collection().create("ec.engine")
for _k in ("encode_ops", "decode_ops", "encode_bytes",
           "decode_bytes", "jit_compiles"):
    _pc.add_u64_counter(_k)
for _k in ("encode_time", "decode_time", "jit_compile_time"):
    _pc.add_time(_k)
_pc.add_histogram("encode_lat")
_pc.add_histogram("decode_lat")
# stripes per batched-encode dispatch (value 1 = the per-stripe path):
# the depth-1-regression canary the aio smoke test gates on
_pc.add_histogram("ec_batch_size", min_value=1)
# signatures already traced+compiled; set membership races only
# double-count a compile, they never corrupt (CPython set ops are
# atomic)
_seen_sigs: set = set()


def book_batch(n_stripes: int) -> None:
    """Record one batched-encode dispatch of ``n_stripes`` stripes
    (the EncodeBatcher and the engine-level batched path both book
    here; per-stripe fallbacks book 1)."""
    _pc.hist_add("ec_batch_size", n_stripes)


def _data_plane_mesh():
    """The process-default data-plane mesh, when one is installed
    (parallel.placement.set_data_plane_mesh).  Reads the
    dependency-free holder, NOT parallel.placement — that module
    pulls the CRUSH mapper (and its x64 config flip), which
    plugin-only processes must never pay for on the encode path."""
    from ..parallel.meshctx import get_mesh

    return get_mesh()


def encode_batched_sharded(code: "BitCode", stripes, mesh,
                           axis_name: str = None):
    """Module-level handle for ``BitCode.encode_batched_sharded`` —
    the name the jaxcheck contract registry and the multichip bench
    lane address the sharded kernel by."""
    return code.encode_batched_sharded(stripes, mesh,
                                       axis_name=axis_name)


def _account(kind: str, sig: tuple, dt: float, nbytes: int,
             jitted: bool = True, nbytes_out: int = 0,
             device_ids=None) -> None:
    """Shared by every EC execution engine (the jitted bit-plane path
    here and native_gf's table engine, which passes jitted=False —
    it has no compile step to separate out).  Jitted launches also
    book into the device plane: the input bytes cross host->device,
    the materialized output crosses back (common/device_metrics.py,
    per-shape-signature).  Mesh launches pass ``device_ids`` so every
    participating chip books a per-device row too."""
    _pc.inc(f"{kind}_ops")
    _pc.inc(f"{kind}_bytes", nbytes)
    if jitted and sig not in _seen_sigs:
        _seen_sigs.add(sig)
        _pc.inc("jit_compiles")
        _pc.tinc("jit_compile_time", dt)
    else:
        _pc.tinc(f"{kind}_time", dt)
        _pc.hist_add(f"{kind}_lat", dt)
    if jitted:
        if device_ids:
            device_metrics.record_mesh_launch(
                "ec.engine", f"{kind}:{sig}", dt, device_ids,
                h2d_bytes=nbytes, d2h_bytes=nbytes_out)
        else:
            device_metrics.record_launch(
                "ec.engine", f"{kind}:{sig}", dt,
                h2d_bytes=nbytes, d2h_bytes=nbytes_out)


@jax.jit
def _mod2_matmul(bm, planes):
    """(R, C) 0/1 int8 @ (C, N) 0/1 int8 -> (R, N) 0/1 uint8.
    Products are 0/1 and C <= a few thousand << 2^31, so the i32
    accumulator is exact; the &1 is the mod-2 epilogue XLA fuses."""
    acc = jax.lax.dot_general(
        bm.astype(jnp.int8), planes.astype(jnp.int8),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc & 1).astype(jnp.uint8)


def _unpack_bytes(data):
    """u8[r, L] -> 0/1 u8[8r, L], row-major (row, bit), LSB first."""
    r, L = data.shape
    planes = (data[:, None, :] >> _BITS8[None, :, None]) & jnp.uint8(1)
    return planes.reshape(8 * r, L)


def _pack_bytes(planes):
    """0/1 u8[8r, L] -> u8[r, L]."""
    r8, L = planes.shape
    p = planes.reshape(r8 // 8, 8, L)
    return jnp.sum(p << _BITS8[None, :, None], axis=1, dtype=jnp.uint8)


class Layout:
    """Chunk bytes <-> GF(2) row-block transform for one code family."""

    def __init__(self, w: int, packetsize: int = 0):
        self.w = w
        self.packetsize = packetsize
        self.is_packet = packetsize > 0

    def check(self, L: int):
        if self.is_packet:
            blk = self.w * self.packetsize
            if L % blk:
                raise ValueError(
                    f"chunk size {L} not a multiple of w*packetsize={blk}")
        else:
            if L % (self.w // 8):
                raise ValueError(
                    f"chunk size {L} not a multiple of word size "
                    f"{self.w // 8}")

    def to_rows(self, chunks):
        """u8[n, L] -> 0/1 u8[n*w, N]: each chunk becomes w GF(2) rows."""
        n, L = chunks.shape
        w = self.w
        if self.is_packet:
            # packet-rows of bytes; the byte's bit axis folds into N so
            # the matmul XORs whole packets bitwise
            ps = self.packetsize
            nb = L // (w * ps)
            r = chunks.reshape(n, nb, w, ps).transpose(0, 2, 1, 3)
            r = r.reshape(n * w, nb * ps)
            bits = (r[:, None, :] >> _BITS8[None, :, None]) & jnp.uint8(1)
            return bits.reshape(n * w, 8 * nb * ps)
        if w == 8:
            return _unpack_bytes(chunks)
        # little-endian words: byte b of a word carries bits 8b..8b+7
        wb = w // 8
        nw = L // wb
        words = chunks.reshape(n, nw, wb)
        planes = (words[:, :, :, None] >> _BITS8[None, None, None, :]) \
            & jnp.uint8(1)
        # [n, nw, wb, 8] -> [n, w, nw] rows (bit index = 8*byte + bit)
        return planes.transpose(0, 2, 3, 1).reshape(n * w, nw)

    def from_rows(self, rows, n: int, L: int):
        """Inverse of to_rows for n chunks of L bytes."""
        w = self.w
        if self.is_packet:
            ps = self.packetsize
            nb = L // (w * ps)
            bits = rows.reshape(n * w, 8, nb * ps)
            by = jnp.sum(bits << _BITS8[None, :, None], axis=1,
                         dtype=jnp.uint8)
            by = by.reshape(n, w, nb, ps).transpose(0, 2, 1, 3)
            return by.reshape(n, L)
        if w == 8:
            return _pack_bytes(rows)
        wb = w // 8
        nw = L // wb
        planes = rows.reshape(n, wb, 8, nw).transpose(0, 3, 1, 2)
        by = jnp.sum(planes << _BITS8[None, None, None, :], axis=3,
                     dtype=jnp.uint8)
        return by.reshape(n, L)


class BitCode:
    """A systematic GF(2)-linear code executed as MXU matmuls.

    ``coding_bm``: (w*m, w*k) 0/1 coding bitmatrix (rows produce the m
    parity chunks' row-blocks from the k data chunks' row-blocks).

    ``force_fused``: route w=8 byte layouts through the Pallas fused
    unpack→MXU→pack kernel unconditionally — compiled on TPU,
    interpret mode elsewhere (the registry's 'pallas-fused' engine).
    Without it the fused kernel still applies opportunistically on a
    TPU backend.
    """

    def __init__(self, k: int, m: int, coding_bm: np.ndarray,
                 layout: Layout, force_fused: bool = False):
        self.k, self.m = k, m
        self.layout = layout
        self.force_fused = force_fused
        if force_fused and (layout.is_packet or layout.w != 8):
            raise ValueError(
                "pallas-fused engine requires a plain byte (w=8) "
                "layout")
        w = layout.w
        assert coding_bm.shape == (w * m, w * k), coding_bm.shape
        self.coding_bm = np.asarray(coding_bm, np.uint8) & 1
        full = np.concatenate(
            [np.eye(w * k, dtype=np.uint8), self.coding_bm], axis=0)
        self.full_bm = full                      # ((k+m)w, kw)
        self._enc_dev = jnp.asarray(self.coding_bm)
        self._dec_cache: Dict[Tuple[int, ...], tuple] = {}
        self._mesh_cache: Dict[tuple, object] = {}

    # -- encode -------------------------------------------------------
    def _fused_w8(self):
        """The Pallas fused path applies on TPU for plain byte (w=8)
        layouts — the bandwidth-bound RS/isa shape — or anywhere when
        ``force_fused`` selected it (interpret mode off-TPU); None
        otherwise."""
        if self.layout.is_packet or self.layout.w != 8:
            return None
        from . import pallas_kernels as PK

        return PK if (self.force_fused or PK.on_tpu()) else None

    def encode(self, data):
        """u8[k, L] -> parity u8[m, L]."""
        data = jnp.asarray(data)
        assert data.shape[0] == self.k
        self.layout.check(data.shape[1])
        t0 = time.monotonic()
        pk = self._fused_w8()
        if pk is not None:
            out = pk.fused_gf2_matmul_w8(self._enc_dev, data,
                                         interpret=not pk.on_tpu())
        else:
            rows = self.layout.to_rows(data)
            out = self.layout.from_rows(
                _mod2_matmul(self._enc_dev, rows), self.m,
                data.shape[1])
        _account("encode",
                 ("enc", self.coding_bm.shape, tuple(data.shape),
                  self.layout.w, self.layout.packetsize,
                  pk is not None),
                 time.monotonic() - t0, int(data.size),
                 nbytes_out=self.m * int(data.shape[1]))
        return out

    def encode_batched(self, stripes, mesh=None):
        """u8[B, k, L] -> parity u8[B, m, L]: ONE kernel dispatch for
        B same-shape stripes.

        Every layout's GF(2) rows treat byte (or word, or packet)
        columns independently, so the B stripes concatenate along the
        byte axis — chunk row i becomes the concat of every stripe's
        chunk i — run through the SAME jitted kernel as ``encode``
        (one dispatch; the compile signature is keyed by (k, B*L), so
        callers batching at fixed sizes stay inside the recompile
        budget), and the parities split back.  Byte-identical to B
        per-stripe ``encode`` calls: the matmul is exact integer
        arithmetic over disjoint columns.

        ``mesh``: an explicit ``jax.sharding.Mesh`` — or, when None,
        the process-default ``parallel.placement.data_plane_mesh()``
        — with more than one device routes through
        ``encode_batched_sharded``: the stripe batch axis sharded
        across the chips, still one launch, still byte-identical."""
        if mesh is None:
            mesh = _data_plane_mesh()
        if mesh is not None and \
                int(np.asarray(mesh.devices).size) > 1:  # jax-ok: mesh.devices is a host-side numpy array of Device handles
            return self.encode_batched_sharded(stripes, mesh)
        stripes = jnp.asarray(stripes)
        B, k, L = stripes.shape
        assert k == self.k, (k, self.k)
        self.layout.check(L)
        t0 = time.monotonic()
        flat = stripes.transpose(1, 0, 2).reshape(self.k, B * L)
        pk = self._fused_w8()
        if pk is not None:
            out = pk.fused_gf2_matmul_w8(self._enc_dev, flat,
                                         interpret=not pk.on_tpu())
        else:
            rows = self.layout.to_rows(flat)
            out = self.layout.from_rows(
                _mod2_matmul(self._enc_dev, rows), self.m, B * L)
        out = out.reshape(self.m, B, L).transpose(1, 0, 2)
        _account("encode",
                 ("encb", self.coding_bm.shape, (B, k, L),
                  self.layout.w, self.layout.packetsize,
                  pk is not None),
                 time.monotonic() - t0, int(stripes.size),
                 nbytes_out=B * self.m * L)
        book_batch(B)
        return out

    def _mesh_fn(self, mesh, axis_name: str):
        """The jitted stripe-batch-sharded encode for one mesh: the
        batch axis carries ``NamedSharding(mesh, P(axis))``, every
        chip encodes its stripe shard against the replicated coding
        bitmatrix, and no collective ever runs — the DrJAX
        data-parallel leaf computation with an empty reduce."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (mesh, axis_name)
        fn = self._mesh_cache.get(key)
        if fn is None:
            shard = NamedSharding(mesh, P(axis_name, None, None))
            layout, enc, m = self.layout, self._enc_dev, self.m

            def one(data):
                L = data.shape[1]
                rows = layout.to_rows(data)
                return layout.from_rows(_mod2_matmul(enc, rows), m, L)

            fn = jax.jit(jax.vmap(one), in_shardings=(shard,),
                         out_shardings=shard)
            self._mesh_cache[key] = fn
        return fn

    def encode_batched_sharded(self, stripes, mesh,
                               axis_name: str = None):
        """The mesh path of ``encode_batched``: u8[B, k, L] with the
        stripe batch axis sharded across ``mesh``'s devices — one pjit
        launch, parity u8[B, m, L] sharded the same way.

        B is pow2-padded with zero stripes up to a multiple of the
        mesh size (a zero stripe's parity is zero for every linear
        code; pad outputs are sliced off), so batch-shape signatures
        stay inside the recompile budget and non-divisible batches
        never fork.  Byte-identical to B per-stripe ``encode`` calls:
        each stripe is encoded by exactly the per-stripe kernel
        composition, vmapped."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.meshctx import pad_batch

        stripes = jnp.asarray(stripes)
        B, k, L = stripes.shape
        assert k == self.k, (k, self.k)
        self.layout.check(L)
        axis_name = axis_name or mesh.axis_names[0]
        n_dev = int(np.asarray(mesh.devices).size)  # jax-ok: mesh.devices is a host-side numpy array of Device handles
        Bp = pad_batch(B, n_dev)
        t0 = time.monotonic()
        if Bp != B:
            stripes = jnp.concatenate(
                [stripes, jnp.zeros((Bp - B, k, L), jnp.uint8)],
                axis=0)
        pk = self._fused_w8()
        if pk is not None:
            # fused mesh path: split the padded batch evenly, flatten
            # each shard along the byte axis ((b, k, L) -> (k, b*L) —
            # GF(2) matmul columns are independent), and run the SAME
            # fused kernel committed to each chip.  Byte-identical to
            # the vmapped path: identical arithmetic over disjoint
            # columns.
            devs = list(np.asarray(mesh.devices).ravel())  # jax-ok: mesh.devices is a host-side numpy array of Device handles
            per = Bp // n_dev
            interp = not pk.on_tpu()
            parts = []
            for d, grp in zip(devs, jnp.split(stripes, n_dev)):
                flat = jax.device_put(
                    grp.transpose(1, 0, 2).reshape(k, per * L), d)
                par = pk.fused_gf2_matmul_w8(self._enc_dev, flat,
                                             interpret=interp)
                parts.append(np.asarray(par).reshape(  # jax-ok: per-device gather — parts are committed to distinct chips and must meet on host
                    self.m, per, L).transpose(1, 0, 2))
            # per-device results are committed to distinct chips;
            # gather on host (the callers materialize anyway)
            out = np.concatenate(parts, axis=0)
        else:
            sharded = jax.device_put(
                stripes, NamedSharding(mesh, P(axis_name, None, None)))
            out = self._mesh_fn(mesh, axis_name)(sharded)
        if Bp != B:
            out = out[:B]
        _account("encode",
                 ("encb_mesh", self.coding_bm.shape, (Bp, k, L),
                  self.layout.w, self.layout.packetsize, n_dev,
                  pk is not None),
                 time.monotonic() - t0, B * k * L,
                 nbytes_out=B * self.m * L,
                 device_ids=[int(d.id) for d in
                             np.asarray(mesh.devices).ravel()])  # jax-ok: mesh.devices is a host-side numpy array of Device handles
        book_batch(B)
        return out

    def all_chunks(self, data):
        data = jnp.asarray(data)
        return jnp.concatenate([data, self.encode(data)], axis=0)

    # -- decode -------------------------------------------------------
    def _decode_mats(self, present: Tuple[int, ...]):
        """Host-inverted GF(2) decode matrix for k survivors, cached by
        erasure signature (the IsaTableCache flow)."""
        mats = self._dec_cache.get(present)
        if mats is None:
            w = self.layout.w
            rows = np.concatenate(
                [self.full_bm[c * w:(c + 1) * w] for c in present], axis=0)
            inv = gf2_mat_inv(rows)
            mats = (jnp.asarray(inv),)
            if len(self._dec_cache) >= 512:   # LRU-ish bound
                self._dec_cache.pop(next(iter(self._dec_cache)))
            self._dec_cache[present] = mats
        return mats

    def decode_data(self, chunks: Dict[int, "jnp.ndarray"]):
        """Recover all k data chunks from any k available chunks.
        ``chunks``: {chunk_id: u8[L]}."""
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise ValueError("need at least k chunks")
        present = tuple(avail[:self.k])
        (inv,) = self._decode_mats(present)
        stack = jnp.stack([jnp.asarray(chunks[i]) for i in present])
        L = stack.shape[1]
        self.layout.check(L)
        t0 = time.monotonic()
        pk = self._fused_w8()
        if pk is not None:
            out = pk.fused_gf2_matmul_w8(inv, stack,
                                         interpret=not pk.on_tpu())
        else:
            rows = self.layout.to_rows(stack)
            out = self.layout.from_rows(_mod2_matmul(inv, rows),
                                        self.k, L)
        _account("decode",
                 ("dec", inv.shape, tuple(stack.shape),
                  self.layout.w, self.layout.packetsize,
                  pk is not None),
                 time.monotonic() - t0, int(stack.size),
                 nbytes_out=self.k * int(L))
        return out

    def decode(self, want: Sequence[int], chunks: Dict[int, "jnp.ndarray"]):
        """Reconstruct the wanted chunk ids (data and/or parity).
        Returns {chunk_id: u8[L]}."""
        have = dict(chunks)
        missing = [i for i in want if i not in have]
        if missing:
            data = self.decode_data(have)
            for i in range(self.k):
                if i not in have:
                    have[i] = data[i]
            par_missing = [i for i in missing if i >= self.k]
            if par_missing:
                parity = self.encode(data)
                for i in par_missing:
                    have[i] = parity[i - self.k]
        return {i: have[i] for i in want}
