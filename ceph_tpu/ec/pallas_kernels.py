"""Pallas TPU kernel for the GF(2) bit-matmul — the EC hot op.

The engine executes every code as ``parity_planes = (BM @ planes) & 1``
(engine.py).  Under plain XLA that is three HLOs with the bit planes
MATERIALIZED in HBM: u8[k, L] unpacks to u8[8k, L] (an 8x byte blowup),
the MXU matmul reads it back, and the pack writes u8[m, L].  EC encode
is bandwidth-bound (SURVEY §7 hard part 4: the win must come from
table-gather/bandwidth + batching), so the 8x round-trip is the cost
that matters.

This kernel fuses unpack → MXU matmul → mod-2 → pack per L-tile inside
VMEM: HBM traffic is k bytes in + m bytes out per lane — the minimum.
The bit matrix (8m x 8k int8, a few KB) stays resident in VMEM across
the grid.

Used by ``engine.BitCode`` for w=8 byte layouts (the RS/isa bench
path) when running on a TPU backend; every other layout/platform rides
the XLA path.  ``interpret=True`` runs the same kernel on CPU for the
correctness tests.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

_LANE_TILE = 512  # lanes per grid step (multiple of 128)


def _kernel(bm_ref, data_ref, out_ref, *, k: int, m: int):
    """One L-tile: u8[k, T] -> u8[m, T] through the resident bit
    matrix int8[8m, 8k].

    All intermediate arithmetic stays int32: the real-TPU Mosaic
    lowering has no unsigned reductions ("Reductions over unsigned
    integers not implemented"), so the plane unpack/repack must not
    touch u8/u32 until the final store."""
    bits = jnp.arange(8, dtype=jnp.int32)
    d = data_ref[:].astype(jnp.int32)                 # i32[k, T]
    planes = (d[:, None, :] >> bits[None, :, None]) & 1
    planes = planes.reshape(8 * k, d.shape[-1])       # i32[8k, T]
    acc = jax.lax.dot_general(
        bm_ref[:], planes.astype(jnp.int8),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)             # i32[8m, T]
    par = (acc & 1).reshape(m, 8, d.shape[-1])        # i32
    out_ref[:] = jnp.sum(par << bits[None, :, None], axis=1,
                         dtype=jnp.int32).astype(jnp.uint8)


@functools.partial(jax.jit,
                   static_argnames=("k", "m", "interpret", "tile"))
def _call(bm, data, k: int, m: int, interpret: bool, tile: int):
    from jax.experimental import pallas as pl

    L = data.shape[1]
    grid = (L // tile,)
    return pl.pallas_call(
        functools.partial(_kernel, k=k, m=m),
        out_shape=jax.ShapeDtypeStruct((m, L), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * m, 8 * k), lambda i: (0, 0)),
            pl.BlockSpec((k, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((m, tile), lambda i: (0, i)),
        interpret=interpret,
    )(bm, data)


def fused_gf2_matmul_w8(bm_bits, data, interpret: bool = False):
    """(8m, 8k) 0/1 matrix applied to u8[k, L] byte chunks -> u8[m, L],
    one fused kernel.  Pads L up to the lane tile and slices back."""
    bm = jnp.asarray(bm_bits, jnp.int8)
    data = jnp.asarray(data, jnp.uint8)
    rout8, rin8 = bm.shape
    assert rout8 % 8 == 0 and rin8 % 8 == 0
    k, m = rin8 // 8, rout8 // 8
    assert data.shape[0] == k
    L = data.shape[1]
    tile = _LANE_TILE  # fixed lane-aligned tile; short inputs pad up
    pad = (-L) % tile
    if pad:
        data = jnp.pad(data, ((0, 0), (0, pad)))
    out = _call(bm, data, k, m, interpret, tile)
    return out[:, :L] if pad else out


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False
