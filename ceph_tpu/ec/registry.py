"""The erasure-code plugin registry.

The role of ``ErasureCodePluginRegistry``
(src/erasure-code/ErasureCodePlugin.h:45-80, ErasureCodePlugin.cc:128):
one factory entry point keyed by plugin name, dispatching to the
in-tree plugins.  Where the reference dlopens ``libec_<name>.so`` and
checks version/entry points, the plugins here are Python modules; the
``preload`` hook (the ``osd_erasure_code_plugins`` startup list) is a
no-op kept for interface parity.
"""

from __future__ import annotations

from typing import Callable, Dict

from .interface import ErasureCode, ErasureCodeError, ErasureCodeProfile

_FACTORIES: Dict[str, Callable[[ErasureCodeProfile], ErasureCode]] = {}


def register(name: str,
             factory: Callable[[ErasureCodeProfile], ErasureCode]) -> None:
    _FACTORIES[name] = factory


def plugins() -> list:
    return sorted(_FACTORIES)


def factory(plugin: str, profile: ErasureCodeProfile) -> ErasureCode:
    """ErasureCodePluginRegistry::factory: instantiate + init.

    ``profile['plugin']`` is the reference's profile convention; the
    explicit argument wins, as in the C++ signature."""
    f = _FACTORIES.get(plugin)
    if f is None:
        raise ErasureCodeError(
            -2, f"unknown erasure-code plugin {plugin!r}; "
                f"have {plugins()}")
    return f(dict(profile))


def profile_factory(profile: ErasureCodeProfile) -> ErasureCode:
    """Build from a profile dict alone (plugin= key, default jerasure —
    the OSDMonitor default profile behavior)."""
    return factory(profile.get("plugin", "jerasure"), profile)


def _register_builtins() -> None:
    from .jerasure import make_jerasure
    from .isa import make_isa
    from .lrc import make_lrc
    from .shec import make_shec
    from .clay import make_clay

    register("jerasure", make_jerasure)
    register("isa", make_isa)
    register("lrc", make_lrc)
    register("shec", make_shec)
    register("clay", make_clay)


_register_builtins()
