"""Erasure coding — the reference's src/erasure-code/ surface on TPU.

One execution engine (``engine.BitCode``: GF(2)-linear codes as mod-2
MXU matmuls with a host decode-matrix cache) behind the reference's
plugin boundary (``interface.ErasureCode`` /
``registry`` — ErasureCodeInterface.h:170 / ErasureCodePlugin.h:45):

- ``jerasure``: all seven techniques (reed_sol_van/r6, cauchy orig/
  good, liberation, blaum_roth, liber8tion), any w in 2..32.
- ``isa``: isa-l's Vandermonde/Cauchy generators, 32-byte alignment.
- ``lrc``: layered locally-repairable codes, k/m/l or explicit layers.
- ``shec``: shingled codes with the parity-subset recovery search.
- ``clay``: coupled-layer MSR regenerating codes with sub-chunked
  bandwidth-optimal single-node repair.
- ``stripe``: the ECUtil stripe math + batched many-stripes data path.
- ``rs_jax``: the array-level RS entry the bench/flagship use.
"""
