"""The LRC plugin — layered locally-repairable codes.

Mirrors src/erasure-code/lrc/ErasureCodeLrc.{h,cc}: a stack of layers,
each a full inner erasure code (jerasure by default) applied to a
subset of the chunk positions described by a ``chunks_map`` string over
{D, c, _}.  Single-chunk losses repair from the LOCAL layer alone —
fewer chunks read than the global k (the whole point of LRC; BASELINE
config 4).

Profile forms, as in the reference:
- k/m/l generated form (parse_kml, ErasureCodeLrc.cc:290-391): builds
  ``mapping``, a global layer plus (k+m)/l local layers, and the
  crush-steps for locality-aware placement.
- explicit ``mapping=`` + ``layers=[[chunks_map, profile], ...]`` JSON
  (layers_parse :140, layers_init :210).

Semantics ported: _minimum_to_decode layer walk with its three cases
(:563-731), reverse-layer encode from the deepest covering layer
(:734-768), decode that feeds each layer's recoveries to the layers
above (:771-857), multi-step rule generation (create_rule :44-110).
"""

from __future__ import annotations

import json
from typing import Dict, List, Set, Tuple

import numpy as np

from .interface import ErasureCode, ErasureCodeError, ErasureCodeProfile

DEFAULT_KML = -1


class Layer:
    """One code layer over a subset of chunk positions."""

    def __init__(self, chunks_map: str, profile: ErasureCodeProfile):
        self.chunks_map = chunks_map
        self.profile = dict(profile)
        self.data = [i for i, c in enumerate(chunks_map) if c == "D"]
        self.coding = [i for i, c in enumerate(chunks_map) if c == "c"]
        self.chunks = self.data + self.coding
        self.chunks_as_set = set(self.chunks)
        self.erasure_code: ErasureCode | None = None


class ErasureCodeLrc(ErasureCode):
    def __init__(self):
        super().__init__()
        self.layers: List[Layer] = []
        self.chunk_count_ = 0
        self.data_chunk_count_ = 0
        self.rule_steps: List[Tuple[str, str, int]] = []  # (op,type,n)

    # -- profile ------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse_kml(profile)
        mapping = profile.get("mapping")
        if not mapping:
            raise ErasureCodeError(-22, "LRC profile needs mapping= "
                                        "or k/m/l")
        layers_json = profile.get("layers")
        if not layers_json:
            raise ErasureCodeError(-22, "LRC profile needs layers= "
                                        "or k/m/l")
        self.layers_parse(layers_json)
        self.chunk_count_ = len(mapping)
        self.data_chunk_count_ = mapping.count("D")
        self.layers_sanity_checks(layers_json)
        self.layers_init()
        if not self.rule_steps:
            self.rule_steps = [("chooseleaf",
                                profile.get("crush-failure-domain",
                                            "host"), 0)]
        super().init(profile)

    def parse_kml(self, profile: ErasureCodeProfile) -> None:
        """Generated form (ErasureCodeLrc.cc:290-391)."""
        k = int(profile.get("k", DEFAULT_KML))
        m = int(profile.get("m", DEFAULT_KML))
        l = int(profile.get("l", DEFAULT_KML))
        if k == DEFAULT_KML and m == DEFAULT_KML and l == DEFAULT_KML:
            return
        if DEFAULT_KML in (k, m, l):
            raise ErasureCodeError(
                -22, "all of k, m, l must be set or none of them")
        for key in ("mapping", "layers", "crush-steps"):
            if key in profile:
                raise ErasureCodeError(
                    -22, f"the {key} parameter cannot be set when "
                         f"k, m, l are set")
        if l == 0 or (k + m) % l:
            raise ErasureCodeError(-22, "k + m must be a multiple of l")
        groups = (k + m) // l
        if k % groups:
            raise ErasureCodeError(
                -22, "k must be a multiple of (k + m) / l")
        if m % groups:
            raise ErasureCodeError(
                -22, "m must be a multiple of (k + m) / l")

        mapping = ""
        for _ in range(groups):
            mapping += "D" * (k // groups) + "_" * (m // groups) + "_"
        profile["mapping"] = mapping

        layers = []
        # global layer
        glob = ""
        for _ in range(groups):
            glob += "D" * (k // groups) + "c" * (m // groups) + "_"
        layers.append([glob, ""])
        # local layers
        for i in range(groups):
            local = ""
            for j in range(groups):
                local += ("D" * l + "c") if i == j else "_" * (l + 1)
            layers.append([local, ""])
        profile["layers"] = json.dumps(layers)

        locality = profile.get("crush-locality", "")
        failure_domain = profile.get("crush-failure-domain", "host")
        if locality:
            self.rule_steps = [("choose", locality, groups),
                               ("chooseleaf", failure_domain, l + 1)]
        elif failure_domain:
            self.rule_steps = [("chooseleaf", failure_domain, 0)]

    def layers_parse(self, description: str) -> None:
        try:
            arr = json.loads(description)
        except json.JSONDecodeError as e:
            raise ErasureCodeError(-22, f"layers is not valid JSON: {e}")
        if not isinstance(arr, list):
            raise ErasureCodeError(-22, "layers must be a JSON array")
        for pos, entry in enumerate(arr):
            if not isinstance(entry, list) or not entry:
                raise ErasureCodeError(
                    -22, f"layers[{pos}] must be a non-empty array")
            chunks_map = entry[0]
            if not isinstance(chunks_map, str):
                raise ErasureCodeError(
                    -22, f"layers[{pos}][0] must be a string")
            prof: ErasureCodeProfile = {}
            if len(entry) > 1:
                second = entry[1]
                if isinstance(second, dict):
                    prof = {str(a): str(b) for a, b in second.items()}
                elif isinstance(second, str):
                    if second:
                        for kv in second.split():
                            a, _, b = kv.partition("=")
                            prof[a] = b
                else:
                    raise ErasureCodeError(
                        -22, f"layers[{pos}][1] must be a string or "
                             f"object")
            self.layers.append(Layer(chunks_map, prof))

    def layers_sanity_checks(self, description: str) -> None:
        if not self.layers:
            raise ErasureCodeError(-22, "at least one layer required")
        for layer in self.layers:
            if len(layer.chunks_map) != self.chunk_count_:
                raise ErasureCodeError(
                    -22, f"layer {layer.chunks_map!r} must be "
                         f"{self.chunk_count_} characters long")

    def layers_init(self) -> None:
        from .registry import factory

        for layer in self.layers:
            prof = layer.profile
            prof.setdefault("k", str(len(layer.data)))
            prof.setdefault("m", str(len(layer.coding)))
            prof.setdefault("plugin", "jerasure")
            prof.setdefault("technique", "reed_sol_van")
            layer.erasure_code = factory(prof["plugin"], prof)

    # -- geometry -----------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.chunk_count_

    def get_data_chunk_count(self) -> int:
        return self.data_chunk_count_

    def get_chunk_size(self, object_size: int) -> int:
        """Delegates to the first (global) layer
        (ErasureCodeLrc.cc:556)."""
        return self.layers[0].erasure_code.get_chunk_size(object_size)

    # -- minimum_to_decode (the local-repair win) ----------------------
    def _minimum_to_decode(self, want_to_read: Set[int],
                           available: Set[int]) -> Set[int]:
        """ErasureCodeLrc.cc:563-731, three cases."""
        n = self.get_chunk_count()
        erasures_total = {i for i in range(n) if i not in available}
        erasures_not_recovered = set(erasures_total)
        erasures_want = erasures_total & set(want_to_read)

        # Case 1: nothing wanted is missing
        if not erasures_want:
            return set(want_to_read)

        # Case 2: recover wanted erasures with as few chunks as possible
        minimum: Set[int] = set()
        for layer in reversed(self.layers):
            layer_want = set(want_to_read) & layer.chunks_as_set
            if not layer_want:
                continue
            layer_erasures = layer_want & erasures_want
            if not layer_erasures:
                layer_minimum = layer_want
            else:
                erasures = layer.chunks_as_set & erasures_not_recovered
                if len(erasures) > \
                        layer.erasure_code.get_coding_chunk_count():
                    continue  # too many for this layer; try upper
                layer_minimum = layer.chunks_as_set \
                    - erasures_not_recovered
                erasures_not_recovered -= erasures
                erasures_want -= erasures
            minimum |= layer_minimum
        if not erasures_want:
            minimum |= set(want_to_read)
            minimum -= erasures_total
            return minimum

        # Case 3: recover anything recoverable hoping it helps above
        erasures_total = {i for i in range(n) if i not in available}
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures_total
            if not layer_erasures:
                continue
            if len(layer_erasures) <= \
                    layer.erasure_code.get_coding_chunk_count():
                erasures_total -= layer_erasures
        if not erasures_total:
            return set(available)

        raise ErasureCodeError(
            -5, f"not enough chunks in {sorted(available)} to read "
                f"{sorted(want_to_read)}")

    # -- data path ----------------------------------------------------
    def encode_chunks(self, want_to_encode: Set[int],
                      chunks: Dict[int, np.ndarray]) -> None:
        """ErasureCodeLrc.cc:734-768: start from the deepest layer that
        covers everything wanted, then encode every layer above."""
        top = len(self.layers)
        for layer in reversed(self.layers):
            top -= 1
            if set(want_to_encode) <= layer.chunks_as_set:
                break
        for layer in self.layers[top:]:
            layer_chunks = {j: chunks[c]
                            for j, c in enumerate(layer.chunks)}
            layer_want = {j for j, c in enumerate(layer.chunks)
                          if c in want_to_encode}
            layer.erasure_code.encode_chunks(layer_want, layer_chunks)
            for j, c in enumerate(layer.chunks):
                chunks[c] = layer_chunks[j]

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Dict[int, np.ndarray],
                      decoded: Dict[int, np.ndarray]) -> None:
        """ErasureCodeLrc.cc:771-857: each layer's recoveries feed the
        layers above via ``decoded``."""
        n = self.get_chunk_count()
        erasures = {i for i in range(n) if i not in chunks}
        want_err = erasures & set(want_to_read)
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures
            if len(layer_erasures) > \
                    layer.erasure_code.get_coding_chunk_count():
                continue  # too many erasures for this layer
            if not layer_erasures:
                continue  # nothing to do here
            layer_chunks = {}
            layer_decoded = {}
            layer_want = set()
            for j, c in enumerate(layer.chunks):
                if c not in erasures:
                    layer_chunks[j] = decoded[c]
                if c in want_to_read:
                    layer_want.add(j)
                layer_decoded[j] = decoded[c]
            layer.erasure_code.decode_chunks(layer_want, layer_chunks,
                                             layer_decoded)
            for j, c in enumerate(layer.chunks):
                decoded[c] = layer_decoded[j]
                erasures.discard(c)
            want_err = erasures & set(want_to_read)
            if not want_err:
                break
        if want_err:
            raise ErasureCodeError(
                -5, f"unable to read {sorted(want_err)}")

    # -- rule generation (ErasureCodeLrc.cc:44-110) --------------------
    def create_rule(self, name: str, crush) -> int:
        from ..crush import constants as C
        from ..crush.map import Rule, RuleStep

        root = crush.get_item_id(self.rule_root)
        if self.rule_device_class:
            if not crush.class_exists(self.rule_device_class):
                raise ErasureCodeError(
                    -2, f"no device class {self.rule_device_class!r}")
            cid = crush.get_or_create_class_id(self.rule_device_class)
            crush.populate_classes()
            shadow = crush.class_bucket.get((root, cid))
            if shadow is None:
                raise ErasureCodeError(
                    -22, f"root {self.rule_root} has no "
                         f"{self.rule_device_class} devices")
            root = shadow
        steps = [
            RuleStep(C.CRUSH_RULE_SET_CHOOSELEAF_TRIES, 5, 0),
            RuleStep(C.CRUSH_RULE_SET_CHOOSE_TRIES, 100, 0),
            RuleStep(C.CRUSH_RULE_TAKE, root, 0),
        ]
        for op_name, type_name, nrep in self.rule_steps:
            op = (C.CRUSH_RULE_CHOOSELEAF_INDEP
                  if op_name == "chooseleaf"
                  else C.CRUSH_RULE_CHOOSE_INDEP)
            steps.append(
                RuleStep(op, nrep, crush.get_type_id(type_name)))
        steps.append(RuleStep(C.CRUSH_RULE_EMIT, 0, 0))
        rid = crush.crush.add_rule(Rule(steps=steps, type=3))
        crush.rule_name_map[rid] = name
        return rid


def make_lrc(profile: ErasureCodeProfile) -> ErasureCodeLrc:
    inst = ErasureCodeLrc()
    inst.init(profile)
    return inst
