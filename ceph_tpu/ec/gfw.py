"""GF(2^w) arithmetic for any w in 2..32 + GF(2) bit-matrix algebra.

The reference's jerasure plugin supports word sizes w=8/16/32 for
Reed-Solomon (src/erasure-code/jerasure/ErasureCodeJerasure.cc:191) and
any w <= 32 for the cauchy bitmatrix codes (:259-336); the GF kernels
live in the vendored gf-complete/jerasure submodules which are ABSENT
from the reference checkout (.gitmodules only).  This module re-derives
the arithmetic from the published field definitions: the standard
primitive-polynomial table used by jerasure's galois.c / gf-complete's
gf_wgen (0x11D at w=8, 0x1100B at w=16, 0x400007 at w=32, etc.);
primitivity of every table entry is asserted by the test suite.

Also here: GF(2) bit-matrix utilities — inversion and the
multiply-by-element expansion that turns any GF(2^w) linear code into a
0/1 matrix over bit planes (jerasure's `matrix_to_bitmatrix`, consumed
on TPU as a mod-2 integer matmul instead of an XOR schedule).

Host-side numpy only: matrices are tiny, built once per profile and
cached.  The bulk data path is ``ceph_tpu.ec.engine``.
"""

from __future__ import annotations

import numpy as np

# Primitive polynomials, low bits only (implicit x^w term) — the
# standard table from jerasure galois.c / gf-complete gf_wgen; w=8/16/32
# match the gf-complete per-width defaults 0x11D / 0x1100B / 0x400007.
GF_POLY = {
    2: 0x3, 3: 0x3, 4: 0x3, 5: 0x5, 6: 0x3, 7: 0x09, 8: 0x1D,
    9: 0x11, 10: 0x09, 11: 0x05, 12: 0x53, 13: 0x1B, 14: 0x443,
    15: 0x03, 16: 0x100B, 17: 0x09, 18: 0x81, 19: 0x27, 20: 0x09,
    21: 0x05, 22: 0x03, 23: 0x21, 24: 0x87, 25: 0x09, 26: 0x47,
    27: 0x27, 28: 0x09, 29: 0x05, 30: 0x800007, 31: 0x09, 32: 0x400007,
}

_TABLE_MAX_W = 16  # log/exp tables up to 2^16; clmul above


class GFW:
    """One GF(2^w) field instance (2 <= w <= 32)."""

    _cache: dict = {}

    def __new__(cls, w: int):
        if w in cls._cache:
            return cls._cache[w]
        self = super().__new__(cls)
        cls._cache[w] = self
        return self

    def __init__(self, w: int):
        if getattr(self, "w", None) == w:
            return
        if w not in GF_POLY:
            raise ValueError(f"unsupported w={w}")
        self.w = w
        self.poly = GF_POLY[w]
        self.size = 1 << w
        self.mask = self.size - 1
        if w <= _TABLE_MAX_W:
            n = self.size - 1
            exp = np.zeros(2 * n, np.int64)
            log = np.zeros(self.size, np.int64)
            x = 1
            for i in range(n):
                exp[i] = x
                log[x] = i
                x <<= 1
                if x & self.size:
                    x ^= (self.poly | self.size)
            exp[n:] = exp[:n]
            self.exp, self.log = exp, log
        else:
            self.exp = self.log = None

    # -- scalar ops (python ints; exact for w=32) ----------------------
    def mul(self, a: int, b: int) -> int:
        if a == 0 or b == 0:
            return 0
        if self.exp is not None:
            return int(self.exp[self.log[a] + self.log[b]])
        # carry-less multiply + poly reduction
        r = 0
        aa, bb = a, b
        while bb:
            if bb & 1:
                r ^= aa
            bb >>= 1
            aa <<= 1
        full_poly = self.poly | (1 << self.w)
        for bit in range(2 * self.w - 2, self.w - 1, -1):
            if r >> bit & 1:
                r ^= full_poly << (bit - self.w)
        return r

    def inv(self, a: int) -> int:
        if a == 0:
            raise ZeroDivisionError("GF inverse of 0")
        if self.exp is not None:
            return int(self.exp[self.size - 1 - self.log[a]])
        # a^(2^w - 2) by square-and-multiply
        r, p, e = 1, a, self.size - 2
        while e:
            if e & 1:
                r = self.mul(r, p)
            p = self.mul(p, p)
            e >>= 1
        return r

    def div(self, a: int, b: int) -> int:
        return self.mul(a, self.inv(b))

    def pow(self, a: int, n: int) -> int:
        r, p = 1, a
        while n:
            if n & 1:
                r = self.mul(r, p)
            p = self.mul(p, p)
            n >>= 1
        return r

    # -- matrix ops (object-dtype safe for w=32; lists of ints) --------
    def mat_inv(self, M):
        """Gauss-Jordan inversion over GF(2^w); M: list-of-lists of int."""
        n = len(M)
        aug = [list(row) + [1 if i == j else 0 for j in range(n)]
               for i, row in enumerate(M)]
        for col in range(n):
            piv = next((r for r in range(col, n) if aug[r][col]), None)
            if piv is None:
                raise np.linalg.LinAlgError("singular GF matrix")
            if piv != col:
                aug[col], aug[piv] = aug[piv], aug[col]
            ic = self.inv(aug[col][col])
            aug[col] = [self.mul(ic, v) for v in aug[col]]
            for r in range(n):
                if r != col and aug[r][col]:
                    f = aug[r][col]
                    aug[r] = [a ^ self.mul(f, b)
                              for a, b in zip(aug[r], aug[col])]
        return [row[n:] for row in aug]

    def mat_mul(self, A, B):
        rows, inner, cols = len(A), len(B), len(B[0])
        out = [[0] * cols for _ in range(rows)]
        for i in range(rows):
            for t in range(inner):
                a = A[i][t]
                if a:
                    Bt = B[t]
                    Oi = out[i]
                    for j in range(cols):
                        if Bt[j]:
                            Oi[j] ^= self.mul(a, Bt[j])
        return out

    # -- bit-matrix expansion ------------------------------------------
    def elem_bitmatrix(self, c: int) -> np.ndarray:
        """w x w 0/1 matrix B with bits(c*x) = B @ bits(x) mod 2
        (bit 0 = LSB).  Column s is the bits of c * x^s."""
        w = self.w
        B = np.zeros((w, w), np.uint8)
        for s in range(w):
            prod = self.mul(c, 1 << s)
            for b in range(w):
                B[b, s] = (prod >> b) & 1
        return B

    def expand_bitmatrix(self, M) -> np.ndarray:
        """(r, c) GF(2^w) matrix -> (w*r, w*c) 0/1 bit matrix —
        jerasure_matrix_to_bitmatrix semantics."""
        r, c = len(M), len(M[0])
        w = self.w
        out = np.zeros((w * r, w * c), np.uint8)
        for i in range(r):
            for j in range(c):
                if M[i][j]:
                    out[w * i:w * i + w, w * j:w * j + w] = \
                        self.elem_bitmatrix(int(M[i][j]))
        return out

    def n_ones(self, c: int) -> int:
        """cauchy_n_ones: ones in the element's bit matrix."""
        return int(self.elem_bitmatrix(c).sum())


# -- GF(2) bit-matrix algebra ------------------------------------------------


def gf2_mat_inv(M: np.ndarray) -> np.ndarray:
    """Invert a 0/1 matrix over GF(2); raises if singular."""
    M = np.asarray(M, np.uint8) & 1
    n = M.shape[0]
    assert M.shape == (n, n)
    aug = np.concatenate([M.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = None
        for r in range(col, n):
            if aug[r, col]:
                piv = r
                break
        if piv is None:
            raise np.linalg.LinAlgError("singular GF(2) matrix")
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        elim = (aug[:, col] == 1)
        elim[col] = False
        aug[elim] ^= aug[col]
    return aug[:, n:].copy()


def poly_mul_matrix(j: int, w: int, check_poly: int) -> np.ndarray:
    """w x w 0/1 matrix of multiply-by-x^j in GF(2)[x]/(check_poly),
    where check_poly has degree w (bit w set).  Used by the Blaum-Roth
    construction over the ring mod M_p(x) = 1 + x + ... + x^(p-1)."""
    B = np.zeros((w, w), np.uint8)
    for s in range(w):
        # (x^s * x^j) mod check_poly
        v = 1 << (s + j)
        deg = v.bit_length() - 1
        while deg >= w:
            v ^= check_poly << (deg - w)
            deg = v.bit_length() - 1
        for b in range(w):
            B[b, s] = (v >> b) & 1
    return B
