"""The balancer mgr module — a closed upmap loop on batched sweeps.

The src/pybind/mgr/balancer role (module.py:Eval/Plan/do_upmap) on the
TPU-batched placement plane: every evaluation of cluster balance is
ONE fused ``PoolMapper.map_all`` launch per pool (no per-PG scalar
mapping anywhere in the loop's evaluation path), tallied host-side
into the deviation stddev the optimizer drives down.  The loop:

  1. pause while the monitor's coded health shows PG_DEGRADED (or
     recovery progress events in flight) — balancing a degraded
     cluster fights recovery for the same PGs;
  2. sweep: batched per-pool remap -> deviation stddev + score;
  3. optimize: ``calc_pg_upmaps`` rounds on a private map copy;
  4. propose: each changed ``pg_upmap_items`` entry goes to the
     monitor as a ``pg_upmap_items_set`` command, committed as a real
     OSDMap incremental every subscriber observes;
  5. verify: once the subscription catches up with the committed
     epoch, re-sweep and record whether the stddev actually dropped.

The same evaluate/optimize core runs offline (``run_offline``)
against synthetic 1000-OSD maps for the ``bench.py --worker
balancer`` lane; PoolMappers are cached across rounds so each
re-sweep only relowers its upmap tables (``refresh_tables``).
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from ..analysis import faults
from ..analysis.lockdep import make_lock
from ..crush.wrapper import CrushWrapper
from ..osdmap.balancer import (build_pgs_by_osd, calc_pg_upmaps,
                               distribution_score, target_osd_weights)
from ..osdmap.osdmap import OSDMap
from .daemon import MgrModule

PgId = Tuple[int, int]


def evaluate(m: OSDMap, wrapper: Optional[CrushWrapper] = None,
             only_pools: Optional[Set[int]] = None,
             use_batched: bool = True,
             mappers: Optional[Dict] = None, mesh=None) -> Dict:
    """One balance sweep (the balancer Eval, module.py:calc_eval):
    batched remap of every selected pool, then host-side deviation
    bookkeeping.  Returns stddev (true root-mean-square deviation),
    max deviation, the [0,1) distribution score, and a per-pool
    breakdown — with exactly one batched launch per pool."""
    if wrapper is None:
        wrapper = CrushWrapper(m.crush)
    pools = sorted(p for p in m.pools
                   if not only_pools or p in only_pools)
    pgs_by_osd = build_pgs_by_osd(
        m, set(pools) if only_pools else None, use_batched,
        mappers=mappers, mesh=mesh)
    osd_weight, weight_total, total_pgs = target_osd_weights(
        m, wrapper, set(pools) if only_pools else None)
    out = {"pools": {}, "sweep_launches": len(pools),
           "mapped_pgs": sum(m.pools[p].pg_num for p in pools),
           "osd_count": len(osd_weight), "stddev": 0.0,
           "sum_sq": 0.0, "max_dev": 0.0, "score": 0.0}
    if not weight_total or not total_pgs or not osd_weight:
        return out
    pgs_per_weight = total_pgs / weight_total
    sum_sq = 0.0
    max_dev = 0.0
    for osd, w in osd_weight.items():
        target = w * pgs_per_weight
        d = len(pgs_by_osd.get(osd, ())) - target
        sum_sq += d * d
        max_dev = max(max_dev, abs(d))
    out["sum_sq"] = sum_sq
    out["stddev"] = math.sqrt(sum_sq / len(osd_weight))
    out["max_dev"] = max_dev
    out["score"] = distribution_score(m, osd_weight, only_pools,
                                      pgs_by_osd)
    # per-pool breakdown from the SAME sweep (no extra launches):
    # each pool's tallies are the pgids of that pool per osd
    for pid in pools:
        pool = m.pools[pid]
        pw, pw_total, p_pgs = target_osd_weights(m, wrapper, {pid})
        row = {"pg_num": pool.pg_num, "size": pool.size,
               "stddev": 0.0, "max_dev": 0.0, "score": 0.0}
        if pw and pw_total and p_pgs:
            ppw = p_pgs / pw_total
            psq = 0.0
            pmax = 0.0
            ptally = {o: len([g for g in pgs_by_osd.get(o, ())
                              if g[0] == pid]) for o in pw}
            for osd, w in pw.items():
                d = ptally[osd] - w * ppw
                psq += d * d
                pmax = max(pmax, abs(d))
            row["stddev"] = math.sqrt(psq / len(pw))
            row["max_dev"] = pmax
            row["score"] = distribution_score(
                m, pw, {pid},
                {o: {g for g in pgs_by_osd.get(o, ()) if g[0] == pid}
                 for o in pw})
        out["pools"][pid] = row
    return out


def run_offline(m: OSDMap, wrapper: Optional[CrushWrapper] = None,
                max_deviation: int = 1, max_iterations: int = 10,
                max_rounds: int = 20, seed: int = 0,
                use_batched: bool = True,
                only_pools: Optional[Set[int]] = None,
                mesh=None, patience: int = 2) -> Dict:
    """Drive the closed loop to convergence against an offline map —
    the bench lane's workload.  One round = one optimize pass + one
    verification sweep.  A round that fails to improve the stddev is
    ROLLED BACK (the map keeps its best state, so the recorded
    trajectory is monotone) and retried with the next round's seed,
    up to ``patience`` consecutive rejected rounds — only then is the
    run ``converged``: zero further-improving rounds at exit.
    Returns the BALANCE record body."""
    if wrapper is None:
        wrapper = CrushWrapper(m.crush)
    mappers: Dict = {}
    sweep_s = 0.0
    sweep_mappings = 0
    launches = 0

    def sweep() -> Dict:
        nonlocal sweep_s, sweep_mappings, launches
        t0 = time.perf_counter()
        ev = evaluate(m, wrapper, only_pools, use_batched,
                      mappers=mappers, mesh=mesh)
        sweep_s += time.perf_counter() - t0
        sweep_mappings += ev["mapped_pgs"]
        launches += ev["sweep_launches"]
        return ev

    ev = sweep()
    trajectory: List[float] = [ev["stddev"]]
    rounds = 0
    upmaps = 0
    rejected = 0
    dry = 0
    converged = ev["max_dev"] <= max_deviation
    while rounds < max_rounds and not converged:
        before = {k: [tuple(p) for p in v]
                  for k, v in m.pg_upmap_items.items()}
        changed = calc_pg_upmaps(
            m, max_deviation=max_deviation,
            max_iterations=max_iterations, only_pools=only_pools,
            wrapper=wrapper, use_batched=use_batched,
            seed=seed + rounds, mappers=mappers, mesh=mesh)
        # the optimizer's own full-cluster remap is a batched sweep
        # too (same launch shape, untimed here)
        launches += ev["sweep_launches"]
        rounds += 1
        prev = trajectory[-1]
        if changed == 0:
            converged = True
            continue
        round_ev = sweep()
        if round_ev["stddev"] >= prev - 1e-9:
            # no improvement: keep the best state, retry with the
            # next seed until patience runs out
            m.pg_upmap_items.clear()
            m.pg_upmap_items.update(before)
            rejected += 1
            dry += 1
            if dry >= patience:
                converged = True
            continue
        ev = round_ev
        dry = 0
        upmaps += changed
        trajectory.append(ev["stddev"])
        if ev["max_dev"] <= max_deviation:
            converged = True
    return {
        "kind": "balance",
        "seed": seed,
        "n_osds": ev["osd_count"],
        "pools": len(m.pools if not only_pools else only_pools),
        "max_deviation": max_deviation,
        "rounds": rounds,
        "rejected_rounds": rejected,
        "upmaps": upmaps,
        "initial_stddev": round(trajectory[0], 4),
        "final_stddev": round(trajectory[-1], 4),
        "stddev_trajectory": [round(s, 4) for s in trajectory],
        "final_score": round(ev["score"], 6),
        "final_max_dev": round(ev["max_dev"], 3),
        "converged": bool(converged),
        "sweep_launches": launches,
        "sweep_s": round(sweep_s, 4),
        "sweep_mappings_per_sec": round(
            sweep_mappings / sweep_s, 1) if sweep_s else 0.0,
    }


def diff_upmap_items(old: Dict[PgId, List], new: Dict[PgId, List]
                     ) -> List[Tuple[PgId, List]]:
    """(pgid, items) pairs to propose; [] items = remove the entry."""
    out: List[Tuple[PgId, List]] = []
    for pgid, items in sorted(new.items()):
        if [tuple(p) for p in old.get(pgid, [])] != \
                [tuple(p) for p in items]:
            out.append((pgid, [list(p) for p in items]))
    for pgid in sorted(old):
        if pgid not in new:
            out.append((pgid, []))
    return out


class BalancerModule(MgrModule):
    """The closed loop as a mgr module (`ceph balancer on` role)."""

    NAME = "balancer"

    def __init__(self, mgr):
        super().__init__(mgr)
        self.active = False
        self.paused = False
        self.last_eval: Optional[Dict] = None
        self.last_round: Optional[Dict] = None
        self.rounds = 0
        self.stale_discards = 0
        # every proposal batch with the health status it was decided
        # under — the thrasher's no-proposals-while-degraded gate
        # audits this log
        self.proposal_log: deque = deque(maxlen=128)
        self.degraded_proposals = 0
        # one round at a time: the tick thread and an admin-socket
        # `balancer execute` must not interleave their sweeps
        self._round_lock = make_lock("mgr::balancer_round")

    @property
    def interval(self) -> float:
        return float(self.mgr.ctx.conf["balancer_interval"])

    # -- health / status ----------------------------------------------
    def health_checks(self) -> Dict[str, str]:
        if self.active and self.paused:
            return {"BALANCER_PAUSED":
                    "balancer paused while cluster is degraded"}
        return {}

    def status(self) -> Dict:
        return {"active": self.active,
                "paused": self.paused,
                "rounds": self.rounds,
                "stale_discards": self.stale_discards,
                "proposals": len(self.proposal_log),
                "degraded_proposals": self.degraded_proposals,
                "last_eval": self.last_eval,
                "last_round": self.last_round}

    # -- admin-socket command surface ---------------------------------
    def command(self, args: Dict) -> Dict:
        argv = [str(a) for a in (args.get("argv") or [])]
        verb = argv[0] if argv else "status"
        if verb == "status":
            return self.status()
        if verb == "on":
            self.active = True
            self.mgr._wake.set()
            return {"success": "balancer on"}
        if verb == "off":
            self.active = False
            return {"success": "balancer off"}
        if verb == "eval":
            snap = self._snapshot()
            if snap is None:
                return {"error": "no map yet"}
            m, w, _epoch = snap
            ev = evaluate(m, w)
            self.pc.inc("balancer_sweep_launches",
                        ev["sweep_launches"])
            self.last_eval = ev
            return ev
        if verb == "execute":
            rec = self._run_round(force=True)
            return rec if rec is not None else {"error": "no map yet"}
        return {"error": f"unknown balancer verb {verb!r}; have "
                         "status|on|off|eval|execute"}

    # -- the loop ------------------------------------------------------
    def tick(self) -> None:
        if not self.active:
            return
        self._run_round(force=False)

    def _snapshot(self):
        """Private (map copy, wrapper, epoch) — calc mutates its map."""
        with self.mgr._lock:
            if self.mgr.map is None:
                return None
            d = self.mgr.map.to_dict()
            epoch = self.mgr.epoch
        m = OSDMap.from_dict(d)
        return m, CrushWrapper(m.crush), epoch

    def _degraded(self, health: Dict) -> bool:
        codes = set(health.get("check_codes") or [])
        return bool(codes & {"PG_DEGRADED", "OSD_DOWN"})

    def _run_round(self, force: bool) -> Optional[Dict]:
        with self._round_lock:
            return self._run_round_locked(force)

    def _run_round_locked(self, force: bool) -> Optional[Dict]:
        conf = self.mgr.ctx.conf
        try:
            health = self.mgr.mon_call({"type": "health"},
                                       timeout=3.0)
        except Exception as e:  # next tick re-probes
            self.log.dout(5, f"balancer: health unavailable {e!r}")
            return None
        if self._degraded(health) and not force:
            # recovery in flight — balancing now would fight it for
            # the same PGs (the reference's no-optimize gate,
            # balancer module.py:Mode busy checks)
            self.paused = True
            self.pc.inc("balancer_paused")
            self.log.dout(4, "balancer: paused (cluster degraded)")
            return None
        self.paused = False

        snap = self._snapshot()
        if snap is None:
            return None
        m, wrapper, epoch = snap
        old_items = {pg: list(v) for pg, v in m.pg_upmap_items.items()}

        ev = evaluate(m, wrapper)
        self.pc.inc("balancer_sweep_launches", ev["sweep_launches"])
        self.pc.set("balancer_stddev", ev["stddev"])
        self.pc.set("balancer_score", ev["score"])
        self.last_eval = ev
        self.rounds += 1
        self.pc.inc("balancer_rounds")

        # a sweep that raced a newer epoch (or the armed failpoint)
        # evaluated a stale map: discard the round, never propose
        # from it
        stale = self.mgr.epoch != epoch
        if faults._ACTIVE and faults.fires("mgr.balancer.stale_map",
                                           self.mgr.name):
            stale = True
        if stale:
            self.stale_discards += 1
            self.log.dout(2, f"balancer: stale sweep (epoch {epoch} "
                             f"vs {self.mgr.epoch}); discarding")
            return None

        rec: Dict = {"epoch": epoch,
                     "stddev_before": round(ev["stddev"], 4),
                     "health": health.get("status")}
        if ev["max_dev"] <= int(conf["balancer_max_deviation"]):
            rec.update(balanced=True, proposed=0)
            self.last_round = rec
            return rec

        changed = calc_pg_upmaps(
            m, max_deviation=int(conf["balancer_max_deviation"]),
            max_iterations=int(conf["balancer_max_iterations"]),
            wrapper=wrapper, use_batched=True, seed=self.rounds)
        rec["balanced"] = False
        if not changed:
            rec["proposed"] = 0
            self.last_round = rec
            return rec

        proposals = diff_upmap_items(old_items, m.pg_upmap_items)
        sent = 0
        commit_epoch = epoch
        for pgid, items in proposals:
            try:
                rep = self.mgr.mon_call(
                    {"type": "pg_upmap_items_set",
                     "pool": pgid[0], "ps": pgid[1], "items": items})
            except Exception as e:  # rest retried next round
                self.log.dout(2, f"balancer: propose {pgid} failed "
                                 f"{e!r}")
                break
            if "error" in rep:
                self.log.dout(2, f"balancer: mon rejected {pgid}: "
                                 f"{rep['error']}")
                continue
            sent += 1
            commit_epoch = max(commit_epoch, int(rep.get("epoch", 0)))
        self.pc.inc("balancer_upmaps_proposed", sent)
        if self._degraded(health):
            self.degraded_proposals += 1  # force=True path only
        self.proposal_log.append(
            {"epoch": epoch, "proposed": sent,
             "health": health.get("status"),
             "degraded": self._degraded(health)})
        rec["proposed"] = sent

        # verify: wait for our own subscription to observe the
        # committed epoch, then one more batched sweep — the stddev
        # must actually have dropped
        from ..common.backoff import Backoff

        bo = Backoff(base=0.05, cap=0.3, deadline=5.0)
        while self.mgr.epoch < commit_epoch:
            if not bo.sleep():
                break
        snap = self._snapshot()
        if snap is not None:
            m2, w2, _e2 = snap
            ev2 = evaluate(m2, w2)
            self.pc.inc("balancer_sweep_launches",
                        ev2["sweep_launches"])
            self.pc.set("balancer_stddev", ev2["stddev"])
            self.pc.set("balancer_score", ev2["score"])
            rec["stddev_after"] = round(ev2["stddev"], 4)
            rec["improved"] = ev2["stddev"] < ev["stddev"]
            if not rec["improved"]:
                self.log.dout(2, f"balancer: round did not improve "
                                 f"({ev['stddev']:.3f} -> "
                                 f"{ev2['stddev']:.3f})")
        self.last_round = rec
        return rec
