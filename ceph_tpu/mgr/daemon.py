"""Manager daemon — the ceph-mgr role with a pluggable module plane.

The reference splits cluster management across a C++ daemon shell
(src/mgr: MgrStandby/Mgr/ActivePyModules) and python modules loaded
into it (src/pybind/mgr: each module a class with ``serve()`` plus
config/health surfaces).  This re-derivation keeps the same split at
single-host scale:

  * ``MgrDaemon`` joins the cluster like any daemon — a messenger
    endpoint, map subscription via ``MapFollower`` (full install +
    incremental catch-up), an admin socket, perf counters, and
    lockdep-named locks;
  * ``MgrModule`` is the module contract: a ``tick()`` the daemon's
    scheduler calls on the module's interval, ``health_checks()``
    folded into the monitor's coded health report, and a ``command()``
    surface routed from the admin socket (``ceph_cli balancer ...``);
  * scheduling is jittered-backoff on ``common/backoff.py``: healthy
    modules re-arm with a jittered draw around their interval (no two
    modules tick in lockstep), a module that raised keeps drawing from
    the SAME decorrelated series, so a wedged module backs off instead
    of spinning — and its error surfaces as an ``MGR_MODULE_ERROR``
    health check at the monitor (the reference's module error health,
    src/mgr/PyModuleRegistry.cc get_health_checks).

Modules are registered by name (``MODULE_REGISTRY``); ``mgr module
ls|enable|disable`` flips them at runtime, mirroring ``ceph mgr
module ...``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..analysis.asyncheck import nonblocking
from ..analysis.lockdep import make_rlock
from ..analysis.racecheck import guarded_by
from ..common.backoff import Backoff
from ..common.context import Context
from ..msg.messenger import Addr, Messenger
from ..osdmap.osdmap import OSDMap
from ..services.map_follower import MapFollower


class MgrModule:
    """Base contract for mgr modules (the src/pybind/mgr MgrModule
    shape, module.py:1561): subclasses override ``tick`` (one
    scheduler pass), ``health_checks`` (code -> summary, folded into
    the monitor's health report) and ``command`` (admin-socket argv
    surface)."""

    NAME = "module"

    def __init__(self, mgr: "MgrDaemon"):
        self.mgr = mgr
        self.pc = mgr.pc
        self.log = mgr.log

    @property
    def interval(self) -> float:
        """Seconds between healthy ticks; modules override to read
        their own option."""
        return float(self.mgr.ctx.conf["mgr_tick_interval"])

    def tick(self) -> None:
        """One scheduler pass; exceptions back the module off and
        surface as MGR_MODULE_ERROR health."""

    def health_checks(self) -> Dict[str, str]:
        """code -> summary, merged into the monitor's health."""
        return {}

    def on_map(self) -> None:
        """Called after every map install (not under the mgr lock)."""

    def command(self, args: Dict) -> Dict:
        return {"error": f"module {self.NAME} has no commands"}

    def status(self) -> Dict:
        return {}


def module_registry() -> Dict[str, type]:
    """Name -> module class (the PyModuleRegistry role).  A function,
    not a module-level dict: balancer_module imports MgrModule from
    here, so the edge back must stay lazy."""
    from .balancer_module import BalancerModule

    return {BalancerModule.NAME: BalancerModule}


@guarded_by("mgr::state", "due", "bo", "error")
class _ModuleSched:
    """Per-module scheduler state: the next-due stamp, the jittered
    backoff series of a failing module, and its last error.  Written
    by the tick thread AND the admin-socket handlers (module
    enable/disable re-arms), so every access runs under the mgr state
    lock — the unlocked tick-loop writes this replaced were the race
    the checker's empty-lockset report flagged."""

    def __init__(self):
        self.due = 0.0
        self.bo: Optional[Backoff] = None
        self.error: Optional[str] = None


@guarded_by("mgr::state", "_sched")
class MgrDaemon(MapFollower):
    """The manager daemon: map follower + module scheduler."""

    def __init__(self, ctx: Context, mgr_id: str, mon_addr,
                 host: str = "127.0.0.1", port: int = 0, keyring=None):
        self.ctx = ctx
        self.id = mgr_id
        self.name = f"mgr.{mgr_id}"
        self.log = ctx.logger("mgr")
        self.tracer = ctx.tracer
        self._init_mons(mon_addr)
        self.msgr = Messenger(self.name, host, port, keyring=keyring,
                              tracer=self.tracer, perf=ctx.perf)
        self.addr: Addr = self.msgr.addr
        self.map: Optional[OSDMap] = None
        self.epoch = 0
        self.osd_addrs: Dict[int, Addr] = {}
        self.ec_profiles: Dict[str, Dict[str, str]] = {}
        self._lock = make_rlock("mgr::state")
        self._running = False
        self._tick_thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self.sock = None

        self.pc = ctx.perf.create(self.name)
        for key in ("ticks", "module_runs", "module_errors",
                    "balancer_rounds", "balancer_upmaps_proposed",
                    "balancer_sweep_launches", "balancer_paused"):
            self.pc.add_u64_counter(key)
        self.pc.add_u64("balancer_stddev")
        self.pc.add_u64("balancer_score")

        self.msgr.register("map_update", self._h_map_update,
                           control=True)
        self.msgr.register("map_inc", self._h_map_inc, control=True)
        self.msgr.register("status", self._h_status, control=False)

        # module plane: every registered module is instantiated;
        # ``enabled`` decides whether the scheduler runs it.  Per
        # module: the next-due stamp, the jittered-backoff series, and
        # the last error (surfaced as MGR_MODULE_ERROR health).
        self.modules: Dict[str, MgrModule] = {
            name: cls(self) for name, cls in module_registry().items()}
        want = {s.strip()
                for s in str(ctx.conf["mgr_modules"]).split(",")
                if s.strip()}
        self.enabled: Dict[str, bool] = {
            name: name in want for name in self.modules}
        self._sched: Dict[str, _ModuleSched] = {
            name: _ModuleSched() for name in self.modules}

    # -- handlers ------------------------------------------------------
    @nonblocking
    def _h_map_update(self, msg):
        self._install_map(msg["payload"])
        return None

    def _h_status(self, _msg):
        with self._lock:
            return {"name": self.name, "epoch": self.epoch,
                    "modules": {n: {"enabled": self.enabled[n],
                                    "last_error":
                                        self._sched[n].error}
                                for n in self.modules}}

    def _post_map_install(self) -> None:
        for name, mod in self.modules.items():
            if self.enabled.get(name):
                mod.on_map()

    # -- admin socket --------------------------------------------------
    def _wire_admin(self, sock) -> None:
        sock.register("mgr", self._admin_mgr,
                      "mgr module ls|enable|disable <name>")
        sock.register(
            "balancer", self._admin_balancer,
            "balancer status|on|off|eval|execute (balancer module)")

    def _module_ls(self) -> Dict:
        with self._lock:
            return {"modules": {
                n: {"enabled": self.enabled[n],
                    "interval": self.modules[n].interval,
                    "last_error": self._sched[n].error}
                for n in sorted(self.modules)}}

    def _admin_mgr(self, args: Dict) -> Dict:
        argv = [str(a) for a in (args.get("argv") or [])]
        if not argv or argv[0] != "module":
            return {"error": "usage: mgr module ls|enable|disable "
                             "<name>"}
        if argv[1:2] == ["ls"] or len(argv) == 1:
            return self._module_ls()
        if len(argv) == 3 and argv[1] in ("enable", "disable"):
            name = argv[2]
            if name not in self.modules:
                return {"error": f"no module {name!r}",
                        "have": sorted(self.modules)}
            self.enabled[name] = argv[1] == "enable"
            if self.enabled[name]:
                with self._lock:
                    st = self._sched[name]
                    st.due, st.bo, st.error = 0.0, None, None
            self._wake.set()
            return {"success": f"module {name} "
                               f"{'enabled' if self.enabled[name] else 'disabled'}"}
        return {"error": "usage: mgr module ls|enable|disable <name>"}

    def _admin_balancer(self, args: Dict) -> Dict:
        mod = self.modules.get("balancer")
        if mod is None:
            return {"error": "balancer module not present"}
        if not self.enabled.get("balancer"):
            return {"error": "balancer module not enabled "
                             "(mgr module enable balancer)"}
        return mod.command(args)

    # -- scheduler -----------------------------------------------------
    def _health_report(self) -> Dict[str, str]:
        checks: Dict[str, str] = {}
        with self._lock:
            errors = {name: st.error
                      for name, st in self._sched.items()}
        for name, err in errors.items():
            if self.enabled.get(name) and err:
                checks["MGR_MODULE_ERROR"] = \
                    f"module {name} failed: {err}"
        for name, mod in self.modules.items():
            if not self.enabled.get(name):
                continue
            try:
                checks.update(mod.health_checks())
            except Exception as e:
                checks["MGR_MODULE_ERROR"] = \
                    f"module {name} health_checks failed: {e!r}"
        return checks

    def _tick_loop(self) -> None:
        base = float(self.ctx.conf["mgr_tick_interval"])
        last_health: Optional[Dict[str, str]] = None
        while self._running:
            self._wake.wait(base / 2)
            self._wake.clear()
            if not self._running:
                break
            self.pc.inc("ticks")
            now = time.monotonic()
            for name, mod in self.modules.items():
                if not self._running or not self.enabled.get(name):
                    continue
                with self._lock:
                    st = self._sched[name]
                    due = st.due
                if now < due:
                    continue
                try:
                    self.pc.inc("module_runs")
                    mod.tick()  # never under the state lock
                except Exception as e:
                    self.pc.inc("module_errors")
                    with self._lock:
                        st.error = repr(e)
                        if st.bo is None:
                            # keep drawing from one decorrelated
                            # series across consecutive failures: the
                            # re-arm delay grows jittered to the cap
                            st.bo = Backoff(base=mod.interval,
                                            cap=mod.interval * 8)
                        st.due = time.monotonic() + \
                            st.bo.next_interval()
                    self.log.dout(1, f"module {name} tick failed: "
                                     f"{e!r}")
                else:
                    with self._lock:
                        st.error = None
                        st.bo = None
                        # healthy pacing still jitters (one fresh
                        # draw): modules desynchronize instead of all
                        # waking on the same beat
                        st.due = time.monotonic() + Backoff(
                            base=mod.interval,
                            cap=mod.interval * 2).next_interval()
            checks = self._health_report()
            if checks != last_health:
                last_health = checks
                try:
                    self.mon_send({"type": "mgr_health_report",
                                   "name": self.name,
                                   "checks": checks})
                except Exception as e:  # next delta re-sends
                    last_health = None
                    self.log.dout(5, f"health report failed: {e!r}")

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "MgrDaemon":
        if self.ctx.conf["admin_socket"]:
            self.sock = self.ctx.start_admin_socket()
            self.tracer.wire(self.sock)
            self._wire_admin(self.sock)
        self.msgr.start()
        payload = self.subscribe_all(self.name)
        self._install_map(payload)
        self._running = True
        self._tick_thread = threading.Thread(
            target=self._tick_loop, daemon=True,
            name=f"{self.name}-tick")
        self._tick_thread.start()
        self.log.dout(1, f"{self.name} up at {self.addr}, modules: "
                         f"{sorted(n for n in self.enabled if self.enabled[n])}")
        return self

    def shutdown(self) -> None:
        self._running = False
        self._wake.set()
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=5)
        self.msgr.shutdown()
        self.ctx.shutdown()
