"""Synthetic cluster maps for offline balancer runs.

The osdmaptool ``--createsimple``/``--test-map-pgs`` role
(src/tools/osdmaptool.cc:330): build an N-OSD host/rack/root
hierarchy with seeded-uneven device weights — the imbalance the
balancer exists to fix comes from heterogeneous capacities, so a
uniform synthetic map would benchmark nothing — plus the variants the
closed loop must survive: device-class split rules (ssd/hdd) and a
compat ``choose_args`` weight-set.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from ..crush.wrapper import CrushWrapper
from ..osdmap.osdmap import OSDMap, PgPool

# heterogeneous capacity mix: 1x / 2x / 4x TiB-class devices
_WEIGHT_STEPS = (0x10000, 0x20000, 0x40000)


def make_synthetic_map(n_osds: int = 1000, osds_per_host: int = 4,
                       hosts_per_rack: int = 10, pg_num: int = 2048,
                       size: int = 3, seed: int = 0,
                       uneven: bool = True,
                       device_classes: Optional[List[str]] = None,
                       failure_domain: str = "host",
                       with_choose_args: bool = False
                       ) -> Tuple[OSDMap, CrushWrapper, Dict[str, int]]:
    """Build (OSDMap, CrushWrapper, {rule_name: ruleno}).

    One pool per rule: pool 1 on the plain ``failure_domain`` rule;
    with ``device_classes`` (e.g. ``["ssd", "hdd"]``) devices
    alternate classes round-robin and each class gets its own rule +
    pool.  ``with_choose_args`` installs a compat weight-set equal to
    the real weights (shape coverage for the choose_args path)."""
    rng = random.Random(seed)
    w = CrushWrapper()
    weights: List[int] = []
    for dev in range(n_osds):
        host = dev // osds_per_host
        rack = host // hosts_per_rack
        wt = rng.choice(_WEIGHT_STEPS) if uneven else 0x10000
        weights.append(wt)
        w.insert_item(dev, wt, f"osd.{dev}",
                      {"host": f"host{host}", "rack": f"rack{rack}",
                       "root": "default"})
        if device_classes:
            w.set_item_class(dev,
                             device_classes[dev % len(device_classes)])
    rules: Dict[str, int] = {}
    rules["repl"] = w.add_simple_rule("repl", "default",
                                      failure_domain, "", "firstn")
    if device_classes:
        for cls in device_classes:
            rules[f"repl-{cls}"] = w.add_simple_rule(
                f"repl-{cls}", "default", failure_domain, cls,
                "firstn")

    m = OSDMap(w.crush)
    for dev in range(n_osds):
        m.add_osd(dev)
    m.pools[1] = PgPool(size=size, pg_num=pg_num,
                        crush_rule=rules["repl"])
    if device_classes:
        pid = 2
        for cls in device_classes:
            m.pools[pid] = PgPool(size=size,
                                  pg_num=max(8, pg_num // 4),
                                  crush_rule=rules[f"repl-{cls}"])
            pid += 1
    if with_choose_args:
        from ..osdmap.balancer import weight_set_to_choose_args

        ws = {dev: weights[dev] / 0x10000 for dev in range(n_osds)}
        m.crush.choose_args["compat"] = weight_set_to_choose_args(
            w, ws)
    return m, w, rules
