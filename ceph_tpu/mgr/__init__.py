"""Manager daemon + module plane (the src/mgr + src/pybind/mgr role)."""

from .balancer_module import (BalancerModule, diff_upmap_items,
                              evaluate, run_offline)
from .daemon import MgrDaemon, MgrModule, module_registry
from .synthetic import make_synthetic_map

__all__ = ["MgrDaemon", "MgrModule", "module_registry",
           "BalancerModule", "evaluate", "run_offline",
           "diff_upmap_items", "make_synthetic_map"]
