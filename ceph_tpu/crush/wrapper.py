"""CrushWrapper — the system-facing facade over the crush map.

The role of the reference's ``CrushWrapper`` (src/crush/CrushWrapper.h):
name/type/rule-name maps, topology edits (insert_item / move_bucket /
remove_item / adjust_item_weight with ancestor propagation,
CrushWrapper.h:802-964,1214), device classes via shadow-tree cloning
(device_class_clone / populate_classes / rebuild_roots_with_classes,
CrushWrapper.h:1304), simple-rule generation (add_simple_rule, :1167),
host-side ``do_rule`` (:1508) backed by the scalar executable spec, and
the upmap remap engine ``try_remap_rule`` / ``_choose_type_stack``
(:1540,1527 / CrushWrapper.cc:3841-4150) that the balancer drives.

The hot path stays in ``mapper_jax``; this class is the mutation-
friendly host layer that owns the map those programs are compiled from.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import constants as C
from .builder import (bucket_add_item, bucket_adjust_item_weight,
                      bucket_remove_item, make_straw2_bucket)
from .map import Bucket, CrushMap, Rule, RuleStep
from .mapper_ref import crush_do_rule

DEFAULT_TYPES = {0: "osd", 1: "host", 2: "rack", 3: "root"}


class CrushWrapper:
    """Mutable, named view of a :class:`CrushMap`."""

    def __init__(self, cmap: Optional[CrushMap] = None,
                 types: Optional[Dict[int, str]] = None):
        self.crush = cmap or CrushMap()
        # an explicitly-empty types dict is honored (the compiler
        # starts from nothing); only None means "use the defaults"
        self.type_map: Dict[int, str] = dict(
            DEFAULT_TYPES if types is None else types)
        self.name_map: Dict[int, str] = {}        # item/bucket id -> name
        self.rule_name_map: Dict[int, str] = {}
        # device classes (CrushWrapper.h:1280-1340)
        self.class_map: Dict[int, int] = {}       # device id -> class id
        self.class_name: Dict[int, str] = {}      # class id -> name
        # (original bucket id, class id) -> shadow bucket id
        self.class_bucket: Dict[Tuple[int, int], int] = {}
        self._shadow_ids: Set[int] = set()
        # shadow ids survive rebuilds so class rules stay valid
        self._shadow_id_registry: Dict[Tuple[int, int], int] = {}
        self._shadow_dirty = False
        # topology caches (parent index, subtree sets, name reverse
        # map): the balancer's remap engine does these lookups per-OSD
        # per-level on 10k-OSD maps, so they must be O(1), not scans.
        # Keyed by (version, bucket count) — wrapper mutators bump the
        # version; direct CrushMap bucket additions change the count;
        # anything else must call invalidate_caches().
        self._topo_version = 0
        self._idx_key: Tuple = ()
        self._parent_idx: Dict[int, int] = {}
        self._name_idx: Dict[str, int] = {}
        self._desc_cache: Dict[int, Set[int]] = {}
        self._cot_cache: Dict[Tuple[int, int], List[int]] = {}

    def invalidate_caches(self) -> None:
        self._topo_version += 1

    def _indexes(self) -> None:
        key = (self._topo_version, len(self.crush.buckets),
               len(self.name_map))
        if self._idx_key != key:
            parent: Dict[int, int] = {}
            for b in self.crush.buckets.values():
                if b.id in self._shadow_ids:
                    continue
                for it in b.items:
                    parent[it] = b.id
            self._parent_idx = parent
            self._name_idx = {n: i for i, n in self.name_map.items()}
            self._desc_cache = {}
            self._cot_cache = {}
            self._idx_key = key

    # -- name maps (CrushWrapper.h:490-630) ---------------------------
    def get_item_name(self, item: int) -> str:
        return self.name_map.get(item, f"item{item}")

    def get_item_id(self, name: str) -> int:
        self._indexes()
        if name not in self._name_idx:
            raise KeyError(f"no item named {name!r}")
        return self._name_idx[name]

    def name_exists(self, name: str) -> bool:
        self._indexes()
        return name in self._name_idx

    def set_item_name(self, item: int, name: str) -> None:
        if self.name_exists(name) and \
                self.name_map.get(item) != name:
            raise ValueError(f"name {name!r} already in use")
        self.name_map[item] = name
        self.invalidate_caches()  # renames keep len(name_map) constant

    def rename_item(self, old: str, new: str) -> None:
        self.set_item_name(self.get_item_id(old), new)

    def get_type_id(self, name: str) -> int:
        for t, n in self.type_map.items():
            if n == name:
                return t
        raise KeyError(f"no type named {name!r}")

    def get_type_name(self, t: int) -> str:
        return self.type_map.get(t, f"type{t}")

    def set_type_name(self, t: int, name: str) -> None:
        self.type_map[t] = name

    def get_rule_id(self, name: str) -> int:
        for r, n in self.rule_name_map.items():
            if n == name:
                return r
        raise KeyError(f"no rule named {name!r}")

    def get_rule_name(self, ruleno: int) -> str:
        return self.rule_name_map.get(ruleno, f"rule{ruleno}")

    # -- device classes -----------------------------------------------
    def get_or_create_class_id(self, name: str) -> int:
        for cid, n in self.class_name.items():
            if n == name:
                return cid
        cid = max(self.class_name, default=-1) + 1
        self.class_name[cid] = name
        return cid

    def class_exists(self, name: str) -> bool:
        return name in self.class_name.values()

    def set_item_class(self, item: int, name: str) -> int:
        cid = self.get_or_create_class_id(name)
        self.class_map[item] = cid
        return cid

    def get_item_class(self, item: int) -> Optional[str]:
        cid = self.class_map.get(item)
        return None if cid is None else self.class_name[cid]

    # -- structure queries --------------------------------------------
    def get_bucket(self, bid: int) -> Bucket:
        b = self.crush.bucket_by_id(bid)
        if b is None:
            raise KeyError(f"no bucket {bid}")
        return b

    def get_bucket_type(self, bid: int) -> int:
        if bid >= 0:
            return 0
        return self.get_bucket(bid).type

    def get_children(self, bid: int) -> List[int]:
        if bid >= 0:
            return []
        return list(self.get_bucket(bid).items)

    def get_immediate_parent_id(self, item: int) -> Optional[int]:
        self._indexes()
        return self._parent_idx.get(item)

    def _descendants(self, root: int) -> Set[int]:
        self._indexes()
        got = self._desc_cache.get(root)
        if got is None:
            got = {root}
            stack = [root]
            while stack:
                cur = stack.pop()
                if cur < 0:
                    for child in self.get_bucket(cur).items:
                        got.add(child)
                        stack.append(child)
            self._desc_cache[root] = got
        return got

    def subtree_contains(self, root: int, item: int) -> bool:
        if root >= 0:
            return root == item
        return item in self._descendants(root)

    def get_leaves(self, root: int) -> List[int]:
        """All devices under ``root`` (subtree walk)."""
        if root >= 0:
            return [root]
        out: List[int] = []
        for child in self.get_bucket(root).items:
            out.extend(self.get_leaves(child))
        return out

    def get_children_of_type(self, root: int, type_: int) -> List[int]:
        self._indexes()
        key = (root, type_)
        got = self._cot_cache.get(key)
        if got is None:
            if self.get_bucket_type(root) == type_:
                got = [root]
            elif root >= 0:
                got = []
            else:
                got = []
                for child in self.get_bucket(root).items:
                    got.extend(self.get_children_of_type(child, type_))
            self._cot_cache[key] = got
        return got

    def find_takes_by_rule(self, ruleno: int) -> List[int]:
        roots = []
        for s in self.crush.rules[ruleno].steps:
            if s.op == C.CRUSH_RULE_TAKE:
                roots.append(s.arg1)
        return roots

    def get_parent_of_type(self, item: int, type_: int,
                           ruleno: int = -1) -> int:
        """CrushWrapper.cc:1662: the ancestor bucket of ``type_``
        containing ``item`` (rule-scoped when ruleno >= 0)."""
        if ruleno < 0:
            cur = item
            while True:
                p = self.get_immediate_parent_id(cur)
                if p is None:
                    return 0
                cur = p
                if self.get_bucket_type(cur) == type_:
                    return cur
        for root in self.find_takes_by_rule(ruleno):
            for cand in self.get_children_of_type(root, type_):
                if self.subtree_contains(cand, item):
                    return cand
        return 0

    def get_item_weight(self, item: int) -> int:
        """Weight of an item in its parent (16.16)."""
        p = self.get_immediate_parent_id(item)
        if p is None:
            raise KeyError(f"item {item} not in any bucket")
        b = self.get_bucket(p)
        return b.item_weight_at(b.items.index(item))

    # -- topology edits (CrushWrapper.h:802-964,1214) ------------------
    def _loc_bucket(self, loc: Dict[str, str],
                    create: bool = True) -> int:
        """Resolve/build the bucket chain described by
        ``{type_name: bucket_name}`` (deepest existing wins); returns
        the id of the LOWEST bucket in the chain."""
        order = sorted(((self.get_type_id(t), t, n)
                        for t, n in loc.items()))
        child_id: Optional[int] = None
        child_weight = 0
        lowest: Optional[int] = None
        for type_id, _t, name in order:
            if self.name_exists(name):
                bid = self.get_item_id(name)
                if child_id is not None and \
                        child_id not in self.get_bucket(bid).items:
                    bucket_add_item(self.get_bucket(bid), child_id,
                                    child_weight)
                    self.invalidate_caches()  # new parent edge
                    self._propagate(bid, child_weight)
            else:
                if not create:
                    raise KeyError(f"no bucket named {name!r}")
                b = make_straw2_bucket([], [], type_id)
                bid = self.crush.add_bucket(b)
                self.set_item_name(bid, name)
                if child_id is not None:
                    bucket_add_item(b, child_id, child_weight)
                    self.invalidate_caches()
            if lowest is None:
                lowest = bid
            child_id = bid
            child_weight = self.get_bucket(bid).weight
        if lowest is None:
            raise ValueError("empty crush location")
        return lowest

    def _propagate(self, start_bid: int, diff: int) -> None:
        """Add ``diff`` to every ancestor's record of its child chain —
        the weight-propagation of adjust_item_weight (CrushWrapper.cc
        adjust_item_weight walking all containing buckets)."""
        cur = start_bid
        while diff:
            parent = self.get_immediate_parent_id(cur)
            if parent is None:
                break
            pb = self.get_bucket(parent)
            pos = pb.items.index(cur)
            if pb.alg == C.CRUSH_BUCKET_UNIFORM:
                break  # uniform parents don't track child weights
            bucket_adjust_item_weight(
                pb, cur, pb.item_weights[pos] + diff)
            cur = parent

    def insert_item(self, item: int, weight: int, name: str,
                    loc: Dict[str, str]) -> None:
        """CrushWrapper::insert_item (CrushWrapper.h:802): place device
        ``item`` at ``loc`` with ``weight``, creating intermediate
        buckets as needed."""
        if item < 0:
            raise ValueError("insert_item inserts devices (id >= 0)")
        bid = self._loc_bucket(loc, create=True)
        bucket_add_item(self.get_bucket(bid), item, weight)
        self._propagate(bid, weight)
        self.set_item_name(item, name)
        self.crush.max_devices = max(self.crush.max_devices, item + 1)
        self._shadow_dirty = True
        self.invalidate_caches()

    def remove_item(self, item: int) -> None:
        """CrushWrapper::remove_item (CrushWrapper.h:964≈)."""
        p = self.get_immediate_parent_id(item)
        if p is None:
            return
        removed = bucket_remove_item(self.get_bucket(p), item)
        self._propagate(p, -removed)
        self.name_map.pop(item, None)
        self.class_map.pop(item, None)
        self._shadow_dirty = True
        self.invalidate_caches()

    def move_bucket(self, bid: int, loc: Dict[str, str]) -> None:
        """CrushWrapper::move_bucket (CrushWrapper.h:817): detach the
        bucket from its parent and re-attach it at ``loc``."""
        b = self.get_bucket(bid)
        # validate BEFORE detaching: a failed move must not corrupt the
        # map (chain creation for dest is harmless — empty buckets)
        dest = self._loc_bucket(loc, create=True)
        if self.subtree_contains(bid, dest):
            raise ValueError("moving a bucket under itself")
        p = self.get_immediate_parent_id(bid)
        if p is not None:
            w = bucket_remove_item(self.get_bucket(p), bid)
            self._propagate(p, -w)
        bucket_add_item(self.get_bucket(dest), bid, b.weight)
        self._propagate(dest, b.weight)
        self._shadow_dirty = True
        self.invalidate_caches()

    def swap_bucket(self, a: int, b: int) -> None:
        """CrushWrapper::swap_bucket: exchange contents (items/weights)
        of two buckets; names/ids stay."""
        ba, bb = self.get_bucket(a), self.get_bucket(b)
        for f in ("items", "item_weights", "sum_weights", "node_weights",
                  "num_nodes", "item_weight", "weight", "straws"):
            va, vb = getattr(ba, f), getattr(bb, f)
            setattr(ba, f, vb)
            setattr(bb, f, va)
        diff = ba.weight - bb.weight
        pa = self.get_immediate_parent_id(a)
        if pa is not None:
            bucket_adjust_item_weight(self.get_bucket(pa), a, ba.weight)
            self._propagate(pa, diff)
        pb_ = self.get_immediate_parent_id(b)
        if pb_ is not None:
            bucket_adjust_item_weight(self.get_bucket(pb_), b, bb.weight)
            self._propagate(pb_, -diff)
        self._shadow_dirty = True
        self.invalidate_caches()

    def adjust_item_weight(self, item: int, weight: int) -> None:
        """CrushWrapper::adjust_item_weight(f) (CrushWrapper.h:964):
        set the device weight everywhere it appears, propagating the
        delta up each ancestor chain."""
        for b in list(self.crush.buckets.values()):
            if b.id in self._shadow_ids:
                continue
            if item in b.items:
                diff = bucket_adjust_item_weight(b, item, weight)
                self._propagate(b.id, diff)
        self._shadow_dirty = True
        self.invalidate_caches()

    def reweight(self) -> None:
        """crushtool --reweight: recompute every bucket's weight
        bottom-up from its children (builder.c crush_reweight_bucket
        over all roots)."""
        from .builder import reweight_bucket

        for b in list(self.crush.buckets.values()):
            if b.id in self._shadow_ids:
                continue
            if self.get_immediate_parent_id(b.id) is None:
                reweight_bucket(self.crush, b)
        self._shadow_dirty = True
        self.invalidate_caches()

    # -- rules ---------------------------------------------------------
    def add_simple_rule(self, name: str, root_name: str,
                        failure_domain: str = "host",
                        device_class: str = "",
                        mode: str = "firstn",
                        rule_type: int = 1,
                        ruleno: int = -1) -> int:
        """CrushWrapper::add_simple_rule (CrushWrapper.h:1167):
        take <root>[~class] -> chooseleaf <mode> 0 type <fd> -> emit.
        This is the signature ``ErasureCode.create_rule`` calls."""
        root = self.get_item_id(root_name)
        if device_class:
            if not self.class_exists(device_class):
                raise KeyError(f"no device class {device_class!r}")
            cid = self.get_or_create_class_id(device_class)
            self.populate_classes()
            shadow = self.class_bucket.get((root, cid))
            if shadow is None:
                raise ValueError(
                    f"root {root_name} has no {device_class} devices")
            root = shadow
        leaf_type = self.get_type_id(failure_domain) \
            if failure_domain else 0
        op = (C.CRUSH_RULE_CHOOSELEAF_FIRSTN if mode == "firstn"
              else C.CRUSH_RULE_CHOOSELEAF_INDEP)
        if leaf_type == 0:
            op = (C.CRUSH_RULE_CHOOSE_FIRSTN if mode == "firstn"
                  else C.CRUSH_RULE_CHOOSE_INDEP)
        steps = [RuleStep(C.CRUSH_RULE_TAKE, root, 0),
                 RuleStep(op, 0, leaf_type),
                 RuleStep(C.CRUSH_RULE_EMIT, 0, 0)]
        rid = self.crush.add_rule(Rule(steps=steps, type=rule_type),
                                  ruleno)
        self.rule_name_map[rid] = name
        return rid

    # -- shadow trees (device classes) ---------------------------------
    def device_class_clone(self, original_id: int, class_id: int) -> int:
        """CrushWrapper.h:1304 device_class_clone: a parallel hierarchy
        containing only devices of ``class_id``.  Devices keep their
        ids; buckets are cloned under fresh ids.  Returns the shadow
        bucket id (devices pass through)."""
        if original_id >= 0:
            return original_id
        key = (original_id, class_id)
        if key in self.class_bucket:
            return self.class_bucket[key]
        orig = self.get_bucket(original_id)
        items: List[int] = []
        weights: List[int] = []
        for pos, child in enumerate(orig.items):
            if child >= 0:
                if self.class_map.get(child) != class_id:
                    continue
                items.append(child)
                weights.append(orig.item_weight_at(pos))
            else:
                sub = self.device_class_clone(child, class_id)
                subw = self.get_bucket(sub).weight
                if not self.get_bucket(sub).items:
                    continue  # empty shadow subtree: skip
                items.append(sub)
                weights.append(subw)
        clone = Bucket(id=self._shadow_id_registry.get(key, 0),
                       alg=orig.alg, type=orig.type,
                       hash=orig.hash, items=items,
                       item_weights=list(weights),
                       weight=sum(weights))
        if orig.alg == C.CRUSH_BUCKET_UNIFORM:
            clone.item_weights = []
            clone.item_weight = orig.item_weight
            clone.weight = orig.item_weight * len(items)
        sid = self.crush.add_bucket(clone)
        self._shadow_id_registry[key] = sid  # stable across rebuilds
        self._shadow_ids.add(sid)
        self.class_bucket[key] = sid
        cname = self.class_name[class_id]
        self.set_item_name(
            sid, f"{self.get_item_name(original_id)}~{cname}")
        return sid

    def populate_classes(self) -> None:
        """Build/refresh shadow trees for every (root, class) pair —
        rebuild_roots_with_classes (CrushWrapper.cc).  Shadow bucket ids
        are stable across rebuilds so existing class rules stay valid."""
        self._clear_shadow()
        roots = [b.id for b in self.crush.buckets.values()
                 if self.get_immediate_parent_id(b.id) is None
                 and b.id not in self._shadow_ids]
        for root in roots:
            classes = {self.class_map[d]
                       for d in self.get_leaves(root)
                       if d in self.class_map}
            for cid in classes:
                self.device_class_clone(root, cid)
        self._shadow_dirty = False

    def _refresh_shadow(self) -> None:
        """Rebuild stale shadow trees before any map consumption —
        topology/weight edits mark them dirty."""
        if self._shadow_dirty and self._shadow_id_registry:
            self.populate_classes()

    def _clear_shadow(self) -> None:
        for sid in self._shadow_ids:
            self.crush.buckets.pop(-1 - sid, None)
            self.name_map.pop(sid, None)
        self._shadow_ids.clear()
        self.class_bucket.clear()

    # -- mapping (host-side) ------------------------------------------
    def do_rule(self, ruleno: int, x: int, numrep: int,
                weight: Sequence[int]) -> List[int]:
        """CrushWrapper::do_rule (CrushWrapper.h:1508) on the scalar
        spec — batch callers go through mapper_jax/BatchedMapper."""
        self._refresh_shadow()
        return crush_do_rule(self.crush, ruleno, x, numrep, list(weight))

    # -- serialization (the framework's native named-map format) -------
    def to_dict(self) -> Dict:
        """CrushWrapper::encode parity: the map plus its name/type/
        class metadata (CrushWrapper.h:1550)."""
        self._refresh_shadow()
        return {
            "map": self.crush.to_dict(),
            "type_map": {str(k): v for k, v in self.type_map.items()},
            "name_map": {str(k): v for k, v in self.name_map.items()},
            "rule_name_map": {str(k): v
                              for k, v in self.rule_name_map.items()},
            "class_map": {str(k): v for k, v in self.class_map.items()},
            "class_name": {str(k): v
                           for k, v in self.class_name.items()},
            "shadow_ids": sorted(self._shadow_ids),
            "class_bucket": [[list(k), v]
                             for k, v in sorted(
                                 self.class_bucket.items())],
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "CrushWrapper":
        w = cls(CrushMap.from_dict(d["map"]),
                types={int(k): v for k, v in d["type_map"].items()})
        w.name_map = {int(k): v for k, v in d["name_map"].items()}
        w.rule_name_map = {int(k): v
                           for k, v in d["rule_name_map"].items()}
        w.class_map = {int(k): v for k, v in d["class_map"].items()}
        w.class_name = {int(k): v for k, v in d["class_name"].items()}
        w._shadow_ids = set(d.get("shadow_ids", []))
        for key, sid in d.get("class_bucket", []):
            w.class_bucket[tuple(key)] = sid
            w._shadow_id_registry[tuple(key)] = sid
        return w

    # -- upmap engine (CrushWrapper.cc:3841-4150) ----------------------
    def try_remap_rule(self, ruleno: int, maxout: int,
                       overfull: Set[int], underfull: List[int],
                       more_underfull: List[int],
                       orig: List[int]) -> List[int]:
        """Remap ``orig`` (a raw pg mapping) swapping overfull devices
        for underfull ones while honoring the rule's failure-domain
        structure; returns the new mapping (possibly == orig)."""
        self._refresh_shadow()
        rule = self.crush.rules[ruleno]
        w: List[int] = []
        out: List[int] = []
        state = {"i": 0, "used": set()}
        type_stack: List[Tuple[int, int]] = []
        root_bucket = 0
        for step in rule.steps:
            if step.op == C.CRUSH_RULE_TAKE:
                w = [step.arg1]
                root_bucket = step.arg1
            elif step.op in (C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
                             C.CRUSH_RULE_CHOOSELEAF_INDEP):
                numrep = step.arg1
                if numrep <= 0:
                    numrep += maxout
                type_stack.append((step.arg2, numrep))
                if step.arg2 > 0:
                    type_stack.append((0, 1))
                w = self._choose_type_stack(
                    type_stack, overfull, underfull, more_underfull,
                    orig, state, w, root_bucket, ruleno)
                type_stack = []
            elif step.op in (C.CRUSH_RULE_CHOOSE_FIRSTN,
                             C.CRUSH_RULE_CHOOSE_INDEP):
                numrep = step.arg1
                if numrep <= 0:
                    numrep += maxout
                type_stack.append((step.arg2, numrep))
            elif step.op == C.CRUSH_RULE_EMIT:
                if type_stack:
                    w = self._choose_type_stack(
                        type_stack, overfull, underfull, more_underfull,
                        orig, state, w, root_bucket, ruleno)
                    type_stack = []
                out.extend(w)
                w = []
        return out

    def _choose_type_stack(self, stack, overfull, underfull,
                           more_underfull, orig, state, pw,
                           root_bucket, ruleno) -> List[int]:
        """CrushWrapper.cc:3841 _choose_type_stack, iterator state in
        ``state`` ({'i': index into orig, 'used': set})."""
        w = list(pw)
        cumulative_fanout = [0] * len(stack)
        f = 1
        for j in range(len(stack) - 1, -1, -1):
            cumulative_fanout[j] = f
            f *= stack[j][1]

        # per-level buckets that still have underfull devices below
        underfull_buckets: List[Set[int]] = \
            [set() for _ in range(max(0, len(stack) - 1))]
        for osd in underfull:
            item = osd
            for j in range(len(stack) - 2, -1, -1):
                type_ = stack[j][0]
                item = self.get_parent_of_type(item, type_, ruleno)
                if not self.subtree_contains(root_bucket, item):
                    continue
                underfull_buckets[j].add(item)

        for j, (type_, fanout) in enumerate(stack):
            cum_fanout = cumulative_fanout[j]
            o: List[int] = []
            # tmpi shadows i at non-leaf levels (i itself only advances
            # at the leaf level), initialized once per level as in the C
            tmpi = state["i"]
            if state["i"] >= len(orig):
                break
            for from_ in w:
                base = len(o)  # this from_'s slice of o
                leaves: List[Set[int]] = [set() for _ in range(fanout)]
                for pos in range(fanout):
                    if type_ > 0:
                        item = self.get_parent_of_type(
                            orig[tmpi], type_, ruleno)
                        o.append(item)
                        n = cum_fanout
                        while n and tmpi < len(orig):
                            leaves[pos].add(orig[tmpi])
                            tmpi += 1
                            n -= 1
                    else:
                        cur = orig[state["i"]]
                        replaced = False
                        if cur in overfull:
                            for cands in (underfull, more_underfull):
                                for item in cands:
                                    if item in state["used"]:
                                        continue
                                    if not self.subtree_contains(
                                            from_, item):
                                        continue
                                    if item in orig:
                                        continue
                                    o.append(item)
                                    state["used"].add(item)
                                    state["i"] += 1
                                    replaced = True
                                    break
                                if replaced:
                                    break
                        if not replaced:
                            o.append(cur)
                            state["i"] += 1
                        if state["i"] >= len(orig):
                            break
                if j + 1 < len(stack):
                    # reject buckets with overfull leaves but no
                    # underfull candidates; prefer same-parent peers
                    for pos in range(base, len(o)):
                        if o[pos] in underfull_buckets[j]:
                            continue
                        if not any(osd in overfull
                                   for osd in leaves[pos - base]):
                            continue
                        for alt in sorted(underfull_buckets[j]):
                            if alt in o:
                                continue
                            if j == 0 or \
                                    self.get_parent_of_type(
                                        o[pos], stack[j - 1][0],
                                        ruleno) == \
                                    self.get_parent_of_type(
                                        alt, stack[j - 1][0], ruleno):
                                o[pos] = alt
                                break
                if (type_ > 0 and tmpi >= len(orig)) or \
                        (type_ == 0 and state["i"] >= len(orig)):
                    break
            w = o
        return w
