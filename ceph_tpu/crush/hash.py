"""The rjenkins1 32-bit mix hash that drives every CRUSH draw.

Bit-exact reimplementation of the reference semantics (src/crush/hash.c:12-90,
seed 1315423911 at hash.c:24).  Written array-generic: every operation is
plain ``+ - ^ << >>`` on unsigned 32-bit values, so the same code runs on

- numpy uint32 arrays / scalars (the scalar reference mapper, host tools), and
- jax.numpy uint32 tracers (the vmapped TPU mapper),

both of which wrap modulo 2^32 like the C ``__u32`` ops do.

Verified bit-exact against tests/golden/hash.json (generated from the
reference C).
"""

import functools

import numpy as np

CRUSH_HASH_SEED = 0x4E67C6A7  # 1315423911

_X = 231232
_Y = 1232


def _wrapping(f):
    """Silence numpy's overflow warnings: u32 wraparound is the contract."""

    @functools.wraps(f)
    def g(*args):
        with np.errstate(over="ignore"):
            return f(*args)

    return g


def _u32(v):
    """Promote a python int to numpy uint32; pass arrays/tracers through."""
    if isinstance(v, (int, np.integer)):
        return np.uint32(v & 0xFFFFFFFF)
    return v


_M = 0xFFFFFFFF


def _mix_int(a, b, c):
    """Pure-python-int mix round (fast path for the scalar reference)."""
    a = (a - b - c) & _M
    a ^= c >> 13
    b = (b - c - a) & _M
    b ^= (a << 8) & _M
    c = (c - a - b) & _M
    c ^= b >> 13
    a = (a - b - c) & _M
    a ^= c >> 12
    b = (b - c - a) & _M
    b ^= (a << 16) & _M
    c = (c - a - b) & _M
    c ^= b >> 5
    a = (a - b - c) & _M
    a ^= c >> 3
    b = (b - c - a) & _M
    b ^= (a << 10) & _M
    c = (c - a - b) & _M
    c ^= b >> 15
    return a, b, c


def hash32_int(a):
    a &= _M
    h = (CRUSH_HASH_SEED ^ a) & _M
    b, x, y = a, _X, _Y
    b, x, h = _mix_int(b, x, h)
    y, a, h = _mix_int(y, a, h)
    return h


def hash32_2_int(a, b):
    a &= _M
    b &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b) & _M
    x, y = _X, _Y
    a, b, h = _mix_int(a, b, h)
    x, a, h = _mix_int(x, a, h)
    b, y, h = _mix_int(b, y, h)
    return h


def hash32_3_int(a, b, c):
    a &= _M
    b &= _M
    c &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c) & _M
    x, y = _X, _Y
    a, b, h = _mix_int(a, b, h)
    c, x, h = _mix_int(c, x, h)
    y, a, h = _mix_int(y, a, h)
    b, x, h = _mix_int(b, x, h)
    y, c, h = _mix_int(y, c, h)
    return h


def hash32_4_int(a, b, c, d):
    a &= _M
    b &= _M
    c &= _M
    d &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d) & _M
    x, y = _X, _Y
    a, b, h = _mix_int(a, b, h)
    c, d, h = _mix_int(c, d, h)
    a, x, h = _mix_int(a, x, h)
    y, b, h = _mix_int(y, b, h)
    c, x, h = _mix_int(c, x, h)
    y, d, h = _mix_int(y, d, h)
    return h


def hash32_5_int(a, b, c, d, e):
    a &= _M
    b &= _M
    c &= _M
    d &= _M
    e &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d ^ e) & _M
    x, y = _X, _Y
    a, b, h = _mix_int(a, b, h)
    c, d, h = _mix_int(c, d, h)
    e, x, h = _mix_int(e, x, h)
    y, a, h = _mix_int(y, a, h)
    b, x, h = _mix_int(b, x, h)
    y, c, h = _mix_int(y, c, h)
    d, x, h = _mix_int(d, x, h)
    y, e, h = _mix_int(y, e, h)
    return h


def _mix(a, b, c):
    """One rjenkins mix round over three u32 lanes (hash.c:12-22)."""
    a = a - b
    a = a - c
    a = a ^ (c >> 13)
    b = b - c
    b = b - a
    b = b ^ (a << 8)
    c = c - a
    c = c - b
    c = c ^ (b >> 13)
    a = a - b
    a = a - c
    a = a ^ (c >> 12)
    b = b - c
    b = b - a
    b = b ^ (a << 16)
    c = c - a
    c = c - b
    c = c ^ (b >> 5)
    a = a - b
    a = a - c
    a = a ^ (c >> 3)
    b = b - c
    b = b - a
    b = b ^ (a << 10)
    c = c - a
    c = c - b
    c = c ^ (b >> 15)
    return a, b, c


@_wrapping
def crush_hash32(a):
    a = _u32(a)
    h = _u32(CRUSH_HASH_SEED) ^ a
    b = a
    x = _u32(_X)
    y = _u32(_Y)
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


@_wrapping
def crush_hash32_2(a, b):
    a, b = _u32(a), _u32(b)
    h = _u32(CRUSH_HASH_SEED) ^ a ^ b
    x = _u32(_X)
    y = _u32(_Y)
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


@_wrapping
def crush_hash32_3(a, b, c):
    a, b, c = _u32(a), _u32(b), _u32(c)
    h = _u32(CRUSH_HASH_SEED) ^ a ^ b ^ c
    x = _u32(_X)
    y = _u32(_Y)
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


@_wrapping
def crush_hash32_4(a, b, c, d):
    a, b, c, d = _u32(a), _u32(b), _u32(c), _u32(d)
    h = _u32(CRUSH_HASH_SEED) ^ a ^ b ^ c ^ d
    x = _u32(_X)
    y = _u32(_Y)
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


@_wrapping
def crush_hash32_5(a, b, c, d, e):
    a, b, c, d, e = _u32(a), _u32(b), _u32(c), _u32(d), _u32(e)
    h = _u32(CRUSH_HASH_SEED) ^ a ^ b ^ c ^ d ^ e
    x = _u32(_X)
    y = _u32(_Y)
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h
