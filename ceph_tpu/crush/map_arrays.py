"""Flat array (SoA) encoding of a CrushMap for the batched TPU mapper.

The reference stores the hierarchy as a pointer forest of per-alg bucket
structs (src/crush/crush.h:219-333).  XLA wants dense, statically-shaped
tensors, so the TPU mapper consumes this padded structure-of-arrays view
instead: every bucket is a row, every per-item field a padded column.  Row
index is the bucket *index* (-1 - id), matching the reference's
``map->buckets[-1-id]`` addressing (src/crush/mapper.c:891).

Split into a static shell (shapes, algs present, tunables — compile-time)
and runtime arrays (weights, items — exchangeable without recompilation, the
property the balancer's mutate-remap loop needs; SURVEY §7 hard part 5).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from . import constants as C
from .map import ChooseArgMap, CrushMap


def _pad2(rows, width, dtype, fill=0):
    out = np.full((len(rows), width), fill, dtype=dtype)
    for i, r in enumerate(rows):
        if len(r):
            out[i, :len(r)] = r
    return out


@dataclass(frozen=True)
class MapStatic:
    """Compile-time facts about a map (hashable; part of the jit key)."""

    max_buckets: int
    max_devices: int
    max_size: int        # padded item width S
    max_nodes: int       # padded tree-node width
    max_positions: int   # padded choose_args weight_set positions
    algs_present: Tuple[int, ...]
    has_uniform: bool
    has_choose_args: bool
    tunables: Tuple[int, int, int, int, int, int]


@dataclass
class MapArrays:
    """Runtime (device-resident) view of the map.  A pytree of arrays; pass
    through jit as an argument so weight mutations don't recompile."""

    alg: np.ndarray            # i32[B]   0 = no bucket at this index
    btype: np.ndarray          # i32[B]
    bhash: np.ndarray          # i32[B]
    size: np.ndarray           # i32[B]
    bid: np.ndarray            # i32[B]   the bucket id (-1-index)
    nnodes: np.ndarray         # i32[B]   tree-bucket num_nodes
    items: np.ndarray          # i32[B,S]
    weights: np.ndarray        # u32[B,S] 16.16 per-item weights (uniform: broadcast)
    sum_weights: np.ndarray    # u32[B,S] list-bucket tail prefix sums
    straws: np.ndarray         # u32[B,S] legacy straw scale factors
    node_weights: np.ndarray   # u32[B,N] tree-bucket node weights
    arg_ids: np.ndarray        # i32[B,S] choose_args id substitution
    arg_weights: np.ndarray    # u32[B,P,S] choose_args weight_set
    has_arg: np.ndarray        # bool[B]

    def tree_flatten(self):
        return (
            (self.alg, self.btype, self.bhash, self.size, self.bid,
             self.nnodes, self.items, self.weights, self.sum_weights,
             self.straws, self.node_weights, self.arg_ids,
             self.arg_weights, self.has_arg), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)


def _register_pytree():
    import jax

    jax.tree_util.register_pytree_node(
        MapArrays,
        lambda m: m.tree_flatten(),
        lambda aux, ch: MapArrays.tree_unflatten(aux, ch))


try:  # register lazily-tolerant: numpy-only users never import jax
    _register_pytree()
except Exception:  # pragma: no cover
    pass


def encode_map(cmap: CrushMap,
               choose_args: Optional[ChooseArgMap] = None,
               ) -> Tuple[MapStatic, MapArrays]:
    """Lower a host CrushMap (+ optional choose_args set) to the SoA view."""
    B = cmap.max_buckets
    bkts: Dict[int, object] = cmap.buckets

    sizes = [bkts[i].size if i in bkts else 0 for i in range(B)]
    S = max([1] + sizes)
    max_nodes = max([1] + [bkts[i].num_nodes for i in bkts
                           if bkts[i].alg == C.CRUSH_BUCKET_TREE])

    max_pos = 1
    if choose_args:
        for a in choose_args.values():
            if a.weight_set is not None:
                max_pos = max(max_pos, len(a.weight_set))

    alg = np.zeros(B, np.int32)
    btype = np.zeros(B, np.int32)
    bhash = np.zeros(B, np.int32)
    size = np.zeros(B, np.int32)
    bid = np.zeros(B, np.int32)
    nnodes = np.zeros(B, np.int32)
    items_rows, w_rows, sw_rows, straw_rows, node_rows = [], [], [], [], []
    arg_id_rows = []
    arg_w = np.zeros((B, max_pos, S), np.uint32)
    has_arg = np.zeros(B, bool)

    for i in range(B):
        b = bkts.get(i)
        if b is None:
            items_rows.append([])
            w_rows.append([])
            sw_rows.append([])
            straw_rows.append([])
            node_rows.append([])
            arg_id_rows.append([])
            continue
        alg[i] = b.alg
        btype[i] = b.type
        bhash[i] = b.hash
        size[i] = b.size
        bid[i] = b.id
        nnodes[i] = b.num_nodes
        items_rows.append(b.items)
        if b.alg == C.CRUSH_BUCKET_UNIFORM:
            w_rows.append([b.item_weight] * b.size)
        else:
            w_rows.append(b.item_weights)
        sw_rows.append(b.sum_weights)
        straw_rows.append(b.straws)
        node_rows.append(b.node_weights)

        ids = list(b.items)
        wts = None
        if choose_args is not None:
            a = choose_args.get(i)
            if a is not None:
                has_arg[i] = True
                if a.ids is not None:
                    ids = list(a.ids)
                if a.weight_set is not None:
                    for p in range(max_pos):
                        row = a.weight_set[min(p, len(a.weight_set) - 1)]
                        arg_w[i, p, :len(row)] = row
                    wts = True
        if wts is None:
            row = w_rows[-1]
            arg_w[i, :, :len(row)] = np.asarray(row, np.uint32)[None, :]
        arg_id_rows.append(ids)

    static = MapStatic(
        max_buckets=B,
        max_devices=cmap.max_devices,
        max_size=S,
        max_nodes=max_nodes,
        max_positions=max_pos,
        algs_present=tuple(sorted(set(int(a) for a in alg if a))),
        has_uniform=C.CRUSH_BUCKET_UNIFORM in alg,
        has_choose_args=bool(choose_args),
        tunables=(
            cmap.tunables.choose_local_tries,
            cmap.tunables.choose_local_fallback_tries,
            cmap.tunables.choose_total_tries,
            cmap.tunables.chooseleaf_descend_once,
            cmap.tunables.chooseleaf_vary_r,
            cmap.tunables.chooseleaf_stable,
        ),
    )
    arrays = MapArrays(
        alg=alg, btype=btype, bhash=bhash, size=size, bid=bid,
        nnodes=nnodes,
        items=_pad2(items_rows, S, np.int32),
        weights=_pad2(w_rows, S, np.uint32),
        sum_weights=_pad2(sw_rows, S, np.uint32),
        straws=_pad2(straw_rows, S, np.uint32),
        node_weights=_pad2(node_rows, max_nodes, np.uint32),
        arg_ids=_pad2(arg_id_rows, S, np.int32),
        arg_weights=arg_w,
        has_arg=has_arg,
    )
    return static, arrays
