"""crush_do_rule_batched — the vmapped TPU CRUSH mapper.

This is the framework's replacement for the reference's scalar map-one-x-at-
a-time core (crush_do_rule, src/crush/mapper.c:878) *and* its thread-pool
batching shim (ParallelPGMapper, src/osd/OSDMapMapping.h:18): one jitted XLA
program maps an entire batch of inputs (PGs) in a single launch.

Bit-exactness contract: identical outputs to the scalar executable spec in
``mapper_ref.py`` (itself golden-tested against the reference C core) for
every map/rule/tunable combination, including the data-dependent retry
descents.  The reformulation:

- ``crush_choose_firstn``'s collision/reject retry descent
  (mapper.c:438-626) becomes a bounded ``lax.while_loop`` whose carried
  state is (current bucket, flocal, ftotal, outcome); one loop iteration is
  one *attempt* (a descend step, a retry, or a terminal outcome), so the
  loop is exactly the C control flow with the gotos flattened.
- ``crush_choose_indep`` (mapper.c:633-821) keeps its breadth-first
  rounds: a while-loop over ftotal < tries, a static unroll over result
  positions, an inner descent while-loop.
- bucket choose methods (mapper.c:51-396) are vectorized over the padded
  item axis: straw2 = masked argmax over fixed-point draws; list = masked
  last-index-satisfying scan; tree = log-depth descent loop; uniform =
  Fisher-Yates permutation state carried functionally.
- the rule VM (mapper.c:923-1080) is unrolled at trace time: rules and
  tunables are static, so each (map-shape, rule, result_max) pair compiles
  to a straight-line XLA program; weights/items/choose_args stay runtime
  arrays so the balancer's mutate-remap loop never recompiles.
- ``vmap`` over x provides the batch axis (the PG/object axis); sharding
  that axis over a device mesh is the job of ``ceph_tpu.parallel``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import numpy as np

import jax

# HARD REQUIREMENT: the straw2 draw is 64-bit fixed-point arithmetic
# (crush_ln in (0, 2^48], div64_s64 by 16.16 weights — mapper.c:312-337);
# without real int64 every mapping silently diverges from the reference.
# Enabling x64 is process-global; hosts embedding this library get 64-bit
# jnp defaults from this point on (ln.py refuses to run otherwise).
if not jax.config.jax_enable_x64:
    jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from . import constants as C  # noqa: E402
from . import hash as H  # noqa: E402
from ..common import device_metrics  # noqa: E402
from ..common.perf_counters import collection  # noqa: E402
from .ln import (LL_NP, RH_LH_NP, ln16_table, recip64,  # noqa: E402
                 straw2_draw, straw2_key)
from .map import ChooseArgMap, CrushMap  # noqa: E402
from .map_arrays import MapArrays, MapStatic, encode_map  # noqa: E402

# process-global batched-mapper metrics (served through every daemon's
# `perf dump`, which merges the global collection): launch count/size,
# steady-state latency, and first-call JIT compile count/time kept
# SEPARATE so compile cost never pollutes the steady-state histogram
_pc = collection().create("crush.mapper")
for _k in ("map_calls", "xs_mapped", "jit_compiles"):
    _pc.add_u64_counter(_k)
_pc.add_time("map_time")
_pc.add_time("jit_compile_time")
_pc.add_histogram("map_lat")

I32 = jnp.int32
U32 = jnp.uint32
I64 = jnp.int64
UNDEF = C.CRUSH_ITEM_UNDEF
NONE = C.CRUSH_ITEM_NONE


def _u32(v):
    return v.astype(U32) if hasattr(v, "astype") else jnp.uint32(v)


def _h2(hash_type, a, b):
    h = H.crush_hash32_2(_u32(a), _u32(b))
    return jnp.where(hash_type == C.CRUSH_HASH_RJENKINS1, h, jnp.uint32(0))


def _h3(hash_type, a, b, c):
    h = H.crush_hash32_3(_u32(a), _u32(b), _u32(c))
    return jnp.where(hash_type == C.CRUSH_HASH_RJENKINS1, h, jnp.uint32(0))


def _h4(hash_type, a, b, c, d):
    h = H.crush_hash32_4(_u32(a), _u32(b), _u32(c), _u32(d))
    return jnp.where(hash_type == C.CRUSH_HASH_RJENKINS1, h, jnp.uint32(0))


class _RuleCompiler:
    """Trace-time compiler for one (map, rule, result_max) triple.

    Instantiated fresh inside the traced function: all methods close over
    the traced map arrays ``A``, weight vector and x of a single lane.
    """

    def __init__(self, static: MapStatic, result_max: int,
                 needs_perm: bool):
        self.st = static
        self.R = result_max
        self.B = static.max_buckets
        self.S = static.max_size
        self.needs_perm = needs_perm
        self.tabs = (jnp.asarray(RH_LH_NP), jnp.asarray(LL_NP))
        # The straw2 selection has two bit-identical lowerings: the
        # arithmetic crush_ln + 64-bit divide (best on CPU, where integer
        # division is native), and the LN16-table + reciprocal-mulhi key
        # (best on TPU, where the divide and the ln pipeline dominate the
        # whole mapper).  Both are golden-tested; pick per backend, with
        # CEPH_TPU_STRAW2={table,compute} as the override.
        mode = os.environ.get("CEPH_TPU_STRAW2", "")
        if mode not in ("table", "compute"):
            mode = "compute" if jax.default_backend() == "cpu" else "table"
        self.use_table_key = mode == "table"
        self.ln16 = jnp.asarray(ln16_table()) if self.use_table_key \
            else None
        # weight reciprocals for the division-free straw2 key; set per
        # trace by single() so they are computed once per launch (they
        # depend only on the unbatched map arrays, so vmap hoists them)
        self.recip_w = None
        self.recip_aw = None

    # -- workspace ----------------------------------------------------
    def perm_init(self):
        if not self.needs_perm:
            return ()
        return (jnp.zeros(self.B, U32),
                jnp.zeros(self.B, I32),
                jnp.broadcast_to(jnp.arange(self.S, dtype=I32),
                                 (self.B, self.S)))

    # -- bucket choose methods (vectorized over the padded item axis) --
    def _perm_choose(self, A, perm, x, bidx, r):
        """bucket_perm_choose (mapper.c:51-109) with functional state."""
        px, pn, pm = perm
        sz = jnp.maximum(A.size[bidx], 1)  # callers reject empty buckets
        hsh = A.bhash[bidx]
        bid = A.bid[bidx]
        pr = jnp.remainder(r, sz).astype(I32)
        reset = (px[bidx] != _u32(x)) | (pn[bidx] == 0)
        shortcut = reset & (pr == 0)

        def do_shortcut(args):
            px, pn, pm = args
            s = jnp.remainder(_h3(hsh, x, bid, jnp.int32(0)), _u32(sz))
            s = s.astype(I32)
            px = px.at[bidx].set(_u32(x))
            pn = pn.at[bidx].set(0xFFFF)
            pm = pm.at[bidx, 0].set(s)
            return A.items[bidx, s], (px, pn, pm)

        def do_full(args):
            px, pn, pm = args
            iota = jnp.arange(self.S, dtype=I32)
            row = pm[bidx]
            # reset path: fresh identity permutation, start at 0
            row_reset = iota
            # cleanup path after a previous r=0 shortcut (mapper.c:77-83):
            # keep row[0]=s, set row[i]=i for i>=1, then row[s]=0
            s_prev = row[0]
            row_clean = iota.at[0].set(s_prev).at[s_prev].set(0)
            cleanup = (~reset) & (pn[bidx] == 0xFFFF)
            row = jnp.where(reset, row_reset,
                            jnp.where(cleanup, row_clean, row))
            n0 = jnp.where(reset, 0, jnp.where(cleanup, 1, pn[bidx]))
            px = px.at[bidx].set(_u32(x))

            def body(p, row):
                act = (p >= n0) & (p <= pr) & (p < sz - 1)
                i = jnp.remainder(_h3(hsh, x, bid, jnp.int32(p)),
                                  _u32(jnp.maximum(sz - p, 1))).astype(I32)
                pi = jnp.clip(p + i, 0, self.S - 1)
                a, b = row[p], row[pi]
                do_swap = act & (i != 0)
                row = row.at[p].set(jnp.where(do_swap, b, a))
                row = row.at[pi].set(jnp.where(do_swap, a, b))
                return row

            row = lax.fori_loop(0, self.S, body, row)
            pn = pn.at[bidx].set(jnp.maximum(n0, pr + 1))
            pm = pm.at[bidx].set(row)
            return A.items[bidx, row[pr]], (px, pn, pm)

        return lax.cond(shortcut, do_shortcut, do_full, perm)

    def _straw2_choose(self, A, x, bidx, r, position):
        """Masked-argmax straw2 (mapper.c:339-362) with choose_args
        weight/id substitution (mapper.c:287-304) pre-baked per bucket."""
        sz = A.size[bidx]
        hsh = A.bhash[bidx]
        if self.st.has_choose_args:
            pos = min(position, self.st.max_positions - 1) \
                if isinstance(position, int) \
                else jnp.minimum(position, self.st.max_positions - 1)
            wts = A.arg_weights[bidx, pos]
            ids = A.arg_ids[bidx]
        else:
            wts = A.weights[bidx]
            ids = A.items[bidx]
        u = _h3(hsh, x, ids, r)
        lane = jnp.arange(self.S, dtype=I32)
        if self.use_table_key:
            rec = self.recip_aw[bidx, pos] if self.st.has_choose_args \
                else self.recip_w[bidx]
            keys = straw2_key(u, wts, rec, xp=jnp, ln_tab=self.ln16)
            keys = jnp.where(lane < sz, keys,
                             jnp.uint64(0xFFFFFFFFFFFFFFFF))
            # argmin/argmax return the x64 index dtype (int64); the
            # gather index lanes are int32 by contract (jaxcheck)
            return A.items[bidx, jnp.argmin(keys).astype(I32)]
        draws = straw2_draw(u & jnp.uint32(0xFFFF), wts, xp=jnp,
                            tables=self.tabs)
        draws = jnp.where(lane < sz, draws, jnp.int64(C.S64_MIN))
        return A.items[bidx, jnp.argmax(draws).astype(I32)]

    def _straw_choose(self, A, x, bidx, r):
        """Legacy straw (mapper.c:205-223)."""
        sz = A.size[bidx]
        hsh = A.bhash[bidx]
        u = _h3(hsh, x, A.items[bidx], r) & jnp.uint32(0xFFFF)
        draws = u.astype(jnp.uint64) * A.straws[bidx].astype(jnp.uint64)
        lane = jnp.arange(self.S, dtype=I32)
        draws = jnp.where(lane < sz, draws, jnp.uint64(0))
        return A.items[bidx, jnp.argmax(draws).astype(I32)]

    def _list_choose(self, A, x, bidx, r):
        """Tail-to-head probabilistic descent (mapper.c:119-142): the C
        loop returns the *largest* index whose draw lands under its
        weight, falling back to items[0]."""
        sz = A.size[bidx]
        hsh = A.bhash[bidx]
        bid = A.bid[bidx]
        h = _h4(hsh, x, A.items[bidx], r, bid) & jnp.uint32(0xFFFF)
        w = (h.astype(jnp.uint64)
             * A.sum_weights[bidx].astype(jnp.uint64)) >> jnp.uint64(16)
        hit = w < A.weights[bidx].astype(jnp.uint64)
        lane = jnp.arange(self.S, dtype=I32)
        cand = jnp.where(hit & (lane < sz), lane, -1)
        j = jnp.max(cand)
        return A.items[bidx, jnp.maximum(j, 0)]

    def _tree_choose(self, A, x, bidx, r):
        """Weighted binary tree descent (mapper.c:145-200).

        Under vmap, lax.switch executes every branch for every lane, so
        this must terminate even when ``bidx`` is a non-tree bucket
        (nnodes=0, where n would get stuck at 0): clamp the start node
        to 1 (odd → immediate exit) and bound the loop by the static
        tree depth as a belt-and-braces guard."""
        hsh = A.bhash[bidx]
        bid = A.bid[bidx]
        n0 = jnp.maximum((A.nnodes[bidx] >> 1).astype(I32), 1)
        max_depth = max(1, int(self.st.max_nodes).bit_length())

        def cond(st):
            n, d = st
            return ((n & 1) == 0) & (d < max_depth)

        def body(st):
            n, d = st
            w = A.node_weights[bidx, n]
            t = (_h4(hsh, x, n, r, bid).astype(jnp.uint64)
                 * w.astype(jnp.uint64)) >> jnp.uint64(32)
            half = ((n & -n) >> 1).astype(I32)
            left = n - half
            lw = A.node_weights[bidx, left].astype(jnp.uint64)
            return jnp.where(t < lw, left, n + half), d + 1

        n, _ = lax.while_loop(cond, body, (n0, jnp.int32(0)))
        return A.items[bidx, n >> 1]

    def bucket_choose(self, A, perm, x, bidx, r, position):
        """crush_bucket_choose dispatch (mapper.c:365-396).  Only the
        algorithms actually present in the map get branches."""
        algs = self.st.algs_present
        if len(algs) == 1 and algs[0] != C.CRUSH_BUCKET_UNIFORM:
            return self._fixed_alg(algs[0], A, x, bidx, r, position), perm

        branches = []
        for alg in algs:
            if alg == C.CRUSH_BUCKET_UNIFORM:
                branches.append(
                    lambda op, a=alg: self._perm_choose(
                        op[0], op[1], op[2], op[3], op[4]))
            else:
                branches.append(
                    lambda op, a=alg: (
                        self._fixed_alg(a, op[0], op[2], op[3], op[4],
                                        position), op[1]))
        table = np.zeros(6, np.int32)
        for i, alg in enumerate(algs):
            table[alg] = i
        br = jnp.asarray(table)[jnp.clip(A.alg[bidx], 0, 5)]
        return lax.switch(br, branches, (A, perm, x, bidx, r))

    def _fixed_alg(self, alg, A, x, bidx, r, position):
        if alg == C.CRUSH_BUCKET_STRAW2:
            return self._straw2_choose(A, x, bidx, r, position)
        if alg == C.CRUSH_BUCKET_STRAW:
            return self._straw_choose(A, x, bidx, r)
        if alg == C.CRUSH_BUCKET_LIST:
            return self._list_choose(A, x, bidx, r)
        if alg == C.CRUSH_BUCKET_TREE:
            return self._tree_choose(A, x, bidx, r)
        raise AssertionError(f"alg {alg} needs perm state")

    # -- device rejection ---------------------------------------------
    def is_out(self, weight, item, x):
        """Weight-based rejection (mapper.c:402-416); item is a valid
        device id when this is called.  The weight vector is the
        caller's runtime array and its length is the C ``weight_max``
        bound: items at or past it are out (mapper.c:406), never a
        clamped gather into the last slot."""
        wmax = weight.shape[0]
        w = weight[jnp.clip(item, 0, wmax - 1)]
        h = _h2(jnp.int32(C.CRUSH_HASH_RJENKINS1), x, item) \
            & jnp.uint32(0xFFFF)
        return jnp.where(item >= wmax, True,
                         jnp.where(w >= 0x10000, False,
                                   jnp.where(w == 0, True, h >= w)))

    # -- child bucket classification ----------------------------------
    def classify(self, A, item):
        """Returns (itemtype, child_idx, valid_child).  itemtype is -1
        for a negative id with no bucket behind it (the C code skips
        before ever reading a type there)."""
        is_neg = item < 0
        cidx = jnp.clip(-1 - item, 0, self.B - 1)
        exists = is_neg & ((-1 - item) < self.B) & (A.alg[cidx] != 0)
        itemtype = jnp.where(
            is_neg, jnp.where(exists, A.btype[cidx], -1), 0)
        return itemtype, cidx, exists


def _seg_any_eq(vec, lo, hi, value):
    """any(vec[i] == value for i in [lo, hi)) without dynamic slicing."""
    idx = jnp.arange(vec.shape[0], dtype=I32)
    return jnp.any((idx >= lo) & (idx < hi) & (vec == value))


def make_choose_firstn(rc: _RuleCompiler, *, numrep: int, type_: int,
                       tries: int, recurse_tries: int, local_retries: int,
                       fallback_retries: int, recurse_to_leaf: bool,
                       vary_r: int, stable: int, single_rep: bool):
    """Builds crush_choose_firstn (mapper.c:438-626) for one static
    configuration.  When ``single_rep`` (the chooseleaf recursion), the
    rep loop collapses to the one position the parent is filling."""
    R = rc.R

    if recurse_to_leaf:
        inner = make_choose_firstn(
            rc, numrep=1, type_=0, tries=recurse_tries, recurse_tries=0,
            local_retries=local_retries, fallback_retries=fallback_retries,
            recurse_to_leaf=False, vary_r=vary_r, stable=stable,
            single_rep=True)

    def run(A, weight, x, root, out, base, outpos0, count0,
            out2, base2, parent_r, perm):
        """Returns (outpos, out, out2, perm)."""

        def attempt_loop(rep, outpos, count, out, out2, perm):
            def cond(st):
                return ~st[0]

            def body(st):
                (done, placed, skip, in_b, flocal, ftotal, item,
                 out2, perm) = st
                r = (rep + parent_r + ftotal).astype(I32)
                sz = A.size[in_b]
                empty = sz == 0

                if fallback_retries > 0:
                    use_pc = (flocal >= (sz >> 1)) \
                        & (flocal > fallback_retries)
                    nitem, perm = lax.cond(
                        use_pc & ~empty,
                        lambda op: rc._perm_choose(A, op[0], x, in_b, r),
                        lambda op: rc.bucket_choose(
                            A, op[0], x, in_b, r, outpos_pos),
                        (perm,))
                else:
                    nitem, perm = rc.bucket_choose(
                        A, perm, x, in_b, r, outpos_pos)
                item = jnp.where(empty, item, nitem)

                over = (~empty) & (item >= rc.st.max_devices)
                itemtype, cidx, exists = rc.classify(A, item)
                want = itemtype == type_
                descend = (~empty) & (~over) & (~want) & exists
                badterm = (~empty) & (~over) & (~want) & (~exists)
                live = (~empty) & (~over) & want

                collide = live & _seg_any_eq(out, base, base + outpos, item)
                reject = empty

                if recurse_to_leaf:
                    do_rec = live & ~collide
                    rec_neg = do_rec & (item < 0)
                    sub_r = (r >> (vary_r - 1)) if vary_r else jnp.int32(0)

                    def rec(op):
                        o2, pm = op
                        got, o2, _, pm = inner(
                            A, weight, x, cidx, o2, base2, outpos, count,
                            None, jnp.int32(0), sub_r, pm)
                        return got, o2, pm

                    def norec(op):
                        return outpos, op[0], op[1]

                    got, out2, perm = lax.cond(
                        rec_neg, rec, norec, (out2, perm))
                    reject = reject | (rec_neg & (got <= outpos))
                    dev_leaf = do_rec & (item >= 0)
                    out2 = jnp.where(
                        dev_leaf,
                        out2.at[jnp.clip(base2 + outpos, 0, R - 1)]
                        .set(item), out2)

                check = live & ~collide & ~reject & (itemtype == 0)
                reject = reject | (check & rc.is_out(weight, item, x))

                fail = (reject | collide) & ~over & ~badterm & ~descend
                nftotal = ftotal + fail.astype(I32)
                nflocal = flocal + fail.astype(I32)
                retry_b = fail & (
                    (collide & (nflocal <= local_retries))
                    | ((fallback_retries > 0)
                       & (nflocal <= sz + fallback_retries)))
                retry_d = fail & ~retry_b & (nftotal < tries)
                give_up = fail & ~retry_b & ~retry_d

                success = live & ~collide & ~reject
                ndone = over | badterm | give_up | success
                nskip = over | badterm | give_up
                nplaced = success
                n_in_b = jnp.where(descend, cidx,
                                   jnp.where(retry_d, root, in_b))
                nflocal = jnp.where(retry_d, 0, nflocal)
                return (ndone, nplaced, nskip, n_in_b, nflocal, nftotal,
                        item, out2, perm)

            outpos_pos = outpos  # the C `outpos` passed to choose_args
            st = (jnp.bool_(False), jnp.bool_(False), jnp.bool_(False),
                  root, jnp.int32(0), jnp.int32(0), jnp.int32(0),
                  out2, perm)
            st = lax.while_loop(cond, body, st)
            _, placed, _, _, _, _, item, out2, perm = st
            out = jnp.where(
                placed,
                out.at[jnp.clip(base + outpos, 0, R - 1)].set(item), out)
            outpos = outpos + placed.astype(I32)
            count = count - placed.astype(I32)
            return outpos, count, out, out2, perm

        if single_rep:
            rep = jnp.int32(0) if stable else outpos0
            outpos, count, out, out2, perm = attempt_loop(
                rep, outpos0, count0, out, out2, perm)
            return outpos, out, out2, perm

        def outer_cond(st):
            rep, outpos, count = st[0], st[1], st[2]
            return (rep < numrep) & (count > 0)

        def outer_body(st):
            rep, outpos, count, out, out2, perm = st
            outpos, count, out, out2, perm = attempt_loop(
                rep, outpos, count, out, out2, perm)
            return rep + 1, outpos, count, out, out2, perm

        st = (jnp.int32(0), outpos0, count0, out, out2, perm)
        _, outpos, _, out, out2, perm = lax.while_loop(
            outer_cond, outer_body, st)
        return outpos, out, out2, perm

    return run


def make_choose_indep(rc: _RuleCompiler, *, numrep: int, type_: int,
                      tries: int, recurse_tries: int,
                      recurse_to_leaf: bool, single_rep: bool):
    """Builds crush_choose_indep (mapper.c:633-821): breadth-first rounds,
    positionally stable, UNDEF backfilled to NONE."""
    R = rc.R

    if recurse_to_leaf:
        inner = make_choose_indep(
            rc, numrep=numrep, type_=0, tries=recurse_tries,
            recurse_tries=0, recurse_to_leaf=False, single_rep=True)

    def run(A, weight, x, root, left0, out, base, outpos0,
            out2, base2, parent_r, perm):
        """Returns (out, out2, perm)."""
        idx = jnp.arange(R, dtype=I32)
        endpos = outpos0 + left0
        seg = (idx >= base + outpos0) & (idx < base + endpos)
        out = jnp.where(seg, UNDEF, out)
        has2 = out2 is not None
        if has2:
            seg2 = (idx >= base2 + outpos0) & (idx < base2 + endpos)
            out2 = jnp.where(seg2, UNDEF, out2)
        else:
            out2 = jnp.zeros((), I32)  # placeholder carried through

        def fill_rep(rep, ftotal, left, out, out2, perm):
            """One descent attempt for one result slot (one round)."""

            def dcond(st):
                return ~st[0]

            def dbody(st):
                done, in_b, left, out, out2, perm = st
                alg_u = (A.alg[in_b] == C.CRUSH_BUCKET_UNIFORM) \
                    & (jnp.remainder(A.size[in_b], numrep) == 0)
                r = rep + parent_r \
                    + jnp.where(alg_u, (numrep + 1) * ftotal,
                                numrep * ftotal)
                r = r.astype(I32)
                sz = A.size[in_b]
                empty = sz == 0

                item, perm = rc.bucket_choose(A, perm, x, in_b, r,
                                              outpos_pos)
                over = (~empty) & (item >= rc.st.max_devices)
                itemtype, cidx, exists = rc.classify(A, item)
                want = itemtype == type_
                descend = (~empty) & (~over) & (~want) & exists
                badterm = ((~empty) & (~over) & (~want) & (~exists)) | over
                live = (~empty) & (~badterm) & want & ~descend

                collide = live & _seg_any_eq(
                    out, base + outpos0, base + endpos, item)
                ok = live & ~collide

                if recurse_to_leaf:
                    rec_neg = ok & (item < 0)

                    def rec(op):
                        o2, pm = op
                        o2, _, pm = inner(
                            A, weight, x, cidx, jnp.int32(1), o2, base2,
                            rep, None, jnp.int32(0), r, pm)
                        return o2, pm

                    out2, perm = lax.cond(
                        rec_neg, rec, lambda op: op, (out2, perm))
                    leaf_fail = rec_neg & (
                        out2[jnp.clip(base2 + rep, 0, R - 1)] == NONE)
                    dev_leaf = ok & (item >= 0)
                    out2 = jnp.where(
                        dev_leaf,
                        out2.at[jnp.clip(base2 + rep, 0, R - 1)]
                        .set(item), out2)
                    ok = ok & ~leaf_fail

                ok = ok & ~((itemtype == 0) & rc.is_out(weight, item, x))

                # terminal NONE (out-of-range item / unresolvable child)
                out = jnp.where(
                    badterm,
                    out.at[jnp.clip(base + rep, 0, R - 1)].set(NONE), out)
                if recurse_to_leaf:
                    out2 = jnp.where(
                        badterm,
                        out2.at[jnp.clip(base2 + rep, 0, R - 1)]
                        .set(NONE), out2)
                out = jnp.where(
                    ok, out.at[jnp.clip(base + rep, 0, R - 1)].set(item),
                    out)
                left = left - (badterm | ok).astype(I32)
                ndone = ~descend
                n_in_b = jnp.where(descend, cidx, in_b)
                return ndone, n_in_b, left, out, out2, perm

            # choose_args position: the C code passes the function's
            # `outpos` parameter (mapper.c:701), not the slot index
            outpos_pos = outpos0
            slot_open = out[jnp.clip(base + rep, 0, R - 1)] == UNDEF
            active = (rep >= outpos0) & (rep < endpos) & slot_open

            def go(op):
                st = (jnp.bool_(False), root) + op
                st = lax.while_loop(dcond, dbody, st)
                return st[2:]

            left, out, out2, perm = lax.cond(
                active, go, lambda op: op, (left, out, out2, perm))
            return left, out, out2, perm

        def round_cond(st):
            ftotal, left = st[0], st[1]
            return (left > 0) & (ftotal < tries)

        def round_body(st):
            ftotal, left, out, out2, perm = st
            if single_rep:
                left, out, out2, perm = fill_rep(
                    outpos0, ftotal, left, out, out2, perm)
            else:
                for rep_i in range(numrep):
                    left, out, out2, perm = fill_rep(
                        outpos0 + rep_i, ftotal, left, out, out2, perm)
            return ftotal + 1, left, out, out2, perm

        st = (jnp.int32(0), left0, out, out2, perm)
        _, _, out, out2, perm = lax.while_loop(round_cond, round_body, st)

        out = jnp.where(seg & (out == UNDEF), NONE, out)
        if has2:
            out2 = jnp.where(seg2 & (out2 == UNDEF), NONE, out2)
            return out, out2, perm
        return out, None, perm

    return run


def make_single_fn(cmap: CrushMap, ruleno: int, result_max: int,
                   choose_args: Optional[ChooseArgMap] = None,
                   encoded=None):
    """The unjitted single-x rule program: ``single(arrays, weight, x)
    -> (result i32[R], len i32)``.  Compose/fuse it into larger programs
    (the OSDMap pipeline) before vmap+jit.  Returns
    ``(single, static, arrays_np)``.

    ``encoded``: a pre-computed ``encode_map`` result, so callers
    compiling many rules over one map pay the host-side encode once.
    """
    static, arrays_np = encoded if encoded is not None \
        else encode_map(cmap, choose_args)
    rule = cmap.rules[ruleno]
    (local_tries, fallback_tries, total_tries, descend_once,
     vary_r0, stable0) = static.tunables

    # Walk the steps once to know whether perm state can ever be touched:
    # uniform buckets present, or a fallback-tries setting > 0 in force.
    fb = fallback_tries
    max_fb = fb
    for s in rule.steps:
        if s.op == C.CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES \
                and s.arg1 >= 0:
            fb = s.arg1
            max_fb = max(max_fb, fb)
    needs_perm = static.has_uniform or max_fb > 0

    rc = _RuleCompiler(static, result_max, needs_perm)
    R = result_max
    B = static.max_buckets

    def single(A, weight, x):
        if rc.use_table_key:
            if static.has_choose_args:
                rc.recip_aw = recip64(A.arg_weights, xp=jnp)
            else:
                rc.recip_w = recip64(A.weights, xp=jnp)
        try:
            return _single_body(A, weight, x)
        finally:
            # the recips are TRACERS while jit traces this function;
            # rc outlives the trace (the closure keeps it), so leaving
            # them set leaks the dead tracer — jax.checking_leaks
            # (the kernel-test gate) rejects the program
            rc.recip_w = rc.recip_aw = None

    def _single_body(A, weight, x):
        choose_tries = total_tries + 1  # mapper.c:906 off-by-one heritage
        choose_leaf_tries = 0
        local_retries = local_tries
        local_fb = fallback_tries
        vary_r = vary_r0
        stable = stable0

        w = jnp.zeros(R, I32)
        result = jnp.full(R, NONE, I32)
        rlen = jnp.int32(0)
        wsize = jnp.int32(0)
        wbound = 0
        perm = rc.perm_init()
        idx = jnp.arange(R, dtype=I32)

        for step in rule.steps:
            op, arg1, arg2 = step.op, step.arg1, step.arg2
            if op == C.CRUSH_RULE_TAKE:
                ok = (0 <= arg1 < cmap.max_devices) or \
                    (arg1 < 0 and cmap.bucket_by_id(arg1) is not None)
                if ok:
                    w = w.at[0].set(arg1)
                    wsize = jnp.int32(1)
                    wbound = 1
            elif op == C.CRUSH_RULE_SET_CHOOSE_TRIES:
                if arg1 > 0:
                    choose_tries = arg1
            elif op == C.CRUSH_RULE_SET_CHOOSELEAF_TRIES:
                if arg1 > 0:
                    choose_leaf_tries = arg1
            elif op == C.CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
                if arg1 >= 0:
                    local_retries = arg1
            elif op == C.CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
                if arg1 >= 0:
                    local_fb = arg1
            elif op == C.CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
                if arg1 >= 0:
                    vary_r = arg1
            elif op == C.CRUSH_RULE_SET_CHOOSELEAF_STABLE:
                if arg1 >= 0:
                    stable = arg1
            elif op in (C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
                        C.CRUSH_RULE_CHOOSE_FIRSTN,
                        C.CRUSH_RULE_CHOOSELEAF_INDEP,
                        C.CRUSH_RULE_CHOOSE_INDEP):
                if wbound == 0:
                    continue
                numrep = arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                firstn = op in (C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                C.CRUSH_RULE_CHOOSE_FIRSTN)
                leafy = op in (C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
                               C.CRUSH_RULE_CHOOSELEAF_INDEP)
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    fn = make_choose_firstn(
                        rc, numrep=numrep, type_=arg2, tries=choose_tries,
                        recurse_tries=recurse_tries,
                        local_retries=local_retries,
                        fallback_retries=local_fb, recurse_to_leaf=leafy,
                        vary_r=vary_r, stable=stable, single_rep=False)
                else:
                    fn = make_choose_indep(
                        rc, numrep=numrep, type_=arg2, tries=choose_tries,
                        recurse_tries=(choose_leaf_tries
                                       if choose_leaf_tries else 1),
                        recurse_to_leaf=leafy, single_rep=False)

                o = jnp.zeros(R, I32)
                cvec = jnp.zeros(R, I32)
                osize = jnp.int32(0)
                for i in range(wbound):
                    src = w[i]
                    sidx = jnp.clip(-1 - src, 0, B - 1)
                    run = (jnp.int32(i) < wsize) & (src < 0) \
                        & ((-1 - src) < B) & (A.alg[sidx] != 0)
                    if firstn:
                        def go_f(op_):
                            o, cvec, perm = op_
                            got, o, cvec, perm = fn(
                                A, weight, x, sidx, o, osize,
                                jnp.int32(0), jnp.int32(R) - osize,
                                cvec, osize, jnp.int32(0), perm)
                            return got, o, cvec, perm

                        got, o, cvec, perm = lax.cond(
                            run, go_f,
                            lambda op_: (jnp.int32(0),) + op_,
                            (o, cvec, perm))
                        osize = osize + got
                    else:
                        out_size = jnp.minimum(
                            jnp.int32(numrep), jnp.int32(R) - osize)

                        def go_i(op_):
                            o, cvec, perm = op_
                            o, cvec, perm = fn(
                                A, weight, x, sidx, out_size, o, osize,
                                jnp.int32(0), cvec, osize, jnp.int32(0),
                                perm)
                            return o, cvec, perm

                        o, cvec, perm = lax.cond(
                            run, go_i, lambda op_: op_, (o, cvec, perm))
                        osize = osize + jnp.where(run, out_size, 0)
                if leafy:
                    o = jnp.where(idx < osize, cvec, o)
                w = o
                wsize = osize
                wbound = min(R, wbound * numrep)
            elif op == C.CRUSH_RULE_EMIT:
                src_i = idx - rlen
                take = (src_i >= 0) & (src_i < wsize)
                gathered = w[jnp.clip(src_i, 0, R - 1)]
                result = jnp.where(take, gathered, result)
                rlen = jnp.minimum(rlen + wsize, R)
                wsize = jnp.int32(0)
                wbound = 0
        return result, rlen

    return single, static, arrays_np


def build_rule_fn(cmap: CrushMap, ruleno: int, result_max: int,
                  choose_args: Optional[ChooseArgMap] = None,
                  encoded=None):
    """Compile one rule into a batched mapper.

    Returns ``(fn, static, arrays)`` where ``fn(arrays, weight_u32[D],
    xs_u32[N]) -> (results i32[N, result_max], lens i32[N])`` is jitted;
    pass updated ``arrays``/``weight`` freely — only shape changes
    recompile.  This is the TPU replacement for the reference hot loop at
    CrushTester.cc:573 / OSDMapMapping.h:18.
    """
    single, static, arrays_np = make_single_fn(
        cmap, ruleno, result_max, choose_args, encoded)
    batched = jax.jit(jax.vmap(single, in_axes=(None, None, 0)))
    return batched, static, arrays_np


def book_map_batch(sig, dt: float, n_xs: int, result_max: int,
                   first_launch: bool, h2d_bytes: int, d2h_bytes: int,
                   device_ids=None) -> None:
    """Shared perf/device-plane booking for one batched-mapper launch
    (the single-device ``BatchedMapper`` and the mesh-sharded
    ``parallel.PlacementPlane`` both land here, so `perf dump` and the
    recompile-budget gate see ONE ``crush.mapper`` story).  First-call
    compiles book separately from steady-state latency; mesh launches
    additionally book a per-device row for every participating chip."""
    _pc.inc("map_calls")
    _pc.inc("xs_mapped", n_xs)
    if first_launch:
        _pc.inc("jit_compiles")
        _pc.tinc("jit_compile_time", dt)
    else:
        _pc.tinc("map_time", dt)
        _pc.hist_add("map_lat", dt)
    if device_ids:
        device_metrics.record_mesh_launch(
            "crush.mapper", sig, dt, device_ids,
            h2d_bytes=h2d_bytes, d2h_bytes=d2h_bytes)
    else:
        device_metrics.record_launch(
            "crush.mapper", sig, dt,
            h2d_bytes=h2d_bytes, d2h_bytes=d2h_bytes)


class BatchedMapper:
    """User-facing handle: compile-per-rule cache + array residency.

    >>> m = BatchedMapper(cmap)
    >>> res, lens = m.map_batch(ruleno, xs, result_max, weight)

    ``mesh``: a ``jax.sharding.Mesh`` routes every ``map_batch``
    through the mesh-sharded ``parallel.PlacementPlane`` (PG axis
    data-parallel across the mesh devices, map arrays replicated) —
    same results, same booking, one pjit launch over all chips.
    """

    def __init__(self, cmap: CrushMap,
                 choose_args: Optional[ChooseArgMap] = None,
                 mesh=None):
        self.cmap = cmap
        self.choose_args = choose_args
        self._cache = {}
        self._compiled_sigs: set = set()  # (rule, result_max, N)
        self._encoded = encode_map(cmap, choose_args)
        self._arrays = jax.tree_util.tree_map(
            jnp.asarray, self._encoded[1])
        self._plane = None
        if mesh is not None:
            # deferred import: parallel.placement imports this module
            from ..parallel.placement import PlacementPlane

            self._plane = PlacementPlane(cmap, choose_args=choose_args,
                                         mesh=mesh,
                                         encoded=self._encoded)

    def rule_fn(self, ruleno: int, result_max: int):
        key = (ruleno, result_max)
        if key not in self._cache:
            fn, static, _ = build_rule_fn(
                self.cmap, ruleno, result_max, self.choose_args,
                encoded=self._encoded)
            self._cache[key] = (fn, static)
        return self._cache[key][0]

    @property
    def arrays(self) -> MapArrays:
        return self._arrays

    def map_batch(self, ruleno: int, xs, result_max: int, weight):
        """Map a batch: xs uint32[N], weight 16.16 uint32[max_devices]."""
        import time

        if self._plane is not None:
            return self._plane.map_batch(ruleno, xs, result_max, weight)
        fn = self.rule_fn(ruleno, result_max)
        xs = jnp.asarray(np.asarray(xs, np.uint32))
        weight = jnp.asarray(np.asarray(weight, np.uint32))
        t0 = time.monotonic()
        out = fn(self._arrays, weight, xs)
        dt = time.monotonic() - t0
        sig = (ruleno, result_max, tuple(xs.shape))
        first = sig not in self._compiled_sigs
        if first:
            self._compiled_sigs.add(sig)
        # device plane: xs + weight cross host->device, the result
        # block (results + lens, i32) crosses back when consumed
        book_map_batch(sig, dt, int(xs.shape[0]), result_max, first,
                       h2d_bytes=int(xs.size) * 4 + int(weight.size) * 4,
                       d2h_bytes=int(xs.shape[0]) * (result_max + 1) * 4)
        return out
