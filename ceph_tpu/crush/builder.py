"""Map construction helpers — the role of the reference's builder
(src/crush/builder.c: crush_make_*_bucket, crush_add_bucket,
crush_reweight_bucket) plus convenience constructors for synthetic
hierarchies (crushtool --build, src/tools/crushtool.cc:135).

All weights are 16.16 fixed point, as everywhere in CRUSH.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

from . import constants as C
from .map import Bucket, CrushMap, Rule, RuleStep


def make_straw2_bucket(items: Sequence[int], weights: Sequence[int],
                       type_: int, bid: int = 0,
                       hash_: int = C.CRUSH_HASH_RJENKINS1) -> Bucket:
    """crush_make_straw2_bucket (builder.c): weights are used raw."""
    return Bucket(id=bid, alg=C.CRUSH_BUCKET_STRAW2, type=type_,
                  hash=hash_, items=list(items),
                  item_weights=list(weights), weight=sum(weights))


def make_uniform_bucket(items: Sequence[int], item_weight: int,
                        type_: int, bid: int = 0,
                        hash_: int = C.CRUSH_HASH_RJENKINS1) -> Bucket:
    return Bucket(id=bid, alg=C.CRUSH_BUCKET_UNIFORM, type=type_,
                  hash=hash_, items=list(items), item_weight=item_weight,
                  weight=item_weight * len(items))


def make_list_bucket(items: Sequence[int], weights: Sequence[int],
                     type_: int, bid: int = 0,
                     hash_: int = C.CRUSH_HASH_RJENKINS1) -> Bucket:
    """sum_weights[i] = head prefix sum, as crush_make_list_bucket."""
    sums, acc = [], 0
    for w in weights:
        acc += w
        sums.append(acc)
    return Bucket(id=bid, alg=C.CRUSH_BUCKET_LIST, type=type_,
                  hash=hash_, items=list(items),
                  item_weights=list(weights), sum_weights=sums,
                  weight=acc)


def make_tree_bucket(items: Sequence[int], weights: Sequence[int],
                     type_: int, bid: int = 0,
                     hash_: int = C.CRUSH_HASH_RJENKINS1) -> Bucket:
    """crush_make_tree_bucket: items sit at odd node ((i+1)<<1)-1 of an
    implicit binary tree; internal node weight = sum of its subtree."""
    n = len(items)
    depth = max(1, math.ceil(math.log2(n)) + 1) if n > 1 else 1
    num_nodes = 1 << depth
    node_weights = [0] * num_nodes
    for i, w in enumerate(weights):
        node = ((i + 1) << 1) - 1
        node_weights[node] = w
        # accumulate up: parent of node j at height h is found by
        # clearing the lowest set bit run — walk ancestors
        j = node
        while True:
            low = j & -j
            parent = (j - low) | (low << 1)
            if parent >= num_nodes:
                break
            node_weights[parent] += w
            j = parent
    return Bucket(id=bid, alg=C.CRUSH_BUCKET_TREE, type=type_,
                  hash=hash_, items=list(items), num_nodes=num_nodes,
                  node_weights=node_weights, weight=sum(weights))


def calc_straw(weights: Sequence[int]) -> List[int]:
    """crush_calc_straw (builder.c), straw_calc_version=1 semantics:
    straw lengths (16.16) such that expected win probability is
    proportional to weight.  Kept for legacy straw buckets; straw2
    needs no precomputation.

    Note: v1 has NO equal-weight skip — that branch exists only in
    straw_calc_version=0 (the historical buggy behavior); at equal
    weights v1's wnext is 0, pbelow 1, and the straw carries unchanged,
    which this port reproduces (pinned by test_calc_straw_v1_values).
    """
    size = len(weights)
    reverse = sorted(range(size), key=lambda i: (weights[i], i))
    straws = [0] * size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0
    numleft = size
    i = 0
    while i < size:
        if weights[reverse[i]] == 0:
            straws[reverse[i]] = 0
            i += 1
            numleft -= 1
            continue
        straws[reverse[i]] = int(straw * 0x10000)
        i += 1
        if i == size:
            break
        wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
        numleft -= 1
        wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
        pbelow = wbelow / (wbelow + wnext)
        straw *= (1.0 / pbelow) ** (1.0 / numleft)
        lastw = float(weights[reverse[i - 1]])
    return straws


def _rebuild_payload(b: Bucket) -> None:
    """Recompute the per-alg payload from items/item_weights — the role
    of builder.c's per-alg adjust/add/remove helpers (builder.h:163-283),
    done by reconstruction (equivalent result, simpler invariant)."""
    if b.alg == C.CRUSH_BUCKET_UNIFORM:
        b.weight = b.item_weight * len(b.items)
        return
    if b.alg == C.CRUSH_BUCKET_LIST:
        t = make_list_bucket(b.items, b.item_weights, b.type, b.id, b.hash)
        b.sum_weights, b.weight = t.sum_weights, t.weight
        return
    if b.alg == C.CRUSH_BUCKET_TREE:
        t = make_tree_bucket(b.items, b.item_weights, b.type, b.id, b.hash)
        b.num_nodes, b.node_weights, b.weight = \
            t.num_nodes, t.node_weights, t.weight
        return
    if b.alg == C.CRUSH_BUCKET_STRAW:
        b.straws = calc_straw(b.item_weights)
    b.weight = sum(b.item_weights)


def bucket_add_item(b: Bucket, item: int, weight: int) -> None:
    """crush_bucket_add_item (builder.h:214)."""
    if b.alg == C.CRUSH_BUCKET_UNIFORM:
        if b.items and weight != b.item_weight:
            raise ValueError("uniform bucket requires equal item weights")
        b.item_weight = weight
        b.items.append(item)
    else:
        b.items.append(item)
        b.item_weights.append(weight)
    _rebuild_payload(b)


def bucket_remove_item(b: Bucket, item: int) -> int:
    """crush_bucket_remove_item (builder.h:232); returns the removed
    weight."""
    pos = b.items.index(item)
    b.items.pop(pos)
    if b.alg == C.CRUSH_BUCKET_UNIFORM:
        removed = b.item_weight
    else:
        removed = b.item_weights.pop(pos)
    _rebuild_payload(b)
    return removed


def bucket_adjust_item_weight(b: Bucket, item: int, weight: int) -> int:
    """crush_bucket_adjust_item_weight (builder.h:223); returns the
    weight delta."""
    pos = b.items.index(item)
    if b.alg == C.CRUSH_BUCKET_UNIFORM:
        diff = (weight - b.item_weight) * len(b.items)
        b.item_weight = weight
    else:
        diff = weight - b.item_weights[pos]
        b.item_weights[pos] = weight
    _rebuild_payload(b)
    return diff


def reweight_bucket(cmap: CrushMap, b: Bucket) -> None:
    """crush_reweight_bucket (builder.h:242): recompute this bucket's
    item weights from its children's (recursive, bottom-up)."""
    for pos, item in enumerate(b.items):
        if item < 0:
            child = cmap.bucket_by_id(item)
            if child is None:
                continue
            reweight_bucket(cmap, child)
            if b.alg == C.CRUSH_BUCKET_UNIFORM:
                b.item_weight = child.weight
            else:
                b.item_weights[pos] = child.weight
    _rebuild_payload(b)


def add_simple_rule(cmap: CrushMap, root_id: int, leaf_type: int,
                    firstn: bool = True, ruleno: int = -1,
                    rule_type: int = 1,
                    choose_type: Optional[int] = None) -> int:
    """CrushWrapper::add_simple_rule (CrushWrapper.h:1167):
    take root -> chooseleaf {firstn|indep} 0 type <leaf_type> -> emit."""
    op = (C.CRUSH_RULE_CHOOSELEAF_FIRSTN if firstn
          else C.CRUSH_RULE_CHOOSELEAF_INDEP)
    steps = [RuleStep(C.CRUSH_RULE_TAKE, root_id, 0),
             RuleStep(op, 0, leaf_type),
             RuleStep(C.CRUSH_RULE_EMIT, 0, 0)]
    return cmap.add_rule(Rule(steps=steps, type=rule_type), ruleno)


def build_hierarchy(cmap: CrushMap, spec: List[tuple],
                    device_weight: int = 0x10000) -> int:
    """Synthetic uniform hierarchy a la ``crushtool --build``:
    ``spec`` = [(type_id, fan_out), ...] bottom-up; level 0 children are
    devices.  Returns the root bucket id."""
    n_dev = 1
    for _, fan in spec:
        n_dev *= fan
    level_ids = list(range(n_dev))
    level_weights = [device_weight] * n_dev
    for type_id, fan in spec:
        next_ids, next_weights = [], []
        for i in range(0, len(level_ids), fan):
            children = level_ids[i:i + fan]
            weights = level_weights[i:i + fan]
            b = make_straw2_bucket(children, weights, type_id)
            bid = cmap.add_bucket(b)
            next_ids.append(bid)
            next_weights.append(b.weight)
        level_ids, level_weights = next_ids, next_weights
    assert len(level_ids) == 1
    cmap.max_devices = max(cmap.max_devices, n_dev)
    return level_ids[0]


def sample_cluster_map(racks: int = 3, hosts_per_rack: int = 4,
                       osds_per_host: int = 4) -> CrushMap:
    """A production-shaped 3-level straw2 map: root -> racks -> hosts ->
    osds, with one replicated chooseleaf rule 0 and one EC indep rule 1."""
    cmap = CrushMap()
    root_id = build_hierarchy(
        cmap, [(1, osds_per_host), (2, hosts_per_rack), (3, racks)])
    add_simple_rule(cmap, root_id, leaf_type=1, firstn=True, ruleno=0)
    add_simple_rule(cmap, root_id, leaf_type=1, firstn=False, ruleno=1,
                    rule_type=3)
    return cmap
