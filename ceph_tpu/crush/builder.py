"""Map construction helpers — the role of the reference's builder
(src/crush/builder.c: crush_make_*_bucket, crush_add_bucket,
crush_reweight_bucket) plus convenience constructors for synthetic
hierarchies (crushtool --build, src/tools/crushtool.cc:135).

All weights are 16.16 fixed point, as everywhere in CRUSH.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

from . import constants as C
from .map import Bucket, CrushMap, Rule, RuleStep


def make_straw2_bucket(items: Sequence[int], weights: Sequence[int],
                       type_: int, bid: int = 0,
                       hash_: int = C.CRUSH_HASH_RJENKINS1) -> Bucket:
    """crush_make_straw2_bucket (builder.c): weights are used raw."""
    return Bucket(id=bid, alg=C.CRUSH_BUCKET_STRAW2, type=type_,
                  hash=hash_, items=list(items),
                  item_weights=list(weights), weight=sum(weights))


def make_uniform_bucket(items: Sequence[int], item_weight: int,
                        type_: int, bid: int = 0,
                        hash_: int = C.CRUSH_HASH_RJENKINS1) -> Bucket:
    return Bucket(id=bid, alg=C.CRUSH_BUCKET_UNIFORM, type=type_,
                  hash=hash_, items=list(items), item_weight=item_weight,
                  weight=item_weight * len(items))


def make_list_bucket(items: Sequence[int], weights: Sequence[int],
                     type_: int, bid: int = 0,
                     hash_: int = C.CRUSH_HASH_RJENKINS1) -> Bucket:
    """sum_weights[i] = head prefix sum, as crush_make_list_bucket."""
    sums, acc = [], 0
    for w in weights:
        acc += w
        sums.append(acc)
    return Bucket(id=bid, alg=C.CRUSH_BUCKET_LIST, type=type_,
                  hash=hash_, items=list(items),
                  item_weights=list(weights), sum_weights=sums,
                  weight=acc)


def make_tree_bucket(items: Sequence[int], weights: Sequence[int],
                     type_: int, bid: int = 0,
                     hash_: int = C.CRUSH_HASH_RJENKINS1) -> Bucket:
    """crush_make_tree_bucket: items sit at odd node ((i+1)<<1)-1 of an
    implicit binary tree; internal node weight = sum of its subtree."""
    n = len(items)
    depth = max(1, math.ceil(math.log2(n)) + 1) if n > 1 else 1
    num_nodes = 1 << depth
    node_weights = [0] * num_nodes
    for i, w in enumerate(weights):
        node = ((i + 1) << 1) - 1
        node_weights[node] = w
        # accumulate up: parent of node j at height h is found by
        # clearing the lowest set bit run — walk ancestors
        j = node
        while True:
            low = j & -j
            parent = (j - low) | (low << 1)
            if parent >= num_nodes:
                break
            node_weights[parent] += w
            j = parent
    return Bucket(id=bid, alg=C.CRUSH_BUCKET_TREE, type=type_,
                  hash=hash_, items=list(items), num_nodes=num_nodes,
                  node_weights=node_weights, weight=sum(weights))


def add_simple_rule(cmap: CrushMap, root_id: int, leaf_type: int,
                    firstn: bool = True, ruleno: int = -1,
                    rule_type: int = 1,
                    choose_type: Optional[int] = None) -> int:
    """CrushWrapper::add_simple_rule (CrushWrapper.h:1167):
    take root -> chooseleaf {firstn|indep} 0 type <leaf_type> -> emit."""
    op = (C.CRUSH_RULE_CHOOSELEAF_FIRSTN if firstn
          else C.CRUSH_RULE_CHOOSELEAF_INDEP)
    steps = [RuleStep(C.CRUSH_RULE_TAKE, root_id, 0),
             RuleStep(op, 0, leaf_type),
             RuleStep(C.CRUSH_RULE_EMIT, 0, 0)]
    return cmap.add_rule(Rule(steps=steps, type=rule_type), ruleno)


def build_hierarchy(cmap: CrushMap, spec: List[tuple],
                    device_weight: int = 0x10000) -> int:
    """Synthetic uniform hierarchy a la ``crushtool --build``:
    ``spec`` = [(type_id, fan_out), ...] bottom-up; level 0 children are
    devices.  Returns the root bucket id."""
    n_dev = 1
    for _, fan in spec:
        n_dev *= fan
    level_ids = list(range(n_dev))
    level_weights = [device_weight] * n_dev
    for type_id, fan in spec:
        next_ids, next_weights = [], []
        for i in range(0, len(level_ids), fan):
            children = level_ids[i:i + fan]
            weights = level_weights[i:i + fan]
            b = make_straw2_bucket(children, weights, type_id)
            bid = cmap.add_bucket(b)
            next_ids.append(bid)
            next_weights.append(b.weight)
        level_ids, level_weights = next_ids, next_weights
    assert len(level_ids) == 1
    cmap.max_devices = max(cmap.max_devices, n_dev)
    return level_ids[0]


def sample_cluster_map(racks: int = 3, hosts_per_rack: int = 4,
                       osds_per_host: int = 4) -> CrushMap:
    """A production-shaped 3-level straw2 map: root -> racks -> hosts ->
    osds, with one replicated chooseleaf rule 0 and one EC indep rule 1."""
    cmap = CrushMap()
    root_id = build_hierarchy(
        cmap, [(1, osds_per_host), (2, hosts_per_rack), (3, racks)])
    add_simple_rule(cmap, root_id, leaf_type=1, firstn=True, ruleno=0)
    add_simple_rule(cmap, root_id, leaf_type=1, firstn=False, ruleno=1,
                    rule_type=3)
    return cmap
