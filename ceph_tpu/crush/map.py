"""The CRUSH map data model.

A host-side, mutation-friendly representation of the crush map: buckets
(the weighted hierarchy), rules (placement programs) and tunables.  This is
the role of ``struct crush_map`` (src/crush/crush.h:344-451) plus the JSON
(de)serialization the framework uses natively; the flat array encoding the
TPU mapper consumes is derived from this by ``map_arrays.py``.

Bucket ids are negative (id = -1 - index); devices are >= 0, exactly as in
the reference, so maps round-trip against the golden schema emitted by the
reference builder.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..common import encoding
from . import constants as C


@dataclass
class Tunables:
    """Mapping behavior knobs (crush.h:363-411).  Defaults = "optimal"
    (builder.c set_optimal_crush_map)."""

    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    choose_total_tries: int = 50
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1

    @classmethod
    def legacy(cls) -> "Tunables":
        """The most ancient behavior (builder.c set_legacy_crush_map)."""
        return cls(2, 5, 19, 0, 0, 0)

    def to_dict(self):
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d):
        return cls(**{k: int(v) for k, v in d.items()})


@dataclass
class Bucket:
    """One weighted container in the hierarchy (crush.h:219-333).

    ``weight`` and all per-item weights are 16.16 fixed point.  Per-alg
    payload fields:
      uniform: item_weight (single value)
      list:    item_weights + sum_weights (prefix sums from the tail)
      tree:    node_weights over the implicit binary tree, num_nodes
      straw:   item_weights + precomputed straws
      straw2:  item_weights
    """

    id: int
    alg: int
    type: int
    items: List[int]
    hash: int = C.CRUSH_HASH_RJENKINS1
    weight: int = 0
    item_weight: int = 0
    item_weights: List[int] = field(default_factory=list)
    sum_weights: List[int] = field(default_factory=list)
    node_weights: List[int] = field(default_factory=list)
    num_nodes: int = 0
    straws: List[int] = field(default_factory=list)

    @property
    def size(self) -> int:
        return len(self.items)

    def item_weight_at(self, pos: int) -> int:
        """crush_get_bucket_item_weight semantics (crush.c)."""
        if pos < 0 or pos >= self.size:
            return 0
        if self.alg == C.CRUSH_BUCKET_UNIFORM:
            return self.item_weight
        if self.alg == C.CRUSH_BUCKET_TREE:
            return self.node_weights[((pos + 1) << 1) - 1]
        return self.item_weights[pos]

    def to_dict(self):
        d = {
            "id": self.id,
            "alg": self.alg,
            "hash": self.hash,
            "type": self.type,
            "weight": self.weight,
            "size": self.size,
            "items": list(self.items),
        }
        if self.alg == C.CRUSH_BUCKET_UNIFORM:
            d["item_weight"] = self.item_weight
        elif self.alg == C.CRUSH_BUCKET_LIST:
            d["item_weights"] = list(self.item_weights)
            d["sum_weights"] = list(self.sum_weights)
        elif self.alg == C.CRUSH_BUCKET_TREE:
            d["num_nodes"] = self.num_nodes
            d["node_weights"] = list(self.node_weights)
        elif self.alg == C.CRUSH_BUCKET_STRAW:
            d["item_weights"] = list(self.item_weights)
            d["straws"] = list(self.straws)
        else:
            d["item_weights"] = list(self.item_weights)
        return d

    @classmethod
    def from_dict(cls, d):
        return cls(
            id=d["id"],
            alg=d["alg"],
            hash=d.get("hash", C.CRUSH_HASH_RJENKINS1),
            type=d["type"],
            weight=d.get("weight", 0),
            items=list(d["items"]),
            item_weight=d.get("item_weight", 0),
            item_weights=list(d.get("item_weights", [])),
            sum_weights=list(d.get("sum_weights", [])),
            node_weights=list(d.get("node_weights", [])),
            num_nodes=d.get("num_nodes", 0),
            straws=list(d.get("straws", [])),
        )


@dataclass
class RuleStep:
    op: int
    arg1: int = 0
    arg2: int = 0


@dataclass
class Rule:
    """A placement program: a list of steps for the rule VM
    (crush.h:78-85; executed by crush_do_rule, mapper.c:878)."""

    steps: List[RuleStep]
    type: int = 1  # pool type tag (replicated/erasure); not used by the VM

    def to_dict(self):
        return {"steps": [[s.op, s.arg1, s.arg2] for s in self.steps],
                "type": self.type}

    @classmethod
    def from_dict(cls, d):
        return cls(steps=[RuleStep(*s) for s in d["steps"]],
                   type=d.get("type", 1))


@dataclass
class ChooseArg:
    """Per-bucket substitute weights/ids for placement (crush.h:263-268):
    the balancer's knob for steering straw2 draws without changing the
    actual hierarchy weights."""

    ids: Optional[List[int]] = None
    weight_set: Optional[List[List[int]]] = None  # [position][item]


class ChooseArgMap(dict):
    """bucket_index -> ChooseArg (crush.h:281-284)."""


class CrushMap:
    """The mutable host-side crush map."""

    # wire/disk JSON form version (wirecheck entry crush.map_json):
    # to_json wraps the dict in the versioned envelope; from_json also
    # accepts the pre-envelope raw dict (writer v0 — the golden-vector
    # era) so archived maps keep decoding
    STRUCT_V = 1
    COMPAT_V = 1

    def __init__(self, tunables: Optional[Tunables] = None):
        self.buckets: Dict[int, Bucket] = {}  # keyed by *bucket index* (-1-id)
        self.rules: Dict[int, Rule] = {}
        self.tunables = tunables or Tunables()
        self.max_devices = 0
        self._max_buckets = 0
        # choose_args sets keyed by an arbitrary index (the reference keys
        # them by pool id or a magic constant inside OSDMap)
        self.choose_args: Dict[object, ChooseArgMap] = {}

    # -- structure ----------------------------------------------------
    @property
    def max_buckets(self) -> int:
        return self._max_buckets

    def bucket_by_id(self, bid: int) -> Optional[Bucket]:
        return self.buckets.get(-1 - bid)

    def add_bucket(self, bucket: Bucket) -> int:
        """Insert with an explicit id (bucket.id < 0) or allocate the next
        free index if bucket.id == 0 (builder.c crush_add_bucket)."""
        if bucket.id == 0:
            idx = 0
            while idx in self.buckets:
                idx += 1
            bucket.id = -1 - idx
        idx = -1 - bucket.id
        if idx < 0:
            raise ValueError(f"bucket id must be negative, got {bucket.id}")
        if idx in self.buckets:
            raise ValueError(f"bucket id {bucket.id} already present")
        self.buckets[idx] = bucket
        self._max_buckets = max(self._max_buckets, idx + 1)
        self._note_devices(bucket.items)
        return bucket.id

    def _note_devices(self, items):
        for it in items:
            if it >= 0:
                self.max_devices = max(self.max_devices, it + 1)

    def add_rule(self, rule: Rule, ruleno: int = -1) -> int:
        if ruleno < 0:
            ruleno = 0
            while ruleno in self.rules:
                ruleno += 1
        if ruleno in self.rules:
            raise ValueError(f"rule {ruleno} already present")
        self.rules[ruleno] = rule
        return ruleno

    @property
    def max_rules(self) -> int:
        return (max(self.rules) + 1) if self.rules else 0

    # -- (de)serialization --------------------------------------------
    def to_dict(self):
        d = {
            "max_devices": self.max_devices,
            "max_buckets": self.max_buckets,
            "max_rules": self.max_rules,
            "tunables": self.tunables.to_dict(),
            "buckets": [self.buckets[i].to_dict()
                        for i in sorted(self.buckets)],
            "rules": [{"ruleno": rno, **self.rules[rno].to_dict()}
                      for rno in sorted(self.rules)],
        }
        if self.choose_args:
            d["choose_args"] = {
                str(key): [{"bucket_index": bi,
                            "ids": ca.ids,
                            "weight_set": ca.weight_set}
                           for bi, ca in sorted(cam.items())]
                for key, cam in self.choose_args.items()
            }
        return d

    @classmethod
    def from_dict(cls, d) -> "CrushMap":
        m = cls(tunables=Tunables.from_dict(d.get("tunables", {})))
        for bd in d.get("buckets", []):
            m.add_bucket(Bucket.from_dict(bd))
        for rd in d.get("rules", []):
            m.add_rule(Rule.from_dict(rd), rd.get("ruleno", -1))
        m.max_devices = max(m.max_devices, d.get("max_devices", 0))
        ca_in = d.get("choose_args")
        if isinstance(ca_in, list):
            # legacy golden-vector format: one anonymous set
            cam = ChooseArgMap()
            for e in ca_in:
                cam[e["bucket_index"]] = ChooseArg(
                    ids=e.get("ids"), weight_set=e.get("weight_set"))
            m.choose_args["golden"] = cam
        elif isinstance(ca_in, dict):
            for key, entries in ca_in.items():
                cam = ChooseArgMap()
                for e in entries:
                    cam[e["bucket_index"]] = ChooseArg(
                        ids=e.get("ids"),
                        weight_set=e.get("weight_set"))
                # JSON stringifies int keys (pool ids); OSDMap looks
                # choose_args up by int, so convert back
                if isinstance(key, str) and key.lstrip("-").isdigit():
                    key = int(key)
                m.choose_args[key] = cam
        return m

    def to_json(self) -> str:
        return encoding.encode(self.to_dict(), self.STRUCT_V,
                               self.COMPAT_V)

    @classmethod
    def from_json(cls, s: str) -> "CrushMap":
        v, d = encoding.decode_any(s, supported=cls.STRUCT_V,
                                   struct="crush.map_json")
        try:
            return cls.from_dict(d)
        except (KeyError, TypeError, ValueError, AttributeError) as e:
            raise encoding.MalformedInput(
                f"crush.map_json v{v}: bad payload: {e!r}")
