"""Speculative straw2 firstn mapper — the divergence-tolerant fast path.

The general batched mapper (``mapper_jax.py``) reproduces the reference's
retry descent (crush_choose_firstn, src/crush/mapper.c:438-626) as a
per-lane ``lax.while_loop``.  Under ``vmap`` that loop runs until the
*slowest* lane finishes and every iteration does only one small descent
step, so the program the TPU sees is long, serial, and narrow — the exact
shape the MXU hates.

This module compiles the *common case* — straw2-only hierarchies mapped by
a ``take / chooseleaf firstn / emit`` rule under modern tunables
(choose_local_tries=0, choose_local_fallback_tries=0) — into a dense
speculative program instead:

- One "try" of the reference's retry loop is a pure descent from the take
  root (r = rep + ftotal, mapper.c:497) whose depth is bounded by the
  static hierarchy depth.  Nothing about try ``ftotal`` depends on try
  ``ftotal-1`` *except* which one is selected, so K tries are evaluated
  at once as (K, fanout)-shaped straw2 draws and the reference's retry
  semantics collapse to "first non-failing try wins" (masked argmax).
- The chooseleaf recursion (mapper.c:548-572: numrep=1, its own retry
  budget ``recurse_tries``, r' = (stable ? 0 : outpos) + (vary_r ?
  r >> (vary_r-1) : 0) + ftotal') is unrolled the same way: with
  chooseleaf_descend_once (tunables since firefly) it is a single pure
  descent per outer try.
- The per-rep round loop remains a ``lax.while_loop``, but its body now
  retires K tries per iteration and virtually always exits after one.

Bit-exactness contract: identical (result, len) to ``mapper_ref.py`` /
``mapper_jax.py`` for every eligible (map, rule, tunables) combination —
asserted for all golden maps in ``tests/test_mapper_spec.py``.  Eligible
rules are detected by :func:`analyze`; ineligible ones raise
:class:`Ineligible` and callers fall back to the general mapper.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax

if not jax.config.jax_enable_x64:
    jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from jax import lax  # noqa: E402

from . import constants as C  # noqa: E402
from . import hash as H  # noqa: E402
from .ln import (LL_NP, RH_LH_NP, ln16_table, recip64,  # noqa: E402
                 straw2_draw, straw2_key)
from .map import ChooseArgMap, CrushMap  # noqa: E402
from .map_arrays import encode_map  # noqa: E402

I32 = jnp.int32
U32 = jnp.uint32
NONE = C.CRUSH_ITEM_NONE
UNDEF = C.CRUSH_ITEM_UNDEF

# per-k try status codes
_DESC = 0     # still descending
_OK = 1       # reached an item of the wanted type (device for inner)
_FAIL = 2     # reject/collide/empty — costs one ftotal, retry from root
_SKIP = 3     # terminal: give up this rep (over / unresolvable child)


class Ineligible(ValueError):
    """The (map, rule, tunables) combination needs the general mapper."""


@dataclass(frozen=True)
class Plan:
    """Static facts the speculative compiler needs (all trace-time)."""

    root_idx: int        # bucket index of the take root
    numrep: int
    type_: int           # target type of the choose step
    leafy: bool          # chooseleaf (recurse to device) vs choose type 0
    firstn: bool         # firstn (compacting) vs indep (positional)
    tries: int           # outer retry budget (choose_total_tries + 1 rule)
    recurse_tries: int   # inner retry budget (1 under descend_once)
    vary_r: int
    stable: int
    depth_outer: int     # max descent levels root -> anywhere
    depth_inner: int     # max descent levels below a type_ bucket


def _max_depth(cmap: CrushMap, idx: int, _seen=()) -> int:
    """Longest chain of bucket hops starting at bucket index ``idx`` (a
    descent performs one choose per hop, so this bounds any terminating
    descent).  Maps are forests (builder/wrapper cannot create cycles);
    a cycle would mean the C descent doesn't terminate either."""
    b = cmap.buckets.get(idx)
    if b is None:
        return 0
    if idx in _seen:
        raise Ineligible("bucket graph has a cycle")
    best = 1
    for it in b.items:
        if it < 0 and (-1 - it) in cmap.buckets:
            best = max(best, 1 + _max_depth(cmap, -1 - it, _seen + (idx,)))
    return best


def analyze(cmap: CrushMap, ruleno: int, result_max: int) -> Plan:
    """Decide eligibility and extract the static plan.

    Eligible iff: every bucket is straw2; the rule is one
    ``take`` / ``choose(leaf) firstn`` / ``emit`` block (SET_* tunable
    steps allowed); the effective local retry knobs are 0 (modern
    tunables — mapper.c:444-449 never takes the retry_bucket or
    perm-fallback paths then); the inner budget unrolls (<= 4); and
    numrep fits result_max.
    """
    for b in cmap.buckets.values():
        if b.alg != C.CRUSH_BUCKET_STRAW2:
            raise Ineligible(f"bucket alg {b.alg} != straw2")
    t = cmap.tunables
    rule = cmap.rules[ruleno]

    choose_tries = t.choose_total_tries + 1  # mapper.c:906 heritage
    choose_leaf_tries = 0
    local_retries = t.choose_local_tries
    local_fb = t.choose_local_fallback_tries
    vary_r = t.chooseleaf_vary_r
    stable = t.chooseleaf_stable

    root = None
    choose = None
    emitted = False
    for step in rule.steps:
        op, arg1, arg2 = step.op, step.arg1, step.arg2
        if emitted:
            raise Ineligible("steps after emit")
        if op == C.CRUSH_RULE_SET_CHOOSE_TRIES:
            if arg1 > 0:
                choose_tries = arg1
        elif op == C.CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            if arg1 > 0:
                choose_leaf_tries = arg1
        elif op == C.CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
            if arg1 >= 0:
                local_retries = arg1
        elif op == C.CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if arg1 >= 0:
                local_fb = arg1
        elif op == C.CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
            if arg1 >= 0:
                vary_r = arg1
        elif op == C.CRUSH_RULE_SET_CHOOSELEAF_STABLE:
            if arg1 >= 0:
                stable = arg1
        elif op == C.CRUSH_RULE_TAKE:
            if root is not None or choose is not None:
                raise Ineligible("multiple takes")
            if arg1 >= 0 or cmap.bucket_by_id(arg1) is None:
                raise Ineligible("take target is not an existing bucket")
            root = -1 - arg1
        elif op in (C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
                    C.CRUSH_RULE_CHOOSE_FIRSTN,
                    C.CRUSH_RULE_CHOOSELEAF_INDEP,
                    C.CRUSH_RULE_CHOOSE_INDEP):
            if root is None or choose is not None:
                raise Ineligible("choose without take / multiple chooses")
            leafy = op in (C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
                           C.CRUSH_RULE_CHOOSELEAF_INDEP)
            firstn = op in (C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
                            C.CRUSH_RULE_CHOOSE_FIRSTN)
            numrep = arg1
            if numrep <= 0:
                numrep += result_max
            if not (0 < numrep <= result_max):
                raise Ineligible("numrep outside [1, result_max]")
            if numrep > 16:
                raise Ineligible("numrep unroll bound exceeded")
            if not leafy and arg2 != 0:
                raise Ineligible("choose of a non-device type")
            if not firstn and leafy and arg2 == 0:
                # the reference writes the candidate device into out2
                # BEFORE the is_out check here (mapper.c:772-776), so
                # an all-rejected slot leaks its last rejected device
                # into the result; reproducing that quirk isn't worth
                # the complexity — fall back to the general VM
                raise Ineligible("chooseleaf indep of type 0 "
                                 "(out2 pre-is_out leak quirk)")
            choose = (numrep, arg2, leafy, firstn)
        elif op == C.CRUSH_RULE_EMIT:
            if choose is None:
                raise Ineligible("emit without choose")
            emitted = True
        else:
            raise Ineligible(f"unsupported step op {op}")
    if not emitted:
        raise Ineligible("rule never emits")
    numrep, type_, leafy, firstn = choose
    if firstn and (local_retries != 0 or local_fb != 0):
        # indep has no local-retry paths at all (mapper.c:633-821),
        # so legacy local tunables only disqualify firstn rules
        raise Ineligible("legacy local retry tunables in force")
    if leafy:
        if choose_leaf_tries:
            recurse_tries = choose_leaf_tries
        elif firstn and t.chooseleaf_descend_once:
            recurse_tries = 1
        elif firstn:
            recurse_tries = choose_tries
        else:
            recurse_tries = 1  # indep default (mapper_jax:692)
    else:
        recurse_tries = 1
    if recurse_tries > 4:
        raise Ineligible(f"recurse_tries {recurse_tries} unroll bound")

    depth_outer = _max_depth(cmap, root)
    depth_inner = 1
    if leafy and type_ > 0:
        depths = [_max_depth(cmap, i) for i, b in cmap.buckets.items()
                  if b.type == type_]
        depth_inner = max(depths) if depths else 1
    return Plan(root_idx=root, numrep=numrep, type_=type_, leafy=leafy,
                firstn=firstn, tries=choose_tries,
                recurse_tries=recurse_tries,
                vary_r=vary_r, stable=stable,
                depth_outer=depth_outer, depth_inner=depth_inner)


def make_single_spec(cmap: CrushMap, ruleno: int, result_max: int,
                     choose_args: Optional[ChooseArgMap] = None,
                     encoded=None, k_tries: int = 8):
    """The unjitted single-x speculative program:
    ``single(arrays, weight, x) -> (result i32[R], len i32)``.

    Raises :class:`Ineligible` when the rule needs the general mapper.
    Returns ``(single, static, arrays_np)`` like
    ``mapper_jax.make_single_fn``.
    """
    plan = analyze(cmap, ruleno, result_max)
    static, arrays_np = encoded if encoded is not None \
        else encode_map(cmap, choose_args)
    mode = os.environ.get("CEPH_TPU_STRAW2", "")
    if mode not in ("table", "compute"):
        mode = "table"  # best in this flat-shaped program on every backend
    use_table = mode == "table"
    ln16 = jnp.asarray(ln16_table()) if use_table else None
    tabs = None if use_table else (jnp.asarray(RH_LH_NP),
                                   jnp.asarray(LL_NP))
    S = static.max_size
    B = static.max_buckets
    R = result_max
    K = max(1, min(k_tries, plan.tries))
    maxdev = static.max_devices
    U64MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)

    def straw2_k(A, rw, x, cur, r, pos):
        """straw2 choose (mapper.c:287-362) over a (K,) vector of bucket
        indices; ``pos`` is the choose_args position (the C outpos) and
        ``rw`` the precomputed weight reciprocals (division-free key)."""
        if static.has_choose_args:
            p = jnp.minimum(pos, static.max_positions - 1)
            wts = A.arg_weights[cur, p]
            rec = rw[cur, p] if use_table else None
            ids = A.arg_ids[cur]
        else:
            wts = A.weights[cur]
            rec = rw[cur] if use_table else None
            ids = A.items[cur]
        h = H.crush_hash32_3(jnp.uint32(x), ids.astype(U32),
                             r[:, None].astype(U32))
        h = jnp.where(A.bhash[cur][:, None] == C.CRUSH_HASH_RJENKINS1,
                      h, jnp.uint32(0))
        lane = jnp.arange(S, dtype=I32)
        in_bucket = lane[None, :] < A.size[cur][:, None]
        if use_table:
            keys = straw2_key(h, wts, rec, xp=jnp, ln_tab=ln16)
            keys = jnp.where(in_bucket, keys, U64MAX)
            return A.items[cur, jnp.argmin(keys, axis=1)]
        draws = straw2_draw(h & jnp.uint32(0xFFFF), wts, xp=jnp,
                            tables=tabs)
        draws = jnp.where(in_bucket, draws, jnp.int64(C.S64_MIN))
        return A.items[cur, jnp.argmax(draws, axis=1)]

    def classify(A, item):
        is_neg = item < 0
        cidx = jnp.clip(-1 - item, 0, B - 1)
        exists = is_neg & ((-1 - item) < B) & (A.alg[cidx] != 0)
        itemtype = jnp.where(is_neg, jnp.where(exists, A.btype[cidx], -1),
                             0)
        return itemtype, cidx, exists

    def is_out(weight, item, x):
        """mapper.c:402-416 over a (K,) item vector."""
        wmax = weight.shape[0]
        w = weight[jnp.clip(item, 0, wmax - 1)]
        h = H.crush_hash32_2(jnp.uint32(x), item.astype(U32)) \
            & jnp.uint32(0xFFFF)
        return jnp.where(item >= wmax, True,
                         jnp.where(w >= 0x10000, False,
                                   jnp.where(w == 0, True, h >= w)))

    def seg_any_eq(vec, n, item):
        """any(vec[i] == item_k for i < n) -> bool (K,)."""
        idx = jnp.arange(vec.shape[0], dtype=I32)
        return jnp.any((idx[None, :] < n) & (vec[None, :] == item[:, None]),
                       axis=1)

    def descend(A, rw, x, start, r, pos, want_type, levels):
        """Lane-parallel pure descents: from bucket indices ``start``
        choose with rank ``r`` per level until an item of ``want_type``
        appears (mapper.c:497-546 minus the retry paths analyze()
        ruled out).  Lane count = len(start) — K speculative tries for
        firstn, numrep slots for indep.
        Returns (status, item, item_bidx), each start-shaped."""
        cur = start
        status = jnp.zeros_like(start)
        fitem = jnp.zeros_like(start)
        fcidx = jnp.zeros_like(start)
        for _ in range(levels):
            item = straw2_k(A, rw, x, cur, r, pos)
            empty = A.size[cur] == 0
            over = item >= maxdev
            itemtype, cidx, exists = classify(A, item)
            want = itemtype == want_type
            new = jnp.where(empty, _FAIL,
                            jnp.where(over, _SKIP,
                                      jnp.where(want, _OK,
                                                jnp.where(exists, _DESC,
                                                          _SKIP))))
            act = status == _DESC
            fitem = jnp.where(act & (new == _OK), item, fitem)
            fcidx = jnp.where(act & (new == _OK), cidx, fcidx)
            cur = jnp.where(act & (new == _DESC), cidx, cur)
            status = jnp.where(act, new, status)
        # levels bounds every terminating descent; anything still
        # descending would not terminate under the C semantics either
        status = jnp.where(status == _DESC, _FAIL, status)
        return status, fitem, fcidx

    def leaf_try(A, rw, weight, x, host_idx, r_in, pos, out2, outpos):
        """One inner try (mapper.c:548-572 recursion, numrep=1): descent
        host->device plus the device checks.  Returns (status, dev)."""
        st, dev, _ = descend(A, rw, x, host_idx, r_in, pos, 0,
                             plan.depth_inner)
        ok = st == _OK
        bad = ok & (seg_any_eq(out2, outpos, dev)
                    | is_out(weight, dev, x))
        return jnp.where(bad, _FAIL, st), dev

    def single_indep(A, weight, x, rw):
        """crush_choose_indep (mapper.c:633-821) as dense rounds: the
        breadth-first structure is already a batch — every open slot's
        descent vectorizes, with a sequential unrolled commit pass that
        reproduces the reference's in-round collision ordering (slot j
        sees slots < j placed this round).  Positional: failed slots
        stay NONE."""
        # analyze() guarantees numrep <= result_max, so the segment
        # is exactly [0, numrep)
        assert plan.numrep <= R
        NR = plan.numrep
        js = jnp.arange(plan.numrep, dtype=I32)
        out = jnp.full(R, UNDEF, I32)    # hosts
        out2 = jnp.full(R, UNDEF, I32)   # devices
        root_vec = jnp.full((plan.numrep,), plan.root_idx, I32)
        pos0 = jnp.int32(0)  # the C passes outpos (0 here) as position

        def round_cond(st):
            ftotal, left, out, out2 = st
            return (left > 0) & (ftotal < plan.tries)

        def round_body(st):
            ftotal, left, out, out2 = st
            # straw2-only: no uniform buckets, so the rank multiplier
            # is always numrep (mapper.c:653-660)
            r = (js + plan.numrep * ftotal).astype(I32)
            ost, host, hidx = descend(A, rw, x, root_vec, r, pos0,
                                      plan.type_, plan.depth_outer)
            found = ost == _OK
            if plan.leafy and plan.type_ > 0:
                # inner: rep=slot, parent_r=r, single round under the
                # default recurse budget (r_in = slot + r + n*ft_in)
                dev = jnp.zeros_like(host)
                got = jnp.zeros((plan.numrep,), bool)
                dead = jnp.zeros((plan.numrep,), bool)
                for t_in in range(plan.recurse_tries):
                    # the inner's choose_args position is the SLOT
                    # index (the recursion's outpos param,
                    # mapper_jax.py:546), vectorized per lane; no
                    # device dedup: the inner indep's collide segment
                    # is its own single slot (mapper_jax.py:508-516)
                    ist, d = leaf_try(
                        A, rw, weight, x, hidx,
                        (js + r + plan.numrep * t_in).astype(I32),
                        js, out2, jnp.int32(0))
                    take = found & ~got & ~dead & (ist == _OK)
                    dev = jnp.where(take, d, dev)
                    got = got | take
                    dead = dead | (~got & (ist == _SKIP))
                cand = found & got
            else:
                dev = host
                cand = found & ~is_out(weight, host, x)

            # sequential commit: the C fills slots in order, so slot
            # j's collision check sees this round's earlier placements
            idx = jnp.arange(R, dtype=I32)
            for j in range(NR):
                slot_open = out[j] == UNDEF
                collide = jnp.any((idx < NR) & (out == host[j]))
                place = cand[j] & slot_open & ~collide
                term = (ost[j] == _SKIP) & slot_open
                out = jnp.where(place | term,
                                out.at[j].set(jnp.where(place, host[j],
                                                        NONE)), out)
                out2 = jnp.where(place | term,
                                 out2.at[j].set(jnp.where(place, dev[j],
                                                          NONE)), out2)
                left = left - (place | term).astype(I32)
            return ftotal + 1, left, out, out2

        st = (jnp.int32(0), jnp.int32(NR), out, out2)
        _, _, out, out2 = lax.while_loop(round_cond, round_body, st)
        result = out2 if plan.leafy else out
        idx = jnp.arange(R, dtype=I32)
        result = jnp.where(idx < NR,
                           jnp.where(result == UNDEF, NONE, result),
                           NONE)
        return result, jnp.int32(NR)

    def single(A, weight, x):
        # weight reciprocals: unbatched under vmap (depend only on A), so
        # they are computed once per launch, not per lane
        rw = None
        if use_table:
            rw = recip64(A.arg_weights, xp=jnp) if static.has_choose_args \
                else recip64(A.weights, xp=jnp)
        if not plan.firstn:
            return single_indep(A, weight, x, rw)
        out = jnp.full(R, NONE, I32)
        out2 = jnp.full(R, NONE, I32)
        outpos = jnp.int32(0)
        ks = jnp.arange(K, dtype=I32)

        for rep in range(plan.numrep):
            def round_body(st, rep=rep):
                ftotal, done, succ, hostv, devv = st
                r = (rep + ftotal + ks).astype(I32)
                ost, host, hidx = descend(A, rw, x,
                                          jnp.full((K,), plan.root_idx,
                                                   I32),
                                          r, outpos, plan.type_,
                                          plan.depth_outer)
                found = ost == _OK
                collide = found & seg_any_eq(out, outpos, host)

                if plan.leafy and plan.type_ > 0:
                    # chooseleaf recursion, unrolled over its try budget
                    sub_r = (r >> (plan.vary_r - 1)) if plan.vary_r \
                        else jnp.zeros((K,), I32)
                    rep_in = jnp.int32(0) if plan.stable else outpos
                    dev = jnp.zeros((K,), I32)
                    got = jnp.zeros((K,), bool)
                    dead = jnp.zeros((K,), bool)
                    for j in range(plan.recurse_tries):
                        ist, d = leaf_try(A, rw, weight, x, hidx,
                                          (rep_in + sub_r + j).astype(I32),
                                          outpos, out2, outpos)
                        take = found & ~got & ~dead & (ist == _OK)
                        dev = jnp.where(take, d, dev)
                        got = got | take
                        dead = dead | (~got & (ist == _SKIP))
                    live = found & ~collide & got
                else:
                    # direct device choose (type 0): out-check the item
                    dev = host
                    live = found & ~collide & ~is_out(weight, host, x)

                eff = jnp.where(found & ~live, _FAIL, ost)
                # tries beyond the rep's remaining budget read as give-up
                eff = jnp.where(ftotal + ks < plan.tries, eff, _SKIP)
                pick = jnp.argmax(eff != _FAIL)
                any_pick = jnp.any(eff != _FAIL)
                win = any_pick & (eff[pick] == _OK)
                return (ftotal + K, any_pick, succ | win,
                        jnp.where(win, host[pick], hostv),
                        jnp.where(win, dev[pick], devv))

            def round_cond(st):
                return (~st[1]) & (st[0] < plan.tries)

            st = (jnp.int32(0), jnp.bool_(False), jnp.bool_(False),
                  jnp.int32(0), jnp.int32(0))
            _, _, succ, host, dev = lax.while_loop(round_cond, round_body,
                                                   st)
            slot = jnp.clip(outpos, 0, R - 1)
            out = jnp.where(succ, out.at[slot].set(host), out)
            out2 = jnp.where(succ, out2.at[slot].set(dev), out2)
            outpos = outpos + succ.astype(I32)

        result = out2 if plan.leafy else out
        idx = jnp.arange(R, dtype=I32)
        result = jnp.where(idx < outpos, result, NONE)
        return result, outpos

    return single, static, arrays_np


def build_spec_rule_fn(cmap: CrushMap, ruleno: int, result_max: int,
                       choose_args: Optional[ChooseArgMap] = None,
                       encoded=None, k_tries: int = 8):
    """Compile one eligible rule into a jitted batched speculative mapper
    with the same signature as ``mapper_jax.build_rule_fn``."""
    single, static, arrays_np = make_single_spec(
        cmap, ruleno, result_max, choose_args, encoded, k_tries)
    batched = jax.jit(jax.vmap(single, in_axes=(None, None, 0)))
    return batched, static, arrays_np


class SpeculativeMapper:
    """Drop-in alternative to ``BatchedMapper`` for eligible rules.

    >>> m = SpeculativeMapper(cmap)          # raises Ineligible lazily
    >>> res, lens = m.map_batch(ruleno, xs, result_max, weight)
    """

    def __init__(self, cmap: CrushMap,
                 choose_args: Optional[ChooseArgMap] = None,
                 k_tries: int = 8):
        self.cmap = cmap
        self.choose_args = choose_args
        self.k_tries = k_tries
        self._cache = {}
        self._encoded = encode_map(cmap, choose_args)
        self._arrays = jax.tree_util.tree_map(jnp.asarray,
                                              self._encoded[1])

    def rule_fn(self, ruleno: int, result_max: int):
        key = (ruleno, result_max)
        if key not in self._cache:
            fn, _, _ = build_spec_rule_fn(
                self.cmap, ruleno, result_max, self.choose_args,
                encoded=self._encoded, k_tries=self.k_tries)
            self._cache[key] = fn
        return self._cache[key]

    @property
    def arrays(self):
        return self._arrays

    def map_batch(self, ruleno: int, xs, result_max: int, weight):
        fn = self.rule_fn(ruleno, result_max)
        xs = jnp.asarray(np.asarray(xs, np.uint32))
        weight = jnp.asarray(np.asarray(weight, np.uint32))
        return fn(self._arrays, weight, xs)
