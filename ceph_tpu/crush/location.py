"""CrushLocation — where a daemon lives in the hierarchy.

The role of src/crush/CrushLocation.cc: each OSD declares its position
as ``type=name`` pairs ("root=default rack=r1 host=node3"), sourced
from the ``crush_location`` config option (or a hook script in the
reference); on boot the map is updated with create-or-move semantics
(`ceph osd crush create-or-move`) so daemons land in the right failure
domain automatically.
"""

from __future__ import annotations

from typing import Dict

from .wrapper import CrushWrapper


def parse_loc(spec: str) -> Dict[str, str]:
    """'root=default host=node1' -> {'root': 'default', ...}
    (CrushLocation::update_from_conf parsing; '=' required)."""
    out: Dict[str, str] = {}
    for token in spec.replace(",", " ").split():
        key, sep, value = token.partition("=")
        if not sep or not key or not value:
            raise ValueError(f"bad crush location token {token!r}")
        out[key] = value
    return out


def format_loc(loc: Dict[str, str]) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted(loc.items()))


def default_location(hostname: str,
                     root: str = "default") -> Dict[str, str]:
    """The reference's fallback: host=<hostname> root=default."""
    return {"host": hostname, "root": root}


def _lowest_existing(wrapper: CrushWrapper,
                     loc: Dict[str, str]):
    """The id of loc's lowest bucket if it already exists — a PURE
    lookup (no bucket creation/linking side effects)."""
    order = sorted((wrapper.get_type_id(t), n) for t, n in loc.items())
    if not order:
        raise ValueError("empty crush location")
    _tid, name = order[0]
    return wrapper.get_item_id(name) if wrapper.name_exists(name) \
        else None


def create_or_move_item(wrapper: CrushWrapper, item: int, weight: int,
                        name: str, loc: Dict[str, str]) -> bool:
    """`ceph osd crush create-or-move` semantics: insert when absent,
    relocate (keeping the existing weight AND device class) when the
    direct parent differs.  Returns True when the map changed; a
    no-move call leaves the map untouched (no speculative bucket
    creation)."""
    if not wrapper.name_map.get(item):
        wrapper.insert_item(item, weight, name, loc)
        return True
    parent = wrapper.get_immediate_parent_id(item)
    if parent is not None and \
            parent == _lowest_existing(wrapper, loc):
        return False
    cur_weight = wrapper.get_item_weight(item)
    cur_class = wrapper.get_item_class(item)
    wrapper.remove_item(item)
    wrapper.insert_item(item, cur_weight, name, loc)
    if cur_class is not None:  # remove_item pops the class; restore
        wrapper.set_item_class(item, cur_class)
    return True
