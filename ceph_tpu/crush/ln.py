"""Fixed-point 2^44*log2(x+1) — the heart of the straw2 draw.

Bit-exact reimplementation of the reference semantics
(crush_ln, src/crush/mapper.c:226-268): normalize the 17-bit input so its
top bit sits at position 15/16, split into a coarse 7-bit index into the
reciprocal/log tables and a fine 8-bit correction index, and assemble the
result as ``(iexpon << 44) + ((LH + LL) >> 4)``.

Array-generic: pass ``xp=numpy`` (default, host tools and the scalar
reference mapper) or ``xp=jax.numpy`` (inside jit; also pass device-resident
``tables``).  Verified against the full 16-bit-domain sweep in
tests/golden/crush_ln.json.
"""

import numpy as np

from ._ln_tables import LL_TBL, RH_LH_TBL

RH_LH_NP = np.array(RH_LH_TBL, dtype=np.uint64)
LL_NP = np.array(LL_TBL, dtype=np.uint64)


def crush_ln(xin, xp=np, tables=None):
    """Vectorized crush_ln.  ``xin``: uint32-like in [0, 0xffff].

    Returns uint64 values in (0, 2^48]: 2^44 * log2(xin+1) in fixed point.
    """
    if tables is None:
        rh_lh, ll_tbl = RH_LH_NP, LL_NP
    else:
        rh_lh, ll_tbl = tables

    if xp.asarray(0, dtype=xp.uint64).dtype.itemsize != 8:
        raise RuntimeError(
            "crush_ln requires real 64-bit integers; enable jax x64 "
            "(jax.enable_x64(True) or jax_enable_x64=True) before tracing")

    x = xp.asarray(xin, dtype=xp.uint32) + xp.uint32(1)

    # locate the msb of the (at most 17-bit) value, branchlessly, then
    # normalize so the top bit sits at position 15 (mapper.c:234-243 uses
    # __builtin_clz; this is the same computation as a 5-step binary search)
    v = x & xp.uint32(0x1FFFF)
    p = xp.zeros_like(v)
    for sh in (16, 8, 4, 2, 1):
        m = v >> xp.uint32(sh)
        take = m > 0
        p = xp.where(take, p + xp.uint32(sh), p)
        v = xp.where(take, m, v)
    x = x << xp.where(p < 15, xp.uint32(15) - p, xp.uint32(0))
    iexpon = xp.where(p < 15, p, xp.uint32(15)).astype(xp.uint64)

    index1 = ((x >> xp.uint32(8)) << xp.uint32(1)).astype(xp.int32)
    rh = rh_lh[index1 - 256]        # ~ 2^56 / index1
    lh = rh_lh[index1 + 1 - 256]    # ~ 2^48 * log2(index1/256)

    # RH*x ~ 2^48 * (2^15 + xf); the byte above bit 48 is the fine index
    xl64 = x.astype(xp.uint64) * rh
    index2 = ((xl64 >> xp.uint64(48)) & xp.uint64(0xFF)).astype(xp.int32)

    lh = (lh + ll_tbl[index2]) >> xp.uint64(48 - 12 - 32)
    return (iexpon << xp.uint64(12 + 32)) + lh


_LN16_NP = None


def ln16_table() -> np.ndarray:
    """The full-domain crush_ln table: ``LN16[u] == crush_ln(u)`` for every
    u in [0, 0xffff].

    crush_ln's input is always ``hash & 0xffff`` (mapper.c:318), so the
    whole normalize + reciprocal/log-table pipeline collapses to one
    65536-entry u64 gather — 512 KiB, VMEM-resident on TPU.  Built once on
    the host from the bit-exact crush_ln itself, so equality is by
    construction (asserted in tests/test_ln.py).
    """
    global _LN16_NP
    if _LN16_NP is None:
        _LN16_NP = crush_ln(np.arange(65536, dtype=np.uint32), xp=np)
    return _LN16_NP


def recip64(weight, xp=np):
    """Per-item reciprocals ``floor((2^64-1) / w)`` for the division-free
    straw2 key (zero weights map to 0; they are sentineled out later).

    Computed once per weight array — on the host or hoisted to the
    unbatched prefix of a jitted program — so the per-draw cost of the
    16.16 division in mapper.c:335 drops from a 64-bit divide per item to
    a multiply-high.
    """
    w = xp.asarray(weight, dtype=xp.uint32).astype(xp.uint64)
    wsafe = xp.where(w == 0, xp.uint64(1), w)
    return xp.where(w == 0, xp.uint64(0),
                    xp.uint64(0xFFFFFFFFFFFFFFFF) // wsafe)


def straw2_key(u16, weight, recip, xp=np, ln_tab=None):
    """Division-free straw2 selection key.

    Returns ``q = (2^48 - crush_ln(u16)) // weight`` as u64, with zero
    weights mapped to U64_MAX.  Because the reference draw is ``-q``
    compared with strict ``>`` keeping the first maximum
    (mapper.c:345-360), ``argmin`` over these keys (first minimum wins)
    selects the identical item — asserted bit-exact against
    ``straw2_draw`` in tests/test_ln.py.

    The floor division is a multiply-high by the precomputed reciprocal
    plus one correction step: with r = floor((2^64-1)/w) the estimate
    ``mulhi64(neg, r)`` is q-1 or q (error < neg/2^64 + 1 <= 1 + eps for
    neg < 2^48), and all correction products fit u64 since
    q*w <= neg < 2^48.
    """
    tab = ln_tab if ln_tab is not None else ln16_table()
    u = xp.asarray(u16, dtype=xp.uint32) & xp.uint32(0xFFFF)
    ln = tab[u.astype(xp.int32)]
    neg = xp.uint64(1 << 48) - ln

    r = xp.asarray(recip, dtype=xp.uint64)
    # mulhi64(neg, r): neg = a1*2^32 + a0 with a1 < 2^16, r = b1*2^32 + b0
    a0 = neg & xp.uint64(0xFFFFFFFF)
    a1 = neg >> xp.uint64(32)
    b0 = r & xp.uint64(0xFFFFFFFF)
    b1 = r >> xp.uint64(32)
    mid = a0 * b1 + a1 * b0 + ((a0 * b0) >> xp.uint64(32))  # < 2^64, no wrap
    q = a1 * b1 + (mid >> xp.uint64(32))
    w = xp.asarray(weight, dtype=xp.uint32).astype(xp.uint64)
    wsafe = xp.where(w == 0, xp.uint64(1), w)
    q = q + ((q + xp.uint64(1)) * wsafe <= neg).astype(xp.uint64)
    return xp.where(w == 0, xp.uint64(0xFFFFFFFFFFFFFFFF), q)


def straw2_draw(u16, weight, xp=np, tables=None):
    """The signed straw2 draw: ``div64_s64(crush_ln(u16) - 2^48, weight)``.

    ``u16``: the masked hash draw (hash & 0xffff); ``weight``: 16.16
    fixed-point item weight (uint32-like).  Zero weights map to S64_MIN
    (mapper.c:349-353).  Division is C truncation-toward-zero; since the
    numerator is <= 0 and the divisor > 0, ``-((-ln) // w)`` is exact.
    """
    ln = crush_ln(u16, xp=xp, tables=tables)
    # neg = 2^48 - ln  (>= 0); draw = -(neg // w)
    neg = (xp.uint64(1 << 48) - ln).astype(xp.int64)
    w = xp.asarray(weight, dtype=xp.uint32).astype(xp.int64)
    wsafe = xp.where(w == 0, xp.int64(1), w)
    draw = -(neg // wsafe)
    return xp.where(w == 0, xp.int64(-(2**63)), draw)
