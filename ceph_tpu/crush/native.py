"""ctypes bridge to the native host mapper (native/crush_host.cpp).

The host-side hot loops (tools' scalar sweeps, the bench's CPU
fallback) run the batched C++ mapper over the SAME SoA arrays the TPU
mapper consumes; Python remains the source of truth (mapper_ref) and
the graceful fallback when the library isn't built.

``ensure_built()`` invokes the Makefile once per process if the .so is
missing (the toolchain is part of the image); failures degrade to
None — callers fall back to the Python/JAX paths.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
from typing import List, Optional, Tuple

from ..analysis.lockdep import make_lock

import numpy as np

from .map import ChooseArgMap, CrushMap
from .map_arrays import encode_map

REPO = pathlib.Path(__file__).resolve().parents[2]
NATIVE_DIR = REPO / "native"
LIB_PATH = NATIVE_DIR / "libcrush_host.so"

_lock = make_lock("crush::native_build")
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def ensure_built() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None on failure."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        # always run make: a no-op when fresh, and source edits never
        # load a stale library (the Makefile carries the deps)
        try:
            subprocess.run(["make", "-s"], cwd=str(NATIVE_DIR),
                           check=True, capture_output=True,
                           timeout=120)
        except Exception:
            if not LIB_PATH.exists():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(str(LIB_PATH))
        except OSError:
            _build_failed = True
            return None
        lib.crush_do_rule_batched.restype = ctypes.c_int
        lib.crush_do_rule_batched.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int,
            _i32p, _i32p, _i32p, _i32p, _i32p, _i32p,
            _u32p, _u32p, _u32p, _u32p,
            _i32p, _u32p, _u8p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            ctypes.c_int, _i32p,
            _u32p, ctypes.c_int,
            ctypes.c_int, _u32p, ctypes.c_int,
            _i32p, _i32p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return ensure_built() is not None


class NativeMapper:
    """Batched do_rule on the C++ engine for one (map, choose_args)."""

    def __init__(self, cmap: CrushMap,
                 choose_args: Optional[ChooseArgMap] = None):
        lib = ensure_built()
        if lib is None:
            raise RuntimeError("native crush mapper unavailable")
        self._lib = lib
        self.cmap = cmap
        self.static, arr = encode_map(cmap, choose_args)
        self._a = {
            "alg": np.ascontiguousarray(arr.alg, np.int32),
            "btype": np.ascontiguousarray(arr.btype, np.int32),
            "bhash": np.ascontiguousarray(arr.bhash, np.int32),
            "size": np.ascontiguousarray(arr.size, np.int32),
            "nnodes": np.ascontiguousarray(arr.nnodes, np.int32),
            "items": np.ascontiguousarray(arr.items, np.int32),
            "weights": np.ascontiguousarray(arr.weights, np.uint32),
            "sum_weights": np.ascontiguousarray(arr.sum_weights,
                                                np.uint32),
            "straws": np.ascontiguousarray(arr.straws, np.uint32),
            "node_weights": np.ascontiguousarray(arr.node_weights,
                                                 np.uint32),
            "arg_ids": np.ascontiguousarray(arr.arg_ids, np.int32),
            "arg_weights": np.ascontiguousarray(arr.arg_weights,
                                                np.uint32),
            "has_arg": np.ascontiguousarray(
                arr.has_arg.astype(np.uint8)),
        }

    def _steps(self, ruleno: int) -> np.ndarray:
        rule = self.cmap.rules[ruleno]
        return np.ascontiguousarray(
            [[s.op, s.arg1, s.arg2] for s in rule.steps], np.int32)

    def map_batch(self, ruleno: int, xs, result_max: int,
                  weight) -> Tuple[np.ndarray, np.ndarray]:
        """Same shape contract as BatchedMapper.map_batch."""
        xs = np.ascontiguousarray(xs, np.uint32)
        weight = np.ascontiguousarray(weight, np.uint32)
        steps = self._steps(ruleno)
        nx = len(xs)
        results = np.zeros((nx, result_max), np.int32)
        lens = np.zeros(nx, np.int32)
        st = self.static
        a = self._a
        t = st.tunables
        self._lib.crush_do_rule_batched(
            st.max_buckets, st.max_size, st.max_nodes,
            st.max_positions, st.max_devices,
            a["alg"], a["btype"], a["bhash"], a["size"], a["nnodes"],
            a["items"], a["weights"], a["sum_weights"], a["straws"],
            a["node_weights"], a["arg_ids"], a["arg_weights"],
            a["has_arg"],
            t[0], t[1], t[2], t[3], t[4], t[5],
            len(steps), steps,
            weight, len(weight),
            nx, xs, result_max,
            results, lens)
        return results, lens

    def do_rule(self, ruleno: int, x: int, result_max: int,
                weight) -> List[int]:
        res, lens = self.map_batch(
            ruleno, np.asarray([x], np.uint32), result_max, weight)
        return list(res[0, :lens[0]])
