"""The scalar CRUSH mapper — the framework's executable specification.

A faithful, readable Python implementation of the complete mapping
semantics of the reference C core (src/crush/mapper.c): the rule-step VM
(mapper.c:878-1083), the firstn retry-descent (mapper.c:438-626), the
breadth-first indep variant (mapper.c:633-821), all five bucket choose
algorithms (mapper.c:51-396) including the stateful uniform-bucket
permutation (mapper.c:51-109), tunables, chooseleaf recursion, vary_r /
stable modes and per-position choose_args overrides.

This is *not* the fast path — the vmapped JAX program in mapper_jax.py is.
It exists to (a) pin the semantics with something reviewable, (b) back the
golden-vector tests, and (c) serve host-side tools where batch size is 1.
Every function is bit-exact against tests/golden/*.json.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import constants as C
from .hash import hash32_2_int, hash32_3_int, hash32_4_int
from .ln import LL_TBL, RH_LH_TBL
from .map import Bucket, ChooseArg, ChooseArgMap, CrushMap


# ---------------------------------------------------------------------------
# crush_ln / straw2 draw on python ints (exact port of mapper.c:226-268,339)
# ---------------------------------------------------------------------------

def crush_ln_int(xin: int) -> int:
    x = (xin + 1) & 0xFFFFFFFF
    iexpon = 15
    if not (x & 0x18000):
        # clz32(v) = 32 - bit_length(v); bits = clz32(v) - 16
        bits = 16 - (x & 0x1FFFF).bit_length()
        x = (x << bits) & 0xFFFFFFFF
        iexpon = 15 - bits
    index1 = (x >> 8) << 1
    rh = RH_LH_TBL[index1 - 256]
    lh = RH_LH_TBL[index1 + 1 - 256]
    xl64 = (x * rh) & 0xFFFFFFFFFFFFFFFF
    xl64 >>= 48
    index2 = xl64 & 0xFF
    lh = (lh + LL_TBL[index2]) >> (48 - 12 - 32)
    return (iexpon << (12 + 32)) + lh


def _h3(hash_type: int, a: int, b: int, c: int) -> int:
    return hash32_3_int(a, b, c) if hash_type == C.CRUSH_HASH_RJENKINS1 else 0


def _h4(hash_type: int, a: int, b: int, c: int, d: int) -> int:
    return hash32_4_int(a, b, c, d) if hash_type == C.CRUSH_HASH_RJENKINS1 \
        else 0


def _straw2_draw(hash_type: int, x: int, item_id: int, r: int,
                 weight: int) -> int:
    """generate_exponential_distribution (mapper.c:312-337)."""
    if weight == 0:
        return C.S64_MIN
    u = _h3(hash_type, x, item_id, r) & 0xFFFF
    ln = crush_ln_int(u) - 0x1000000000000
    # div64_s64 truncates toward zero; ln <= 0, weight > 0
    return -((-ln) // weight)


# ---------------------------------------------------------------------------
# workspace (struct crush_work, mapper.c:824-865): only uniform buckets
# carry state — the incrementally-built Fisher-Yates permutation
# ---------------------------------------------------------------------------

class _PermState:
    __slots__ = ("perm_x", "perm_n", "perm")

    def __init__(self, size: int):
        self.perm_x = 0
        self.perm_n = 0
        self.perm = list(range(size))


class Workspace:
    def __init__(self):
        self._perm: Dict[int, _PermState] = {}

    def perm_for(self, bucket: Bucket) -> _PermState:
        st = self._perm.get(bucket.id)
        if st is None:
            st = _PermState(bucket.size)
            self._perm[bucket.id] = st
        return st


# ---------------------------------------------------------------------------
# bucket choose methods (mapper.c:51-396)
# ---------------------------------------------------------------------------

def bucket_perm_choose(bucket: Bucket, work: _PermState, x: int,
                       r: int) -> int:
    """Fisher-Yates-on-demand permutation choose (mapper.c:51-109)."""
    pr = r % bucket.size
    if work.perm_x != (x & 0xFFFFFFFF) or work.perm_n == 0:
        work.perm_x = x & 0xFFFFFFFF
        if pr == 0:
            s = _h3(bucket.hash, x, bucket.id, 0) % bucket.size
            work.perm[0] = s
            work.perm_n = 0xFFFF  # magic: see mapper.c:68
            return bucket.items[s]
        for i in range(bucket.size):
            work.perm[i] = i
        work.perm_n = 0
    elif work.perm_n == 0xFFFF:
        # clean up after the r=0 shortcut
        for i in range(1, bucket.size):
            work.perm[i] = i
        work.perm[work.perm[0]] = 0
        work.perm_n = 1
    while work.perm_n <= pr:
        p = work.perm_n
        if p < bucket.size - 1:
            i = _h3(bucket.hash, x, bucket.id, p) % (bucket.size - p)
            if i:
                work.perm[p + i], work.perm[p] = work.perm[p], work.perm[p + i]
        work.perm_n += 1
    return bucket.items[work.perm[pr]]


def bucket_list_choose(bucket: Bucket, x: int, r: int) -> int:
    """Tail-to-head probabilistic descent (mapper.c:119-142)."""
    for i in range(bucket.size - 1, -1, -1):
        w = _h4(bucket.hash, x, bucket.items[i], r, bucket.id) & 0xFFFF
        w = (w * bucket.sum_weights[i]) >> 16
        if w < bucket.item_weights[i]:
            return bucket.items[i]
    return bucket.items[0]


def bucket_tree_choose(bucket: Bucket, x: int, r: int) -> int:
    """Weighted binary-tree descent (mapper.c:145-200)."""
    n = bucket.num_nodes >> 1
    while not (n & 1):
        w = bucket.node_weights[n]
        t = (_h4(bucket.hash, x, n, r, bucket.id) * w) >> 32
        h = 0
        nn = n
        while (nn & 1) == 0:
            h += 1
            nn >>= 1
        left = n - (1 << (h - 1))
        n = left if t < bucket.node_weights[left] else n + (1 << (h - 1))
    return bucket.items[n >> 1]


def bucket_straw_choose(bucket: Bucket, x: int, r: int) -> int:
    """Legacy straw: 16-bit draw scaled by precomputed straws
    (mapper.c:205-223)."""
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        draw = (_h3(bucket.hash, x, bucket.items[i], r) & 0xFFFF) \
            * bucket.straws[i]
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def bucket_straw2_choose(bucket: Bucket, x: int, r: int,
                         arg: Optional[ChooseArg], position: int) -> int:
    """Exponential-minimum sampling (mapper.c:339-362) with choose_args
    weight/ids substitution (mapper.c:287-304)."""
    weights = bucket.item_weights
    ids = bucket.items
    if arg is not None:
        if arg.weight_set is not None:
            pos = min(position, len(arg.weight_set) - 1)
            weights = arg.weight_set[pos]
        if arg.ids is not None:
            ids = arg.ids
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        draw = _straw2_draw(bucket.hash, x, ids[i], r, weights[i])
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def crush_bucket_choose(bucket: Bucket, work: Workspace, x: int, r: int,
                        arg: Optional[ChooseArg], position: int) -> int:
    alg = bucket.alg
    if alg == C.CRUSH_BUCKET_UNIFORM:
        return bucket_perm_choose(bucket, work.perm_for(bucket), x, r)
    if alg == C.CRUSH_BUCKET_LIST:
        return bucket_list_choose(bucket, x, r)
    if alg == C.CRUSH_BUCKET_TREE:
        return bucket_tree_choose(bucket, x, r)
    if alg == C.CRUSH_BUCKET_STRAW:
        return bucket_straw_choose(bucket, x, r)
    if alg == C.CRUSH_BUCKET_STRAW2:
        return bucket_straw2_choose(bucket, x, r, arg, position)
    return bucket.items[0]


def is_out(weight: List[int], item: int, x: int) -> bool:
    """Weight-based rejection of a device (mapper.c:402-416)."""
    if item >= len(weight):
        return True
    w = weight[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (hash32_2_int(x, item) & 0xFFFF) >= w


# ---------------------------------------------------------------------------
# choose_firstn (mapper.c:438-626)
# ---------------------------------------------------------------------------

def _carg(choose_args, bucket: Bucket) -> Optional[ChooseArg]:
    if choose_args is None:
        return None
    return choose_args.get(-1 - bucket.id)


def crush_choose_firstn(cmap: CrushMap, work: Workspace, bucket: Bucket,
                        weight: List[int], x: int, numrep: int, type_: int,
                        out: List[int], base: int, outpos: int, out_size: int,
                        tries: int, recurse_tries: int, local_retries: int,
                        local_fallback_retries: int, recurse_to_leaf: bool,
                        vary_r: int, stable: int, out2: Optional[List[int]],
                        out2_base: int, parent_r: int,
                        choose_args: Optional[ChooseArgMap]) -> int:
    """Depth-first retry descent choosing ``numrep`` distinct items
    (mapper.c:438-626).  ``out``/``out2`` are the full scratch vectors;
    ``base`` is the segment origin (the C code's ``o+osize`` pointer), and
    ``outpos`` is the position *within* the segment, so collision checks are
    segment-local exactly like the pointer arithmetic in the reference."""
    count = out_size
    rep = 0 if stable else outpos
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        item = 0
        retry_descent = True
        while retry_descent:
            retry_descent = False
            in_bucket = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                collide = False
                reject = False
                r = rep + parent_r + ftotal
                if in_bucket.size == 0:
                    reject = True
                else:
                    if (local_fallback_retries > 0
                            and flocal >= (in_bucket.size >> 1)
                            and flocal > local_fallback_retries):
                        item = bucket_perm_choose(
                            in_bucket, work.perm_for(in_bucket), x, r)
                    else:
                        item = crush_bucket_choose(
                            in_bucket, work, x, r,
                            _carg(choose_args, in_bucket), outpos)
                    if item >= cmap.max_devices:
                        skip_rep = True
                        break

                    if item < 0:
                        sub = cmap.bucket_by_id(item)
                        itemtype = sub.type if sub is not None else None
                    else:
                        itemtype = 0

                    if itemtype != type_:
                        if item >= 0 or (-1 - item) >= cmap.max_buckets \
                                or cmap.bucket_by_id(item) is None:
                            skip_rep = True
                            break
                        in_bucket = cmap.bucket_by_id(item)
                        retry_bucket = True
                        continue

                    for i in range(outpos):
                        if out[base + i] == item:
                            collide = True
                            break

                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = r >> (vary_r - 1) if vary_r else 0
                            got = crush_choose_firstn(
                                cmap, work, cmap.bucket_by_id(item), weight,
                                x, 1 if stable else outpos + 1, 0,
                                out2, out2_base, outpos, count,
                                recurse_tries, 0,
                                local_retries, local_fallback_retries,
                                False, vary_r, stable, None, 0, sub_r,
                                choose_args)
                            if got <= outpos:
                                reject = True  # didn't get a leaf
                        else:
                            out2[out2_base + outpos] = item  # already a leaf

                    if not reject and not collide and itemtype == 0:
                        reject = is_out(weight, item, x)

                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (local_fallback_retries > 0
                          and flocal <= in_bucket.size
                          + local_fallback_retries):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                        break
                    else:
                        skip_rep = True

        if not skip_rep:
            out[base + outpos] = item
            outpos += 1
            count -= 1
        rep += 1
    return outpos


# ---------------------------------------------------------------------------
# choose_indep (mapper.c:633-821)
# ---------------------------------------------------------------------------

def crush_choose_indep(cmap: CrushMap, work: Workspace, bucket: Bucket,
                       weight: List[int], x: int, left: int, numrep: int,
                       type_: int, out: List[int], base: int, outpos: int,
                       tries: int, recurse_tries: int, recurse_to_leaf: bool,
                       out2: Optional[List[int]], out2_base: int,
                       parent_r: int,
                       choose_args: Optional[ChooseArgMap]) -> None:
    """Breadth-first, positionally-stable variant (mapper.c:633-821).
    Same segment convention as crush_choose_firstn."""
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[base + rep] = C.CRUSH_ITEM_UNDEF
        if out2 is not None:
            out2[out2_base + rep] = C.CRUSH_ITEM_UNDEF

    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[base + rep] != C.CRUSH_ITEM_UNDEF:
                continue
            in_bucket = bucket
            while True:
                r = rep + parent_r
                if in_bucket.alg == C.CRUSH_BUCKET_UNIFORM \
                        and in_bucket.size % numrep == 0:
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal

                if in_bucket.size == 0:
                    break

                item = crush_bucket_choose(
                    in_bucket, work, x, r,
                    _carg(choose_args, in_bucket), outpos)
                if item >= cmap.max_devices:
                    out[base + rep] = C.CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[out2_base + rep] = C.CRUSH_ITEM_NONE
                    left -= 1
                    break

                if item < 0:
                    sub = cmap.bucket_by_id(item)
                    itemtype = sub.type if sub is not None else None
                else:
                    itemtype = 0

                if itemtype != type_:
                    if item >= 0 or (-1 - item) >= cmap.max_buckets \
                            or cmap.bucket_by_id(item) is None:
                        out[base + rep] = C.CRUSH_ITEM_NONE
                        if out2 is not None:
                            out2[out2_base + rep] = C.CRUSH_ITEM_NONE
                        left -= 1
                        break
                    in_bucket = cmap.bucket_by_id(item)
                    continue

                collide = False
                for i in range(outpos, endpos):
                    if out[base + i] == item:
                        collide = True
                        break
                if collide:
                    break

                if recurse_to_leaf:
                    if item < 0:
                        crush_choose_indep(
                            cmap, work, cmap.bucket_by_id(item), weight,
                            x, 1, numrep, 0, out2, out2_base, rep,
                            recurse_tries, 0, False, None, 0, r,
                            choose_args)
                        if out2 is not None \
                                and out2[out2_base + rep] == C.CRUSH_ITEM_NONE:
                            break  # placed nothing; no leaf
                    elif out2 is not None:
                        out2[out2_base + rep] = item

                if itemtype == 0 and is_out(weight, item, x):
                    break

                out[base + rep] = item
                left -= 1
                break
        ftotal += 1

    for rep in range(outpos, endpos):
        if out[base + rep] == C.CRUSH_ITEM_UNDEF:
            out[base + rep] = C.CRUSH_ITEM_NONE
        if out2 is not None and out2[out2_base + rep] == C.CRUSH_ITEM_UNDEF:
            out2[out2_base + rep] = C.CRUSH_ITEM_NONE


# ---------------------------------------------------------------------------
# the rule VM (crush_do_rule, mapper.c:878-1083)
# ---------------------------------------------------------------------------

def crush_do_rule(cmap: CrushMap, ruleno: int, x: int, result_max: int,
                  weight: List[int],
                  choose_args: Optional[ChooseArgMap] = None) -> List[int]:
    """Run rule ``ruleno`` for input ``x``; returns the result list
    (length <= result_max)."""
    if ruleno not in cmap.rules:
        return []
    rule = cmap.rules[ruleno]
    t = cmap.tunables

    # the three scratch vectors carved out after the workspace in C
    w: List[int] = [0] * result_max
    o: List[int] = [0] * result_max
    cvec: List[int] = [0] * result_max
    result: List[int] = []
    wsize = 0

    choose_tries = t.choose_total_tries + 1  # off-by-one heritage
    choose_leaf_tries = 0
    choose_local_retries = t.choose_local_tries
    choose_local_fallback_retries = t.choose_local_fallback_tries
    vary_r = t.chooseleaf_vary_r
    stable = t.chooseleaf_stable

    work = Workspace()

    for step in rule.steps:
        op, arg1, arg2 = step.op, step.arg1, step.arg2
        if op == C.CRUSH_RULE_TAKE:
            if (0 <= arg1 < cmap.max_devices) or \
                    (0 <= -1 - arg1 < cmap.max_buckets
                     and cmap.bucket_by_id(arg1) is not None):
                w[0] = arg1
                wsize = 1
        elif op == C.CRUSH_RULE_SET_CHOOSE_TRIES:
            if arg1 > 0:
                choose_tries = arg1
        elif op == C.CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            if arg1 > 0:
                choose_leaf_tries = arg1
        elif op == C.CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
            if arg1 >= 0:
                choose_local_retries = arg1
        elif op == C.CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if arg1 >= 0:
                choose_local_fallback_retries = arg1
        elif op == C.CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
            if arg1 >= 0:
                vary_r = arg1
        elif op == C.CRUSH_RULE_SET_CHOOSELEAF_STABLE:
            if arg1 >= 0:
                stable = arg1
        elif op in (C.CRUSH_RULE_CHOOSELEAF_FIRSTN, C.CRUSH_RULE_CHOOSE_FIRSTN,
                    C.CRUSH_RULE_CHOOSELEAF_INDEP, C.CRUSH_RULE_CHOOSE_INDEP):
            if wsize == 0:
                continue
            firstn = op in (C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
                            C.CRUSH_RULE_CHOOSE_FIRSTN)
            recurse_to_leaf = op in (C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                     C.CRUSH_RULE_CHOOSELEAF_INDEP)
            osize = 0
            for i in range(wsize):
                numrep = arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                bucket = cmap.bucket_by_id(w[i]) if w[i] < 0 else None
                if bucket is None:
                    continue  # w[i] is a device or CRUSH_ITEM_NONE
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif t.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    osize += crush_choose_firstn(
                        cmap, work, bucket, weight, x, numrep, arg2,
                        o, osize, 0, result_max - osize, choose_tries,
                        recurse_tries, choose_local_retries,
                        choose_local_fallback_retries, recurse_to_leaf,
                        vary_r, stable, cvec, osize, 0, choose_args)
                else:
                    out_size = min(numrep, result_max - osize)
                    crush_choose_indep(
                        cmap, work, bucket, weight, x, out_size, numrep,
                        arg2, o, osize, 0, choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf, cvec, osize, 0, choose_args)
                    osize += out_size
            if recurse_to_leaf:
                for i in range(osize):
                    o[i] = cvec[i]
            w, o = o, w
            wsize = osize
        elif op == C.CRUSH_RULE_EMIT:
            for i in range(wsize):
                if len(result) >= result_max:
                    break
                result.append(w[i])
            wsize = 0
    return result
