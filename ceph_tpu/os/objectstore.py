"""ObjectStore API — transactional object persistence.

The role of src/os/ObjectStore.h + src/os/Transaction.{h,cc}: a store
holds collections (one per PG in the OSD); a collection holds objects;
an object has byte data, xattrs and an omap (ordered key-value).
All mutation happens through a ``Transaction`` — an ordered op list
applied atomically by ``queue_transaction`` — which is exactly the
property the recovery/peering flows rely on.

Op encoding mirrors Transaction::Op (touch/write/zero/truncate/remove/
clone/setattr/omap_* /create+remove collection); ops are plain tuples
so a transaction is serializable (the journal/wire form).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

# op codes (Transaction.h enum)
OP_TOUCH = "touch"
OP_WRITE = "write"
OP_ZERO = "zero"
OP_TRUNCATE = "truncate"
OP_REMOVE = "remove"
OP_CLONE = "clone"
OP_SETATTR = "setattr"
OP_RMATTR = "rmattr"
OP_OMAP_SETKEYS = "omap_setkeys"
OP_OMAP_RMKEYS = "omap_rmkeys"
OP_OMAP_CLEAR = "omap_clear"
OP_MKCOLL = "mkcoll"
OP_RMCOLL = "rmcoll"


class Transaction:
    """An ordered, atomically-applied op list."""

    def __init__(self):
        self.ops: List[Tuple] = []

    # -- collection ops ----------------------------------------------
    def create_collection(self, cid: str) -> "Transaction":
        self.ops.append((OP_MKCOLL, cid))
        return self

    def remove_collection(self, cid: str) -> "Transaction":
        self.ops.append((OP_RMCOLL, cid))
        return self

    # -- object ops ---------------------------------------------------
    def touch(self, cid: str, oid: str) -> "Transaction":
        self.ops.append((OP_TOUCH, cid, oid))
        return self

    def write(self, cid: str, oid: str, offset: int,
              data: bytes) -> "Transaction":
        """``data`` may be any buffer-protocol object (bytes, or a
        memoryview into a pooled recv segment) — it is staged AS IS,
        zero-copy.  The contract is the reference's bufferlist one:
        the buffer must stay valid until queue_transaction returns
        (both stores materialise into their own image inside it, and
        every caller queues within the handler that owns the view)."""
        self.ops.append((OP_WRITE, cid, oid, offset, data))
        return self

    def zero(self, cid: str, oid: str, offset: int,
             length: int) -> "Transaction":
        self.ops.append((OP_ZERO, cid, oid, offset, length))
        return self

    def truncate(self, cid: str, oid: str, size: int) -> "Transaction":
        self.ops.append((OP_TRUNCATE, cid, oid, size))
        return self

    def remove(self, cid: str, oid: str) -> "Transaction":
        self.ops.append((OP_REMOVE, cid, oid))
        return self

    def clone(self, cid: str, src: str, dst: str) -> "Transaction":
        self.ops.append((OP_CLONE, cid, src, dst))
        return self

    def setattr(self, cid: str, oid: str, key: str,
                value: bytes) -> "Transaction":
        # copy-ok: attr values are tiny metadata (version stamps) the
        # store retains by reference past the caller's buffer lifetime
        self.ops.append((OP_SETATTR, cid, oid, key, bytes(value)))
        return self

    def rmattr(self, cid: str, oid: str, key: str) -> "Transaction":
        self.ops.append((OP_RMATTR, cid, oid, key))
        return self

    def omap_setkeys(self, cid: str, oid: str,
                     kv: Dict[str, bytes]) -> "Transaction":
        # omap values are small keys/records the store retains by
        # reference past the caller's buffer lifetime
        self.ops.append((OP_OMAP_SETKEYS, cid, oid,
                         {k: bytes(v) for k, v in kv.items()}))  # copy-ok: small omap records, retained by reference
        return self

    def omap_rmkeys(self, cid: str, oid: str,
                    keys: Iterable[str]) -> "Transaction":
        self.ops.append((OP_OMAP_RMKEYS, cid, oid, list(keys)))
        return self

    def omap_clear(self, cid: str, oid: str) -> "Transaction":
        self.ops.append((OP_OMAP_CLEAR, cid, oid))
        return self

    def append(self, other: "Transaction") -> "Transaction":
        self.ops.extend(other.ops)
        return self

    def __len__(self) -> int:
        return len(self.ops)


class ObjectStore:
    """The abstract store (ObjectStore.h)."""

    def mount(self) -> None: ...

    def umount(self) -> None: ...

    def mkfs(self) -> None: ...

    def queue_transaction(self, txn: Transaction) -> None:
        raise NotImplementedError

    # reads (never transactional, ObjectStore.h read side)
    def read(self, cid: str, oid: str, offset: int = 0,
             length: int = -1) -> bytes:
        raise NotImplementedError

    def stat(self, cid: str, oid: str) -> Optional[Dict]:
        raise NotImplementedError

    def getattr(self, cid: str, oid: str, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def omap_get(self, cid: str, oid: str) -> Dict[str, bytes]:
        raise NotImplementedError

    def list_collections(self) -> List[str]:
        raise NotImplementedError

    def list_objects(self, cid: str) -> List[str]:
        raise NotImplementedError

    def collection_exists(self, cid: str) -> bool:
        raise NotImplementedError
