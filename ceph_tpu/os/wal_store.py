"""WALStore — the crash-consistent disk-backed ObjectStore.

The BlueStore role (src/os/bluestore/BlueStore.cc WAL/deferred writes,
src/os/ObjectStore.h atomicity contract), re-shaped for this framework:
state lives in RAM (a MemStore twin — the OSD working set), durability
comes from a write-ahead log plus periodic checkpoints:

  queue_transaction:  encode + stage in-memory (validation — an invalid
                      txn never journals) → append WAL record → fsync
                      (the ack point) → swap staged state visible
                      (cannot fail, so memory never diverges from the
                      journal even on ENOSPC/EIO mid-append)
  checkpoint:         snapshot full state to a temp file → fsync →
                      atomic rename over ``checkpoint`` → truncate WAL
  mount:              load newest valid checkpoint, replay WAL records
                      with seq > checkpoint seq, stopping at the first
                      torn/corrupt record (a kill -9 mid-append leaves
                      a torn tail; everything before it was acked and
                      must survive — everything after was never acked)

Record format (binary, little-endian):
  magic u32 | seq u64 | len u32 | crc32c u32 | payload(len)
payload = bincode-encoded Transaction op list.  crc32c is the same
vectorized castagnoli the EC HashInfo path uses, so torn or bit-rotted
tails are detected, not replayed.
"""

from __future__ import annotations

import errno
import os
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis import faults
from ..analysis.lockdep import make_lock, make_rlock
from ..analysis.racecheck import guarded_by
from ..common import copytrack
from ..common.bincode import (DecodeError, Decoder, Encoder, decode_txn,
                              encode_txn)
from ..common.encoding import MalformedInput
from ..common.log import getLogger
from ..common.perf_counters import collection
from .memstore import MemStore, _Object
from .objectstore import ObjectStore, Transaction

# process-global WAL metrics (every in-process store shares them;
# daemons' `perf dump` merges the global collection, the ec.engine
# pattern): txn count, shared fsyncs, and the group-size histogram —
# the depth-1-regression canary the aio smoke test gates on
_pc = collection().create("os.wal")
for _k in ("txns", "group_commits"):
    _pc.add_u64_counter(_k)
_pc.add_time("group_commit_time")
_pc.add_histogram("wal_group_size", min_value=1)

_MAGIC = 0x57414C31   # "WAL1": raw body
_MAGIC_Z = 0x57414C5A  # "WALZ": compressed body (compressor name
#                        prefixed to the payload, length-prefixed)
_HDR = struct.Struct("<IQII")

CHECKPOINT_V = 1  # struct_v of the checkpoint's bincode envelope


def _pack_body(body: bytes, comp) -> Tuple[int, bytes]:
    """(magic, on-disk body): checkpoints/records run through the
    compressor registry (the BlueStore per-pool compression role,
    src/compressor) when one is configured."""
    if comp is None or comp.name == "none":
        return _MAGIC, body
    packed = comp.compress(body)
    tag = comp.name.encode()
    # copy-ok: one-byte compressor-tag length header, not payload
    return _MAGIC_Z, bytes([len(tag)]) + tag + packed


def _unpack_body(magic: int, body: bytes) -> bytes:
    """Raises MalformedInput for an unknown compressor tag or a body
    that fails to decompress — a store written with a codec this build
    lacks (or bit-rotted in the compressed region) must surface a
    typed error the mount path can recover from, never a raw
    KeyError/zlib.error crash."""
    if magic == _MAGIC:
        return body
    from ..common.compressor import Compressor

    try:
        n = body[0]
        name = body[1:1 + n].decode()
    except (IndexError, UnicodeDecodeError) as e:
        raise MalformedInput(f"os.wal_checkpoint: bad compressor "
                             f"tag: {e}")
    try:
        codec = Compressor(name)
    except KeyError as e:
        raise MalformedInput(f"os.wal_checkpoint: {e.args[0]}")
    try:
        return codec.decompress(body[1 + n:])
    except Exception as e:
        raise MalformedInput(f"os.wal_checkpoint: body fails "
                             f"{name} decompression: {e!r}")


def _crc32c(data: bytes) -> int:
    from ..ec.stripe import crc32c as _c

    return int(_c(data))


# -- pure record/checkpoint codecs (the wirecheck-registered seam) ----

def encode_record(seq: int, ops: List[Tuple]) -> bytes:
    """One WAL record: header (magic, seq, len, crc32c) + bincode txn
    payload.  Records are never compressed — their latency is the
    write ack path."""
    enc = Encoder()
    encode_txn(ops, enc)
    payload = enc.bytes()
    return _HDR.pack(_MAGIC, seq, len(payload),
                     _crc32c(payload)) + payload


def decode_record(buf: bytes, pos: int = 0) -> Tuple[int, bytes, int]:
    """Parse one record at ``pos``; returns (seq, payload, end).
    Every torn/forged shape — short header, bad magic, truncated
    payload, crc mismatch — raises MalformedInput, which replay
    interprets as the un-acked tail."""
    if pos + _HDR.size > len(buf):
        raise MalformedInput("os.wal_record: truncated header")
    magic, seq, ln, crc = _HDR.unpack_from(buf, pos)
    if magic != _MAGIC:
        raise MalformedInput(f"os.wal_record: bad magic {magic:#x}")
    end = pos + _HDR.size + ln
    if end > len(buf):
        raise MalformedInput("os.wal_record: truncated payload")
    payload = buf[pos + _HDR.size:end]
    if _crc32c(payload) != crc:
        raise MalformedInput("os.wal_record: crc mismatch")
    return seq, payload, end


def encode_checkpoint(seq: int,
                      colls: Dict[str, Dict[str, _Object]],
                      comp=None) -> bytes:
    """The full checkpoint file image: header + (optionally
    compressed) bincode-enveloped store snapshot."""
    enc = Encoder()
    enc.start(CHECKPOINT_V, 1)
    enc.u64(seq)
    enc.u32(len(colls))
    for cid in sorted(colls):
        enc.str_(cid)
        objs = colls[cid]
        enc.u32(len(objs))
        for oid in sorted(objs):
            o = objs[oid]
            enc.str_(oid)
            enc.blob(o.data)  # staged by reference; materialised by
            # the enc.bytes() join below, under the store lock
            enc.str_blob_map(o.xattr)
            enc.str_blob_map(o.omap)
    enc.finish()
    magic, body = _pack_body(enc.bytes(), comp)
    return _HDR.pack(magic, seq, len(body), _crc32c(body)) + body


def decode_checkpoint(raw: bytes
                      ) -> Tuple[int, Dict[str, Dict[str, _Object]]]:
    """Returns (seq, collections).  All corruption classes — short
    file, bad magic, length/crc mismatch, unknown compressor,
    truncated compressed body, envelope damage — raise MalformedInput
    so mount() can fall back to WAL replay instead of crashing."""
    if len(raw) < _HDR.size:
        raise MalformedInput("os.wal_checkpoint: truncated header")
    magic, seq, ln, crc = _HDR.unpack_from(raw)
    body = raw[_HDR.size:_HDR.size + ln]
    if magic not in (_MAGIC, _MAGIC_Z) or len(body) != ln \
            or _crc32c(body) != crc:
        raise MalformedInput(
            "os.wal_checkpoint: bad magic/length/crc")
    dec = Decoder(_unpack_body(magic, body),
                  struct_name="os.wal_checkpoint")
    dec.start(CHECKPOINT_V)
    got_seq = dec.u64()
    if got_seq != seq:
        raise MalformedInput(
            f"os.wal_checkpoint: header seq {seq} != body seq "
            f"{got_seq}")
    colls: Dict[str, Dict[str, _Object]] = {}
    for _ in range(dec.u32()):
        cid = dec.str_()
        objs: Dict[str, _Object] = {}
        for _ in range(dec.u32()):
            oid = dec.str_()
            o = _Object()
            o.data = bytearray(dec.blob())
            o.xattr = dec.str_blob_map()
            o.omap = dec.str_blob_map()
            objs[oid] = o
        colls[cid] = objs
    dec.finish()
    return seq, colls


class _TxnWaiter:
    """One queued transaction's completion: set (durable) or errored
    by whichever group-commit leader's fsync — or checkpoint — covered
    it."""

    __slots__ = ("done", "error")

    def __init__(self):
        self.done = threading.Event()
        self.error: Optional[BaseException] = None

    def finish(self, error: Optional[BaseException] = None) -> None:
        if error is not None and self.error is None:
            self.error = error
        self.done.set()


@guarded_by("os::wal", "_pending", "_seq")
class WALStore(ObjectStore):
    def __init__(self, path: str, checkpoint_every_bytes: int = 1 << 24,
                 sync: bool = True, compression: str = "zlib",
                 group_commit_max_delay_us: int = 0, copy_coll=None):
        from ..common.compressor import Compressor

        self.path = path
        # byte-copy ledger target (see MemStore.__init__): the
        # mounting daemon's collection, or the process-global one
        self._copy_coll = copy_coll
        self._copy_pc = copytrack.ledger(copy_coll)
        self.log = getLogger("wal")
        # set when mount() found a checkpoint it could not decode and
        # fell back to WAL-only recovery — surfaced, not swallowed
        self.last_mount_error: Optional[str] = None
        # checkpoints compress through the registry (WAL records stay
        # raw: their latency is the write ack path); mount reads both
        # formats, so the option can change between runs
        self._comp = Compressor(compression)
        self._mem = MemStore(copy_coll=copy_coll)
        self._wal_path = os.path.join(path, "wal.log")
        self._ckpt_path = os.path.join(path, "checkpoint")
        self._wal_f = None
        self._seq = 0  # newest journaled+visible txn seq
        self._ckpt_seq = 0
        self._wal_bytes = 0
        self._ckpt_every = checkpoint_every_bytes
        self._sync = sync
        self._lock = make_rlock("os::wal")
        # -- group commit (the kv_sync_thread role, leader-elected) --
        # appended-but-not-yet-fsynced txns awaiting the shared fsync;
        # guarded by the store lock.  The first waiter to take the
        # sync mutex plays kv_sync_thread for everyone queued (a
        # dedicated thread would leak into every abandoned test
        # store); with one writer the leader is the writer itself —
        # the synchronous depth-1 fallback, identical to the old
        # fsync-per-txn path.
        self._pending: List[Tuple[int, _TxnWaiter]] = []
        self._sync_mutex = make_lock("os::wal_sync")
        self._wal_gen = 0  # bumped whenever _wal_f is replaced, so a
        # leader fsyncing a stale fd can tell a swap from a failure
        self._group_delay = max(0, group_commit_max_delay_us) / 1e6
        # test seam: runs between the group's last append and the
        # shared fsync (crash-consistency fault injection)
        self._fault_before_sync: Optional[Callable[[List[int]],
                                                   None]] = None

    # -- lifecycle ----------------------------------------------------
    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        self._write_checkpoint(seq=0)
        with open(self._wal_path, "wb") as f:
            f.flush()
            os.fsync(f.fileno())

    def mount(self) -> None:
        with self._lock:
            self._load_checkpoint()
            valid_end = self._replay_wal()
            # a torn tail must be CUT, not appended past: records
            # written after garbage bytes would be unreachable to the
            # next replay, silently dropping acked transactions
            try:
                size = os.path.getsize(self._wal_path)
            except FileNotFoundError:
                size = 0
                open(self._wal_path, "wb").close()
            if valid_end < size:
                with open(self._wal_path, "r+b") as f:
                    f.truncate(valid_end)
                    f.flush()
                    os.fsync(f.fileno())  # conc-ok: mount-time only; nothing else can hold the store yet
            self._wal_f = open(self._wal_path, "ab")
            self._wal_bytes = self._wal_f.tell()

    def umount(self) -> None:
        with self._lock:
            if self._wal_f is not None:
                self.checkpoint()
                self._wal_f.close()
                self._wal_f = None

    # -- the write path (group commit) --------------------------------
    def queue_transaction(self, txn: Transaction) -> None:
        """Append under the store lock, share the fsync.

        Concurrent transactions append to the log back to back (the
        store lock is the journal order) but the fsync — the ack
        point — is COALESCED: the first waiter to take the sync mutex
        fsyncs once for every record appended so far and completes
        all their waiters (BlueStore's kv_sync_thread aggregation,
        leader-elected).  N concurrent shard writes cost ~1-2 fsyncs
        instead of N.  Returning still means durable: this call blocks
        until a shared fsync (or a checkpoint) covered the record."""
        waiter = None
        with self._lock:
            assert self._wal_f is not None, "not mounted"
            # 1. encode (an unencodable txn never journals) and
            #    validate + stage in memory (atomic: all ops or none)
            seq = self._seq + 1
            rec = encode_record(seq, txn.ops)
            commit = self._mem.prepare_transaction(txn)
            # 2. journal the record (buffered write + flush; the
            #    shared fsync below is the ack point).  Journal BEFORE
            #    the visible swap: if the append fails (ENOSPC, EIO)
            #    the store state still equals the journal.
            try:
                if faults.fires("os.torn_append"):
                    # the torn-write crash image: half the record
                    # reaches the log, then the append "dies" — the
                    # rollback below must cut the torn bytes so they
                    # can never replay
                    self._wal_f.write(rec[:max(1, len(rec) // 2)])
                    self._wal_f.flush()
                    raise OSError(errno.EIO, "injected torn append")
                self._wal_f.write(rec)
                self._wal_f.flush()
            except Exception:
                # the append may have partially landed (buffered
                # bytes, EIO).  Roll the log back to the last valid
                # record boundary — the end of the last GOOD append,
                # fsynced or not: earlier group members' records must
                # survive the cut — so the failed txn can never replay
                # and later records are never stranded behind torn
                # bytes; if even that fails, poison the store.
                self._rollback_wal()
                raise
            # 3. the journaled record exists: swap state in (cannot
            #    fail).  Visible-before-durable, like the reference's
            #    on_applied vs on_commit split — the caller's ack
            #    (this call returning) still waits for the fsync.
            self._seq = seq
            commit()
            self._wal_bytes += len(rec)
            _pc.inc("txns")
            # copy ledger: the journal record materialises every op
            # payload once (encode_record above), and the MemStore
            # commit splices write payloads into backing bytearrays
            # once more (this path bypasses MemStore.queue_transaction
            # and its booking — prepare_transaction is called
            # directly, so this is the only site that counts it)
            copytrack.book_pc(self._copy_pc, "store_txn", len(rec),
                              copies=2)
            if self._sync:
                waiter = _TxnWaiter()
                self._pending.append((seq, waiter))
            if self._wal_bytes >= self._ckpt_every:
                self.checkpoint()  # completes every pending waiter
        if waiter is None:
            return
        # leader-follower: whoever holds the sync mutex fsyncs for
        # everyone queued; everyone else just waits for their waiter.
        while not waiter.done.is_set():
            if self._sync_mutex.acquire(timeout=0.05):
                try:
                    if not waiter.done.is_set():
                        self._drain_group()
                finally:
                    self._sync_mutex.release()
        if waiter.error is not None:
            raise waiter.error

    def _drain_group(self) -> None:
        """The shared fsync, run under the sync mutex: complete every
        transaction appended so far with ONE fsync."""
        if self._group_delay > 0:
            # widen the group: let concurrent writers land their
            # appends before the shared fsync (bounded by the knob)
            time.sleep(self._group_delay)  # the sync mutex is the group-commit leader role, not a data lock; waiting here IS the coalescing window
        with self._lock:
            batch, self._pending = self._pending, []
            f, gen = self._wal_f, self._wal_gen
        if not batch:
            return
        if self._fault_before_sync is not None:
            self._fault_before_sync([seq for seq, _w in batch])
        t0 = time.monotonic()
        err: Optional[BaseException] = None
        for _attempt in range(2):
            try:
                if f is None:
                    raise OSError("store poisoned (journal failure)")
                if faults.fires("os.fsync_eio"):
                    # a bad sector under the journal: the store must
                    # poison itself — memory shows the txns but disk
                    # cannot prove them (the reference asserts out)
                    raise OSError(errno.EIO, "injected fsync error")
                os.fsync(f.fileno())  # the shared group fsync IS the ack point; the sync mutex serializes leaders, appends proceed under the store lock meanwhile
                err = None
                break
            except Exception as e:
                err = e
                with self._lock:
                    if self._wal_gen == gen:
                        # genuine fsync failure on the live journal:
                        # memory already shows these txns (visible-
                        # before-durable) but the disk cannot prove
                        # them — the acked-write contract is gone.
                        # Poison the store and fail every waiter (the
                        # reference asserts out on journal fsync
                        # failure for the same reason).
                        self._wal_f = None
                        self._wal_gen += 1
                        break
                    # the fd was swapped under us (another writer's
                    # append-failure rollback reopened the log); this
                    # group's records survived the cut — retry the
                    # fsync on the new fd
                    f, gen = self._wal_f, self._wal_gen
        if err is not None:
            for _seq, w in batch:
                w.finish(err if isinstance(err, OSError)
                         else OSError(repr(err)))
            return
        _pc.inc("group_commits")
        _pc.tinc("group_commit_time", time.monotonic() - t0)
        _pc.hist_add("wal_group_size", len(batch))
        for _seq, w in batch:
            w.finish()

    def _rollback_wal(self) -> None:
        """Truncate the log back to ``_wal_bytes`` (the end of the
        last good append — group members' not-yet-fsynced records must
        survive the cut) after a failed append — the runtime twin of
        mount()'s torn-tail cut."""
        try:
            try:
                self._wal_f.close()
            except Exception:
                pass
            with open(self._wal_path, "r+b") as f:
                f.truncate(self._wal_bytes)
                f.flush()
                os.fsync(f.fileno())
            self._wal_f = open(self._wal_path, "ab")
        except Exception:
            self._wal_f = None  # poisoned: every later op asserts
        finally:
            self._wal_gen += 1

    # -- checkpointing ------------------------------------------------
    def checkpoint(self) -> None:
        """Fold the WAL into a durable snapshot and truncate it.

        Completes every pending group-commit waiter too: the snapshot
        holds their (already visible) state, so the rename IS their
        durability — no separate fsync needed."""
        with self._lock:
            batch, self._pending = self._pending, []
            self._write_checkpoint(self._seq)
            self._ckpt_seq = self._seq
            # crash after the rename but before this truncate replays
            # records with seq <= ckpt seq; the seq check skips them.
            # Truncate IN PLACE (append-mode writes land at EOF
            # regardless): the fd must stay valid — a group-commit
            # leader may be fsyncing it right now, which must not see
            # the journal yanked out from under it
            if self._wal_f is not None:
                self._wal_f.flush()
                os.ftruncate(self._wal_f.fileno(), 0)
                if self._sync:
                    os.fsync(self._wal_f.fileno())  # conc-ok: checkpoint must be atomic vs writers; the lock is the barrier
            self._wal_bytes = 0
        for _seq, w in batch:
            w.finish()

    def _write_checkpoint(self, seq: int) -> None:
        os.makedirs(self.path, exist_ok=True)
        blob = encode_checkpoint(seq, self._mem._coll, self._comp)
        tmp = self._ckpt_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckpt_path)  # atomic on POSIX
        if self._sync:
            dirfd = os.open(self.path, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)

    def _load_checkpoint(self) -> None:
        self._mem = MemStore(copy_coll=self._copy_coll)
        self._seq = self._ckpt_seq = 0  # race-ok: mount-time, before any writer thread exists
        self.last_mount_error = None
        try:
            raw = open(self._ckpt_path, "rb").read()
        except FileNotFoundError:
            return
        try:
            seq, colls = decode_checkpoint(raw)
        except MalformedInput as e:
            # an undecodable checkpoint (unknown compressor tag,
            # truncated compressed body, bit rot) must not brick the
            # store: surface the error and recover from the WAL alone
            # (ckpt_seq stays 0, so every journaled record replays).
            # Anything folded into the bad checkpoint and already
            # truncated out of the WAL is gone either way — mounting
            # what the journal proves beats refusing to mount.
            self.last_mount_error = (
                f"checkpoint at {self._ckpt_path} undecodable "
                f"({e}); recovering from WAL only")
            self.log.derr(f"wal: {self.last_mount_error}")
            return
        self._mem._coll = colls
        self._seq = self._ckpt_seq = seq  # race-ok: mount-time, before any writer thread exists

    def _replay_wal(self) -> int:
        """Apply WAL records past the checkpoint; stop at the first
        torn/corrupt record (the un-acked tail).  Returns the byte
        offset of the end of the last valid record, so mount can
        truncate the torn tail before appending."""
        try:
            raw = open(self._wal_path, "rb").read()
        except FileNotFoundError:
            return 0
        pos = 0
        while pos < len(raw):
            try:
                seq, payload, end = decode_record(raw, pos)
            except MalformedInput:
                break  # torn tail
            if seq <= self._ckpt_seq:
                pos = end
                continue  # folded into the checkpoint already
            try:
                ops = decode_txn(Decoder(payload))
            except DecodeError:
                break
            txn = Transaction()
            txn.ops = ops
            try:
                self._mem.queue_transaction(txn)
            except Exception as e:
                # a record whose base state is gone (checkpoint lost
                # to bit rot, so this txn's preconditions vanished):
                # stop replay at the last applicable prefix and SAY
                # so — the prefix contract holds, the loss is
                # surfaced, and the store still mounts
                self.last_mount_error = (
                    (self.last_mount_error or "") +
                    f"; WAL record seq {seq} no longer applies "
                    f"({e!r}) — replay stopped there").lstrip("; ")
                self.log.derr(f"wal: {self.last_mount_error}")
                break
            pos = end
            self._seq = seq  # race-ok: mount-time replay, single-threaded before any writer exists
        return pos

    # -- reads delegate to the in-memory twin -------------------------
    def read(self, cid, oid, offset=0, length=-1) -> bytes:
        return self._mem.read(cid, oid, offset, length)

    def stat(self, cid, oid) -> Optional[Dict]:
        return self._mem.stat(cid, oid)

    def getattr(self, cid, oid, key) -> Optional[bytes]:
        return self._mem.getattr(cid, oid, key)

    def omap_get(self, cid, oid) -> Dict[str, bytes]:
        return self._mem.omap_get(cid, oid)

    def list_collections(self) -> List[str]:
        return self._mem.list_collections()

    def list_objects(self, cid) -> List[str]:
        return self._mem.list_objects(cid)

    def collection_exists(self, cid) -> bool:
        return self._mem.collection_exists(cid)
