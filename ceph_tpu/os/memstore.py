"""MemStore — the in-RAM ObjectStore backend.

The role of src/os/memstore/MemStore.{h,cc}: a dict-of-dicts store
applying transactions under one lock (transactions are small; the OSD
serializes per-PG anyway).  Atomicity: ops are applied to a shallow
working copy of the touched objects and swapped in only when every op
succeeded — a failed op leaves the store untouched (the
queue_transaction contract recovery relies on).

``export_state``/``import_state`` serialize the whole store — the
checkpoint/restart path the OSD-analogue service uses as its
superblock+journal stand-in.
"""

from __future__ import annotations

import errno
from typing import Dict, List, Optional

from ..analysis import faults
from ..analysis.lockdep import make_rlock
from ..common import copytrack, encoding
from .objectstore import (ObjectStore, Transaction, OP_CLONE, OP_MKCOLL,
                          OP_OMAP_CLEAR, OP_OMAP_RMKEYS,
                          OP_OMAP_SETKEYS, OP_REMOVE, OP_RMATTR,
                          OP_RMCOLL, OP_SETATTR, OP_TOUCH, OP_TRUNCATE,
                          OP_WRITE, OP_ZERO)


class _Object:
    __slots__ = ("data", "xattr", "omap")

    def __init__(self):
        self.data = bytearray()
        self.xattr: Dict[str, bytes] = {}
        self.omap: Dict[str, bytes] = {}

    def clone(self) -> "_Object":
        o = _Object()
        o.data = bytearray(self.data)
        o.xattr = dict(self.xattr)
        o.omap = dict(self.omap)
        return o


class TransactionError(Exception):
    pass


class MemStore(ObjectStore):
    def __init__(self, copy_coll=None):
        self._coll: Dict[str, Dict[str, _Object]] = {}
        self._lock = make_rlock("os::mem")
        # byte-copy ledger target: a mounting daemon passes its
        # Context's collection so store_txn bookings ride that
        # daemon's asok perf dump; library/test use books globally
        self._copy_pc = copytrack.ledger(copy_coll)

    # -- transaction application --------------------------------------
    def queue_transaction(self, txn: Transaction) -> None:
        with self._lock:  # RLock: spans prepare AND commit — atomic
            self.prepare_transaction(txn)()
        # copy ledger: each OP_WRITE materialises its payload into
        # the object's backing bytearray once (full replace or RMW
        # splice).  The WAL path books its own queue_transaction —
        # it calls prepare_transaction directly, never this method,
        # so the two sites can't double count.
        nbytes = sum(len(op[4]) for op in txn.ops
                     if op[0] == OP_WRITE)
        if nbytes:
            copytrack.book_pc(self._copy_pc, "store_txn", nbytes,
                              copies=1)

    def prepare_transaction(self, txn: Transaction):
        """Validate and stage a transaction without committing it;
        returns a cannot-fail commit callable that swaps the staged
        state in.  WAL stores journal between the two, so a journaled
        record is always applicable and a failed validation never
        journals.  The caller is responsible for serializing
        prepare→commit windows (WALStore holds its own lock across
        both); interleaved prepares would lose updates."""
        with self._lock:
            # lazy copy-on-touch: only the top-level dict is copied up
            # front; a collection's object dict is copied the first
            # time an op touches it (a shard write must not cost
            # O(total objects across all PGs))
            staged = dict(self._coll)
            copied: set = set()
            for op in txn.ops:
                self._apply(staged, copied, op)

        def commit():
            with self._lock:
                self._coll = staged

        return commit

    @staticmethod
    def _coll_for_write(staged, copied, cid: str):
        if cid not in staged:
            raise TransactionError(f"no collection {cid!r}")
        if cid not in copied:
            staged[cid] = dict(staged[cid])
            copied.add(cid)
        return staged[cid]

    def _obj(self, staged, copied, cid: str, oid: str,
             create: bool = False) -> _Object:
        objs = self._coll_for_write(staged, copied, cid)
        o = objs.get(oid)
        if o is None:
            if not create:
                raise TransactionError(f"no object {cid}/{oid}")
            o = _Object()
            objs[oid] = o
        else:
            # copy-on-write: staged holds shallow copies of the
            # collection dicts; objects mutate via private clones
            o = o.clone()
            objs[oid] = o
        return o

    def _apply(self, staged, copied, op) -> None:
        kind = op[0]
        if kind == OP_MKCOLL:
            _, cid = op
            if cid in staged:
                raise TransactionError(f"collection {cid!r} exists")
            staged[cid] = {}
            copied.add(cid)
        elif kind == OP_RMCOLL:
            _, cid = op
            if staged.get(cid):
                raise TransactionError(f"collection {cid!r} not empty")
            if cid not in staged:
                raise TransactionError(f"no collection {cid!r}")
            del staged[cid]
        elif kind == OP_TOUCH:
            _, cid, oid = op
            self._obj(staged, copied, cid, oid, create=True)
        elif kind == OP_WRITE:
            _, cid, oid, offset, data = op
            o = self._obj(staged, copied, cid, oid, create=True)
            if offset == 0 and len(o.data) <= len(data):
                # full replace (the data-path common case): one copy,
                # no zero-fill pass
                o.data = bytearray(data)
            else:
                end = offset + len(data)
                if len(o.data) < end:
                    o.data.extend(b"\0" * (end - len(o.data)))
                o.data[offset:end] = data
        elif kind == OP_ZERO:
            _, cid, oid, offset, length = op
            # extends past EOF like the reference's _zero-via-_write
            o = self._obj(staged, copied, cid, oid)
            end = offset + length
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[offset:end] = b"\0" * (end - offset)
        elif kind == OP_TRUNCATE:
            _, cid, oid, size = op
            o = self._obj(staged, copied, cid, oid)
            if len(o.data) > size:
                del o.data[size:]
            else:
                o.data.extend(b"\0" * (size - len(o.data)))
        elif kind == OP_REMOVE:
            _, cid, oid = op
            if cid not in staged or oid not in staged[cid]:
                raise TransactionError(f"no object {cid}/{oid}")
            del self._coll_for_write(staged, copied, cid)[oid]
        elif kind == OP_CLONE:
            _, cid, src, dst = op
            o = self._obj(staged, copied, cid, src)
            self._coll_for_write(staged, copied, cid)[dst] = o.clone()
        elif kind == OP_SETATTR:
            _, cid, oid, key, value = op
            self._obj(staged, copied, cid, oid, create=True).xattr[key] = value
        elif kind == OP_RMATTR:
            _, cid, oid, key = op
            self._obj(staged, copied, cid, oid).xattr.pop(key, None)
        elif kind == OP_OMAP_SETKEYS:
            _, cid, oid, kv = op
            self._obj(staged, copied, cid, oid, create=True).omap.update(kv)
        elif kind == OP_OMAP_RMKEYS:
            _, cid, oid, keys = op
            o = self._obj(staged, copied, cid, oid)
            for k in keys:
                o.omap.pop(k, None)
        elif kind == OP_OMAP_CLEAR:
            _, cid, oid = op
            self._obj(staged, copied, cid, oid).omap.clear()
        else:
            raise TransactionError(f"unknown op {kind!r}")

    # -- reads --------------------------------------------------------
    def read(self, cid: str, oid: str, offset: int = 0,
             length: int = -1) -> bytes:
        if faults.fires("os.read_eio"):
            # the filestore_debug_inject_read_err role: a bad sector
            # under an object — WALStore delegates reads here, so one
            # hook covers both store flavors
            raise OSError(errno.EIO,
                          f"injected read error: {cid}/{oid}")
        with self._lock:
            o = self._coll.get(cid, {}).get(oid)
            if o is None:
                raise KeyError(f"no object {cid}/{oid}")
            # the returned payload must stay valid after the lock
            # drops and later writes mutate o.data, so it cannot be a
            # view into the object
            if length < 0:
                out = bytes(o.data[offset:])  # copy-ok: read materialisation, survives later writes
            else:
                out = bytes(o.data[offset:offset + length])  # copy-ok: read materialisation, survives later writes
        if faults._ACTIVE and faults.fires("store.bit_rot"):
            # silent media corruption: the store returns success with
            # one flipped byte — only crc verification above can tell
            out = faults.flip_byte(out)
        return out

    def stat(self, cid: str, oid: str) -> Optional[Dict]:
        with self._lock:
            o = self._coll.get(cid, {}).get(oid)
            if o is None:
                return None
            return {"size": len(o.data), "xattrs": len(o.xattr),
                    "omap_keys": len(o.omap)}

    def getattr(self, cid: str, oid: str, key: str) -> Optional[bytes]:
        with self._lock:
            o = self._coll.get(cid, {}).get(oid)
            return None if o is None else o.xattr.get(key)

    def omap_get(self, cid: str, oid: str) -> Dict[str, bytes]:
        with self._lock:
            o = self._coll.get(cid, {}).get(oid)
            return dict(o.omap) if o is not None else {}

    def list_collections(self) -> List[str]:
        with self._lock:
            return sorted(self._coll)

    def list_objects(self, cid: str) -> List[str]:
        with self._lock:
            return sorted(self._coll.get(cid, {}))

    def collection_exists(self, cid: str) -> bool:
        with self._lock:
            return cid in self._coll

    # -- checkpoint/restart -------------------------------------------
    def export_state(self) -> Dict:
        with self._lock:
            return {
                cid: {oid: {"data": bytes(o.data).hex(),  # copy-ok: checkpoint export, off the data path
                            "xattr": {k: v.hex()
                                      for k, v in o.xattr.items()},
                            "omap": {k: v.hex()
                                     for k, v in o.omap.items()}}
                      for oid, o in objs.items()}
                for cid, objs in self._coll.items()
            }

    # the wire/disk form of a full-store export (wirecheck entry
    # os.memstore_export): the raw hex-dict state, enveloped
    EXPORT_V = 1

    def export_blob(self) -> str:
        # the collections live under their own key so a future writer
        # can add sibling fields old readers skip (DECODE_FINISH)
        return encoding.encode({"colls": self.export_state()},
                               self.EXPORT_V, 1)

    @classmethod
    def import_blob(cls, blob) -> "MemStore":
        """Lenient: pre-envelope raw-dict exports (writer v0 — the
        bare collections dict) still decode — archived store dumps
        stay importable."""
        v, data = encoding.decode_any(blob, supported=cls.EXPORT_V,
                                      struct="os.memstore_export")
        try:
            state = data if v < 1 else data["colls"]
            return cls.import_state(state)
        except (KeyError, TypeError, ValueError, AttributeError) as e:
            raise encoding.MalformedInput(
                f"os.memstore_export v{v}: bad payload: {e!r}")

    @classmethod
    def import_state(cls, state: Dict) -> "MemStore":
        st = cls()
        for cid, objs in state.items():
            st._coll[cid] = {}
            for oid, od in objs.items():
                o = _Object()
                o.data = bytearray(bytes.fromhex(od["data"]))
                o.xattr = {k: bytes.fromhex(v)
                           for k, v in od["xattr"].items()}
                o.omap = {k: bytes.fromhex(v)
                          for k, v in od["omap"].items()}
                st._coll[cid][oid] = o
        return st
