"""Local object persistence — the reference's src/os surface.

``ObjectStore`` / ``Transaction`` (src/os/ObjectStore.h,
src/os/Transaction.h): transactional collections of named objects with
byte extents, attrs and omap.  ``MemStore`` is the in-RAM backend the
test tiers build on (src/os/memstore — SURVEY §4 explicitly calls for
it); services persist EC shards through this API so a disk-backed
store can slot in behind the same transactions.
"""
