"""KeyValueDB — the src/kv wrapper seam, MemStore-backed.

The reference wraps RocksDB behind ``KeyValueDB`` (get/set/rm by
(prefix, key), iterators, atomic transactions); the monitor and
BlueStore metadata ride it.  Here the same interface runs on an
ObjectStore collection: each prefix is an object, keys live in its
omap — so the KV plane shares the transactional store and its
checkpoint path, and a RocksDB-backed implementation can slot behind
the same class later.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from .memstore import MemStore
from .objectstore import Transaction

_CID = "kv"


class KVTransaction:
    def __init__(self):
        self.ops: List[Tuple[str, str, str, Optional[bytes]]] = []

    def set(self, prefix: str, key: str,
            value: bytes) -> "KVTransaction":
        # copy-ok: KV values are small metadata records the store
        # retains by reference past the caller's buffer lifetime
        self.ops.append(("set", prefix, key, bytes(value)))
        return self

    def rmkey(self, prefix: str, key: str) -> "KVTransaction":
        self.ops.append(("rm", prefix, key, None))
        return self

    def rmkeys_by_prefix(self, prefix: str) -> "KVTransaction":
        self.ops.append(("rmprefix", prefix, "", None))
        return self


class KeyValueDB:
    def __init__(self, store: Optional[MemStore] = None):
        self.store = store or MemStore()
        if not self.store.collection_exists(_CID):
            self.store.queue_transaction(
                Transaction().create_collection(_CID))

    def submit_transaction(self, t: KVTransaction) -> None:
        txn = Transaction()
        for op, prefix, key, value in t.ops:
            if op == "set":
                txn.omap_setkeys(_CID, prefix, {key: value})
            elif op == "rm":
                txn.touch(_CID, prefix)
                txn.omap_rmkeys(_CID, prefix, [key])
            elif op == "rmprefix":
                txn.touch(_CID, prefix)
                txn.omap_clear(_CID, prefix)
        self.store.queue_transaction(txn)

    def get(self, prefix: str, key: str) -> Optional[bytes]:
        return self.store.omap_get(_CID, prefix).get(key)

    def get_by_prefix(self, prefix: str) -> Dict[str, bytes]:
        return dict(self.store.omap_get(_CID, prefix))

    def iterator(self, prefix: str) -> Iterator[Tuple[str, bytes]]:
        return iter(sorted(self.store.omap_get(_CID, prefix).items()))
