"""Fault-injection plane — the named-failpoint registry.

The reference ships a first-class injection surface that made its
thrasher suites possible: ``ms inject socket failures`` (random
connection kills in the messenger, msg/async/AsyncConnection.cc),
``filestore_debug_inject_read_err`` / ``bluestore_debug_inject_read_err``
(sector-level EIO, os/), ``osd_debug_inject_dispatch_delay``, and the
kill points qa/tasks drives through mon/osd debug commands.  This
module is that surface for the framework: every injectable fault is a
*named failpoint*; hot paths ask ``fires(name)`` and get ``False``
after one module-global bool test when nothing is armed, so an unarmed
build pays nothing.

Arming — three equivalent doors, all speaking one spec syntax:

  * config: ``conf.set("fault_inject_spec", SPEC)`` — MiniCluster's
    shared Config propagates it live to every daemon (observer).
  * admin socket: ``fault set|list|clear`` on any daemon
    (``AdminSocket.request(path, "fault", mode="set", spec=SPEC)``).
  * in-process: ``faults.apply_spec(SPEC)`` / ``faults.arm(...)``.

Spec syntax (semicolon-separated failpoints)::

    name=arm[,extra:value...][;name=arm...]
    arm   := p:<float>   fire with probability p per check
           | count:<n>   fire the next n checks, then disarm
           | oneshot     fire exactly once
           | off         explicitly disarmed (documentation value)
    extra := delay:<seconds>     (msgr.delay_frame / osd.slow_op)
           | who:<name-prefix>   only fire for daemons whose name
                                 matches the prefix ("osd.1", "mon")

    e.g.  msgr.corrupt_frame=p:0.02;osd.slow_op=p:0.1,delay:0.05;
          osd.shard_read_eio=count:1,who:osd.2

Determinism: probability arms draw from one module RNG; ``seed(n)``
makes a chaos run reproducible (tools/thrasher.py records the seed in
its CHAOS_r*.json).  Every firing books a per-failpoint counter in
the process-global perf collection (logger ``faults`` — declared in
common/counters.py like every other family), so a soak can assert
each armed failpoint actually fired and `perf dump` shows them.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from .lockdep import make_lock

# every failpoint a hook site checks, with the layer it cuts —
# the README/COVERAGE table and the spec parser's typo guard
FAILPOINTS: Dict[str, str] = {
    # messenger wire faults (ms inject socket failures role)
    "msgr.drop_frame": "outbound frame not sent; connection killed "
                       "(TCP would never silently lose it)",
    "msgr.delay_frame": "outbound frame delayed `delay` seconds",
    "msgr.dup_frame": "outbound frame sent twice",
    "msgr.corrupt_frame": "one payload byte flipped on the wire",
    "msgr.close_mid_frame": "socket hard-closed after a partial "
                            "frame write",
    "msgr.stall_dispatch": "control-lane dispatch callback delayed "
                           "`delay` seconds inside its non-blocking "
                           "scope (asyncheck loop-stall drill)",
    # objectstore / WAL faults (filestore_debug_inject_read_err role)
    "os.read_eio": "objectstore read raises EIO",
    "os.fsync_eio": "WAL group-commit fsync raises EIO (store "
                    "poisons itself, as on a real bad sector)",
    "os.torn_append": "WAL append writes a truncated record then "
                      "fails (torn-write crash image)",
    # osd write-pipeline kill points / delays
    "osd.kill_before_commit": "shard write dropped before the WAL "
                              "commit (daemon died early: no data, "
                              "no ack)",
    "osd.kill_after_commit": "shard write dropped after the WAL "
                             "commit (daemon died late: data landed, "
                             "ack lost)",
    "osd.slow_op": "shard write delayed `delay` seconds",
    "osd.shard_read_eio": "shard read returns EIO; EC reads must "
                          "decode from survivors + mark for repair",
    # store data-corruption faults (silent bit rot on media)
    "store.bit_rot": "one byte flipped in a store shard read; crc "
                     "verification must catch it, degrade the read, "
                     "and mark the shard for repair",
    # monitor faults
    "mon.drop_pg_stats": "monitor drops an incoming pg_stats beacon",
    "mon.isolate_rank": "monitor drops all mon-to-mon traffic "
                        "(rank isolation / partition)",
    # network partitions (directional, daemon-pair scoped): the
    # receiving messenger swallows any typed frame whose sender->
    # receiver pair matches an armed `pairs` extra — no handler, no
    # reply, no ack, exactly the silence a cut link leaves.  The
    # extra is `pairs:<src>><dst>|<src>><dst>...` with name-prefix
    # matching per side and `*` (or empty) as a wildcard; listing
    # only one direction gives an ASYMMETRIC (one-way) cut, e.g.
    # `net.partition=p:1.0,pairs:osd.3>mon|mon>osd.3` (symmetric
    # mon<->osd.3 split) vs `...,pairs:mon.0>mon.2|mon.1>mon.2`
    # (one-way: rank 2 deaf to its peers, its own sends still land)
    "net.partition": "directional traffic drop between scoped "
                     "daemon pairs (pairs:<src>><dst>|..., prefix "
                     "match, '*' wildcard; asymmetric supported)",
    # manager faults
    "mgr.balancer.stale_map": "balancer sweep evaluated a stale "
                              "OSDMap; the round's proposals are "
                              "discarded",
}

_VALID_ARMS = ("p", "count", "oneshot", "off")


class InjectedKill(Exception):
    """A fired kill point: the handler "died" mid-op.  The messenger
    treats it specially — NO reply, NO ack, as if the daemon went
    down holding the op — so the sender sees a timeout/retry, never
    an error reply a live daemon would have framed."""


@dataclass
class FailPoint:
    """One armed failpoint: arm semantics + extras + firing count."""

    name: str
    mode: str                      # "p" | "count" | "oneshot"
    p: float = 0.0
    remaining: int = 0
    extras: Dict[str, str] = field(default_factory=dict)
    fired: int = 0

    def describe(self) -> Dict:
        d: Dict = {"mode": self.mode, "fired": self.fired}
        if self.mode == "p":
            d["p"] = self.p
        if self.mode in ("count", "oneshot"):
            d["remaining"] = self.remaining
        if self.extras:
            d["extras"] = dict(self.extras)
        return d


# -- module state (process-global: the messenger has no Context) ------
_lock = make_lock("faults::plane")
_armed: Dict[str, FailPoint] = {}
_fired_total: Dict[str, int] = {}
_rng = random.Random()
# the zero-overhead switch: every hook site's fires() returns False
# after testing this one bool when nothing is armed
_ACTIVE = False

_pc = None  # lazy: the process-global "faults" PerfCounters


def _counters():
    global _pc
    if _pc is None:
        from ..common.perf_counters import collection

        pc = collection().create("faults")
        for name in FAILPOINTS:
            pc.add_u64_counter(name)  # obs-ok: names enumerate
            # FAILPOINTS, mirrored 1:1 in counters.py's faults family
        _pc = pc
    return _pc


def seed(n: int) -> None:
    """Re-seed the probability arms — a chaos run's reproducibility
    anchor (the thrasher records this in CHAOS_r*.json)."""
    global _rng
    _rng = random.Random(n)


# -- arming -----------------------------------------------------------
def arm(name: str, mode: str = "oneshot", p: float = 0.0,
        count: int = 1, **extras: str) -> None:
    if name not in FAILPOINTS:
        raise ValueError(f"unknown failpoint {name!r} "
                         f"(have: {sorted(FAILPOINTS)})")
    if mode not in _VALID_ARMS:
        raise ValueError(f"unknown arm mode {mode!r}")
    global _ACTIVE
    with _lock:
        if mode == "off":
            _armed.pop(name, None)
        else:
            _armed[name] = FailPoint(
                name, mode, p=p,
                remaining=(1 if mode == "oneshot" else count),
                extras={k: str(v) for k, v in extras.items()})
        _ACTIVE = bool(_armed)


def clear(name: Optional[str] = None) -> None:
    """Disarm one failpoint, or all of them (name=None).  Firing
    totals survive — a soak reads them after clearing."""
    global _ACTIVE
    with _lock:
        if name is None:
            _armed.clear()
        else:
            _armed.pop(name, None)
        _ACTIVE = bool(_armed)


def reset() -> None:
    """Full reset: disarm everything AND zero the firing totals
    (test isolation)."""
    global _ACTIVE
    with _lock:
        _armed.clear()
        _fired_total.clear()
        _ACTIVE = False


def parse_spec(spec: str) -> Dict[str, FailPoint]:
    """Parse a spec string into failpoints (without arming) — raises
    ValueError on unknown names/arms so a typo'd spec fails loudly
    instead of silently injecting nothing."""
    out: Dict[str, FailPoint] = {}
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        name, sep, rest = part.partition("=")
        name = name.strip()
        if not sep or name not in FAILPOINTS:
            raise ValueError(f"bad failpoint {part!r} "
                             f"(have: {sorted(FAILPOINTS)})")
        tokens = [t.strip() for t in rest.split(",") if t.strip()]
        if not tokens:
            raise ValueError(f"failpoint {name!r} has no arm")
        arm_tok, extras = tokens[0], tokens[1:]
        kind, _, val = arm_tok.partition(":")
        if kind not in _VALID_ARMS:
            raise ValueError(f"unknown arm {arm_tok!r} for {name!r}")
        fp = FailPoint(name, kind)
        if kind == "p":
            fp.p = float(val)
        elif kind == "count":
            fp.remaining = int(val)
        elif kind == "oneshot":
            fp.remaining = 1
        for tok in extras:
            k, sep2, v = tok.partition(":")
            if not sep2:
                raise ValueError(f"bad extra {tok!r} for {name!r}")
            fp.extras[k.strip()] = v.strip()
        out[name] = fp
    return out


def apply_spec(spec: str) -> Dict[str, Dict]:
    """Replace the armed set with what a spec string describes (the
    ``fault_inject_spec`` semantics: the option value IS the armed
    set; an empty string disarms everything)."""
    parsed = parse_spec(spec)
    global _ACTIVE
    with _lock:
        _armed.clear()
        for name, fp in parsed.items():
            if fp.mode != "off":
                _armed[name] = fp
        _ACTIVE = bool(_armed)
    return list_faults()


def list_faults() -> Dict[str, Dict]:
    """The ``fault list`` payload: armed arms + lifetime totals."""
    with _lock:
        return {"armed": {n: fp.describe()
                          for n, fp in _armed.items()},
                "fired": dict(_fired_total)}


def snapshot() -> Dict[str, int]:
    """Lifetime firing totals (what the thrasher records)."""
    with _lock:
        return dict(_fired_total)


# -- the hook-site API ------------------------------------------------
def fires(name: str, who: Optional[str] = None) -> bool:
    """Should the failpoint ``name`` fire for daemon ``who``?  The
    hot-path door: one bool test when nothing is armed anywhere."""
    global _ACTIVE
    if not _ACTIVE:
        return False
    with _lock:
        fp = _armed.get(name)
        if fp is None:
            return False
        target = fp.extras.get("who")
        if target and (who is None or not who.startswith(target)):
            return False
        if fp.mode == "p":
            if _rng.random() >= fp.p:
                return False
        else:  # count / oneshot
            if fp.remaining <= 0:
                return False
            fp.remaining -= 1
            if fp.remaining <= 0:
                del _armed[name]
                _ACTIVE = bool(_armed)
        fp.fired += 1
        _fired_total[name] = _fired_total.get(name, 0) + 1
    _counters().inc(name)
    return True


def _side_match(name: str, pat: str) -> bool:
    return pat in ("", "*") or name.startswith(pat)


def partitioned(src: Optional[str], dst: Optional[str]) -> bool:
    """Directional ``net.partition`` check: should traffic from
    daemon ``src`` to daemon ``dst`` be dropped?  Consulted by the
    receiving messenger per typed frame (the sender's name rides
    every call/send frame as ``frm``).  One bool test when nothing
    is armed, like :func:`fires`."""
    global _ACTIVE
    if not _ACTIVE or not src or not dst:
        return False
    with _lock:
        fp = _armed.get("net.partition")
        if fp is None:
            return False
        for pair in fp.extras.get("pairs", "").split("|"):
            s, sep, d = pair.partition(">")
            if sep and _side_match(src, s.strip()) and \
                    _side_match(dst, d.strip()):
                break
        else:
            return False
        if fp.mode == "p":
            if _rng.random() >= fp.p:
                return False
        else:  # count / oneshot
            if fp.remaining <= 0:
                return False
            fp.remaining -= 1
            if fp.remaining <= 0:
                del _armed["net.partition"]
                _ACTIVE = bool(_armed)
        fp.fired += 1
        _fired_total["net.partition"] = \
            _fired_total.get("net.partition", 0) + 1
    _counters().inc("net.partition")
    return True


def flip_byte(data: bytes) -> bytes:
    """Seeded single-byte corruption for the ``store.bit_rot`` class
    of faults: XOR one RNG-chosen byte with 0xFF.  The draw uses the
    module RNG under the plane lock so a seeded run flips the same
    offset every time."""
    if not data:
        return data
    with _lock:
        i = _rng.randrange(len(data))
    out = bytearray(data)
    out[i] ^= 0xFF
    return bytes(out)


def extra(name: str, key: str, default: float) -> float:
    """An armed failpoint's numeric extra (e.g. the injected delay);
    ``sleep_if`` reads it BEFORE firing, while the arm still exists."""
    with _lock:
        fp = _armed.get(name)
        if fp is None or key not in fp.extras:
            return default
        return float(fp.extras[key])


def sleep_if(name: str, who: Optional[str] = None,
             default_delay: float = 0.05) -> bool:
    """Fire-and-delay helper for the slow-op class of faults; the
    sleep happens HERE so hook sites never sleep under their own
    locks (CONC002)."""
    if not _ACTIVE:
        return False
    delay = extra(name, "delay", default_delay)
    if not fires(name, who):
        return False
    time.sleep(delay)
    return True


# -- wiring -----------------------------------------------------------
_installed_configs: set = set()


def install(config) -> None:
    """Bind a Config to the plane: apply the current
    ``fault_inject_spec`` and track it live (observer).  Idempotent
    per Config — MiniCluster shares one Config across every daemon
    Context, and one observer is enough."""
    if "fault_inject_spec" not in config.schema:
        return
    if id(config) in _installed_configs:
        return
    _installed_configs.add(id(config))

    def _cb(_name, value):
        apply_spec(value or "")

    config.add_observer("fault_inject_spec", _cb)
    current = config["fault_inject_spec"]
    if current:
        apply_spec(current)


def wire(sock) -> None:
    """Register the ``fault`` admin-socket command:
    ``fault mode=set spec=...`` | ``fault mode=list`` |
    ``fault mode=clear [name=...]``."""
    def _h(a: Dict) -> Dict:
        mode = a.get("mode", "list")
        if mode == "set":
            return apply_spec(a.get("spec", ""))
        if mode == "clear":
            clear(a.get("name"))
            return list_faults()
        if mode == "seed":
            seed(int(a["value"]))
            return {"seeded": int(a["value"])}
        return list_faults()

    sock.register("fault", _h,
                  "fault injection: mode=set spec=<spec> | "
                  "mode=list | mode=clear [name=] | mode=seed "
                  "value=<n>")
