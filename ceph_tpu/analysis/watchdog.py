"""Stall/deadlock watchdog — flag wedged locks and handlers, dump
every thread's stack.

The heartbeat-timeout role of the reference's internal watchdogs
(OSD op thread timeouts, ``dump_historic_ops`` for the slow tail,
lockdep backtraces for the wedged case): a daemon thread scans

- the lockdep held-lock table (analysis/lockdep.py): any lock held
  beyond the threshold, and
- the SECTION registry: any instrumented code region (a messenger
  handler, a scheduler job) running beyond the threshold,

and on the first offence of each offender writes a full all-thread
stack dump to stderr — the information a wedged-cluster post-mortem
actually needs, available the moment the wedge forms instead of after
a kill -9.  ``dump_blocked()`` serves the same snapshot on demand and
is wired into every daemon's admin socket as the ``dump_blocked``
command (common/admin_socket.py), next to ``dump_historic_ops``.

Stack capture uses ``sys._current_frames`` — read-only, no tracing
hooks, safe to run against live threads.
"""

from __future__ import annotations

import contextlib
import itertools
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

from . import lockdep

# raw lock: the registry must never feed the graph it helps debug
_sections_lock = threading.Lock()  # watchdog's own registry lock
_sections: Dict[int, Dict] = {}
_tokens = itertools.count()


@contextlib.contextmanager
def section(name: str):
    """Mark a code region the watchdog should time, e.g. a messenger
    handler execution (``with watchdog.section(f"handler:{type_}")``)."""
    tok = next(_tokens)
    info = {"name": name,
            "thread": threading.current_thread().name,
            "since": time.monotonic()}
    with _sections_lock:
        _sections[tok] = info
    try:
        yield
    finally:
        with _sections_lock:
            _sections.pop(tok, None)


def thread_stacks() -> Dict[str, str]:
    """Formatted stack per live thread, keyed ``name(ident)``."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, '?')}({tid})"
        out[label] = "".join(traceback.format_stack(frame))
    return out


def dump_blocked(threshold: float = 0.0,
                 with_stacks: bool = True) -> Dict:
    """The ``dump_blocked`` admin-socket payload: locks held and
    sections running at least ``threshold`` seconds, plus (optionally)
    every thread's current stack."""
    now = time.monotonic()
    locks = []
    for info in lockdep.held_snapshot():
        age = now - info["since"]
        if age >= threshold:
            locks.append({"name": info["name"],
                          "thread": info["thread"],
                          "depth": info["depth"],
                          "held_secs": round(age, 3)})
    sections = []
    with _sections_lock:
        for info in _sections.values():
            age = now - info["since"]
            if age >= threshold:
                sections.append({"name": info["name"],
                                 "thread": info["thread"],
                                 "running_secs": round(age, 3)})
    out = {"threshold": threshold, "blocked_locks": locks,
           "stalled_sections": sections}
    if with_stacks:
        out["threads"] = thread_stacks()
    return out


class Watchdog:
    """Scan loop over the lock + section registries.

    Each offender (a specific hold/run instance, keyed by its start
    stamp) is reported once, to ``reports`` and stderr with a full
    thread dump; a lock re-acquired later starts a fresh instance."""

    def __init__(self, threshold: float = 30.0,
                 interval: Optional[float] = None, stream=None):
        self.threshold = threshold
        self.interval = interval if interval is not None \
            else max(0.25, threshold / 4.0)
        self.stream = stream if stream is not None else sys.stderr
        self.reports: List[Dict] = []
        self._seen: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="conc-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll()
            except Exception as e:  # the scanner must never die silently
                self.stream.write(f"watchdog poll failed: {e!r}\n")

    def poll(self, now: Optional[float] = None) -> List[Dict]:
        """One scan; returns the NEW reports it generated (tests drive
        this directly for determinism)."""
        now = time.monotonic() if now is None else now
        fresh: List[Dict] = []
        for info in lockdep.held_snapshot():
            age = now - info["since"]
            if age >= self.threshold:
                key = ("lock", info["name"], info["thread"],
                       info["since"])
                if key not in self._seen:
                    self._seen.add(key)
                    fresh.append({"kind": "lock", "name": info["name"],
                                  "thread": info["thread"],
                                  "age": round(age, 3)})
        with _sections_lock:
            stalled = [(tok, dict(info))
                       for tok, info in _sections.items()
                       if now - info["since"] >= self.threshold]
        for tok, info in stalled:
            key = ("section", tok)
            if key not in self._seen:
                self._seen.add(key)
                fresh.append({"kind": "section", "name": info["name"],
                              "thread": info["thread"],
                              "age": round(now - info["since"], 3)})
        if fresh:
            self.reports.extend(fresh)
            self._emit(fresh)
        return fresh

    def _emit(self, fresh: List[Dict]) -> None:
        w = self.stream.write
        w(f"\n=== watchdog: {len(fresh)} stalled "
          f"(threshold {self.threshold}s) ===\n")
        for r in fresh:
            w(f"  {r['kind']} {r['name']!r} on {r['thread']} "
              f"for {r['age']}s\n")
        for label, stack in thread_stacks().items():
            w(f"--- thread {label} ---\n{stack}")
        w("=== end watchdog report ===\n")


_global: Optional[Watchdog] = None


def start_global(threshold: float = 30.0,
                 interval: Optional[float] = None) -> Watchdog:
    """Process-wide singleton (idempotent; re-thresholds on repeat)."""
    global _global
    if _global is None:
        _global = Watchdog(threshold, interval).start()
    else:
        _global.threshold = threshold
        if interval is not None:
            _global.interval = interval
    return _global


def global_watchdog() -> Optional[Watchdog]:
    return _global
