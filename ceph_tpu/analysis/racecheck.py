"""Racecheck — guarded-state registry + Eraser-style lockset checker.

The data-race half of the sanitizer plane: lockdep (this package)
catches lock-ORDER cycles, this module catches lock-COVERAGE holes —
a field written under no lock, or under the wrong lock, from two
threads.  The reference runs its threaded core under lockdep.cc *and*
ThreadSanitizer in CI; this is the TSan role, recast on top of the
named-lock registry so a violation can say which declared guard was
missing.

Usage::

    from ..analysis.racecheck import guarded_by, shared

    @guarded_by("msgr::conn", "_conns", "_accepted")
    @guarded_by("msgr::pending", "_pending", "_waiters")
    class Messenger: ...

    _sock_writers = shared({}, guard="msgr::send_guard",
                           name="msgr.sock_writers")

``guarded_by(lock_name, *fields)`` declares which named lock guards
which shared mutable attributes.  Instrumented reads/writes consult
lockdep's per-thread held-lock set and refine a per-field candidate
lockset (the Eraser algorithm): the set seeds from the locks held at
the first genuinely-shared access and shrinks by intersection on
every later one; a write (or a read after a shared-state write) with
an EMPTY candidate set is a violation, reported with BOTH access
stacks — the racing write and the current access — exactly like
lockdep's two-witness cycle reports.

Init phase: every instance starts in a single-owner phase bound to
the constructing thread; accesses by that thread are unchecked, so
constructors never false-positive.  The phase ends at an explicit
``publish(obj)`` or implicitly on the first access from any other
thread (the object escaped — Eraser's Exclusive->Shared edge).

``owned_by_thread=(...)`` declares writer-confined fields (a sampler
thread's own books): the first post-publish write binds the owning
thread and any later write from another thread is a confinement
violation.  Reads stay free — telemetry may peek.

``shared(container, guard=..., name=...)`` wraps a bare dict/list
whose guard cannot ride a class decorator (module-level tables,
per-instance free lists): every MUTATION must hold the named guard
once the container has been touched by a second thread; lock-free
reads stay legal (the GIL-atomic ``get()`` idiom).

Enablement mirrors lockdep: ``CEPH_TPU_RACECHECK=1`` (on for the
whole test suite via conftest) or ``enable(True)``.  When disabled at
import/decoration time the decorators are identity functions — zero
production overhead.  Lockset consultation needs lockdep's held set,
so checking is live only when BOTH planes are enabled.

Violations are recorded, not raised (a racing thread must not crash
mid-flight); the per-test conftest gate fails the owning test, the
``dump_racecheck`` admin command and the ``analysis.race.*`` counters
surface them in a live cluster, and ``tools/thrasher.py --race-audit``
drives the chaos drills under the checker.
"""

from __future__ import annotations

import os
import sys
import threading
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Tuple

from . import lockdep

ENV = "CEPH_TPU_RACECHECK"

_forced: Optional[bool] = None

# registry bookkeeping (decoration-time; read by dump()/counters)
_guarded_classes: List[str] = []
_guarded_fields: int = 0
_shared_objects: int = 0

_violations: List[Dict] = []
_vlock = threading.Lock()

_STATE_KEY = "__racecheck_state__"
_MAX_FRAMES = 12


# read once at import: every entry point (conftest, thrasher's
# --race-audit, the bench subprocesses) sets the env before importing
# ceph_tpu; enable() overrides at runtime
_env_on = os.environ.get(ENV, "") not in ("", "0")


def enabled() -> bool:
    if _forced is not None:
        return _forced
    return _env_on


def enable(on: bool = True) -> None:
    """Force the plane on/off at runtime (tests).  Note decoration
    happens at import: enabling here only activates classes that were
    decorated while the plane was enabled."""
    global _forced
    _forced = on


def _active() -> bool:
    # lockset refinement is meaningless without lockdep's held set
    return enabled() and lockdep.enabled()


def _held_names() -> frozenset:
    return lockdep.held_names()  # per-thread cached


def _fast_stack() -> Tuple[tuple, ...]:
    """A cheap stack witness: raw (file, line, func) frames walked
    via _getframe (traceback.extract_stack is ~10x the cost and this
    runs on hot guarded writes); formatting is deferred to report
    time.  Skips racecheck's own frames."""
    out = []
    f = sys._getframe(1)
    own = __file__
    while f is not None and len(out) < _MAX_FRAMES:
        code = f.f_code
        if code.co_filename != own:
            out.append((code.co_filename, f.f_lineno,
                        code.co_name))
        f = f.f_back
    return tuple(out)


def _fmt_stack(frames: Optional[Tuple[tuple, ...]]) -> str:
    if not frames:
        return "  (no prior access recorded)\n"
    return "\n".join(f"  {fn}:{ln} in {fun}"
                     for fn, ln, fun in frames) + "\n"


class _Access:
    """One recorded access: the potential racing-write witness."""

    __slots__ = ("stack", "thread", "locks", "write")

    def __init__(self, stack, thread, locks, write):
        self.stack = stack
        self.thread = thread
        self.locks = locks
        self.write = write


class _FieldState:
    __slots__ = ("tid", "lockset", "written", "last", "reported",
                 "lh", "wc")

    def __init__(self, tid: int):
        self.tid: Optional[int] = tid  # exclusive owner; None = shared
        self.lockset: Optional[frozenset] = None
        self.written = False
        self.last: Optional[_Access] = None
        self.reported = False
        # hot-path bookkeeping: the held-names frozenset OBJECT seen
        # by the last shared read (lockdep's per-thread cache returns
        # the same object while that thread's held set is unchanged,
        # so an identity hit means refinement can learn nothing new)
        # and the write count driving witness-capture throttling
        self.lh: Optional[frozenset] = None
        self.wc = 0


class _RCState:
    __slots__ = ("owner", "published", "cls", "fields")

    def __init__(self, owner: int, cls: str):
        self.owner = owner
        self.published = False
        self.cls = cls
        self.fields: Dict[str, _FieldState] = {}


def _state_of(obj, cls_name: str) -> _RCState:
    d = obj.__dict__
    st = d.get(_STATE_KEY)
    if st is None:
        st = d[_STATE_KEY] = _RCState(threading.get_ident(), cls_name)
    return st


def _record(kind: str, message: str, existing: Optional[_Access],
            current_stack: Tuple[str, ...],
            current_locks: frozenset) -> None:
    rec = {
        "kind": kind,
        "message": message,
        "thread": threading.current_thread().name,
        "current_stack": _fmt_stack(current_stack),
        "current_locks": sorted(current_locks),
        "existing_stack": _fmt_stack(existing.stack
                                     if existing else None),
        "existing_thread": existing.thread if existing else "?",
        "existing_locks": sorted(existing.locks) if existing else [],
    }
    with _vlock:
        _violations.append(rec)
    try:
        _race_pc().inc("violations")
    except Exception:
        pass  # counters must never mask the violation record itself


_pc_cache = None


def _race_pc():
    """The process-global analysis.race counter family (created
    lazily: perf_counters imports lockdep from this package, so the
    edge back must not run at module import)."""
    global _pc_cache
    if _pc_cache is None:
        from ..common.perf_counters import collection

        pc = collection().create("analysis.race")
        pc.add_u64_counter("violations")
        pc.add_u64("guarded_classes")
        pc.add_u64("guarded_fields")
        pc.add_u64("shared_objects")
        _pc_cache = pc
    return _pc_cache


def _sync_gauges() -> None:
    if not enabled():
        return
    try:
        pc = _race_pc()
    except Exception:
        return
    pc.set("guarded_classes", len(_guarded_classes))
    pc.set("guarded_fields", _guarded_fields)
    pc.set("shared_objects", _shared_objects)


# -- the checker core -------------------------------------------------

def _check(obj, cls_name: str, field: str, guard: str, owned: bool,
           is_write: bool) -> None:
    if not _active():
        return
    st = _state_of(obj, cls_name)
    tid = threading.get_ident()
    if not st.published:
        if tid == st.owner:
            return  # single-owner init phase: unchecked
        st.published = True  # escaped before publish(): implicit edge
    fs = st.fields.get(field)
    if fs is None:
        fs = st.fields[field] = _FieldState(tid)
        if is_write:
            fs.written = False  # exclusive write: not yet a shared one
            fs.last = _Access(_fast_stack(),
                              threading.current_thread().name,
                              _held_names(), True)
        return
    if owned:
        if not is_write:
            return  # writer confinement only: reads may peek
        if fs.tid is None:
            fs.tid = tid  # first post-publish write binds the owner
        elif fs.tid != tid and not fs.reported:
            fs.reported = True
            cur = _fast_stack()
            _record(
                "confinement",
                f"{cls_name}.{field} is owned_by_thread (bound to "
                f"{fs.last.thread if fs.last else fs.tid}) but was "
                f"written from thread "
                f"{threading.current_thread().name!r}",
                fs.last, cur, _held_names())
        fs.last = _Access(_fast_stack(),
                          threading.current_thread().name,
                          _held_names(), True)
        return
    held = _held_names()
    if fs.tid is not None and fs.tid == tid:
        # still exclusive to one thread: no lockset discipline yet
        if is_write:
            fs.wc += 1
            if fs.wc < 64 or not fs.wc % 64:
                fs.last = _Access(_fast_stack(),
                                  threading.current_thread().name,
                                  held, True)
        return
    if not is_write and held is fs.lh:
        # identity hit: lockdep's per-thread cache hands back the
        # SAME frozenset object while this thread's held set is
        # unchanged, so this read refines exactly like the last one
        # did — nothing new to learn (the hot-loop fast path)
        return
    changed = False
    if fs.tid is not None:
        # Exclusive -> Shared: seed the candidate lockset from the
        # locks held NOW (Eraser's C(v) initialisation)
        fs.tid = None
        fs.lockset = held
        changed = True
    else:
        refined = fs.lockset & held \
            if fs.lockset is not None else held
        changed = refined != fs.lockset
        fs.lockset = refined
    if is_write:
        fs.written = True
    elif fs.lockset:
        fs.lh = held  # clean read: arm the identity fast path
    if not fs.lockset and fs.written and not fs.reported:
        fs.reported = True
        cur = _fast_stack()
        _record(
            "lockset",
            f"{cls_name}.{field} (declared guard {guard!r}): "
            f"candidate lockset is EMPTY — "
            f"{'write' if is_write else 'read-after-write'} with "
            f"locks {sorted(held) or '{}'} races a prior access",
            fs.last, cur, held)
    if is_write or changed:
        # the racing-write witness, capture-throttled past 64 writes
        # (a hot field's report may then show a slightly stale write
        # site — still a genuine racing writer); lockset shrinks are
        # monotonic so read-side captures stay rare
        fs.wc += 1
        if fs.wc < 64 or not fs.wc % 64 or changed:
            fs.last = _Access(_fast_stack(),
                              threading.current_thread().name,
                              held, is_write)


class _GuardedField:
    """Data descriptor installed per declared field: intercepts
    attribute reads/writes and feeds the lockset checker.  Values
    live in the instance ``__dict__`` under the same name (the data
    descriptor wins the lookup)."""

    __slots__ = ("field", "guard", "owned", "cls_name")

    def __init__(self, field: str, guard: str, owned: bool,
                 cls_name: str):
        self.field = field
        self.guard = guard
        self.owned = owned
        self.cls_name = cls_name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        _check(obj, self.cls_name, self.field, self.guard,
               self.owned, False)
        try:
            return obj.__dict__[self.field]
        except KeyError:
            raise AttributeError(
                f"{self.cls_name!r} object has no attribute "
                f"{self.field!r}") from None

    def __set__(self, obj, value):
        _check(obj, self.cls_name, self.field, self.guard,
               self.owned, True)
        obj.__dict__[self.field] = value

    def __delete__(self, obj):
        _check(obj, self.cls_name, self.field, self.guard,
               self.owned, True)
        try:
            del obj.__dict__[self.field]
        except KeyError:
            raise AttributeError(
                f"{self.cls_name!r} object has no attribute "
                f"{self.field!r}") from None


def guarded_by(lock_name: str, *fields: str,
               owned_by_thread: Iterable[str] = ()):
    """Class decorator: declare that ``lock_name`` guards ``fields``.

    Stackable — a class with two locks applies it twice.  Classes
    using ``__slots__`` are rejected: wrap the owning container (the
    attribute holding the slotted objects) instead, which is where
    the sharing decision is made anyway.
    """
    owned = tuple(owned_by_thread)

    def deco(cls):
        global _guarded_fields
        if not enabled():
            return cls
        if "__slots__" in cls.__dict__:
            raise TypeError(
                f"guarded_by: {cls.__name__} uses __slots__; declare "
                f"the guard on the attribute holding these objects "
                f"instead")
        for field in tuple(fields) + owned:
            setattr(cls, field,
                    _GuardedField(field, lock_name,
                                  field in owned, cls.__name__))
            _guarded_fields += 1
        _guarded_classes.append(
            f"{cls.__module__}.{cls.__name__}[{lock_name}]")
        _sync_gauges()
        return cls

    return deco


def publish(obj) -> None:
    """End the single-owner init phase NOW: later accesses — even
    from the constructing thread — run under full lockset
    discipline.  Optional: the first access from a second thread
    publishes implicitly."""
    if not _active():
        return
    st = _state_of(obj, type(obj).__name__)
    st.published = True
    st.fields.clear()


# -- shared(): guarded proxy for bare dicts/lists ---------------------

_MUTATORS_COMMON = ("__setitem__", "__delitem__", "clear", "pop")
_MUTATORS_DICT = ("setdefault", "update", "popitem")
_MUTATORS_LIST = ("append", "extend", "insert", "remove", "sort",
                  "reverse", "__iadd__")
_READERS = ("__getitem__", "__contains__", "__len__", "__iter__",
            "__bool__", "__eq__", "__ne__", "__repr__", "get", "keys",
            "values", "items", "copy", "count", "index", "__reversed__")


class _SharedProxy:
    """Mutation-checked wrapper around a dict or list: every mutating
    call must hold the declared guard once the container is shared
    between threads.  Reads stay lock-free — the GIL-atomic ``get()``
    pattern is a deliberate idiom on hot paths."""

    __slots__ = ("_target", "_guard", "_name", "_owner", "_published",
                 "_last_mut", "_reported")

    def __init__(self, target, guard: str, name: str):
        self._target = target
        self._guard = guard
        self._name = name
        self._owner = threading.get_ident()
        self._published = False
        self._last_mut: Optional[_Access] = None
        self._reported = False

    def _mutate(self) -> None:
        if not _active():
            return
        tid = threading.get_ident()
        if not self._published:
            if tid == self._owner:
                return
            self._published = True
        held = _held_names()
        if self._guard not in held and not self._reported:
            self._reported = True
            cur = _fast_stack()
            _record(
                "lockset",
                f"shared({self._name!r}): mutation without its "
                f"declared guard {self._guard!r} (held: "
                f"{sorted(held) or '{}'})",
                self._last_mut, cur, held)
        self._last_mut = _Access(_fast_stack(),
                                 threading.current_thread().name,
                                 held, True)

    def _touch(self) -> None:
        # a read from a second thread publishes (the container
        # escaped); reads themselves are never checked
        if not self._published and \
                threading.get_ident() != self._owner:
            self._published = True


def _proxy_method(mname: str, mutating: bool):
    if mutating:
        def call(self, *a, **kw):
            self._mutate()
            return getattr(self._target, mname)(*a, **kw)
    else:
        def call(self, *a, **kw):
            self._touch()
            return getattr(self._target, mname)(*a, **kw)
    call.__name__ = mname
    return call


for _m in _MUTATORS_COMMON + _MUTATORS_DICT + _MUTATORS_LIST:
    setattr(_SharedProxy, _m, _proxy_method(_m, True))
for _m in _READERS:
    setattr(_SharedProxy, _m, _proxy_method(_m, False))
del _m


def shared(container, guard: str, name: str):
    """Wrap a bare dict/list in a mutation-checked proxy declaring
    ``guard`` as its lock.  Identity passthrough when the plane is
    disabled at call time — zero production overhead."""
    global _shared_objects
    if not enabled():
        return container
    _shared_objects += 1
    _sync_gauges()
    return _SharedProxy(container, guard, name)


# -- surfaces ---------------------------------------------------------

def violations() -> List[Dict]:
    with _vlock:
        return list(_violations)


def clear_violations() -> None:
    with _vlock:
        _violations.clear()


@contextmanager
def trap():
    """Capture-and-remove violations recorded inside the block (the
    lockdep.trap() twin — tests provoke races without tripping the
    conftest gate)."""
    with _vlock:
        base = len(_violations)
    got: List[Dict] = []
    try:
        yield got
    finally:
        with _vlock:
            got.extend(_violations[base:])
            del _violations[base:]


def mark() -> int:
    """Per-test gate anchor: the violation count before the test."""
    with _vlock:
        return len(_violations)


def gate_check(base: int) -> Optional[str]:
    """The conftest gate body: format violations recorded past
    ``base`` (both stacks, lockdep-report style) and clear them so a
    single race cannot re-fail every later test.  Returns None when
    clean."""
    with _vlock:
        vs = _violations[base:]
        if not vs:
            return None
        _violations.clear()
    detail = "\n".join(
        f"- {v['message']} [{v['thread']}]\n"
        f"  racing access ({v['existing_thread']}, locks "
        f"{v['existing_locks']}) at:\n{v['existing_stack']}"
        f"  current access (locks {v['current_locks']}) at:\n"
        f"{v['current_stack']}"
        for v in vs)
    return (f"racecheck: {len(vs)} data-race violation(s) recorded "
            f"during this test:\n{detail}")


def dump() -> Dict:
    """The ``dump_racecheck`` admin-command payload."""
    with _vlock:
        vs = list(_violations)
    return {
        "enabled": enabled(),
        "active": _active(),
        "guarded_classes": list(_guarded_classes),
        "guarded_fields": _guarded_fields,
        "shared_objects": _shared_objects,
        "violations": vs,
        "num_violations": len(vs),
    }
