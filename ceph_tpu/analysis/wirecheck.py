"""Wire-format conformance — the ceph-dencoder / object-corpus role.

The reference proves every wire/disk structure with three machines:
``ceph-dencoder`` (encode/decode any registered type from the command
line), the ceph-object-corpus (committed encodings of every struct at
every historical version, byte-compared and back-decoded each build),
and ``test/encoding/readable.sh`` (old blobs must stay readable).
This module is all three for this framework: a declarative registry of
every wire/disk type in the system — messenger frames (each typed
family), OSDMap full/crush binary encodes, Incremental deltas, crush
JSON, WALStore records and compressed checkpoints, cephx keyring and
tickets, MemStore exports, PG log entries, rbd image headers, and the
monitor's epoch-store payload — each entry carrying its
struct_v/compat_v, a deterministic example factory, and its
encode/decode pair.

For every entry ``check()`` machine-proves five properties:

1. round-trip identity   decode(encode(x)) == x
2. determinism           encode is byte-stable (twice from fresh
                         examples, and re-encode of the decoded form)
3. forward-compat        a v+1 writer's unknown fields are skipped,
                         per the DECODE_START/DECODE_FINISH contract
4. compat-floor refusal  a blob whose compat exceeds this reader is
                         refused with a typed ``MalformedInput`` —
                         never a hang, assert, or raw KeyError
5. mutation robustness   truncation, length-word and flags tampering,
                         bit flips, undecodable bytes all fail CLEAN
                         (MalformedInput or a benign decode — no
                         other exception class may escape)

tests/test_wirecheck.py runs all five per entry and byte-compares the
committed golden corpus (tests/corpus/encodings/<type>/<struct_v>/);
``ceph_cli dencoder`` is the command-line surface; tools/lint_wire.py
is the static half (WIRE001-WIRE004), fed by ``covered_classes()``
and ``frame_type_names()`` below.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common.encoding import MalformedInput

# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


@dataclass
class WireType:
    """One registered wire/disk format."""

    name: str
    kind: str                 # "json" | "bincode" | "frame" | "custom"
    struct_v: int
    compat_v: int
    factory: Callable[[], Any]
    encode: Callable[[Any], bytes]
    decode: Callable[[bytes], Any]
    # comparable form of a decoded/example object (to_dict and kin)
    extract: Callable[[Any], Any] = lambda o: o
    # craft a blob demanding a FUTURE reader (property 4) / written by
    # a v+1 writer with extra fields (property 3); kind defaults below
    forge_compat: Optional[Callable[[bytes], bytes]] = None
    forge_forward: Optional[Callable[[bytes], bytes]] = None
    # encode(decode(blob)) == blob is additionally enforced when set
    reencode: bool = True
    # source class names this entry proves (lint WIRE002)
    covers: Tuple[str, ...] = ()
    # frame-type literals this entry owns (lint WIRE003)
    frame_types: Tuple[str, ...] = ()
    # legacy pre-envelope blobs (writer v0) decode too
    legacy: bool = False


_REGISTRY: Optional[Dict[str, WireType]] = None


def _to_bytes(blob) -> bytes:
    return blob.encode() if isinstance(blob, str) else bytes(blob)


# -- default forges by codec kind -------------------------------------------

def _json_forge_compat(e: WireType, blob: bytes) -> bytes:
    env = json.loads(blob)
    env["v"] = env["compat"] = e.struct_v + 1
    return json.dumps(env).encode()


def _json_forge_forward(e: WireType, blob: bytes) -> bytes:
    env = json.loads(blob)
    env["v"] = e.struct_v + 1
    if isinstance(env.get("data"), dict):
        env["data"]["__added_in_v_next__"] = {"unknown": True}
    return json.dumps(env).encode()


def _bin_forge_compat(e: WireType, blob: bytes) -> bytes:
    # bincode envelope at offset 0: u8 struct_v, u8 compat_v, u32 len
    return bytes([blob[0] + 1, blob[1] + 1]) + blob[2:]


def _bin_forge_forward(e: WireType, blob: bytes) -> bytes:
    # a v+1 writer appended 4 unknown bytes inside the envelope: bump
    # struct_v, splice at the envelope end, patch the length word —
    # DECODE_FINISH must skip them
    (ln,) = struct.unpack_from("<I", blob, 2)
    end = 6 + ln
    return (bytes([blob[0] + 1]) + blob[1:2]
            + struct.pack("<I", ln + 4) + blob[6:end]
            + b"\x00\x01\x02\x03" + blob[end:])


def _frame_forge_compat(e: WireType, blob: bytes) -> bytes:
    # the frame's compat floor is its version byte
    return bytes([blob[0] + 1]) + blob[1:]


# ---------------------------------------------------------------------------
# example factories (all deterministic — the corpus byte-compares them)
# ---------------------------------------------------------------------------

def _mini_map():
    from ..crush.wrapper import CrushWrapper
    from ..osdmap.osdmap import OSDMap, PgPool

    w = CrushWrapper()
    for d in range(4):
        w.insert_item(d, 0x10000, f"osd.{d}",
                      {"host": f"h{d % 2}", "root": "default"})
    rid = w.add_simple_rule("r", "default", "host", "", "firstn")
    m = OSDMap(w.crush)
    for d in range(4):
        m.add_osd(d)
    m.pools[1] = PgPool(size=2, pg_num=8, crush_rule=rid)
    m.pg_upmap[(1, 1)] = [1, 2]
    m.pg_upmap_items[(1, 2)] = [(0, 3)]
    m.pg_temp[(1, 3)] = [2, 0]
    m.primary_temp[(1, 3)] = 2
    m.set_primary_affinity(1, 0x8000)
    m.epoch = 7
    return m


def _ex_incremental():
    from ..osdmap.incremental import Incremental
    from ..osdmap.osdmap import PgPool

    inc = Incremental(epoch=8)
    inc.new_max_osd = 5
    inc.new_pools = {2: PgPool(size=3, pg_num=4).to_dict()}
    inc.old_pools = [3]
    inc.new_state = {0: 2}            # XOR
    inc.new_weight = {1: 0x8000}
    inc.new_primary_affinity = {2: 0x4000}
    inc.new_pg_upmap = {(1, 1): [0, 1]}
    inc.old_pg_upmap = [(1, 2)]
    inc.new_pg_upmap_items = {(1, 3): [(0, 2)]}
    inc.old_pg_upmap_items = [(1, 4)]
    inc.new_pg_temp = {(1, 5): [1, 0]}
    inc.new_primary_temp = {(1, 5): 1}
    return inc


def _ex_epoch_payload():
    m = _mini_map()
    return {"epoch": m.epoch, "map": m.to_dict(),
            "osd_addrs": {"0": ["127.0.0.1", 6800],
                          "1": ["127.0.0.1", 6801]},
            "ec_profiles": {"ec22": {"k": "2", "m": "2",
                                     "plugin": "jerasure"}}}


def _ex_txn_ops():
    from ..os.objectstore import (OP_MKCOLL, OP_OMAP_SETKEYS,
                                  OP_SETATTR, OP_WRITE)

    return [
        (OP_MKCOLL, "pg-1.3"),
        (OP_WRITE, "pg-1.3", "obj-1.s2", 0, b"\x00\x01\x02\x03" * 4),
        (OP_SETATTR, "pg-1.3", "obj-1.s2", "v",
         b"000000000007.00000000000000000001"),
        (OP_OMAP_SETKEYS, "pg-1.3", "pglog",
         {"000000000007.00000000000000000001|2": b"{}"}),
    ]


def _ex_memstore():
    from ..os.memstore import MemStore, _Object

    st = MemStore()
    o = _Object()
    o.data = bytearray(b"\x01\x02\x03\x04payload")
    o.xattr = {"v": b"000000000007.00000000000000000001",
               "size": b"11"}
    o.omap = {"k1": b"v1"}
    st._coll = {"pg-1.3": {"obj-1.s0": o}}
    return st


def _ex_ckpt_state():
    from ..os.memstore import _Object

    o1 = _Object()
    o1.data = bytearray(b"alpha" * 8)
    o1.xattr = {"crc": b"12345"}
    o2 = _Object()
    o2.omap = {"000000000003.00000000000000000001|d": b"{}"}
    return (9, {"pg-1.0": {"obj-a.s1": o1, "pglog": o2}})


def _colls_plain(colls) -> Dict:
    return {cid: {oid: (bytes(o.data), dict(o.xattr), dict(o.omap))
                  for oid, o in objs.items()}
            for cid, objs in colls.items()}


def _ex_pg_log_entry():
    from ..services.pg_log import PgLogEntry

    return PgLogEntry(op="write", oid="obj-1",
                      v="000000000007.00000000000000000001",
                      shard=2, size=4096)


def _ex_image_header():
    return {"size": 1 << 20, "stripe_unit": 4096, "stripe_count": 4,
            "object_size": 1 << 16,
            "snaps": [{"name": "s1", "size": 1 << 20,
                       "protected": True}],
            "parent": None,
            "children": [{"name": "clone-1", "snap": "s1"}]}


_FIXED_KEY = bytes(range(32))
_FIXED_NOW = 1_700_000_000.0


def _ex_keyring():
    from ..msg.auth import Keyring

    return Keyring(_FIXED_KEY)


def _ex_ticket():
    return _ex_keyring().issue_ticket("client.admin", lifetime=3600.0,
                                      now=_FIXED_NOW)


def _ex_frame_op():
    return {"type": "shard_write", "tid": "tid-0001",
            "frm": "client.x", "_s": 5, "_sess": "sess0001",
            "pool": 1, "ps": 3, "oid": "obj-1", "shard": 2,
            "v": "000000000007.00000000000000000001",
            "size": 32, "data": b"\x00\x01\x02\x03" * 8,
            # a LITERAL sentinel-shaped value: must ride the escape
            # path and come back verbatim
            "odd": {"__frame_blob__": 0}}


def _ex_frame_hello():
    return {"type": "__hello__", "tid": "tid-0002", "frm": "osd.1",
            "sess": "sess0001"}


def _ex_frame_ack():
    return {"type": "__ack__", "sess": "sess0001", "in_seq": 7,
            "addr": ["127.0.0.1", 6789]}


def _ex_frame_reply():
    return {"type": "__reply__", "tid": "tid-0001",
            "payload": {"ok": True, "epoch": 7}}


def _ex_frame_map_push():
    # a control segment big enough to cross the zlib threshold, so
    # the compressed-frame path is corpus-pinned and mutation-tested
    return {"type": "map_full", "frm": "mon",
            "epoch": 7, "filler": ["x" * 64] * 512,
            "osd_addrs": {"0": ["127.0.0.1", 6800]}}


def _frame_encode(msg: Dict) -> bytes:
    from ..msg.messenger import encode_frame

    return encode_frame(msg)


def _frame_decode(payload: bytes) -> Dict:
    from ..msg.messenger import _restore_blobs, decode_frame

    msg, blobs = decode_frame(payload)
    return _restore_blobs(msg, blobs)


def _frame_forward(example_factory):
    """A same-version peer with a NEWER application schema added an
    unknown control field — handlers must ignore it."""
    def forge(_blob: bytes) -> bytes:
        msg = dict(example_factory())
        msg["__added_in_v_next__"] = {"unknown": True}
        return _frame_encode(msg)
    return forge


# -- WAL forges (header crc must be rebuilt around the patched body) --

def _wal_rec_forge(inner):
    def forge(blob: bytes) -> bytes:
        from ..os import wal_store as W

        seq, payload, _end = W.decode_record(blob)
        p2 = inner(payload)
        return W._HDR.pack(W._MAGIC, seq, len(p2),
                           W._crc32c(p2)) + p2
    return forge


def _ckpt_forge(inner):
    def forge(blob: bytes) -> bytes:
        from ..common.compressor import Compressor
        from ..os import wal_store as W

        magic, seq, ln, _crc = W._HDR.unpack_from(blob)
        body = W._unpack_body(magic, blob[W._HDR.size:W._HDR.size + ln])
        body = inner(body)
        comp = Compressor("zlib") if magic == W._MAGIC_Z else None
        magic2, packed = W._pack_body(body, comp)
        return W._HDR.pack(magic2, seq, len(packed),
                           W._crc32c(packed)) + packed
    return forge


def _bin_patch_compat(body: bytes) -> bytes:
    return bytes([body[0] + 1, body[1] + 1]) + body[2:]


def _bin_patch_forward(body: bytes) -> bytes:
    (ln,) = struct.unpack_from("<I", body, 2)
    end = 6 + ln
    return (bytes([body[0] + 1]) + body[1:2]
            + struct.pack("<I", ln + 4) + body[6:end]
            + b"\x00\x01\x02\x03" + body[end:])


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

def _build() -> Dict[str, WireType]:
    from ..common.bincode import Decoder, Encoder, decode_txn, encode_txn
    from ..common.compressor import Compressor
    from ..crush.map import CrushMap
    from ..msg import auth
    from ..os import wal_store as W
    from ..os.memstore import MemStore
    from ..osdmap import bincode_maps as B
    from ..osdmap.incremental import Incremental
    from ..osdmap.osdmap import PgPool
    from ..services import image, monitor
    from ..services.pg_log import PgLogEntry

    reg: Dict[str, WireType] = {}

    def add(e: WireType) -> None:
        if e.forge_compat is None:
            e.forge_compat = {
                "json": lambda b, e=e: _json_forge_compat(e, b),
                "bincode": lambda b, e=e: _bin_forge_compat(e, b),
                "frame": lambda b, e=e: _frame_forge_compat(e, b),
            }.get(e.kind)
        if e.forge_forward is None:
            e.forge_forward = {
                "json": lambda b, e=e: _json_forge_forward(e, b),
                "bincode": lambda b, e=e: _bin_forge_forward(e, b),
            }.get(e.kind)
        reg[e.name] = e

    # -- messenger frame families ------------------------------------
    from ..msg.messenger import _FRAME_V

    for name, fac, ftypes in (
            ("msg.frame", _ex_frame_op, ()),
            ("msg.frame.hello", _ex_frame_hello, ("__hello__",)),
            ("msg.frame.ack", _ex_frame_ack, ("__ack__",)),
            ("msg.frame.reply", _ex_frame_reply, ("__reply__",)),
            ("msg.frame.map_push", _ex_frame_map_push, ())):
        add(WireType(
            name=name, kind="frame", struct_v=_FRAME_V,
            compat_v=_FRAME_V, factory=fac,
            encode=_frame_encode, decode=_frame_decode,
            forge_forward=_frame_forward(fac),
            frame_types=ftypes))

    # -- auth ----------------------------------------------------------
    add(WireType(
        name="msg.auth.keyring", kind="json",
        struct_v=auth.KEYRING_V, compat_v=1,
        factory=_ex_keyring,
        encode=lambda k: k.to_wire().encode(),
        decode=auth.Keyring.from_wire,
        extract=lambda k: k.to_hex(),
        covers=("Keyring",)))
    add(WireType(
        name="msg.auth.ticket", kind="json",
        struct_v=auth.TICKET_V, compat_v=1,
        factory=_ex_ticket,
        encode=lambda t: auth.encode_ticket(t).encode(),
        decode=auth.decode_ticket, legacy=True))

    # -- osdmap family -------------------------------------------------
    add(WireType(
        name="osdmap.full", kind="bincode", struct_v=1, compat_v=1,
        factory=_mini_map, encode=B.osdmap_to_bytes,
        decode=B.osdmap_from_bytes,
        extract=lambda m: m.to_dict(), covers=("OSDMap",)))
    add(WireType(
        name="osdmap.crush", kind="bincode", struct_v=1, compat_v=1,
        factory=lambda: _mini_map().crush, encode=B.crush_to_bytes,
        decode=B.crush_from_bytes, extract=lambda m: m.to_dict()))
    add(WireType(
        name="osdmap.pg_pool", kind="json",
        struct_v=PgPool.STRUCT_V, compat_v=PgPool.COMPAT_V,
        factory=lambda: PgPool(pool_type=3, size=4, min_size=3,
                               pg_num=16, crush_rule=1,
                               erasure_code_profile="ec22"),
        encode=lambda p: p.encode_versioned().encode(),
        decode=PgPool.decode_versioned,
        extract=lambda p: p.to_dict(), covers=("PgPool",)))
    add(WireType(
        name="osdmap.incremental", kind="json",
        struct_v=Incremental.STRUCT_V, compat_v=Incremental.COMPAT_V,
        factory=_ex_incremental,
        encode=lambda i: i.encode_versioned().encode(),
        decode=Incremental.decode_versioned,
        extract=lambda i: i.to_dict(), covers=("Incremental",)))
    add(WireType(
        name="crush.map_json", kind="json",
        struct_v=CrushMap.STRUCT_V, compat_v=CrushMap.COMPAT_V,
        factory=lambda: _mini_map().crush,
        encode=lambda m: m.to_json().encode(),
        decode=CrushMap.from_json,
        extract=lambda m: m.to_dict(), legacy=True))

    # -- object store family -------------------------------------------
    def _txn_encode(ops) -> bytes:
        enc = Encoder()
        encode_txn(ops, enc)
        return enc.bytes()

    add(WireType(
        name="os.txn", kind="bincode", struct_v=1, compat_v=1,
        factory=_ex_txn_ops, encode=_txn_encode,
        decode=lambda b: decode_txn(Decoder(b, struct_name="os.txn"))))
    add(WireType(
        name="os.wal_record", kind="custom", struct_v=1, compat_v=1,
        factory=lambda: (5, _ex_txn_ops()),
        encode=lambda t: W.encode_record(t[0], t[1]),
        decode=lambda b: (lambda s, p, _e:
                          (s, decode_txn(Decoder(
                              p, struct_name="os.txn"))))(
                              *W.decode_record(b)),
        forge_compat=_wal_rec_forge(_bin_patch_compat),
        forge_forward=_wal_rec_forge(_bin_patch_forward)))
    add(WireType(
        name="os.wal_checkpoint", kind="custom",
        struct_v=W.CHECKPOINT_V, compat_v=1,
        factory=_ex_ckpt_state,
        encode=lambda t: W.encode_checkpoint(t[0], t[1],
                                             Compressor("zlib")),
        decode=W.decode_checkpoint,
        extract=lambda t: (t[0], _colls_plain(t[1])),
        forge_compat=_ckpt_forge(_bin_patch_compat),
        forge_forward=_ckpt_forge(_bin_patch_forward)))
    add(WireType(
        name="os.memstore_export", kind="json",
        struct_v=MemStore.EXPORT_V, compat_v=1,
        factory=_ex_memstore,
        encode=lambda st: st.export_blob().encode(),
        decode=MemStore.import_blob,
        extract=lambda st: st.export_state(),
        covers=("MemStore",), legacy=True))

    # -- services ------------------------------------------------------
    add(WireType(
        name="osd.pg_log_entry", kind="json",
        struct_v=PgLogEntry.STRUCT_V, compat_v=PgLogEntry.COMPAT_V,
        factory=_ex_pg_log_entry,
        encode=lambda e: e.encode_blob(),
        decode=PgLogEntry.decode_blob,
        extract=lambda e: e.to_dict(),
        covers=("PgLogEntry",), legacy=True))
    add(WireType(
        name="rbd.image_header", kind="json",
        struct_v=image.HEADER_V, compat_v=1,
        factory=_ex_image_header,
        encode=image.encode_header, decode=image.decode_header,
        legacy=True))
    add(WireType(
        name="mon.epoch_payload", kind="json",
        struct_v=monitor.EPOCH_PAYLOAD_V, compat_v=1,
        factory=_ex_epoch_payload,
        encode=lambda p: monitor.encode_epoch_payload(p).encode(),
        decode=monitor.decode_epoch_payload,
        # the payload is built from to_dict forms holding tuples;
        # JSON canonicalizes them to lists — compare in wire shape
        extract=lambda p: json.loads(json.dumps(p)),
        legacy=True))

    return reg


def _registry() -> Dict[str, WireType]:
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = _build()
    return _REGISTRY


def entries() -> List[WireType]:
    return [(_registry())[k] for k in sorted(_registry())]


def get(name: str) -> WireType:
    reg = _registry()
    if name not in reg:
        raise KeyError(f"no wire type {name!r}; have {sorted(reg)}")
    return reg[name]


def registered_names() -> List[str]:
    return sorted(_registry())


def covered_classes() -> set:
    """Class names whose wire form a registry entry proves — the
    WIRE002 ground truth."""
    out = set()
    for e in _registry().values():
        out.update(e.covers)
    return out


def frame_type_names() -> set:
    """Frame-type literals owned by a registry entry — the WIRE003
    ground truth."""
    out = set()
    for e in _registry().values():
        out.update(e.frame_types)
    return out


# ---------------------------------------------------------------------------
# the five-property checker
# ---------------------------------------------------------------------------

def _forward_ok(known, got) -> bool:
    """Forward-compat equality: every field THIS reader knows must
    round-trip; fields a future writer added may ride along in
    free-dict payloads."""
    if isinstance(known, dict) and isinstance(got, dict):
        return all(k in got and got[k] == v for k, v in known.items())
    return known == got


def _mutations(e: WireType, blob: bytes):
    """The corruption battery: truncations, bit flips at structural
    offsets, length-word bombs, pure garbage."""
    n = len(blob)
    yield b""
    yield blob[:1]
    yield blob[:n // 3]
    yield blob[:max(0, n - 1)]
    for pos in sorted({0, 1, 2, 5, n // 2, max(0, n - 4),
                       max(0, n - 1)}):
        if pos < n:
            b = bytearray(blob)
            b[pos] ^= 0xFF
            yield bytes(b)
    yield b"\xff" * 64
    yield bytes(range(256))
    if e.kind in ("frame", "bincode") and n >= 6:
        # forge the inner length word to claim ~4 GiB: must be refused
        # by bounds checks, never allocated or walked off the end
        b = bytearray(blob)
        b[2:6] = struct.pack("<I", 0xFFFFFFF0)
        yield bytes(b)


def check(e: WireType) -> List[str]:
    """Run all five conformance properties; returns failure strings
    (empty = conformant)."""
    fails: List[str] = []
    try:
        a, b = e.factory(), e.factory()
        blob = _to_bytes(e.encode(a))
    except Exception as ex:  # pragma: no cover - registration bug
        return [f"example/encode failed: {ex!r}"]

    # 1. round-trip identity
    try:
        got = e.decode(blob)
        if e.extract(got) != e.extract(a):
            fails.append("roundtrip: decoded object differs from "
                         "the example")
    except Exception as ex:
        fails.append(f"roundtrip: decode failed: {ex!r}")

    # 2. byte-level determinism
    if _to_bytes(e.encode(b)) != blob:
        fails.append("determinism: two encodes of fresh examples "
                     "differ")
    if e.reencode:
        try:
            if _to_bytes(e.encode(e.decode(blob))) != blob:
                fails.append("determinism: re-encode of the decoded "
                             "form differs")
        except Exception as ex:
            fails.append(f"determinism: re-encode failed: {ex!r}")

    # 3. forward-compat (unknown v+1 fields are skipped)
    if e.forge_forward is not None:
        try:
            fwd = e.forge_forward(blob)
            got = e.decode(fwd)
            if not _forward_ok(e.extract(a), e.extract(got)):
                fails.append("forward-compat: known fields did not "
                             "survive a v+1 blob")
        except Exception as ex:
            fails.append(f"forward-compat: v+1 blob refused: {ex!r}")

    # 4. compat-floor refusal, typed
    if e.forge_compat is not None:
        try:
            e.decode(e.forge_compat(blob))
            fails.append("compat-floor: a future-compat blob decoded "
                         "instead of being refused")
        except MalformedInput:
            pass
        except Exception as ex:
            fails.append(f"compat-floor: refusal is "
                         f"{type(ex).__name__}, not MalformedInput: "
                         f"{ex!r}")

    # 5. mutation robustness: every corruption fails clean
    for i, mut in enumerate(_mutations(e, blob)):
        try:
            e.decode(mut)
        except MalformedInput:
            pass
        except Exception as ex:
            fails.append(
                f"mutation[{i}] ({len(mut)}B): unclean failure "
                f"{type(ex).__name__}: {ex!r}")
    return fails


def check_all() -> Dict[str, List[str]]:
    """name -> failures for every registered type (the dencoder
    self-test / CI gate)."""
    return {e.name: check(e) for e in entries()}
