"""JAX kernel-contract registry + recompilation budget gate.

The XLA-axis twin of lockdep (PR 1): on TPU the silent killers are not
segfaults but recompilation storms, dtype drift, and host-device sync
points.  None of those are Python exceptions, so — like lock order —
they are CHECKED as structure, not assumed:

- **Contract registry**: every jitted kernel in ``ceph_tpu/ec/`` and
  ``ceph_tpu/crush/`` registers a declarative shape/dtype contract
  (inputs over a k/m/stripe grid → exact output ShapeDtypeStructs).
  ``verify_all()`` proves them via ``jax.eval_shape`` — abstract
  tracing only, no device execution, no XLA compile — under
  ``jax_numpy_dtype_promotion='strict'``, so a silent weak-type
  promotion to int64/float64 anywhere in a kernel fails the contract
  the way a lock-order inversion fails lockdep.  Integer lanes must
  stay uint8 (EC chunk bytes) / int32 (CRUSH results): any output
  leaf drifting to a 64-bit or float dtype is a violation even if the
  declared dtype matched nothing.
- **Recompile gate**: ``steady_state()`` marks a phase that must hit
  the XLA jit cache.  The EC engine and the batched CRUSH mapper
  already book first-call compiles per shape signature
  (``ec.engine``/``crush.mapper`` ``jit_compiles`` perf counters, PR
  2); any growth inside the window is recorded as a violation that
  the per-test conftest gate turns into a test failure — the
  "recompilation storm" class (a shape-unstable batch axis, a
  forgotten static arg) caught at the test that introduces it.

The static half of this layer lives in ``tools/lint_jax.py``
(JAX001..JAX004), mirrored on ``tools/lint_concurrency.py``.
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass
from typing import Callable, Dict, List, Sequence, Tuple

# NOTE: jax is imported lazily inside functions — this module is
# imported by the analysis package for every process, including ones
# that never touch a device.

# dtypes an integer kernel may legitimately produce; anything outside
# (int64/float64 from weak-type promotion, float32 from an accidental
# true-divide) is dtype drift.  The CRUSH mapper runs under
# jax_enable_x64 by DESIGN (straw2 is 64-bit fixed-point) but its
# public outputs are int32 — internal i64 lanes never leak out.
_INTEGER_LANES = ("uint8", "int32", "uint32")


@dataclass
class ContractViolation:
    contract: str
    case: str
    message: str

    def __str__(self) -> str:
        return f"[{self.contract}/{self.case}] {self.message}"


@dataclass
class Case:
    """One (kernel, input-grid-point) check.

    ``mode='eval_shape'`` (the default) proves the contract abstractly;
    ``mode='concrete'`` runs the kernel on the tiny given inputs — only
    for host-side engines (native GF) that have no traceable form.
    ``allow64`` exempts a case from the integer-lane drift check (none
    of the builtin contracts need it)."""

    label: str
    fn: Callable
    args: Sequence
    want: Sequence[Tuple[Tuple[int, ...], str]]
    mode: str = "eval_shape"
    allow64: bool = False


_REGISTRY: Dict[str, Callable[[], List[Case]]] = {}


def register_contract(name: str,
                      builder: Callable[[], List[Case]]) -> None:
    """``builder()`` returns the contract's cases; it runs at verify
    time so registering costs nothing at import."""
    _REGISTRY[name] = builder


def contracts() -> List[str]:
    return sorted(_REGISTRY)


def _leaf_specs(out) -> List[Tuple[Tuple[int, ...], str]]:
    import jax

    leaves = jax.tree_util.tree_leaves(out)
    return [(tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves]


def _run_case(contract: str, case: Case) -> List[ContractViolation]:
    import jax

    out: List[ContractViolation] = []
    try:
        with jax.numpy_dtype_promotion("strict"):
            if case.mode == "eval_shape":
                got = jax.eval_shape(case.fn, *case.args)
            else:
                got = case.fn(*case.args)
    except Exception as e:
        return [ContractViolation(
            contract, case.label,
            f"kernel failed to trace under strict dtype promotion: "
            f"{e!r}")]
    specs = _leaf_specs(got)
    want = [(tuple(s), str(d)) for s, d in case.want]
    if specs != want:
        out.append(ContractViolation(
            contract, case.label,
            f"output signature mismatch: got {specs}, want {want}"))
    if not case.allow64:
        for shape, dtype in specs:
            if dtype not in _INTEGER_LANES:
                out.append(ContractViolation(
                    contract, case.label,
                    f"integer-lane drift: output {shape} has dtype "
                    f"{dtype} (allowed: {_INTEGER_LANES}) — a silent "
                    f"weak-type promotion or float leak"))
    return out


def verify(name: str) -> List[ContractViolation]:
    builder = _REGISTRY.get(name)
    if builder is None:
        raise KeyError(f"no contract {name!r}; have {contracts()}")
    try:
        cases = builder()
    except Exception as e:
        return [ContractViolation(name, "<build>",
                                  f"contract builder failed: {e!r}")]
    out: List[ContractViolation] = []
    for case in cases:
        out.extend(_run_case(name, case))
    return out


def verify_all() -> List[ContractViolation]:
    """Prove every registered contract.  Empty list = all kernels honor
    their declared shape/dtype signatures under strict promotion."""
    out: List[ContractViolation] = []
    for name in contracts():
        out.extend(verify(name))
    return out


# ---------------------------------------------------------------------------
# recompilation budget gate
# ---------------------------------------------------------------------------

_recompile_violations: List[Dict] = []

# the perf counters that book first-call JIT compiles per shape
# signature (PR 2): ec.engine (bit-plane + Pallas engines) and
# crush.mapper (BatchedMapper launches)
_COMPILE_COUNTERS = ("ec.engine", "crush.mapper")


def compile_counters() -> Dict[str, float]:
    """Snapshot of every booked-compile counter that currently exists
    (a counter appears when its module first imports)."""
    from ..common.perf_counters import collection

    out: Dict[str, float] = {}
    for name in _COMPILE_COUNTERS:
        try:
            dumped = collection().dump(name)
        except KeyError:
            continue
        pc = dumped.get(name, {})
        if "jit_compiles" in pc:
            out[f"{name}.jit_compiles"] = pc["jit_compiles"]
    return out


@contextlib.contextmanager
def steady_state(label: str = ""):
    """Wrap a phase that must be compile-free: every shape signature it
    launches has already been traced+compiled (warmup ran outside the
    window).  A new compile inside the window — a shape-unstable batch
    axis, a dtype flip, a missing static arg — records a violation
    that the per-test conftest gate fails the test on."""
    before = compile_counters()
    yield
    after = compile_counters()
    grew = {key: (before.get(key, 0), val)
            for key, val in after.items() if val > before.get(key, 0)}
    if grew:
        detail = ", ".join(f"{key} {int(a)}->{int(b)}"
                           for key, (a, b) in sorted(grew.items()))
        _recompile_violations.append({
            "label": label or "<steady-state>",
            "message": (f"steady-state phase {label or '?'!r} "
                        f"triggered new XLA compile(s): {detail} — a "
                        f"shape/dtype-unstable launch is recompiling "
                        f"per call"),
            "counters": grew,
        })


def recompile_violations() -> List[Dict]:
    return list(_recompile_violations)


def clear_recompile_violations() -> None:
    del _recompile_violations[:]


# ---------------------------------------------------------------------------
# builtin contracts: every jitted EC / CRUSH kernel
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _bitplane_engine():
    """Plugin construction under CEPH_TPU_EC_ENGINE=bitplane: contracts
    check the JITted array kernels, and the registry would otherwise
    put the host-native GF engine behind w=8 matrix techniques."""
    old = os.environ.get("CEPH_TPU_EC_ENGINE")
    os.environ["CEPH_TPU_EC_ENGINE"] = "bitplane"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("CEPH_TPU_EC_ENGINE", None)
        else:
            os.environ["CEPH_TPU_EC_ENGINE"] = old


def _u8(*shape):
    import jax

    return jax.ShapeDtypeStruct(shape, "uint8")


def _bitcode_cases(label: str, bc, L: int) -> List[Case]:
    """Encode + decode-with-erasures contracts for one engine.BitCode:
    the exact to_rows → mod-2 MXU matmul → from_rows composition the
    XLA path executes (the Pallas fusion has its own contract)."""
    from ..ec.engine import _mod2_matmul

    k, m, layout = bc.k, bc.m, bc.layout
    layout.check(L)

    def enc(data):
        rows = layout.to_rows(data)
        return layout.from_rows(_mod2_matmul(bc._enc_dev, rows), m, L)

    # erase one data chunk and one parity chunk (the classic
    # double-fault), survive on the first k of what remains
    erased = {0, k} if m > 1 else {0}
    present = tuple(i for i in range(k + m) if i not in erased)[:k]
    (inv,) = bc._decode_mats(present)

    def dec(stack):
        rows = layout.to_rows(stack)
        return layout.from_rows(_mod2_matmul(inv, rows), k, L)

    tag = f"{label}/L={L}"
    return [
        Case(f"{tag}/encode", enc, [_u8(k, L)], [((m, L), "uint8")]),
        Case(f"{tag}/decode[erased={sorted(erased)}]", dec,
             [_u8(k, L)], [((k, L), "uint8")]),
    ]


def _plugin_chunk(plugin, object_size: int = 1 << 12) -> int:
    return plugin.get_chunk_size(object_size)


def _contract_mod2_matmul() -> List[Case]:
    from ..ec.engine import _mod2_matmul

    out = []
    for (r, c, n) in ((8, 16, 512), (24, 64, 4096), (256, 128, 1024)):
        out.append(Case(
            f"({r}x{c})@({c}x{n})", _mod2_matmul,
            [_u8(r, c), _u8(c, n)], [((r, n), "uint8")]))
    return out


def _contract_rs_jax() -> List[Case]:
    from ..ec import gf
    from ..ec.rs_jax import RSCode, gf_matmul_bits

    out: List[Case] = []
    for k, m in ((2, 1), (4, 2), (8, 3)):
        code = RSCode(k, m)
        out.extend(_bitcode_cases(f"rs(k={k},m={m})", code._bit, 4096))
    # the expanded-bitmatrix byte API the stripe layer shares
    bm = gf.expand_bitmatrix(gf.rs_vandermonde_matrix(4, 2)[4:])
    out.append(Case(
        "gf_matmul_bits(4->2)", gf_matmul_bits,
        [bm, _u8(4, 1024)], [((2, 1024), "uint8")]))
    return out


def _contract_jerasure() -> List[Case]:
    from ..ec.jerasure import make_jerasure

    grids = [
        ("reed_sol_van", {"k": "2", "m": "1", "w": "8"}),
        ("reed_sol_van", {"k": "4", "m": "2", "w": "8"}),
        ("reed_sol_van", {"k": "3", "m": "2", "w": "16"}),
        ("reed_sol_van", {"k": "3", "m": "2", "w": "32"}),
        ("reed_sol_r6_op", {"k": "4", "m": "2", "w": "8"}),
        ("cauchy_good", {"k": "4", "m": "2", "w": "8",
                         "packetsize": "8"}),
        ("cauchy_orig", {"k": "3", "m": "2", "w": "8",
                         "packetsize": "8"}),
        ("liberation", {"k": "3", "m": "2", "w": "7",
                        "packetsize": "8"}),
        ("blaum_roth", {"k": "3", "m": "2", "w": "6",
                        "packetsize": "8"}),
        ("liber8tion", {"k": "4", "m": "2", "w": "8",
                        "packetsize": "8"}),
    ]
    out: List[Case] = []
    with _bitplane_engine():
        for tech, prof in grids:
            plugin = make_jerasure(dict(prof, technique=tech))
            L = _plugin_chunk(plugin)
            label = (f"{tech}(k={prof['k']},m={prof['m']},"
                     f"w={prof['w']})")
            out.extend(_bitcode_cases(label, plugin._code, L))
    return out


def _contract_isa() -> List[Case]:
    from ..ec.isa import make_isa

    out: List[Case] = []
    with _bitplane_engine():
        for tech, k, m in (("reed_sol_van", 7, 3),
                           ("reed_sol_van", 4, 2),
                           ("cauchy", 4, 2)):
            plugin = make_isa({"technique": tech, "k": str(k),
                               "m": str(m)})
            out.extend(_bitcode_cases(
                f"{tech}(k={k},m={m})", plugin._code,
                _plugin_chunk(plugin)))
    return out


def _contract_lrc() -> List[Case]:
    """LRC is layered: each layer executes on its own jerasure BitCode,
    so the jitted kernels ARE the layers' engines."""
    from ..ec.registry import factory

    out: List[Case] = []
    with _bitplane_engine():
        for prof in ({"k": "4", "m": "2", "l": "3"},
                     {"k": "2", "m": "2", "l": "2"}):
            lrc = factory("lrc", dict(prof))
            L = _plugin_chunk(lrc)
            tag = f"k={prof['k']},m={prof['m']},l={prof['l']}"
            for i, layer in enumerate(lrc.layers):
                code = getattr(layer.erasure_code, "_code", None)
                if code is None:
                    continue
                out.extend(_bitcode_cases(
                    f"lrc({tag})/layer{i}", code, L))
    return out


def _contract_shec() -> List[Case]:
    """SHEC has no BitCode facade: encode is to_rows → matmul(enc_bm)
    → from_rows over its multiple-locality matrix; decode solves the
    minimal recovery system per erasure (host GF(w) inversion) and
    runs the same matmul — mirrored here exactly."""
    import numpy as np

    from ..ec.engine import _mod2_matmul
    from ..ec.registry import factory

    out: List[Case] = []
    for prof in ({"k": "4", "m": "3", "c": "2"},
                 {"k": "6", "m": "2", "c": "1"}):
        shec = factory("shec", dict(prof))
        L = _plugin_chunk(shec)
        layout = shec._layout
        enc_bm = np.asarray(shec._enc_bm)
        tag = f"shec(k={prof['k']},m={prof['m']},c={prof['c']})"

        def enc(data, layout=layout, enc_bm=enc_bm, shec=shec, L=L):
            rows = layout.to_rows(data)
            return layout.from_rows(_mod2_matmul(enc_bm, rows),
                                    shec.m, L)

        out.append(Case(f"{tag}/L={L}/encode", enc,
                        [_u8(shec.k, L)],
                        [((shec.m, L), "uint8")]))
        # decode-with-erasures: lose data chunk 0, recover it from the
        # minimal system (the locality win) — the runtime decode_chunks
        # flow: GF(w) sub-matrix inversion on host, expand to bits,
        # one mod-2 matmul over the [rows] survivor stack
        n = shec.k + shec.m
        want = [1] + [0] * (n - 1)
        avails = [0] + [1] * (n - 1)
        found = shec._search(want, avails)
        if found is None:
            out.append(Case(
                f"{tag}/decode[erased=[0]]",
                lambda: (_ for _ in ()).throw(AssertionError(
                    "shec: single data erasure unrecoverable")),
                [], [], mode="concrete"))
            continue
        _dup, rows_idx, cols, _minimum = found
        sub = [[(1 if r == c_ else 0) if r < shec.k
                else shec.matrix[r - shec.k][c_] for c_ in cols]
               for r in rows_idx]
        inv = shec._gf.mat_inv(sub)
        need_idx = [i for i, c_ in enumerate(cols) if not avails[c_]]
        bm = np.asarray(
            shec._gf.expand_bitmatrix([inv[i] for i in need_idx]))

        def dec(stack, layout=layout, bm=bm, L=L,
                nrec=len(need_idx)):
            rows = layout.to_rows(stack)
            return layout.from_rows(_mod2_matmul(bm, rows), nrec, L)

        out.append(Case(
            f"{tag}/L={L}/decode[erased=[0]]", dec,
            [_u8(len(rows_idx), L)],
            [((len(need_idx), L), "uint8")]))
    return out


def _contract_clay() -> List[Case]:
    """CLAY orchestrates sub-chunk planes on the host; every byte of
    device math runs on its scalar-MDS sub-codes (mds + pft), so those
    BitCodes carry the contract.  Geometry (sub_chunk_no = q^t) is
    asserted here too — a wrong sub-chunk count scrambles every plane."""
    from ..ec.registry import factory

    out: List[Case] = []
    with _bitplane_engine():
        for prof in ({"k": "4", "m": "2"},
                     {"k": "3", "m": "3", "d": "5"}):
            clay = factory("clay", dict(prof))
            # geometry invariant checked at build: a wrong sub-chunk
            # count scrambles every plane before any kernel runs
            assert clay.sub_chunk_no == clay.q ** clay.t, \
                (clay.sub_chunk_no, clay.q, clay.t)
            tag = f"clay(k={prof['k']},m={prof['m']})"
            for sub, name in ((clay.mds, "mds"), (clay.pft, "pft")):
                code = getattr(sub, "_code", None)
                if code is not None:
                    out.extend(_bitcode_cases(
                        f"{tag}/{name}", code,
                        _plugin_chunk(sub, 1 << 10)))
    return out


def _contract_native_gf() -> List[Case]:
    """The host GF(2^8) table engine has no traced form; its contract
    runs concrete on tiny chunks (microseconds) — same shape/dtype
    assertions, same strict-promotion context."""
    from ..ec.native_gf import NativeRS, available

    if not available():
        return []  # engine absent: nothing to hold to the contract
    out: List[Case] = []
    for k, m in ((4, 2), (8, 3)):
        code = NativeRS(k, m)
        L = 64
        data = __import__("numpy").zeros((k, L), "uint8")
        out.append(Case(
            f"native_rs(k={k},m={m})/encode", code.encode, [data],
            [((m, L), "uint8")], mode="concrete"))
        full = code.all_chunks(data)
        chunks = {i: full[i] for i in range(k + m)}
        out.append(Case(
            f"native_rs(k={k},m={m})/decode[erased=[0,1]]",
            code.decode, [chunks, [0, 1]],
            [((k, L), "uint8")], mode="concrete"))
    return out


def _contract_pallas() -> List[Case]:
    """The fused unpack→MXU→pack kernel: same byte-level signature as
    the XLA path it replaces on TPU."""
    import functools

    import numpy as np

    from ..ec import gf
    from ..ec.pallas_kernels import fused_gf2_matmul_w8

    out: List[Case] = []
    for k, m, L in ((4, 2, 4096), (8, 3, 8192)):
        bm = gf.expand_bitmatrix(
            gf.rs_vandermonde_matrix(k, m)[k:]).astype(np.int8)
        out.append(Case(
            f"fused_w8(k={k},m={m},L={L})",
            functools.partial(fused_gf2_matmul_w8, interpret=True),
            [bm, _u8(k, L)], [((m, L), "uint8")]))
    return out


def _contract_pallas_engine() -> List[Case]:
    """The registry-promoted 'pallas-fused' engine: a jerasure/isa
    profile with ``engine=pallas-fused`` must honor the same byte
    signatures as the engines it replaces, on the single-device path
    (encode + batched encode) AND the mesh path (per-device fused
    dispatch).  Concrete mode on tiny shapes: the per-device split is
    host-side orchestration with no single traceable form."""
    import numpy as np

    from ..ec.jerasure import make_jerasure
    from ..parallel.placement import make_mesh

    plugin = make_jerasure({"technique": "reed_sol_van", "k": "4",
                            "m": "2", "w": "8",
                            "engine": "pallas-fused"})
    bc = plugin._code
    assert bc.force_fused, "profile engine=pallas-fused not routed"
    k, m, L, B = bc.k, bc.m, 64, 4
    rng = np.random.default_rng(0xFA)
    data = rng.integers(0, 256, (k, L), dtype=np.uint8)
    stripes = rng.integers(0, 256, (B, k, L), dtype=np.uint8)
    out = [
        Case("encode", bc.encode, [data], [((m, L), "uint8")],
             mode="concrete"),
        Case(f"encode_batched/B={B}",
             lambda s: bc.encode_batched(s, mesh=None), [stripes],
             [((B, m, L), "uint8")], mode="concrete"),
    ]
    import jax

    devs = jax.devices()
    meshes = [(1, make_mesh(devs[:1], axis_name="ec"))]
    if len(devs) > 1:
        meshes.append((len(devs), make_mesh(devs, axis_name="ec")))
    for n_dev, mesh in meshes:
        out.append(Case(
            f"encode_batched_sharded/B={B}/ndev={n_dev}",
            lambda s, mesh=mesh: bc.encode_batched_sharded(s, mesh),
            [stripes], [((B, m, L), "uint8")], mode="concrete"))
    return out


def _contract_crush_mapper() -> List[Case]:
    """crush_do_rule_batched: (arrays, weight u32[D], xs u32[N]) →
    (results i32[N, R], lens i32[N]) for both rule families (firstn
    chooseleaf and indep/EC) on a production-shaped 3-level map.  The
    mapper computes in 64-bit fixed point BY DESIGN (straw2); the
    contract pins that none of it leaks into the outputs."""
    import jax

    from ..crush.builder import sample_cluster_map
    from ..crush.mapper_jax import build_rule_fn

    cmap = sample_cluster_map(racks=2, hosts_per_rack=2,
                              osds_per_host=2)

    def abstract_args(arrays, n):
        return [
            jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                arrays),
            jax.ShapeDtypeStruct((cmap.max_devices,), "uint32"),
            jax.ShapeDtypeStruct((n,), "uint32"),
        ]

    out: List[Case] = []
    for ruleno in (0, 1):
        for result_max, n in ((3, 64), (5, 256)):
            fn, _static, arrays = build_rule_fn(cmap, ruleno,
                                                result_max)
            out.append(Case(
                f"rule{ruleno}/R={result_max}/N={n}", fn,
                abstract_args(arrays, n),
                [((n, result_max), "int32"), ((n,), "int32")]))
    # the division-free table-key straw2 lowering (the TPU default;
    # CPU defaults to the arithmetic path, so force it)
    old = os.environ.get("CEPH_TPU_STRAW2")
    os.environ["CEPH_TPU_STRAW2"] = "table"
    try:
        fn, _static, arrays = build_rule_fn(cmap, 0, 3)
    finally:
        if old is None:
            os.environ.pop("CEPH_TPU_STRAW2", None)
        else:
            os.environ["CEPH_TPU_STRAW2"] = old
    out.append(Case(
        "rule0/R=3/N=64/straw2=table", fn, abstract_args(arrays, 64),
        [((64, 3), "int32"), ((64,), "int32")]))
    return out


def _contract_crush_mapper_spec() -> List[Case]:
    """The divergence-free speculative lowering (the fast TPU engine):
    same public signature as the general rule VM."""
    import jax

    from ..crush.builder import sample_cluster_map
    from ..crush.mapper_spec import build_spec_rule_fn

    cmap = sample_cluster_map(racks=2, hosts_per_rack=2,
                              osds_per_host=2)
    out: List[Case] = []
    for ruleno in (0, 1):
        fn, _static, arrays = build_spec_rule_fn(cmap, ruleno, 3,
                                                 k_tries=1)
        abstract = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), arrays)
        weight = jax.ShapeDtypeStruct((cmap.max_devices,), "uint32")
        xs = jax.ShapeDtypeStruct((64,), "uint32")
        out.append(Case(
            f"rule{ruleno}/R=3/N=64", fn, [abstract, weight, xs],
            [((64, 3), "int32"), ((64,), "int32")]))
    return out


def _contract_encode_batched() -> List[Case]:
    """The batched-encode entry (engine.BitCode.encode_batched): B
    same-shape stripes stack on a leading batch axis, flatten to one
    (k, B*L) launch of the SAME mod-2 kernel, and split back — the
    exact composition the data-plane coalescer dispatches."""
    from ..ec.engine import _mod2_matmul
    from ..ec.rs_jax import RSCode

    out: List[Case] = []
    for k, m, B, L in ((2, 1, 4, 4096), (4, 2, 8, 4096),
                       (8, 3, 16, 1024)):
        bc = RSCode(k, m)._bit
        layout, enc = bc.layout, bc._enc_dev

        def encb(stripes, bc=bc, layout=layout, enc=enc, B=B, L=L):
            flat = stripes.transpose(1, 0, 2).reshape(bc.k, B * L)
            rows = layout.to_rows(flat)
            par = layout.from_rows(_mod2_matmul(enc, rows), bc.m,
                                   B * L)
            return par.reshape(bc.m, B, L).transpose(1, 0, 2)

        out.append(Case(
            f"rs(k={k},m={m})/B={B}/L={L}", encb,
            [_u8(B, k, L)], [((B, m, L), "uint8")]))
    return out


def _contract_sharded_rule_fn() -> List[Case]:
    """parallel.sharded_rule_fn (the PlacementPlane engine): the
    masked, PG-axis-sharded batched mapper over a 1-device mesh (the
    degenerate CI case) and the full device mesh when more than one
    device exists.  Outputs: PG-sharded (results, lens) plus — with
    gather_stats — the all-reduced utilization tally, all int32."""
    import jax

    from ..crush.builder import sample_cluster_map
    from ..parallel.placement import make_mesh, sharded_rule_fn

    cmap = sample_cluster_map(racks=2, hosts_per_rack=2,
                              osds_per_host=2)
    devs = jax.devices()
    meshes = [(1, make_mesh(devs[:1]))]
    if len(devs) > 1:
        meshes.append((len(devs), make_mesh(devs)))
    out: List[Case] = []
    for n_dev, mesh in meshes:
        for gather in (False, True):
            fn, static, arrays = sharded_rule_fn(
                cmap, 0, 3, mesh, gather_stats=gather, masked=True)
            N = 64
            args = [
                jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    arrays),
                jax.ShapeDtypeStruct((cmap.max_devices,), "uint32"),
                jax.ShapeDtypeStruct((N,), "uint32"),
                jax.ShapeDtypeStruct((N,), "bool"),
            ]
            want = [((N, 3), "int32"), ((N,), "int32")]
            if gather:
                want.append(((static.max_devices,), "int32"))
            out.append(Case(
                f"rule0/R=3/N={N}/ndev={n_dev}/gather={gather}",
                fn, args, want))
    return out


def _contract_encode_batched_sharded() -> List[Case]:
    """ec.engine.encode_batched_sharded: the stripe-batch-sharded
    encode — u8[B, k, L] with B sharded across the mesh -> parity
    u8[B, m, L] sharded the same way, on the 1-device degenerate mesh
    and the full mesh."""
    import jax

    from ..ec.rs_jax import RSCode
    from ..parallel.placement import make_mesh

    devs = jax.devices()
    meshes = [(1, make_mesh(devs[:1], axis_name="ec"))]
    if len(devs) > 1:
        meshes.append((len(devs), make_mesh(devs, axis_name="ec")))
    out: List[Case] = []
    for k, m, B, L in ((4, 2, 8, 4096), (8, 3, 16, 1024)):
        bc = RSCode(k, m)._bit
        for n_dev, mesh in meshes:
            fn = bc._mesh_fn(mesh, "ec")
            out.append(Case(
                f"rs(k={k},m={m})/B={B}/L={L}/ndev={n_dev}", fn,
                [_u8(B, k, L)], [((B, m, L), "uint8")]))
    return out


def _register_builtin_contracts() -> None:
    register_contract("ec.engine.mod2_matmul", _contract_mod2_matmul)
    register_contract("ec.engine.encode_batched",
                      _contract_encode_batched)
    register_contract("ec.engine.encode_batched_sharded",
                      _contract_encode_batched_sharded)
    register_contract("parallel.sharded_rule_fn",
                      _contract_sharded_rule_fn)
    register_contract("ec.rs_jax", _contract_rs_jax)
    register_contract("ec.jerasure", _contract_jerasure)
    register_contract("ec.isa", _contract_isa)
    register_contract("ec.lrc", _contract_lrc)
    register_contract("ec.shec", _contract_shec)
    register_contract("ec.clay", _contract_clay)
    register_contract("ec.native_gf", _contract_native_gf)
    register_contract("ec.pallas", _contract_pallas)
    register_contract("ec.pallas_engine", _contract_pallas_engine)
    register_contract("crush.mapper_jax", _contract_crush_mapper)
    register_contract("crush.mapper_spec", _contract_crush_mapper_spec)


_register_builtin_contracts()
