"""Correctness-analysis layer: lockdep, stall watchdog, JAX contracts.

The src/common/lockdep.cc + sanitizer-wiring role for a framework
that is dozens of threads deep (messenger readers + dispatch pool,
quorum ticks, scheduler workers, recovery, heartbeats): concurrency
structure is CHECKED at runtime, not assumed.  ``jaxcheck`` extends
the same posture to the XLA axis — kernel shape/dtype contracts
proven via ``jax.eval_shape`` under strict promotion, plus a
recompilation budget gate over the booked per-shape compile counters.
The static halves live in tools/lint_concurrency.py and
tools/lint_jax.py.

``jaxcheck`` is NOT imported here: importing it is free, but its
verify path imports jax + the ec/crush kernels, and this package is
loaded by every process (conftest pulls it before pinning the
platform).  Import ``ceph_tpu.analysis.jaxcheck`` explicitly.
"""

from .lockdep import (DLock, DRLock, enable, enabled, make_lock,
                      make_rlock, violations)
from .watchdog import Watchdog, dump_blocked, section, start_global

__all__ = ["DLock", "DRLock", "enable", "enabled", "make_lock",
           "make_rlock", "violations", "Watchdog", "dump_blocked",
           "section", "start_global"]
