"""Concurrency-correctness layer: lockdep, stall watchdog.

The src/common/lockdep.cc + sanitizer-wiring role for a framework
that is dozens of threads deep (messenger readers + dispatch pool,
quorum ticks, scheduler workers, recovery, heartbeats): concurrency
structure is CHECKED at runtime, not assumed.  The static half lives
in tools/lint_concurrency.py.
"""

from .lockdep import (DLock, DRLock, enable, enabled, make_lock,
                      make_rlock, violations)
from .watchdog import Watchdog, dump_blocked, section, start_global

__all__ = ["DLock", "DRLock", "enable", "enabled", "make_lock",
           "make_rlock", "violations", "Watchdog", "dump_blocked",
           "section", "start_global"]
