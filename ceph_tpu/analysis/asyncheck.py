"""Asyncheck — `@nonblocking` contracts + runtime loop-stall enforcement.

The blocking-safety half of the sanitizer plane, and the runtime twin
of ``tools/lint_async.py``'s BLOCK001 reachability analyzer: the lint
proves *statically* which may-block primitives are reachable from a
declared non-blocking context (Linux's sleep-in-atomic checker, for
this codebase); this module proves it *at runtime* by timing every
declared scope against a wallclock budget and capturing both-end stack
witnesses when one overruns.  Together they are the readiness audit
ROADMAP item 1's event-loop refactor must keep green — an epoll
reactor dies of a thousand hidden ``time.sleep``/``fsync``/
``Event.wait`` calls, and this plane names each one before it ships.

Usage::

    from ..analysis.asyncheck import nonblocking, scope

    @nonblocking
    def _dispatch(self, conn, msg, ...): ...     # contract + timing

    with asyncheck.scope(f"{self.name}:{type_}"):
        reply = handler(msg)                      # explicit scope

``@nonblocking`` declares a function as a non-blocking context: the
static analyzer roots its call-graph walk there, and (when the plane
is enabled) the function body runs inside a timed scope.  ``scope()``
is the explicit form for dispatch/reactor callback sites where the
callback itself is dynamic (the messenger's handler table).

Every live scope carries a wallclock budget — the module default comes
from the ``asyncheck_loop_budget_ms`` option via ``configure()``, a
per-scope override rides the call.  Overruns are detected at BOTH
ends:

  * exit-side: scope exit past budget records an overrun with the
    entry stack and the exit stack (who declared the scope, who it
    returned through);
  * in-flight: an ``Enforcer`` poll (or a live ``dump()``) finds a
    scope still open past budget and captures the thread's CURRENT
    stack via ``sys._current_frames()`` — the mid-stall witness that
    names the blocking call while it is still blocking, the same
    two-witness shape lockdep and racecheck reports use.

Enablement mirrors racecheck: ``CEPH_TPU_ASYNCHECK=1`` in the
environment (set before import — the decorator is identity when the
plane is disabled at decoration time, zero production overhead) or
``enable(True)`` at runtime for explicit ``scope()`` sites.  Tier-1
does NOT enable the plane suite-wide: budgets are wallclock and the
1-core CI container time-slices freely — the runtime tests drive
``enable(True)`` + ``Enforcer.poll()`` deterministically instead, and
``tools/thrasher.py --loop-stall`` drills the live enforcement path.

Overruns are recorded, not raised (a dispatch thread must not crash
mid-frame); the ``dump_asyncheck`` admin command, the
``analysis.block.*`` counters, and daemonperf's ``blk`` column surface
them in a live cluster.
"""

from __future__ import annotations

import functools
import os
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

ENV = "CEPH_TPU_ASYNCHECK"

DEFAULT_BUDGET_MS = 50.0

_forced: Optional[bool] = None
_budget_ms = DEFAULT_BUDGET_MS

# registry bookkeeping (decoration-time; read by dump()/counters)
_contracts: List[str] = []

_violations: List[Dict] = []
_vlock = threading.Lock()

# live scopes: token -> _Scope (token is the _Scope itself; a dict
# keyed by identity keeps enter/exit O(1) under one small lock)
_scopes: Dict[int, "_Scope"] = {}
_slock = threading.Lock()

_MAX_FRAMES = 12


# read once at import: every entry point (tests, thrasher's
# --loop-stall, the bench subprocesses) sets the env before importing
# ceph_tpu; enable() overrides at runtime
_env_on = os.environ.get(ENV, "") not in ("", "0")


def enabled() -> bool:
    if _forced is not None:
        return _forced
    return _env_on


def enable(on: bool = True) -> None:
    """Force the plane on/off at runtime (tests).  Note decoration
    happens at import: enabling here activates explicit ``scope()``
    sites immediately but only ``@nonblocking`` functions that were
    decorated while the plane was enabled."""
    global _forced
    _forced = on


def configure(budget_ms: float) -> None:
    """Set the module-default scope budget (wired from the
    ``asyncheck_loop_budget_ms`` option by ``Context``)."""
    global _budget_ms
    _budget_ms = float(budget_ms)


def budget_ms() -> float:
    return _budget_ms


def _fast_stack(skip: int = 1) -> Tuple[tuple, ...]:
    """A cheap stack witness: raw (file, line, func) frames walked
    via _getframe (traceback.extract_stack is ~10x the cost and this
    runs on every scope entry); formatting is deferred to report
    time.  Skips asyncheck's own frames."""
    out = []
    f = sys._getframe(skip)
    own = __file__
    while f is not None and len(out) < _MAX_FRAMES:
        code = f.f_code
        if code.co_filename != own:
            out.append((code.co_filename, f.f_lineno,
                        code.co_name))
        f = f.f_back
    return tuple(out)


def _frames_of(frame) -> Tuple[tuple, ...]:
    """Raw frames from a live frame object (the mid-stall witness
    pulled out of ``sys._current_frames()``)."""
    out = []
    f = frame
    own = __file__
    while f is not None and len(out) < _MAX_FRAMES:
        code = f.f_code
        if code.co_filename != own:
            out.append((code.co_filename, f.f_lineno,
                        code.co_name))
        f = f.f_back
    return tuple(out)


def _fmt_stack(frames: Optional[Tuple[tuple, ...]]) -> str:
    if not frames:
        return "  (no stack captured)\n"
    return "\n".join(f"  {fn}:{ln} in {fun}"
                     for fn, ln, fun in frames) + "\n"


class _Scope:
    """One live non-blocking scope on one thread."""

    __slots__ = ("name", "tid", "thread", "start", "budget_s",
                 "entry", "reported")

    def __init__(self, name: str, budget_s: float):
        self.name = name
        self.tid = threading.get_ident()
        self.thread = threading.current_thread().name
        self.start = time.monotonic()
        self.budget_s = budget_s
        self.entry = _fast_stack(3)  # caller of scope()
        self.reported = False  # one overrun record per scope instance


def _record(kind: str, sc: _Scope, elapsed_s: float,
            witness: Optional[Tuple[tuple, ...]]) -> None:
    rec = {
        "kind": kind,
        "scope": sc.name,
        "thread": sc.thread,
        "elapsed_ms": round(elapsed_s * 1000.0, 3),
        "budget_ms": round(sc.budget_s * 1000.0, 3),
        "message": (f"non-blocking scope {sc.name!r} "
                    f"{'still blocked' if kind == 'stall' else 'ran'} "
                    f"{elapsed_s * 1000.0:.1f}ms "
                    f"(budget {sc.budget_s * 1000.0:.1f}ms) "
                    f"on thread {sc.thread!r}"),
        "entry_stack": _fmt_stack(sc.entry),
        "witness_stack": _fmt_stack(witness),
    }
    with _vlock:
        _violations.append(rec)
    try:
        _block_pc().inc("overruns")
    except Exception:
        pass  # counters must never mask the violation record itself


_pc_cache = None


def _block_pc():
    """The process-global analysis.block counter family (created
    lazily: perf_counters sits above this package, so the edge back
    must not run at module import)."""
    global _pc_cache
    if _pc_cache is None:
        from ..common.perf_counters import collection

        pc = collection().create("analysis.block")
        pc.add_u64_counter("overruns")
        pc.add_u64("contracts")
        pc.add_u64("live_scopes")
        _pc_cache = pc
    return _pc_cache


def _sync_gauges() -> None:
    if not enabled():
        return
    try:
        pc = _block_pc()
    except Exception:
        return
    pc.set("contracts", len(_contracts))
    with _slock:
        pc.set("live_scopes", len(_scopes))


# -- the contract surface ---------------------------------------------

def nonblocking(fn):
    """Declare ``fn`` a non-blocking context.

    Statically: ``tools/lint_async.py`` roots its may-block
    reachability walk at every ``@nonblocking`` function — any
    primitive blocking call reachable through the call graph is a
    BLOCK001 violation unless the path carries a reasoned
    ``# block-ok:`` mark.

    At runtime (plane enabled at decoration time): the body runs
    inside a timed ``scope()`` carrying the module budget; identity
    function otherwise — zero production overhead.
    """
    if not enabled():
        return fn
    qual = getattr(fn, "__qualname__", getattr(fn, "__name__", "?"))
    _contracts.append(qual)
    _sync_gauges()

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with scope(qual):
            return fn(*args, **kwargs)

    return wrapper


@contextmanager
def scope(name: str, budget_ms: Optional[float] = None):
    """A timed non-blocking scope: the explicit form for dynamic
    callback sites (the messenger wraps each control-lane handler
    run).  Records an overrun on exit past budget unless an Enforcer
    poll already reported this scope mid-stall."""
    if not enabled():
        yield
        return
    sc = _Scope(name, (budget_ms if budget_ms is not None
                       else _budget_ms) / 1000.0)
    with _slock:
        _scopes[id(sc)] = sc
    try:
        yield
    finally:
        elapsed = time.monotonic() - sc.start
        with _slock:
            _scopes.pop(id(sc), None)
        if elapsed > sc.budget_s and not sc.reported:
            sc.reported = True
            _record("overrun", sc, elapsed, _fast_stack(2))


class Enforcer:
    """The in-flight stall detector: polls the live-scope table and
    captures the mid-stall stack of any scope open past its budget —
    the witness that names the blocking call WHILE it blocks, before
    the scope ever exits.  ``poll()`` is directly drivable (tests,
    ``dump()``); ``start()`` runs it on a daemon thread in a live
    cluster (the ``--loop-stall`` drill's enforcement path)."""

    def __init__(self, interval: float = 0.05):
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # last few poll failures, surfaced via dump() — the enforcer
        # outlives a bad poll but must not lose the evidence
        self.poll_errors: deque = deque(maxlen=8)

    def poll(self, now: Optional[float] = None) -> List[Dict]:
        """One scan: record (once per scope instance) every live
        scope past budget, with the owning thread's current stack.
        Returns the records made by THIS poll."""
        if not enabled():
            return []
        if now is None:
            now = time.monotonic()
        with _slock:
            over = [sc for sc in _scopes.values()
                    if not sc.reported
                    and now - sc.start > sc.budget_s]
        if not over:
            _sync_gauges()
            return []
        frames = sys._current_frames()
        made = []
        base = len(_violations)
        for sc in over:
            if sc.reported:
                continue  # racing exit already reported it
            sc.reported = True
            witness = _frames_of(frames.get(sc.tid))
            _record("stall", sc, now - sc.start, witness)
        with _vlock:
            made = list(_violations[base:])
        _sync_gauges()
        return made

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll()
            except Exception as e:
                # the enforcer must outlive a bad poll, but the
                # failure stays visible (dump() carries the tail)
                self.poll_errors.append(repr(e))

    def start(self) -> "Enforcer":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="asyncheck-enforcer")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)


_global_enforcer: Optional[Enforcer] = None
_glock = threading.Lock()


def start_global(interval: float = 0.05) -> Enforcer:
    """Process-global enforcer (Context wires this next to the
    watchdog when the plane is enabled)."""
    global _global_enforcer
    with _glock:
        if _global_enforcer is None:
            _global_enforcer = Enforcer(interval).start()
        return _global_enforcer


def stop_global() -> None:
    global _global_enforcer
    with _glock:
        e, _global_enforcer = _global_enforcer, None
    if e is not None:
        e.stop()


# -- surfaces ---------------------------------------------------------

def violations() -> List[Dict]:
    with _vlock:
        return list(_violations)


def clear_violations() -> None:
    with _vlock:
        _violations.clear()


@contextmanager
def trap():
    """Capture-and-remove overruns recorded inside the block (the
    racecheck.trap() twin — tests provoke stalls without leaking
    records into later assertions)."""
    with _vlock:
        base = len(_violations)
    got: List[Dict] = []
    try:
        yield got
    finally:
        with _vlock:
            got.extend(_violations[base:])
            del _violations[base:]


def mark() -> int:
    """Gate anchor: the overrun count before a block of work."""
    with _vlock:
        return len(_violations)


def gate_check(base: int) -> Optional[str]:
    """Format overruns recorded past ``base`` (both witnesses,
    lockdep-report style) and clear them.  Returns None when clean."""
    with _vlock:
        vs = _violations[base:]
        if not vs:
            return None
        _violations.clear()
    detail = "\n".join(
        f"- {v['message']}\n"
        f"  scope entered at:\n{v['entry_stack']}"
        f"  {'mid-stall' if v['kind'] == 'stall' else 'exit'} "
        f"witness:\n{v['witness_stack']}"
        for v in vs)
    return (f"asyncheck: {len(vs)} loop-stall overrun(s) recorded:\n"
            f"{detail}")


def live_overruns(now: Optional[float] = None) -> List[Dict]:
    """Scopes open past budget RIGHT NOW (computed on the fly — the
    admin query names a stalled victim without an enforcer thread),
    with mid-stall stacks."""
    if not enabled():
        return []
    if now is None:
        now = time.monotonic()
    with _slock:
        over = [sc for sc in _scopes.values()
                if now - sc.start > sc.budget_s]
    if not over:
        return []
    frames = sys._current_frames()
    return [{
        "scope": sc.name,
        "thread": sc.thread,
        "elapsed_ms": round((now - sc.start) * 1000.0, 3),
        "budget_ms": round(sc.budget_s * 1000.0, 3),
        "stack": _fmt_stack(_frames_of(frames.get(sc.tid))),
    } for sc in over]


def dump() -> Dict:
    """The ``dump_asyncheck`` admin-command payload."""
    with _vlock:
        vs = list(_violations)
    with _slock:
        live = len(_scopes)
    return {
        "enabled": enabled(),
        "budget_ms": _budget_ms,
        "contracts": list(_contracts),
        "live_scopes": live,
        "live_overruns": live_overruns(),
        "violations": vs,
        "num_violations": len(vs),
    }
