"""Lockdep — runtime lock-order checking.

The role of src/common/lockdep.cc (g_lockdep + mutex_debug wrappers):
every lock is REGISTERED BY NAME, each thread's current hold set feeds
a global "B was acquired while A was held" graph, and an acquisition
that would close a cycle in that graph is reported immediately — with
the stack that is taking the locks in the new order AND the stack that
recorded the conflicting order first (lockdep.cc keeps both backtraces
for exactly this report).  A potential deadlock is caught the first
time the two orders ever run, long before the interleaving that would
actually wedge two threads.

Design points, mirroring the reference:

- Nodes are lock NAMES, not instances: every ``osd::pg`` lock across
  every OSD service is one node, so an ordering discipline is enforced
  for the whole class.  Same-name nesting (two different ``osd::pg``
  instances in one thread) is intentionally NOT an edge — per-class
  nesting has its own invariants (a PG has one primary; documented at
  the construction site) that an instance-blind graph cannot judge.
- Edges record a witness stack ONCE, at first observation; steady
  state costs two dict probes per acquire.  (lockdep.cc similarly
  caches follows[][] and backtraces.)
- Violations are RECORDED, not raised: daemon threads keep running so
  a detected inversion cannot cascade into unrelated test failures;
  the test harness (tests/conftest.py) fails the owning test and
  prints both witness stacks.  The one exception is a blocking
  re-acquire of a non-recursive lock by its holder — that is a
  certain self-deadlock, so it raises before hanging forever.
- The currently-held table doubles as the stall watchdog's input
  (analysis/watchdog.py): holder thread + acquire stamp per lock.

Enabled by env ``CEPH_TPU_LOCKDEP`` (any value but ``0``/``false``)
or ``enable()``; when disabled, ``make_lock``/``make_rlock`` return
raw ``threading`` primitives — zero overhead outside the harness.
This module depends only on the stdlib (it instruments everything
else, so it must sit below the whole package).
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

ENV = "CEPH_TPU_LOCKDEP"

_forced: Optional[bool] = None

# raw lock on purpose: guards lockdep's own tables and must not feed
# back into the graph it maintains
_state = threading.Lock()  # lockdep's own registry lock
_follows: Dict[str, Dict[str, str]] = {}  # a -> {b: witness stack}
_reported: set = set()
_violations: List[Dict] = []
# (thread id, id(lock)) -> {"name", "thread", "since", "depth"}
_held_registry: Dict[Tuple[int, int], Dict] = {}
# same key -> the ACQUIRING thread's _tls.held list object, so a
# release on a DIFFERENT thread (a ``with lock:`` suspended inside a
# generator and closed elsewhere, a callback handed across threads)
# can scrub the acquirer's stale entry instead of leaving a phantom
# hold that poisons its next order edge and the stall watchdog
_holder_lists: Dict[Tuple[int, int], list] = {}

# per-thread frozenset of held lock NAMES, rebuilt lazily on demand
# and invalidated on every acquire/release touching that thread's
# held list (including foreign scrubs) — racecheck consults the held
# set on EVERY guarded attribute access, so this must not rebuild a
# frozenset per access
_held_names_cache: Dict[int, frozenset] = {}

_tls = threading.local()

# the env is read once: every entry point (conftest, thrasher, the
# daemons) sets it before importing ceph_tpu, and enable() overrides
# it at runtime
_env_on = os.environ.get(ENV, "") not in ("", "0", "false", "no")


def enabled() -> bool:
    if _forced is not None:
        return _forced
    return _env_on


def enable(on: bool = True) -> None:
    """Force lockdep on/off for the process (overrides the env)."""
    global _forced
    _forced = on


def violations() -> List[Dict]:
    with _state:
        return list(_violations)


def clear_violations() -> None:
    with _state:
        del _violations[:]
        _reported.clear()


def forget(prefix: str) -> None:
    """Drop every graph node whose name starts with ``prefix`` — test
    hook so deliberately-inverted throwaway locks cannot poison the
    order graph for later acquisitions of reused names."""
    with _state:
        for a in [a for a in _follows if a.startswith(prefix)]:
            del _follows[a]
        for a in _follows:
            for b in [b for b in _follows[a] if b.startswith(prefix)]:
                del _follows[a][b]


class trap:
    """Context manager capturing violations raised inside it (and
    removing them from the global record) — for tests that trigger an
    inversion ON PURPOSE without tripping the per-test lockdep gate.

        with lockdep.trap() as got:
            ...provoke...
        assert got
    """

    def __enter__(self) -> List[Dict]:
        with _state:
            self._base = len(_violations)
        self._got: List[Dict] = []
        return self._got

    def __exit__(self, *exc) -> None:
        with _state:
            self._got.extend(_violations[self._base:])
            del _violations[self._base:]


def held_snapshot() -> List[Dict]:
    """Currently-held locks (holder thread + age) — the watchdog's
    scan input."""
    with _state:
        return [dict(info) for info in _held_registry.values()]


def _held() -> list:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


def held_names() -> frozenset:
    """Frozenset of lock names the calling thread holds, cached per
    thread between acquire/release events (racecheck's hot read)."""
    tid = threading.get_ident()
    v = _held_names_cache.get(tid)
    if v is None:
        v = frozenset(n for n, _ in _held())
        if len(_held_names_cache) > 512:  # dead-thread hygiene
            _held_names_cache.clear()
        _held_names_cache[tid] = v
    return v


def _stack() -> str:
    frames = traceback.extract_stack()
    while frames and frames[-1].filename == __file__:
        frames.pop()
    return "".join(traceback.format_list(frames[-14:]))


def _find_chain(src: str, dst: str) -> Optional[List[str]]:
    """Name path src -> ... -> dst in the follows graph, or None."""
    parent = {src: None}
    queue = [src]
    while queue:
        n = queue.pop(0)
        if n == dst:
            chain = []
            while n is not None:
                chain.append(n)
                n = parent[n]
            return chain[::-1]
        for m in _follows.get(n, ()):
            if m not in parent:
                parent[m] = n
                queue.append(m)
    return None


def _report(first: str, then: str, message: str,
            existing_stack: str, current_stack: str) -> None:
    v = {"first": first, "then": then, "message": message,
         "existing_stack": existing_stack,
         "current_stack": current_stack,
         "thread": threading.current_thread().name}
    _violations.append(v)
    import sys

    sys.stderr.write(
        f"\n=== lockdep: {message} [{v['thread']}] ===\n"
        f"--- existing order recorded at:\n{existing_stack}"
        f"--- conflicting order taken at:\n{current_stack}"
        f"=== end lockdep report ===\n")


def _check_edge(have: str, want: str) -> None:
    """Record ``want`` acquired while ``have`` is held; flag a cycle
    (an already-recorded path want -> ... -> have) with both witness
    stacks, lockdep.cc-style."""
    # steady-state fast path: a dict probe, no lock (GIL-consistent
    # reads; a rare stale miss just re-checks under the lock)
    if want in _follows.get(have, ()):
        return
    with _state:
        existing = _follows.setdefault(have, {})
        if want in existing:
            return
        chain = _find_chain(want, have)
        if chain is not None:
            key = (have, want)
            if key in _reported:
                return
            _reported.add(key)
            witness = _follows.get(chain[0], {}).get(
                chain[1], "(witness stack unavailable)") \
                if len(chain) > 1 else "(self edge)"
            _report(have, want,
                    f"lock order inversion: acquiring {want!r} while "
                    f"holding {have!r}, but the order "
                    f"{' -> '.join(chain)} was already recorded",
                    witness, _stack())
            return  # keep the graph acyclic: don't add the back edge
        existing[want] = _stack()


def _will_lock(lk, certain_block: bool) -> None:
    held = _held()
    for _name, inst in held:
        if inst is lk:
            if not lk._recursive and certain_block:
                msg = (f"recursive acquire of non-recursive lock "
                       f"{lk._name!r} (certain self-deadlock)")
                with _state:
                    _report(lk._name, lk._name, msg, "(same thread)",
                            _stack())
                raise RuntimeError(msg)
            return  # re-entry: no new ordering information
    name = lk._name
    seen = set()
    for have, _inst in held:
        if have == name or have in seen:
            continue  # same-name class nesting: documented invariant
        seen.add(have)
        _check_edge(have, name)


def _locked(lk) -> None:
    held = _held()
    held.append((lk._name, lk))
    tid = threading.get_ident()
    _held_names_cache.pop(tid, None)
    key = (tid, id(lk))
    with _state:
        info = _held_registry.get(key)
        if info is None:
            _held_registry[key] = {
                "name": lk._name,
                "thread": threading.current_thread().name,
                "since": time.monotonic(), "depth": 1}
            _holder_lists[key] = held
        else:
            info["depth"] += 1


def _released(lk) -> int:
    """Pop one hold level; returns levels popped (0 if untracked)."""
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][1] is lk:
            del held[i]
            break
    else:
        return _released_foreign(lk)
    tid = threading.get_ident()
    _held_names_cache.pop(tid, None)
    key = (tid, id(lk))
    with _state:
        info = _held_registry.get(key)
        if info is not None:
            info["depth"] -= 1
            if info["depth"] <= 0:
                del _held_registry[key]
                _holder_lists.pop(key, None)
    return 1


def _released_foreign(lk) -> int:
    """Release attributed to the wrong thread: the acquire ran
    elsewhere (a ``with lock:`` suspended in a generator and resumed
    on another thread, a registered callback).  Without this, the
    acquiring thread keeps a phantom entry in its held-set — every
    later acquisition there records a false order edge, and the
    watchdog reports a lock nobody holds.  Scrub the acquirer's
    bookkeeping by the lock's identity instead."""
    with _state:
        for key in list(_held_registry):
            if key[1] != id(lk):
                continue
            info = _held_registry[key]
            info["depth"] -= 1
            lst = _holder_lists.get(key)
            if lst is not None:
                for i in range(len(lst) - 1, -1, -1):
                    if lst[i][1] is lk:
                        del lst[i]
                        break
                _held_names_cache.pop(key[0], None)
            if info["depth"] <= 0:
                del _held_registry[key]
                _holder_lists.pop(key, None)
            return 1
    return 0


def _released_all(lk) -> int:
    """Pop every hold level of ``lk`` (Condition.wait's full release);
    returns how many were held so the restore can re-push them."""
    n = 0
    while _released(lk):
        n += 1
    return n


class DLock:
    """Drop-in ``threading.Lock`` with lockdep order tracking."""

    _recursive = False

    def __init__(self, name: str = "anon"):
        self._name = name
        self._lock = self._alloc()

    @staticmethod
    def _alloc():
        return threading.Lock()  # the wrapped primitive

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        on = enabled()
        if on:
            _will_lock(self, blocking and timeout < 0)
        got = self._lock.acquire(blocking, timeout)
        if got and on:
            _locked(self)
        return got

    def release(self) -> None:
        if enabled():
            _released(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "DLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self._name!r}>"


class DRLock(DLock):
    """Drop-in ``threading.RLock`` with lockdep order tracking.

    Implements the ``_release_save``/``_acquire_restore``/``_is_owned``
    trio so ``threading.Condition`` built over one releases the full
    recursion depth during ``wait()`` — and the held-lock bookkeeping
    follows (a waiting thread does NOT hold the lock: no false stall
    flags, no phantom order edges)."""

    _recursive = True

    @staticmethod
    def _alloc():
        return threading.RLock()  # the wrapped primitive

    def locked(self) -> bool:
        return self._lock._is_owned()

    def _is_owned(self) -> bool:
        return self._lock._is_owned()

    def _release_save(self):
        n = _released_all(self) if enabled() else 0
        return (self._lock._release_save(), n)

    def _acquire_restore(self, state) -> None:
        inner, n = state
        self._lock._acquire_restore(inner)
        if enabled():
            for _ in range(max(1, n)):
                _locked(self)


def make_lock(name: str):
    """Registry hook: a named, lockdep-tracked mutex when the checker
    is enabled, a raw ``threading.Lock`` (zero overhead) otherwise."""
    return DLock(name) if enabled() else threading.Lock()  # registry fallback


def make_rlock(name: str):
    return DRLock(name) if enabled() else threading.RLock()  # registry fallback
