"""ceph_tpu — a TPU-native storage placement + erasure coding framework.

A ground-up reimplementation of the capabilities of Ceph's pure math engines
(reference: wjwithagen/ceph) designed for JAX/XLA/Pallas on TPU:

- ``ceph_tpu.crush``: the CRUSH placement solver.  The straw2 draw and the
  rule-step walk of the reference (src/crush/mapper.c) become a vmapped JAX
  program (``crush_do_rule_batched``) that maps millions of placement-group
  inputs to OSD sets in a single device launch.
- ``ceph_tpu.ec``: erasure coding.  Reed-Solomon/GF(2^8) encode and decode
  (the role of the reference's jerasure / ISA-L plugins behind
  src/erasure-code/ErasureCodeInterface.h) as bit-sliced XOR matmuls on the
  MXU, plus the LRC / SHEC / CLAY composed codes.
- ``ceph_tpu.osdmap``: the cluster-map placement pipeline
  (pps seed -> crush -> upmap -> up filter -> primary affinity), fused into
  one batched program, and the upmap balancer built around it.
- ``ceph_tpu.parallel``: sharding the PG axis / chunk striping across a
  ``jax.sharding.Mesh`` (ICI/DCN collectives take the place of the
  reference's AsyncMessenger data plane).
- ``ceph_tpu.tools``: crushtool / osdmaptool / EC-benchmark equivalents.

Bit-exactness contract: every placement this package computes matches the
reference C core bit for bit; see tests/golden/ (vectors generated from the
reference implementation) and ceph_tpu/crush/mapper_ref.py (the executable
scalar specification).
"""

__version__ = "0.1.0"

# Honor CEPH_TPU_PLATFORM for EVERY library entry point, not just the
# CLIs: deployment images may preload jax pinned to a hardware backend,
# so the env var alone is a no-op; routing it through jax.config here
# (cheap — no backend client is created) makes
# ``CEPH_TPU_PLATFORM=cpu python anything_importing_ceph_tpu.py`` work.
from .utils.platform import apply_platform_env as _apply_platform_env

_apply_platform_env()
del _apply_platform_env
