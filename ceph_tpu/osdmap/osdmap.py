"""The cluster map: pools, OSD states/weights, upmap tables, CRUSH.

Host-side data model + scalar reference pipeline with the semantics of
the reference's OSDMap (src/osd/OSDMap.{h,cc}):

    pg → pps seed        (pg_pool_t::raw_pg_to_pps, osd_types.cc:1798)
    → crush do_rule      (_pg_to_raw_osds, OSDMap.cc:2433)
    → drop nonexistent   (_remove_nonexistent_osds, OSDMap.cc:2408)
    → upmap exceptions   (_apply_upmap, OSDMap.cc:2463)
    → drop down OSDs     (_raw_to_up_osds, OSDMap.cc:2510)
    → primary affinity   (_apply_primary_affinity, OSDMap.cc:2535)
    → pg_temp overlay    (_get_temp_osds, OSDMap.cc:2590)
    =  _pg_to_up_acting_osds (OSDMap.cc:2665)

The scalar path here is the executable spec and the batch-size-1 host
tool; the fused batched TPU program lives in ``pipeline_jax.py`` and is
tested against this one.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..common.encoding import Versioned
from ..crush.constants import CRUSH_ITEM_NONE
from ..crush.hash import hash32_2_int
from ..crush.map import CrushMap
from ..crush.mapper_ref import crush_do_rule

POOL_TYPE_REPLICATED = 1
POOL_TYPE_ERASURE = 3

FLAG_HASHPSPOOL = 1  # pg_pool_t::FLAG_HASHPSPOOL (osd_types.h)

OSD_EXISTS = 1  # CEPH_OSD_EXISTS
OSD_UP = 2      # CEPH_OSD_UP

DEFAULT_PRIMARY_AFFINITY = 0x10000
MAX_PRIMARY_AFFINITY = 0x10000


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Stable modulo that lets pg_num grow smoothly
    (src/include/rados.h:96)."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def _calc_mask(n: int) -> int:
    return (1 << (n - 1).bit_length()) - 1 if n > 1 else 0


@dataclass
class PgPool(Versioned):
    """pg_pool_t essentials (src/osd/osd_types.h:1300-1850)."""

    STRUCT_V = 1
    COMPAT_V = 1

    pool_type: int = POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 64
    pgp_num: int = 0  # defaults to pg_num
    crush_rule: int = 0
    flags: int = FLAG_HASHPSPOOL
    erasure_code_profile: str = ""

    def __post_init__(self):
        if not self.pgp_num:
            self.pgp_num = self.pg_num

    @property
    def pg_num_mask(self) -> int:
        return _calc_mask(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return _calc_mask(self.pgp_num)

    def can_shift_osds(self) -> bool:
        """Replicated pools compact their osd lists; EC pools are
        positional and hold CRUSH_ITEM_NONE (osd_types.h)."""
        return self.pool_type == POOL_TYPE_REPLICATED

    def raw_pg_to_ps(self, ps: int) -> int:
        return ceph_stable_mod(ps, self.pg_num, self.pg_num_mask)

    def raw_pg_to_pps(self, pool_id: int, ps: int) -> int:
        """osd_types.cc:1798."""
        m = ceph_stable_mod(ps, self.pgp_num, self.pgp_num_mask)
        if self.flags & FLAG_HASHPSPOOL:
            return hash32_2_int(m, pool_id)
        return (m + pool_id) & 0xFFFFFFFF

    def to_dict(self):
        return {
            "pool_type": self.pool_type, "size": self.size,
            "min_size": self.min_size, "pg_num": self.pg_num,
            "pgp_num": self.pgp_num, "crush_rule": self.crush_rule,
            "flags": self.flags,
            "erasure_code_profile": self.erasure_code_profile,
        }

    @classmethod
    def from_dict(cls, d):
        # skip fields a NEWER writer added (the DECODE_FINISH
        # contract): an old reader must decode the fields it knows
        # and ignore the rest, not crash on an unexpected kwarg
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


class OSDMap:
    """The mutable host cluster map (src/osd/OSDMap.h)."""

    # JSON tool/debug form version: to_json wraps the dict in the
    # versioned envelope; from_json also accepts the pre-envelope raw
    # dict (writer v0).  The WIRE form is the bincode encode
    # (osdmap/bincode_maps.py, wirecheck entry osdmap.full).
    STRUCT_V = 1
    COMPAT_V = 1

    def __init__(self, crush: Optional[CrushMap] = None):
        self.epoch = 1
        self.crush = crush or CrushMap()
        self.pools: Dict[int, PgPool] = {}
        self.max_osd = 0
        self.osd_state: List[int] = []
        self.osd_weight: List[int] = []       # 16.16 in/out weight
        self.osd_primary_affinity: Optional[List[int]] = None
        # exception tables, keyed (pool, ps)
        self.pg_upmap: Dict[Tuple[int, int], List[int]] = {}
        self.pg_upmap_items: Dict[Tuple[int, int],
                                  List[Tuple[int, int]]] = {}
        self.pg_temp: Dict[Tuple[int, int], List[int]] = {}
        self.primary_temp: Dict[Tuple[int, int], int] = {}

    # -- osd lifecycle ------------------------------------------------
    def set_max_osd(self, n: int) -> None:
        while self.max_osd < n:
            self.osd_state.append(0)
            self.osd_weight.append(0)
            if self.osd_primary_affinity is not None:
                self.osd_primary_affinity.append(
                    DEFAULT_PRIMARY_AFFINITY)
            self.max_osd += 1
        del self.osd_state[n:]
        del self.osd_weight[n:]
        if self.osd_primary_affinity is not None:
            del self.osd_primary_affinity[n:]
        self.max_osd = n

    def add_osd(self, osd: int, weight: int = 0x10000,
                up: bool = True) -> None:
        if osd >= self.max_osd:
            self.set_max_osd(osd + 1)
        self.osd_state[osd] = OSD_EXISTS | (OSD_UP if up else 0)
        self.osd_weight[osd] = weight

    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and \
            bool(self.osd_state[osd] & OSD_EXISTS)

    def is_up(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and \
            bool(self.osd_state[osd] & OSD_UP)

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def set_primary_affinity(self, osd: int, aff: int) -> None:
        if self.osd_primary_affinity is None:
            self.osd_primary_affinity = \
                [DEFAULT_PRIMARY_AFFINITY] * self.max_osd
        self.osd_primary_affinity[osd] = aff

    # -- scalar pipeline (the executable spec) ------------------------
    def _pg_to_raw_osds(self, pool_id: int, pool: PgPool,
                        ps: int) -> Tuple[List[int], int]:
        pps = pool.raw_pg_to_pps(pool_id, ps)
        raw: List[int] = []
        if pool.crush_rule in self.crush.rules:
            cargs = self.crush.choose_args.get(pool_id)
            raw = crush_do_rule(self.crush, pool.crush_rule, pps,
                                pool.size, self.osd_weight,
                                choose_args=cargs)
        # _remove_nonexistent_osds (OSDMap.cc:2408)
        if pool.can_shift_osds():
            raw = [o for o in raw if self.exists(o)]
        else:
            raw = [o if self.exists(o) else CRUSH_ITEM_NONE
                   for o in raw]
        return raw, pps

    def _apply_upmap(self, pool: PgPool, pgid: Tuple[int, int],
                     raw: List[int]) -> List[int]:
        p = self.pg_upmap.get(pgid)
        if p is not None:
            for osd in p:
                if osd != CRUSH_ITEM_NONE and 0 <= osd < self.max_osd \
                        and self.osd_weight[osd] == 0:
                    # reject/ignore the explicit mapping entirely —
                    # pg_upmap_items are skipped too (OSDMap.cc:2472)
                    return raw
            raw = list(p)
        q = self.pg_upmap_items.get(pgid)
        if q is not None:
            for frm, to in q:
                exists = False
                pos = -1
                for i, osd in enumerate(raw):
                    if osd == to:
                        exists = True
                        break
                    if osd == frm and pos < 0 and not (
                            to != CRUSH_ITEM_NONE and 0 <= to
                            < self.max_osd and self.osd_weight[to] == 0):
                        pos = i
                if not exists and pos >= 0:
                    raw[pos] = to
        return raw

    def _raw_to_up_osds(self, pool: PgPool,
                        raw: List[int]) -> List[int]:
        if pool.can_shift_osds():
            return [o for o in raw
                    if self.exists(o) and not self.is_down(o)]
        return [o if self.exists(o) and not self.is_down(o)
                else CRUSH_ITEM_NONE for o in raw]

    @staticmethod
    def _pick_primary(osds: List[int]) -> int:
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def _apply_primary_affinity(self, pps: int, pool: PgPool,
                                osds: List[int],
                                primary: int) -> Tuple[List[int], int]:
        aff = self.osd_primary_affinity
        if aff is None:
            return osds, primary
        if not any(o != CRUSH_ITEM_NONE
                   and aff[o] != DEFAULT_PRIMARY_AFFINITY
                   for o in osds):
            return osds, primary
        pos = -1
        for i, o in enumerate(osds):
            if o == CRUSH_ITEM_NONE:
                continue
            a = aff[o]
            if a < MAX_PRIMARY_AFFINITY and \
                    (hash32_2_int(pps, o) >> 16) >= a:
                if pos < 0:
                    pos = i  # fallback if nobody accepts
            else:
                pos = i
                break
        if pos < 0:
            return osds, primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            osds = [osds[pos]] + osds[:pos] + osds[pos + 1:]
        return osds, primary

    def _get_temp_osds(self, pool: PgPool, pgid: Tuple[int, int],
                       ) -> Tuple[List[int], int]:
        temp: List[int] = []
        t = self.pg_temp.get(pgid)
        if t is not None:
            for o in t:
                if not self.exists(o) or self.is_down(o):
                    if pool.can_shift_osds():
                        continue
                    temp.append(CRUSH_ITEM_NONE)
                else:
                    temp.append(o)
        tp = self.primary_temp.get(pgid, -1)
        if tp == -1 and temp:
            for o in temp:
                if o != CRUSH_ITEM_NONE:
                    tp = o
                    break
        return temp, tp

    def pg_to_up_acting_osds(self, pool_id: int, ps: int):
        """OSDMap.cc:2665.  Returns (up, up_primary, acting,
        acting_primary)."""
        pool = self.pools.get(pool_id)
        if pool is None or ps >= pool.pg_num:
            return [], -1, [], -1
        pgid = (pool_id, pool.raw_pg_to_ps(ps))
        acting, acting_primary = self._get_temp_osds(pool, pgid)
        raw, pps = self._pg_to_raw_osds(pool_id, pool, ps)
        raw = self._apply_upmap(pool, pgid, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._pick_primary(up)
        up, up_primary = self._apply_primary_affinity(
            pps, pool, up, up_primary)
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    # -- serialization (the framework's native map format) -------------
    def to_dict(self):
        def kv(d):
            return [[list(k), v] for k, v in sorted(d.items())]

        return {
            "epoch": self.epoch,
            "max_osd": self.max_osd,
            "osd_state": list(self.osd_state),
            "osd_weight": list(self.osd_weight),
            "osd_primary_affinity": self.osd_primary_affinity,
            "pools": {str(k): v.to_dict() for k, v in self.pools.items()},
            "pg_upmap": kv(self.pg_upmap),
            "pg_upmap_items": kv(self.pg_upmap_items),
            "pg_temp": kv(self.pg_temp),
            "primary_temp": kv(self.primary_temp),
            "crush": self.crush.to_dict(),
        }

    @classmethod
    def from_dict(cls, d) -> "OSDMap":
        m = cls(CrushMap.from_dict(d["crush"]))
        m.epoch = d.get("epoch", 1)
        m.max_osd = d["max_osd"]
        m.osd_state = list(d["osd_state"])
        m.osd_weight = list(d["osd_weight"])
        m.osd_primary_affinity = d.get("osd_primary_affinity")
        m.pools = {int(k): PgPool.from_dict(v)
                   for k, v in d["pools"].items()}
        m.pg_upmap = {tuple(k): list(v) for k, v in d["pg_upmap"]}
        m.pg_upmap_items = {tuple(k): [tuple(p) for p in v]
                            for k, v in d["pg_upmap_items"]}
        m.pg_temp = {tuple(k): list(v) for k, v in d["pg_temp"]}
        m.primary_temp = {tuple(k): v for k, v in d["primary_temp"]}
        return m

    def to_json(self) -> str:
        from ..common import encoding

        return encoding.encode(self.to_dict(), self.STRUCT_V,
                               self.COMPAT_V)

    @classmethod
    def from_json(cls, s: str) -> "OSDMap":
        from ..common import encoding

        v, d = encoding.decode_any(s, supported=cls.STRUCT_V,
                                   struct="osdmap.json")
        try:
            return cls.from_dict(d)
        except (KeyError, TypeError, ValueError, AttributeError) as e:
            raise encoding.MalformedInput(
                f"osdmap.json v{v}: bad payload: {e!r}")
