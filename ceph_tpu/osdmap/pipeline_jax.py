"""The fused batched placement pipeline — map every PG in one launch.

One jitted XLA program for the full OSDMap chain (OSDMap.cc:2665
_pg_to_up_acting_osds): pps seed → CRUSH → nonexistent-filter → upmap →
up-filter → primary affinity → pg_temp overlay.  The reference runs this
per-PG on CPU and batches with a thread pool (ParallelPGMapper,
src/osd/OSDMapMapping.h:18); here the PG axis is the vmapped batch axis
and shards across the TPU mesh.

Exception tables (pg_upmap/pg_upmap_items/pg_temp/primary_temp) are
lowered host-side to dense per-PG arrays; stages that no PG uses are
statically compiled out.  OSD weights/states/affinities stay runtime
arrays: mark-out and reweight re-run without recompiling — the property
the balancer loop (OSDMap.cc:4618 calc_pg_upmaps) needs.  Upmap/temp
edits go through ``PoolMapper.refresh_tables()``: a cheap host relower
when the same stages stay active, a rebuild when a stage appears.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..crush import hash as H
from ..crush.constants import CRUSH_ITEM_NONE as NONE
from ..crush.mapper_jax import make_single_fn
from .osdmap import (DEFAULT_PRIMARY_AFFINITY, FLAG_HASHPSPOOL,
                     MAX_PRIMARY_AFFINITY, OSD_EXISTS, OSD_UP, OSDMap,
                     PgPool)

I32 = jnp.int32
U32 = jnp.uint32


def _stable_mod(x, b: int, bmask: int):
    lo = x & jnp.uint32(bmask)
    return jnp.where(lo < b, lo, x & jnp.uint32(bmask >> 1))


def _compact(row, keep, rlen, R: int):
    """Stable left-compaction of kept entries (can_shift pools); drops
    the rest, pads with NONE.  Returns (row, new_len)."""
    idx = jnp.arange(R, dtype=I32)
    keep = keep & (idx < rlen)
    order = jnp.argsort(jnp.where(keep, idx, idx + R))
    newlen = jnp.sum(keep.astype(I32))
    return jnp.where(idx < newlen, row[order], NONE), newlen


def _mask_none(row, keep, rlen, R: int):
    """Positional pools: non-kept entries become NONE, length kept."""
    idx = jnp.arange(R, dtype=I32)
    return jnp.where(idx < rlen, jnp.where(keep, row, NONE), NONE), rlen


@dataclass
class _DenseTables:
    """Host-lowered exception tables, one row per raw ps."""

    upmap: Optional[np.ndarray]        # i32[pg, R]
    upmap_len: Optional[np.ndarray]    # i32[pg]  (-1 = no entry)
    pairs: Optional[np.ndarray]        # i32[pg, P, 2]
    npairs: Optional[np.ndarray]       # i32[pg]
    temp: Optional[np.ndarray]         # i32[pg, T]
    temp_len: Optional[np.ndarray]     # i32[pg]  (-1 = no entry)
    ptemp: Optional[np.ndarray]        # i32[pg]  (-1 = no entry)


def _lower_tables(m: OSDMap, pool_id: int, pool: PgPool) -> _DenseTables:
    n = pool.pg_num
    R = pool.size

    def rows(table, name, maxw=None):
        # entries with ps >= pg_num are unreachable in the scalar path
        # (lookups go through raw_pg_to_ps < pg_num); drop them here too
        out = {ps: v for (pid, ps), v in table.items()
               if pid == pool_id and ps < n}
        if maxw is not None:
            for ps, v in out.items():
                if len(v) > maxw:
                    raise ValueError(
                        f"{name}[{pool_id}.{ps}] has {len(v)} entries, "
                        f"more than pool size {maxw}; the reference "
                        f"monitor rejects such mappings and the batched "
                        f"pipeline's fixed result width cannot hold them")
        return out

    up = rows(m.pg_upmap, "pg_upmap", R)
    items = rows(m.pg_upmap_items, "pg_upmap_items")
    temps = rows(m.pg_temp, "pg_temp", R)
    ptemps = rows(m.primary_temp, "primary_temp")

    t = _DenseTables(None, None, None, None, None, None, None)
    if up:
        W = R
        t.upmap = np.full((n, W), NONE, np.int32)
        t.upmap_len = np.full(n, -1, np.int32)
        for ps, v in up.items():
            t.upmap[ps, :len(v)] = v
            t.upmap_len[ps] = len(v)
    if items:
        P = max(len(v) for v in items.values())
        t.pairs = np.zeros((n, P, 2), np.int32)
        t.npairs = np.zeros(n, np.int32)
        for ps, v in items.items():
            for j, (a, b) in enumerate(v):
                t.pairs[ps, j] = (a, b)
            t.npairs[ps] = len(v)
    if temps:
        T = R
        t.temp = np.full((n, T), NONE, np.int32)
        t.temp_len = np.full(n, -1, np.int32)
        for ps, v in temps.items():
            t.temp[ps, :len(v)] = v
            t.temp_len[ps] = len(v)
    if ptemps:
        t.ptemp = np.full(n, -1, np.int32)
        for ps, v in ptemps.items():
            t.ptemp[ps] = v
    return t


class PoolMapper:
    """Compiled batched ``pg_to_up_acting`` for one pool.

    >>> pm = PoolMapper(osdmap, pool_id)
    >>> out = pm.map_all()   # dict of arrays over every PG

    ``mesh``: a ``jax.sharding.Mesh`` shards the PG axis (ps, every
    per-PG exception-table row, and every output) across the mesh
    devices — ``map_all`` becomes one pjit launch over all chips, with
    the OSDMap runtime vectors replicated.  The PG count is pow2-
    padded to a mesh multiple (pad lanes carry inactive table rows and
    are sliced off), so non-divisible pools never fork and the compile
    signature set stays bounded.
    """

    def __init__(self, m: OSDMap, pool_id: int, mesh=None):
        self.m = m
        self.pool_id = pool_id
        self.mesh = mesh
        pool = m.pools[pool_id]
        self.pool = pool
        R = pool.size
        D = max(m.max_osd, 1)
        self.R, self.D = R, D
        shift = pool.can_shift_osds()

        cargs = m.crush.choose_args.get(pool_id)
        if pool.crush_rule in m.crush.rules:
            # the speculative lowering (mapper_spec) is bit-exact and
            # ~an order of magnitude faster where eligible (straw2
            # take/chooseleaf-firstn/emit, modern tunables) — the
            # balancer's mutate-remap loop and osdmaptool sweeps live
            # on this path; everything else takes the general rule VM.
            # CEPH_TPU_SPEC_PIPELINE=0 forces the general mapper.
            import os as _os

            single = None
            if _os.environ.get("CEPH_TPU_SPEC_PIPELINE", "1") != "0":
                from ..crush.mapper_spec import (Ineligible,
                                                 make_single_spec)

                try:
                    single, static, arrays = make_single_spec(
                        m.crush, pool.crush_rule, R,
                        choose_args=cargs, k_tries=1)
                except Ineligible:
                    single = None
            if single is None:
                single, static, arrays = make_single_fn(
                    m.crush, pool.crush_rule, R, choose_args=cargs)
            self.arrays = jax.tree_util.tree_map(jnp.asarray, arrays)
        else:
            single = None
            self.arrays = None

        tabs = _lower_tables(m, pool_id, pool)
        self.tabs = tabs
        has_aff = m.osd_primary_affinity is not None
        pgp, pgp_mask = pool.pgp_num, pool.pgp_num_mask
        hashpspool = bool(pool.flags & FLAG_HASHPSPOOL)
        pid_u32 = pool_id & 0xFFFFFFFF

        def seed(ps):
            mm = _stable_mod(ps, pgp, pgp_mask)
            if hashpspool:
                return H.crush_hash32_2(mm, jnp.uint32(pid_u32))
            return mm + jnp.uint32(pid_u32)

        idx = jnp.arange(R, dtype=I32)

        def osd_ok(osd, exists_up):
            """exists/up lookup with range guard; returns (exists, up)."""
            inr = (osd >= 0) & (osd < D)
            st = exists_up[jnp.clip(osd, 0, D - 1)]
            ex = inr & ((st & OSD_EXISTS) != 0)
            upb = inr & ((st & OSD_UP) != 0)
            return ex, upb

        def single_pg(A, weight, state, paff, trow, ps):
            pps = seed(ps)
            if single is not None:
                raw, rlen = single(A, weight, pps)
            else:
                raw = jnp.full(R, NONE, I32)
                rlen = jnp.int32(0)

            # _remove_nonexistent_osds (OSDMap.cc:2408)
            ex, upb = osd_ok(raw, state)
            if shift:
                raw, rlen = _compact(raw, ex, rlen, R)
            else:
                raw, rlen = _mask_none(raw, ex, rlen, R)

            # _apply_upmap (OSDMap.cc:2463)
            upmap_rejected = jnp.bool_(False)
            if tabs.upmap is not None:
                urow, ulen = trow["upmap"], trow["upmap_len"]
                uvalid = (urow != NONE) & (urow >= 0) & (urow < D)
                marked_out = uvalid & \
                    (weight[jnp.clip(urow, 0, D - 1)] == 0) & \
                    (idx < ulen)
                # a marked-out target rejects the whole exception entry
                # AND skips pg_upmap_items for this PG (OSDMap.cc:2472)
                upmap_rejected = (ulen >= 0) & jnp.any(marked_out)
                use = (ulen >= 0) & ~upmap_rejected
                raw = jnp.where(use,
                                jnp.where(idx < ulen, urow, NONE), raw)
                rlen = jnp.where(use, ulen, rlen)
            if tabs.pairs is not None:
                pr, npair = trow["pairs"], trow["npairs"]
                # width from the traced row, not the closure: stays
                # correct when refresh_tables retraces with more pairs
                P = pr.shape[0]
                for p in range(P):
                    frm, to = pr[p, 0], pr[p, 1]
                    active = p < npair
                    in_seg = idx < rlen
                    has_to = jnp.any(in_seg & (raw == to))
                    to_out = (to != NONE) & (to >= 0) & (to < D) & \
                        (weight[jnp.clip(to, 0, D - 1)] == 0)
                    cand = in_seg & (raw == frm) & ~to_out
                    pos = jnp.argmax(cand)
                    do = active & ~has_to & jnp.any(cand) \
                        & ~upmap_rejected
                    raw = jnp.where(
                        do, raw.at[pos].set(to), raw)

            # _raw_to_up_osds (OSDMap.cc:2510)
            ex, upb = osd_ok(raw, state)
            keep = ex & upb
            if shift:
                up, ulen2 = _compact(raw, keep, rlen, R)
            else:
                up, ulen2 = _mask_none(raw, keep, rlen, R)

            # _pick_primary (OSDMap.cc:2452)
            valid = (idx < ulen2) & (up != NONE)
            first = jnp.argmax(valid)
            up_primary = jnp.where(jnp.any(valid), up[first], -1)

            # _apply_primary_affinity (OSDMap.cc:2535)
            if has_aff:
                a = paff[jnp.clip(up, 0, D - 1)]
                nondefault = valid & (a != DEFAULT_PRIMARY_AFFINITY)
                h = H.crush_hash32_2(pps, _u32i(up)) >> jnp.uint32(16)
                rejected = valid & (a < MAX_PRIMARY_AFFINITY) & (h >= a)
                accept = valid & ~rejected
                pos = jnp.where(jnp.any(accept), jnp.argmax(accept),
                                jnp.where(jnp.any(valid),
                                          jnp.argmax(valid), -1))
                engage = jnp.any(nondefault) & (pos >= 0)
                posc = jnp.clip(pos, 0, R - 1)
                new_primary = jnp.where(engage, up[posc], up_primary)
                if shift:
                    rolled = jnp.where(idx == 0, up[posc],
                                       jnp.where(idx <= posc,
                                                 up[jnp.clip(idx - 1, 0,
                                                             R - 1)],
                                                 up))
                    up = jnp.where(engage & (posc > 0), rolled, up)
                up_primary = new_primary

            # _get_temp_osds overlay (OSDMap.cc:2590)
            acting, alen = up, ulen2
            acting_primary = up_primary
            if tabs.temp is not None:
                trow_t, tlen = trow["temp"], trow["temp_len"]
                tex, tup = osd_ok(trow_t, state)
                tkeep = tex & tup
                if shift:
                    ft, flen = _compact(trow_t, tkeep,
                                        jnp.maximum(tlen, 0), R)
                else:
                    ft, flen = _mask_none(trow_t, tkeep,
                                          jnp.maximum(tlen, 0), R)
                use_t = (tlen >= 0) & (flen > 0)
                tvalid = (idx < flen) & (ft != NONE)
                tprim = jnp.where(jnp.any(tvalid),
                                  ft[jnp.argmax(tvalid)], -1)
                acting = jnp.where(use_t, ft, acting)
                alen = jnp.where(use_t, flen, alen)
                acting_primary = jnp.where(use_t, tprim, acting_primary)
            if tabs.ptemp is not None:
                pt = trow["ptemp"]
                acting_primary = jnp.where(pt != -1, pt, acting_primary)

            return (up, ulen2, up_primary, acting, alen, acting_primary)

        # vmapped over ps + per-pg table rows
        self._trow = {}
        if tabs.upmap is not None:
            self._trow["upmap"] = jnp.asarray(tabs.upmap)
            self._trow["upmap_len"] = jnp.asarray(tabs.upmap_len)
        if tabs.pairs is not None:
            self._trow["pairs"] = jnp.asarray(tabs.pairs)
            self._trow["npairs"] = jnp.asarray(tabs.npairs)
        if tabs.temp is not None:
            self._trow["temp"] = jnp.asarray(tabs.temp)
            self._trow["temp_len"] = jnp.asarray(tabs.temp_len)
        if tabs.ptemp is not None:
            self._trow["ptemp"] = jnp.asarray(tabs.ptemp)
        trow_axes = {k: 0 for k in self._trow}

        vmapped = jax.vmap(
            single_pg, in_axes=(None, None, None, None, trow_axes, 0))
        if mesh is None:
            self.fn = jax.jit(vmapped)
            self._npad = None
        else:
            from jax.sharding import NamedSharding, PartitionSpec

            from ..parallel.meshctx import pad_batch

            repl = NamedSharding(mesh, PartitionSpec())
            shard = NamedSharding(mesh,
                                  PartitionSpec(mesh.axis_names[0]))
            self.fn = jax.jit(
                vmapped,
                in_shardings=(repl, repl, repl, repl,
                              {k: shard for k in self._trow}, shard),
                out_shardings=(shard,) * 6)
            self._npad = pad_batch(
                pool.pg_num, int(np.asarray(mesh.devices).size))
            self._pad_trow()

    def _pad_trow(self):
        """Extend every per-PG table row to the padded PG count with
        INACTIVE entries (len fields -1, npairs 0, ptemp -1, row
        contents NONE) — pad lanes execute the same program but engage
        no exception stage, and their outputs are sliced off."""
        npad = self._npad
        inactive = {"upmap_len": -1, "npairs": 0, "temp_len": -1,
                    "ptemp": -1}
        for k, v in list(self._trow.items()):
            n = int(v.shape[0])
            if n >= npad:
                continue
            fill = inactive.get(k, NONE)
            pad_shape = (npad - n,) + tuple(v.shape[1:])
            pad = jnp.full(pad_shape, fill, v.dtype)
            self._trow[k] = jnp.concatenate([v, pad], axis=0)

    def refresh_tables(self):
        """Re-lower the exception tables after upmap/pg_temp edits.

        Cheap when the set of active stages is unchanged (host relower,
        same compiled program; pair-count shape changes just retrace);
        rebuilds the whole mapper when a stage appears or disappears
        (its code was statically compiled in/out)."""
        tabs = _lower_tables(self.m, self.pool_id, self.pool)
        same = all(
            (getattr(tabs, f) is None) == (getattr(self.tabs, f) is None)
            for f in ("upmap", "pairs", "temp", "ptemp"))
        if not same:
            self.__init__(self.m, self.pool_id, self.mesh)
            return
        self.tabs = tabs
        for k, v in (("upmap", tabs.upmap), ("upmap_len", tabs.upmap_len),
                     ("pairs", tabs.pairs), ("npairs", tabs.npairs),
                     ("temp", tabs.temp), ("temp_len", tabs.temp_len),
                     ("ptemp", tabs.ptemp)):
            if v is not None:
                self._trow[k] = jnp.asarray(v)
        if self._npad is not None:
            self._pad_trow()

    def runtime_args(self):
        m = self.m
        weight = jnp.asarray(np.asarray(m.osd_weight, np.uint32))
        state = jnp.asarray(np.asarray(m.osd_state, np.int32))
        paff = jnp.asarray(np.asarray(
            m.osd_primary_affinity
            if m.osd_primary_affinity is not None
            else [DEFAULT_PRIMARY_AFFINITY] * m.max_osd, np.uint32))
        return weight, state, paff

    def map_all(self, weight=None, state=None, paff=None):
        """Map every PG of the pool.  Returns dict of device arrays:
        up[pg,R], up_len[pg], up_primary[pg], acting*, ...

        On a meshed mapper the launch runs over the padded PG axis
        sharded across the chips; pad lanes are sliced off host-side
        before return."""
        w0, s0, p0 = self.runtime_args()
        weight = w0 if weight is None else jnp.asarray(weight)
        state = s0 if state is None else jnp.asarray(state)
        paff = p0 if paff is None else jnp.asarray(paff)
        n = self.pool.pg_num
        ps = jnp.arange(self._npad or n, dtype=jnp.uint32)
        up, ulen, uprim, acting, alen, aprim = self.fn(
            self.arrays, weight, state, paff, self._trow, ps)
        out = {"up": up, "up_len": ulen, "up_primary": uprim,
               "acting": acting, "acting_len": alen,
               "acting_primary": aprim}
        if self._npad is not None and self._npad != n:
            out = {k: np.asarray(v)[:n] for k, v in out.items()}
        return out


def _u32i(v):
    return v.astype(jnp.uint32)
